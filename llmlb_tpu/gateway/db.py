"""SQLite persistence layer.

The reference accumulated 27 migrations (reference llmlb/migrations/, db/ at
~16.6k LoC over sqlx); this is the collapsed clean schema plus typed accessors.
Single connection in WAL mode guarded by a lock — the gateway's write rates
(stats, history, audit) are far below SQLite's WAL throughput, and reads are
mostly served from in-memory caches (registry, TPS tracker) seeded at boot.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
import uuid
from typing import Iterable

from llmlb_tpu.gateway.types import (
    AcceleratorInfo,
    Capability,
    Endpoint,
    EndpointModel,
    EndpointStatus,
    EndpointType,
)

SCHEMA = """
PRAGMA journal_mode=WAL;

CREATE TABLE IF NOT EXISTS users (
    id TEXT PRIMARY KEY,
    username TEXT NOT NULL UNIQUE,
    password_hash TEXT NOT NULL,
    role TEXT NOT NULL DEFAULT 'viewer',
    must_change_password INTEGER NOT NULL DEFAULT 0,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);

CREATE TABLE IF NOT EXISTS api_keys (
    id TEXT PRIMARY KEY,
    user_id TEXT NOT NULL REFERENCES users(id) ON DELETE CASCADE,
    name TEXT NOT NULL,
    key_hash TEXT NOT NULL UNIQUE,
    key_prefix TEXT NOT NULL,
    permissions TEXT NOT NULL DEFAULT '[]',
    created_at REAL NOT NULL,
    last_used_at REAL,
    expires_at REAL,
    revoked INTEGER NOT NULL DEFAULT 0
);

CREATE TABLE IF NOT EXISTS endpoints (
    id TEXT PRIMARY KEY,
    name TEXT NOT NULL,
    base_url TEXT NOT NULL UNIQUE,
    api_key TEXT,
    endpoint_type TEXT NOT NULL,
    status TEXT NOT NULL,
    latency_ms REAL,
    consecutive_failures INTEGER NOT NULL DEFAULT 0,
    accelerator TEXT,
    chip_count INTEGER NOT NULL DEFAULT 0,
    hbm_used_bytes INTEGER NOT NULL DEFAULT 0,
    hbm_total_bytes INTEGER NOT NULL DEFAULT 0,
    utilization REAL,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL,
    last_checked_at REAL
);

CREATE TABLE IF NOT EXISTS endpoint_models (
    id TEXT PRIMARY KEY,
    endpoint_id TEXT NOT NULL REFERENCES endpoints(id) ON DELETE CASCADE,
    model_id TEXT NOT NULL,
    canonical_name TEXT NOT NULL,
    capabilities TEXT NOT NULL DEFAULT '[]',
    context_length INTEGER,
    created_at REAL NOT NULL,
    UNIQUE(endpoint_id, model_id)
);
CREATE INDEX IF NOT EXISTS idx_endpoint_models_canonical
    ON endpoint_models(canonical_name);

CREATE TABLE IF NOT EXISTS endpoint_health_checks (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    endpoint_id TEXT NOT NULL,
    ok INTEGER NOT NULL,
    latency_ms REAL,
    error TEXT,
    checked_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_health_checks_endpoint
    ON endpoint_health_checks(endpoint_id, checked_at);

CREATE TABLE IF NOT EXISTS registered_models (
    id TEXT PRIMARY KEY,
    name TEXT NOT NULL UNIQUE,
    source_repo TEXT,
    format TEXT,
    capabilities TEXT NOT NULL DEFAULT '[]',
    manifest TEXT,
    created_at REAL NOT NULL
);

CREATE TABLE IF NOT EXISTS request_history (
    id TEXT PRIMARY KEY,
    ts REAL NOT NULL,
    endpoint_id TEXT,
    endpoint_name TEXT,
    model TEXT,
    api_kind TEXT,
    path TEXT,
    status_code INTEGER,
    duration_ms REAL,
    prompt_tokens INTEGER NOT NULL DEFAULT 0,
    completion_tokens INTEGER NOT NULL DEFAULT 0,
    client_ip TEXT,
    api_key_id TEXT,
    user_id TEXT,
    stream INTEGER NOT NULL DEFAULT 0,
    error TEXT,
    request_body TEXT,
    response_body TEXT
);
CREATE INDEX IF NOT EXISTS idx_request_history_ts ON request_history(ts);
CREATE INDEX IF NOT EXISTS idx_request_history_ip ON request_history(client_ip, ts);

CREATE TABLE IF NOT EXISTS endpoint_daily_stats (
    endpoint_id TEXT NOT NULL,
    date TEXT NOT NULL,
    model TEXT NOT NULL,
    api_kind TEXT NOT NULL,
    request_count INTEGER NOT NULL DEFAULT 0,
    error_count INTEGER NOT NULL DEFAULT 0,
    prompt_tokens INTEGER NOT NULL DEFAULT 0,
    completion_tokens INTEGER NOT NULL DEFAULT 0,
    total_duration_ms REAL NOT NULL DEFAULT 0,
    PRIMARY KEY (endpoint_id, date, model, api_kind)
);

CREATE TABLE IF NOT EXISTS settings (
    key TEXT PRIMARY KEY,
    value TEXT NOT NULL,
    updated_at REAL NOT NULL
);

CREATE TABLE IF NOT EXISTS invitations (
    id TEXT PRIMARY KEY,
    code TEXT NOT NULL UNIQUE,
    role TEXT NOT NULL DEFAULT 'viewer',
    created_by TEXT,
    created_at REAL NOT NULL,
    expires_at REAL,
    used_by TEXT,
    used_at REAL
);

CREATE TABLE IF NOT EXISTS audit_log (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    ts REAL NOT NULL,
    method TEXT NOT NULL,
    path TEXT NOT NULL,
    status INTEGER NOT NULL,
    duration_ms REAL NOT NULL,
    actor TEXT,
    actor_type TEXT,
    ip TEXT,
    detail TEXT,
    batch_id INTEGER
);
CREATE INDEX IF NOT EXISTS idx_audit_log_ts ON audit_log(ts);
CREATE INDEX IF NOT EXISTS idx_audit_log_batch ON audit_log(batch_id);

CREATE TABLE IF NOT EXISTS audit_batches (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    batch_hash TEXT NOT NULL,
    prev_hash TEXT NOT NULL,
    entry_count INTEGER NOT NULL,
    created_at REAL NOT NULL
);

CREATE TABLE IF NOT EXISTS download_tasks (
    id TEXT PRIMARY KEY,
    endpoint_id TEXT NOT NULL,
    model TEXT NOT NULL,
    status TEXT NOT NULL DEFAULT 'pending',
    progress REAL NOT NULL DEFAULT 0,
    error TEXT,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
"""


# Full-text audit search (parity: db/audit_log.rs:82-98 FTS5 table+triggers).
# Kept out of SCHEMA so a sqlite build without the fts5 module still boots
# (AuditLog.search falls back to LIKE when Database.fts_enabled is False).
# External-content table: rows live in audit_log; triggers keep FTS in sync,
# including deletes from the 90-day archiver.
FTS_SCHEMA = """
CREATE VIRTUAL TABLE IF NOT EXISTS audit_log_fts USING fts5(
    path, actor, detail,
    content='audit_log', content_rowid='id'
);
CREATE TRIGGER IF NOT EXISTS audit_log_fts_ai AFTER INSERT ON audit_log BEGIN
    INSERT INTO audit_log_fts(rowid, path, actor, detail)
    VALUES (new.id, new.path, new.actor, new.detail);
END;
CREATE TRIGGER IF NOT EXISTS audit_log_fts_ad AFTER DELETE ON audit_log BEGIN
    INSERT INTO audit_log_fts(audit_log_fts, rowid, path, actor, detail)
    VALUES ('delete', old.id, old.path, old.actor, old.detail);
END;
"""


def _caps_to_json(caps: Iterable[Capability]) -> str:
    return json.dumps([c.value for c in caps])


def _caps_from_json(raw: str | None) -> list[Capability]:
    if not raw:
        return []
    out = []
    for v in json.loads(raw):
        try:
            out.append(Capability(v))
        except ValueError:
            continue
    return out


class _Transaction:
    """BEGIN IMMEDIATE transaction holding the connection lock for its whole
    extent (re-entrant: accessors called inside still acquire it)."""

    def __init__(self, db: "Database"):
        self.db = db

    def __enter__(self) -> "Database":
        self.db._lock.acquire()
        try:
            self.db._conn.execute("BEGIN IMMEDIATE")
        except BaseException:
            # BEGIN can itself time out on a sibling process's write lock;
            # __exit__ will never run, so release here or the RLock leaks
            # and every later caller deadlocks
            self.db._lock.release()
            raise
        return self.db

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            if exc_type is None:
                self.db._conn.execute("COMMIT")
            else:
                self.db._conn.execute("ROLLBACK")
        finally:
            self.db._lock.release()


class Database:
    """Thread-safe SQLite wrapper with typed accessors."""

    def __init__(self, path: str = ":memory:"):
        self.path = path
        self._conn = sqlite3.connect(
            path, check_same_thread=False, isolation_level=None
        )
        self._conn.row_factory = sqlite3.Row
        self._conn.execute("PRAGMA foreign_keys=ON")
        # Multi-worker serving (gateway/worker.py) gives each process its
        # own connection to one WAL file; writers queue on the file lock
        # instead of throwing SQLITE_BUSY at the first collision.
        self._conn.execute("PRAGMA busy_timeout=5000")
        # WAL + synchronous=NORMAL is the documented SQLite pairing: commits
        # skip the per-transaction fsync (the WAL is still fsynced at
        # checkpoint), which is the difference between request-path writes
        # costing ~µs and costing a disk flush each. Durability window on
        # power loss is the last checkpoint — request history/stats, not
        # ledger data.
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._lock = threading.RLock()
        with self._lock:
            # N forked workers initialize the same file concurrently at
            # boot; executescript's implicit transaction handling can
            # surface SQLITE_BUSY despite busy_timeout (the WAL-mode switch
            # needs a moment of exclusivity), so schema init retries
            # briefly instead of killing the worker.
            for attempt in range(50):
                try:
                    self._conn.executescript(SCHEMA)
                    break
                except sqlite3.OperationalError as e:
                    if "locked" not in str(e) or attempt == 49:
                        raise
                    time.sleep(0.1)
            try:
                # Backfill on upgrade: a DB that predates the FTS table has
                # unindexed rows — searches would miss them and the delete
                # trigger would corrupt the external-content index when the
                # archiver removes a never-indexed rowid. (count(*) can't
                # detect this: on external-content tables it reads the
                # content table, so test table existence instead.)
                fts_is_new = not self._conn.execute(
                    "SELECT 1 FROM sqlite_master WHERE name='audit_log_fts'"
                ).fetchone()
                self._conn.executescript(FTS_SCHEMA)
                self.fts_enabled = True
                has_rows = self._conn.execute(
                    "SELECT 1 FROM audit_log LIMIT 1"
                ).fetchone()
                if fts_is_new and has_rows:
                    self._conn.execute(
                        "INSERT INTO audit_log_fts(audit_log_fts) "
                        "VALUES('rebuild')"
                    )
            except sqlite3.OperationalError:
                self.fts_enabled = False

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    def execute(self, sql: str, params: tuple = ()) -> sqlite3.Cursor:
        with self._lock:
            return self._conn.execute(sql, params)

    def transaction(self):
        """Context manager: BEGIN IMMEDIATE ... COMMIT under the connection
        lock, so a read-then-write sequence (the audit chain's prev-hash
        read + batch insert) is atomic against sibling worker processes,
        not just sibling threads."""
        return _Transaction(self)

    def executemany(self, sql: str, rows: list[tuple]) -> None:
        with self._lock:
            self._conn.executemany(sql, rows)

    def query(self, sql: str, params: tuple = ()) -> list[sqlite3.Row]:
        with self._lock:
            return self._conn.execute(sql, params).fetchall()

    def query_one(self, sql: str, params: tuple = ()) -> sqlite3.Row | None:
        with self._lock:
            return self._conn.execute(sql, params).fetchone()

    # ------------------------------------------------------------- endpoints

    def upsert_endpoint(self, ep: Endpoint) -> None:
        self.execute(
            """INSERT INTO endpoints (id, name, base_url, api_key, endpoint_type,
                   status, latency_ms, consecutive_failures, accelerator,
                   chip_count, hbm_used_bytes, hbm_total_bytes, utilization,
                   created_at, updated_at, last_checked_at)
               VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)
               ON CONFLICT(id) DO UPDATE SET
                   name=excluded.name, base_url=excluded.base_url,
                   api_key=excluded.api_key,
                   endpoint_type=excluded.endpoint_type, status=excluded.status,
                   latency_ms=excluded.latency_ms,
                   consecutive_failures=excluded.consecutive_failures,
                   accelerator=excluded.accelerator,
                   chip_count=excluded.chip_count,
                   hbm_used_bytes=excluded.hbm_used_bytes,
                   hbm_total_bytes=excluded.hbm_total_bytes,
                   utilization=excluded.utilization,
                   updated_at=excluded.updated_at,
                   last_checked_at=excluded.last_checked_at""",
            (
                ep.id, ep.name, ep.base_url, ep.api_key, ep.endpoint_type.value,
                ep.status.value, ep.latency_ms, ep.consecutive_failures,
                ep.accelerator.accelerator, ep.accelerator.chip_count,
                ep.accelerator.hbm_used_bytes, ep.accelerator.hbm_total_bytes,
                ep.accelerator.utilization, ep.created_at, ep.updated_at,
                ep.last_checked_at,
            ),
        )

    def delete_endpoint(self, endpoint_id: str) -> None:
        self.execute("DELETE FROM endpoints WHERE id=?", (endpoint_id,))

    def list_endpoints(self) -> list[Endpoint]:
        return [self._row_to_endpoint(r) for r in self.query("SELECT * FROM endpoints")]

    @staticmethod
    def _row_to_endpoint(r: sqlite3.Row) -> Endpoint:
        return Endpoint(
            id=r["id"], name=r["name"], base_url=r["base_url"],
            api_key=r["api_key"],
            endpoint_type=EndpointType(r["endpoint_type"]),
            status=EndpointStatus(r["status"]),
            latency_ms=r["latency_ms"],
            consecutive_failures=r["consecutive_failures"],
            accelerator=AcceleratorInfo(
                accelerator=r["accelerator"], chip_count=r["chip_count"],
                hbm_used_bytes=r["hbm_used_bytes"],
                hbm_total_bytes=r["hbm_total_bytes"],
                utilization=r["utilization"],
            ),
            created_at=r["created_at"], updated_at=r["updated_at"],
            last_checked_at=r["last_checked_at"],
        )

    # -------------------------------------------------------- endpoint models

    def replace_endpoint_models(
        self, endpoint_id: str, models: list[EndpointModel]
    ) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM endpoint_models WHERE endpoint_id=?", (endpoint_id,)
            )
            self._conn.executemany(
                """INSERT INTO endpoint_models
                   (id, endpoint_id, model_id, canonical_name, capabilities,
                    context_length, created_at)
                   VALUES (?,?,?,?,?,?,?)""",
                [
                    (
                        uuid.uuid4().hex, m.endpoint_id, m.model_id,
                        m.canonical_name, _caps_to_json(m.capabilities),
                        m.context_length, m.created_at,
                    )
                    for m in models
                ],
            )

    def list_endpoint_models(self, endpoint_id: str | None = None) -> list[EndpointModel]:
        if endpoint_id is None:
            rows = self.query("SELECT * FROM endpoint_models")
        else:
            rows = self.query(
                "SELECT * FROM endpoint_models WHERE endpoint_id=?", (endpoint_id,)
            )
        return [
            EndpointModel(
                endpoint_id=r["endpoint_id"], model_id=r["model_id"],
                canonical_name=r["canonical_name"],
                capabilities=_caps_from_json(r["capabilities"]),
                context_length=r["context_length"], created_at=r["created_at"],
            )
            for r in rows
        ]

    # ---------------------------------------------------------- health checks

    def record_health_check(
        self, endpoint_id: str, ok: bool, latency_ms: float | None,
        error: str | None, checked_at: float,
    ) -> None:
        self.execute(
            """INSERT INTO endpoint_health_checks
               (endpoint_id, ok, latency_ms, error, checked_at)
               VALUES (?,?,?,?,?)""",
            (endpoint_id, int(ok), latency_ms, error, checked_at),
        )

    def list_health_checks(
        self, endpoint_id: str, limit: int = 100
    ) -> list[sqlite3.Row]:
        return self.query(
            """SELECT * FROM endpoint_health_checks WHERE endpoint_id=?
               ORDER BY checked_at DESC LIMIT ?""",
            (endpoint_id, limit),
        )

    # --------------------------------------------------------------- settings

    def get_setting(self, key: str) -> str | None:
        row = self.query_one("SELECT value FROM settings WHERE key=?", (key,))
        return row["value"] if row else None

    def set_setting(self, key: str, value: str) -> None:
        self.execute(
            """INSERT INTO settings (key, value, updated_at) VALUES (?,?,?)
               ON CONFLICT(key) DO UPDATE SET value=excluded.value,
               updated_at=excluded.updated_at""",
            (key, value, time.time()),
        )

    def list_settings(self) -> dict[str, str]:
        return {r["key"]: r["value"] for r in self.query("SELECT * FROM settings")}

    # ------------------------------------------------- registered models
    # Parity: reference db/models.rs — metadata+manifest only, no weights
    # (api/models.rs:1021 register, :1167 manifest serving).

    def register_model(self, name: str, source_repo: str | None,
                       format_: str | None, capabilities: list[str],
                       manifest: dict) -> str:
        model_id = uuid.uuid4().hex
        self.execute(
            """INSERT INTO registered_models
               (id, name, source_repo, format, capabilities, manifest, created_at)
               VALUES (?,?,?,?,?,?,?)
               ON CONFLICT(name) DO UPDATE SET source_repo=excluded.source_repo,
               format=excluded.format, capabilities=excluded.capabilities,
               manifest=excluded.manifest""",
            (model_id, name, source_repo, format_, json.dumps(capabilities),
             json.dumps(manifest), time.time()),
        )
        # On re-registration the UPDATE path keeps the existing row's id, so
        # return the id actually stored rather than the freshly generated one.
        row = self.query_one(
            "SELECT id FROM registered_models WHERE name=?", (name,))
        return row["id"] if row else model_id

    def list_registered_models(self) -> list[dict]:
        return [
            {
                "id": r["id"], "name": r["name"],
                "source_repo": r["source_repo"], "format": r["format"],
                "capabilities": json.loads(r["capabilities"] or "[]"),
                "created_at": r["created_at"],
            }
            for r in self.query(
                "SELECT * FROM registered_models ORDER BY created_at DESC"
            )
        ]

    def get_registered_model(self, name: str) -> dict | None:
        r = self.query_one(
            "SELECT * FROM registered_models WHERE name=?", (name,)
        )
        if r is None:
            return None
        return {
            "id": r["id"], "name": r["name"], "source_repo": r["source_repo"],
            "format": r["format"],
            "capabilities": json.loads(r["capabilities"] or "[]"),
            "manifest": json.loads(r["manifest"] or "null"),
            "created_at": r["created_at"],
        }

    def delete_registered_model(self, name: str) -> bool:
        cur = self.execute(
            "DELETE FROM registered_models WHERE name=?", (name,)
        )
        return cur.rowcount > 0
