"""Endpoint type auto-detection by probe priority.

Parity with reference detection/mod.rs probe order, with `tpu` probed FIRST
(our in-tree engine marks itself via GET /api/system → {"tpu_engine": true}):

    tpu > xllm (/api/system w/ xllm_version) > lm_studio (/api/v1/models)
    > ollama (/api/tags) > vllm (Server header) > llama_cpp (Server header or
    /v1/version) > openai_compatible (/v1/models)

Distinguishes Unreachable (no TCP/HTTP at all) from UnsupportedType (answers,
but no probe matches) like the reference does.
"""

from __future__ import annotations

import asyncio

import aiohttp

from llmlb_tpu.gateway.types import EndpointType


class DetectionError(Exception):
    pass


class Unreachable(DetectionError):
    pass


class UnsupportedType(DetectionError):
    pass


async def _get(
    session: aiohttp.ClientSession, url: str, timeout: float
) -> tuple[int, dict | None, dict]:
    """GET returning (status, json_or_none, headers). Raises on transport error."""
    async with session.get(
        url, timeout=aiohttp.ClientTimeout(total=timeout)
    ) as resp:
        try:
            body = await resp.json(content_type=None)
        except Exception:
            body = None
        return resp.status, body if isinstance(body, dict) else None, dict(resp.headers)


async def detect_endpoint_type(
    base_url: str,
    session: aiohttp.ClientSession,
    timeout: float = 5.0,
    api_key: str | None = None,
) -> EndpointType:
    base = base_url.rstrip("/")
    reachable = False

    async def probe(path: str):
        nonlocal reachable
        try:
            status, body, headers = await _get(session, base + path, timeout)
            reachable = True
            return status, body, headers
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            return None, None, {}

    # 1. tpu / xllm — both live on /api/system
    status, body, _ = await probe("/api/system")
    if status == 200 and body:
        if body.get("tpu_engine"):
            return EndpointType.TPU
        if "xllm_version" in body:
            return EndpointType.XLLM

    # 2. LM Studio
    status, body, _ = await probe("/api/v1/models")
    if status == 200 and body is not None:
        return EndpointType.LM_STUDIO

    # 3. Ollama
    status, body, _ = await probe("/api/tags")
    if status == 200 and body is not None and "models" in body:
        return EndpointType.OLLAMA

    # 4/5/6. /v1/models + Server header discrimination
    status, body, headers = await probe("/v1/models")
    if status == 200 and body is not None:
        server = headers.get("Server", "").lower()
        if "vllm" in server:
            return EndpointType.VLLM
        if "llama.cpp" in server or "llama-cpp" in server:
            return EndpointType.LLAMA_CPP
        vstatus, vbody, _ = await probe("/v1/version")
        if vstatus == 200 and vbody is not None and (
            "build" in vbody or "llama" in str(vbody.get("version", "")).lower()
        ):
            return EndpointType.LLAMA_CPP
        return EndpointType.OPENAI_COMPATIBLE

    if not reachable:
        raise Unreachable(f"no HTTP service responding at {base}")
    raise UnsupportedType(f"{base} answers HTTP but matches no known endpoint type")
