"""Per-engine model metadata probes (context length and friends).

Parity with reference metadata/ (Ollama `/api/show` context-length extraction,
metadata/ollama.rs:221 and is_context_length_key :67-76; LM Studio model
listing fields). The sync path calls these to enrich models whose `/v1/models`
entry carried no context length — the dashboard and admission logic use it.
"""

from __future__ import annotations

import asyncio
import logging

import aiohttp

from llmlb_tpu.gateway.types import Endpoint, EndpointType

log = logging.getLogger("llmlb_tpu.gateway.metadata")


def _context_length_from(obj) -> int | None:
    """Search a metadata mapping for a context-length-ish key. Engines bury
    it under arch-prefixed keys ('llama.context_length'), plain keys, or
    nested dicts."""
    if not isinstance(obj, dict):
        return None
    for key, value in obj.items():
        k = str(key).lower()
        if (k in ("context_length", "max_context_length", "num_ctx",
                  "max_model_len", "loaded_context_length")
                or k.endswith(".context_length")
                or k.endswith("_context_length")):
            try:
                n = int(value)
            except (TypeError, ValueError):
                continue
            if n > 0:
                return n
    for value in obj.values():  # one level of nesting (model_info, details)
        if isinstance(value, dict):
            n = _context_length_from(value)
            if n:
                return n
    return None


async def fetch_context_length(
    ep: Endpoint,
    model_id: str,
    session: aiohttp.ClientSession,
    timeout: float = 5.0,
) -> int | None:
    """Engine-specific context-length probe; None when the engine doesn't
    expose one (or the probe fails — metadata must never break a sync)."""
    headers = {}
    if ep.api_key:
        headers["Authorization"] = f"Bearer {ep.api_key}"
    try:
        if ep.endpoint_type == EndpointType.OLLAMA:
            async with session.post(
                ep.url + "/api/show", json={"name": model_id},
                headers=headers,
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as resp:
                if resp.status != 200:
                    return None
                body = await resp.json(content_type=None)
            return _context_length_from(body if isinstance(body, dict) else {})
        if ep.endpoint_type == EndpointType.LM_STUDIO:
            listing = await _lm_studio_listing(ep, session, timeout)
            return _context_length_from(listing.get(model_id, {}))
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError, ValueError):
        return None
    return None


async def _lm_studio_listing(
    ep: Endpoint, session: aiohttp.ClientSession, timeout: float = 5.0
) -> dict[str, dict]:
    """One fetch of LM Studio's /api/v1/models, indexed by model id — the
    listing carries every model's metadata, so per-model fetches are waste."""
    headers = {}
    if ep.api_key:
        headers["Authorization"] = f"Bearer {ep.api_key}"
    try:
        async with session.get(
            ep.url + "/api/v1/models", headers=headers,
            timeout=aiohttp.ClientTimeout(total=timeout),
        ) as resp:
            if resp.status != 200:
                return {}
            body = await resp.json(content_type=None)
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError, ValueError):
        return {}
    entries = body.get("data") if isinstance(body, dict) else None
    return {
        e["id"]: e for e in entries or []
        if isinstance(e, dict) and "id" in e
    }


async def enrich_context_lengths(
    ep: Endpoint,
    models: list,
    session: aiohttp.ClientSession,
    *,
    concurrency: int = 4,
) -> None:
    """Fill missing context_length on EndpointModel entries in place."""
    targets = [m for m in models if m.context_length is None]
    if not targets or ep.endpoint_type not in (
        EndpointType.OLLAMA, EndpointType.LM_STUDIO
    ):
        return
    if ep.endpoint_type == EndpointType.LM_STUDIO:
        listing = await _lm_studio_listing(ep, session)
        for m in targets:
            m.context_length = _context_length_from(
                listing.get(m.model_id, {})
            )
        return
    sem = asyncio.Semaphore(concurrency)

    async def probe(m):
        async with sem:
            m.context_length = await fetch_context_length(
                ep, m.model_id, session
            )

    await asyncio.gather(*(probe(m) for m in targets))
