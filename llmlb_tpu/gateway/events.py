"""Dashboard event bus: broadcast pub/sub feeding the /ws/dashboard socket.

Parity with reference events/mod.rs:20-122 (tokio::broadcast): bounded
per-subscriber queues; slow subscribers drop oldest events rather than block
publishers. Event names match the reference set plus TPU telemetry.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Any


class DashboardEventBus:
    EVENTS = (
        "EndpointRegistered",
        "EndpointStatusChanged",
        "EndpointRemoved",
        "BreakerStateChanged",
        "MetricsUpdated",
        "TpsUpdated",
        "UpdateStateChanged",
        "TelemetryUpdated",
        "TraceCompleted",
    )

    def __init__(self, queue_size: int = 256):
        self._queue_size = queue_size
        self._subscribers: dict[int, asyncio.Queue] = {}
        self._loops: dict[int, asyncio.AbstractEventLoop] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        # Dropped-event accounting: a slow subscriber silently losing events
        # is invisible without it. Per-subscriber counts reset with the
        # subscription; the total survives for /metrics.
        self._dropped: dict[int, int] = {}
        self._dropped_total = 0

    def subscribe(self) -> tuple[int, asyncio.Queue]:
        """Called from the event loop that will consume the queue."""
        q: asyncio.Queue = asyncio.Queue(self._queue_size)
        loop = asyncio.get_running_loop()
        with self._lock:
            sub_id = self._next_id
            self._next_id += 1
            self._subscribers[sub_id] = q
            self._loops[sub_id] = loop
            self._dropped[sub_id] = 0
        return sub_id, q

    def unsubscribe(self, sub_id: int) -> None:
        with self._lock:
            self._subscribers.pop(sub_id, None)
            self._loops.pop(sub_id, None)
            self._dropped.pop(sub_id, None)

    def dropped_events(self, sub_id: int) -> int:
        with self._lock:
            return self._dropped.get(sub_id, 0)

    def dropped_events_total(self) -> int:
        with self._lock:
            return self._dropped_total

    def publish(self, event_type: str, payload: dict[str, Any] | None = None) -> None:
        """Thread-safe: usable from engine threads and the health checker."""
        event = {
            "type": event_type,
            "ts": time.time(),
            "data": payload or {},
        }
        with self._lock:
            targets = list(self._subscribers.items())
            loops = dict(self._loops)
        for sub_id, q in targets:
            loop = loops.get(sub_id)
            if loop is None or loop.is_closed():
                continue

            def _put(q=q, event=event, sub_id=sub_id):
                if q.full():
                    try:
                        q.get_nowait()  # drop oldest for slow consumers
                    except asyncio.QueueEmpty:
                        pass
                    else:
                        with self._lock:
                            if sub_id in self._dropped:
                                self._dropped[sub_id] += 1
                            self._dropped_total += 1
                q.put_nowait(event)

            try:
                loop.call_soon_threadsafe(_put)
            except RuntimeError:
                continue

    @staticmethod
    def serialize(event: dict) -> str:
        return json.dumps(event, separators=(",", ":"))
