"""Deterministic fault injection at the gateway's upstream HTTP boundary.

The resilience layer (resilience.py) exists to absorb endpoint death, slow
death, and mid-stream cuts — none of which can be tested reliably by killing
real sockets on cue. This module injects those failures *inside the proxy's
HTTP boundary* instead: every upstream POST consults a rule table and may be
turned into a connect error, delayed, answered with a synthetic HTTP status,
or have its response stream cut after K bytes. Rules fire deterministically
(`every_n` counters, or probabilities drawn from one seeded RNG), so chaos
tests replay bit-for-bit.

Rules come from the ``LLMLB_FAULTS`` env var (a JSON list, see FaultRule) or
are installed programmatically (``state.faults.add_rule``, used by tests and
``scripts/bench_gateway.py --workload chaos``). No rules configured = zero
work on the hot path (``state.faults`` is None).

No reference counterpart: the reference repo has no failure-injection story
at all; this is the harness the ROADMAP's "handles as many scenarios as you
can imagine" demands.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading

import aiohttp

VALID_KINDS = ("connect_refused", "latency", "http", "stream_cut",
               "engine_abort", "stalled_reader")

# Kinds applied at the upstream POST boundary (resilience.upstream_post);
# stalled_reader is applied in the CLIENT-side stream pump instead — it
# simulates a reader that stops draining the SSE stream (after_bytes sets
# the stall point, latency_ms the stall duration), which the pump's write
# timeout must catch (docs/scheduling.md slow-loris protection).
UPSTREAM_KINDS = ("connect_refused", "latency", "http", "stream_cut",
                  "engine_abort")


@dataclasses.dataclass
class FaultRule:
    """One injection rule.

    JSON shape (``LLMLB_FAULTS`` is a list of these)::

        {"kind": "connect_refused",        # or latency | http | stream_cut
         "endpoint": "tpu-a",              # endpoint name/id/URL substring,
                                           # "*" matches every endpoint
         "path": "/v1/chat",               # request-path substring (optional)
         "every_n": 1,                     # fire on every Nth matching call…
         "probability": 0.25,              # …or with seeded probability
         "status": 500,                    # kind=http: synthetic status
         "latency_ms": 250,                # kind=latency: added delay
         "after_bytes": 100,               # kind=stream_cut: cut point
         "max_fires": 10}                  # optional cap, then rule is inert

    Exactly one of ``every_n`` / ``probability`` should be set; neither means
    fire on every match (same as ``every_n: 1``).
    """

    kind: str
    endpoint: str = "*"
    path: str | None = None
    every_n: int | None = None
    probability: float | None = None
    status: int = 500
    latency_ms: float = 0.0
    after_bytes: int = 0
    max_fires: int | None = None
    # runtime counters (not part of the config surface)
    seen: int = 0
    fires: int = 0

    def __post_init__(self):
        if self.kind not in VALID_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (expected one of "
                f"{', '.join(VALID_KINDS)})"
            )

    def matches(self, endpoint, path: str) -> bool:
        if self.path is not None and self.path not in path:
            return False
        if self.endpoint == "*":
            return True
        return (
            self.endpoint in endpoint.name
            or self.endpoint == endpoint.id
            or self.endpoint in endpoint.url
        )


class InjectedHTTPResponse:
    """Quacks enough like an aiohttp ClientResponse for the proxy paths:
    ``status``, ``headers``, ``read()``, ``release()``. Never streams —
    the proxies only stream 200s, and injected statuses are errors."""

    def __init__(self, status: int):
        self.status = status
        self.headers: dict[str, str] = {"Content-Type": "application/json"}
        self._body = json.dumps(
            {"error": {"message": "fault injected", "type": "server_error",
                       "code": "fault_injected"}}
        ).encode()

    async def read(self) -> bytes:
        return self._body

    def release(self) -> None:
        pass


class _CutContent:
    """Async-iterates the inner response content, raising a client error
    after the byte budget is spent — a mid-stream connection cut."""

    def __init__(self, inner, after_bytes: int):
        self._inner = inner
        self._budget = after_bytes

    async def iter_any(self):
        async for chunk in self._inner.iter_any():
            if len(chunk) >= self._budget:
                if self._budget > 0:
                    yield chunk[: self._budget]
                raise aiohttp.ServerDisconnectedError(
                    "fault injected: stream cut"
                )
            self._budget -= len(chunk)
            yield chunk


class _AbortContent:
    """Async-iterates the inner response content, raising ConnectionResetError
    once `after_bytes` whole chunks have been delivered — the SIGKILLed-engine
    signature: the socket resets cleanly between frames, with NO partial event
    and NO prior error frame. Distinct from `_CutContent`, which delivers a
    truncated partial chunk first (a cut that can land mid-line): with
    `after_bytes` aligned to a frame boundary this rule reproduces exactly
    what a killed engine process looks like to the proxy, so the mid-stream
    resume path is unit-testable without forking processes."""

    def __init__(self, inner, after_bytes: int):
        self._inner = inner
        self._budget = after_bytes

    async def iter_any(self):
        async for chunk in self._inner.iter_any():
            if len(chunk) > self._budget:
                raise ConnectionResetError(
                    "fault injected: engine abort"
                )
            self._budget -= len(chunk)
            yield chunk
        if self._budget > 0:
            # the stream ended before the abort point: reset at EOF anyway —
            # the rule promised a reset, and a silently clean end would make
            # a mis-sized test pass for the wrong reason
            raise ConnectionResetError("fault injected: engine abort at EOF")


class EngineAbortResponse:
    """Wraps a real upstream response so its connection resets after K
    delivered bytes with no prior error frame (kind="engine_abort")."""

    def __init__(self, inner, after_bytes: int):
        self._inner = inner
        self.content = _AbortContent(inner.content, after_bytes)

    @property
    def status(self) -> int:
        return self._inner.status

    @property
    def headers(self):
        return self._inner.headers

    async def read(self) -> bytes:
        raise ConnectionResetError("fault injected: engine abort")

    def release(self) -> None:
        self._inner.release()


class StreamCutResponse:
    """Wraps a real upstream response so its body stream dies after K bytes."""

    def __init__(self, inner, after_bytes: int):
        self._inner = inner
        self.content = _CutContent(inner.content, after_bytes)

    @property
    def status(self) -> int:
        return self._inner.status

    @property
    def headers(self):
        return self._inner.headers

    async def read(self) -> bytes:
        return await self._inner.read()

    def release(self) -> None:
        self._inner.release()


class FaultInjector:
    """Rule table + deterministic firing state. Thread-safe (counters are
    read from /api/health while the event loop proxies)."""

    def __init__(self, rules: list[FaultRule] | None = None, seed: int = 0):
        self._lock = threading.Lock()
        self._rules: list[FaultRule] = list(rules or [])
        self._rng = random.Random(seed)

    @classmethod
    def from_env(cls) -> "FaultInjector | None":
        raw = os.environ.get("LLMLB_FAULTS")
        if not raw:
            return None
        try:
            spec = json.loads(raw)
            rules = [FaultRule(**r) for r in spec]
        except (ValueError, TypeError) as e:
            raise ValueError(f"LLMLB_FAULTS is not a valid rule list: {e}")
        seed = int(os.environ.get("LLMLB_FAULTS_SEED", "0") or 0)
        return cls(rules, seed=seed)

    def add_rule(self, rule: FaultRule) -> FaultRule:
        with self._lock:
            self._rules.append(rule)
        return rule

    def remove_rule(self, rule: FaultRule) -> None:
        with self._lock:
            if rule in self._rules:
                self._rules.remove(rule)

    def clear(self) -> None:
        with self._lock:
            self._rules.clear()

    def decide(self, endpoint, path: str,
               kinds: tuple[str, ...] | None = None) -> list[FaultRule]:
        """All rules that fire for this upstream call, in table order.
        Counters advance per *matching* call, so `every_n` is deterministic
        regardless of what other endpoints are doing. `kinds` restricts
        which rule kinds this call site applies (rules outside it neither
        fire nor advance their counters here — the stream pump and the
        upstream POST each consult their own kinds exactly once)."""
        fired: list[FaultRule] = []
        with self._lock:
            for rule in self._rules:
                if kinds is not None and rule.kind not in kinds:
                    continue
                if not rule.matches(endpoint, path):
                    continue
                if rule.max_fires is not None and rule.fires >= rule.max_fires:
                    continue
                rule.seen += 1
                if rule.probability is not None:
                    fire = self._rng.random() < rule.probability
                else:
                    n = rule.every_n or 1
                    fire = rule.seen % n == 0
                if fire:
                    rule.fires += 1
                    fired.append(rule)
        return fired

    def snapshot(self) -> list[dict]:
        """Per-rule config + fire counts for /api/health."""
        with self._lock:
            return [
                {
                    "kind": r.kind, "endpoint": r.endpoint, "path": r.path,
                    "every_n": r.every_n, "probability": r.probability,
                    "status": r.status if r.kind == "http" else None,
                    "latency_ms": (r.latency_ms
                                   if r.kind in ("latency", "stalled_reader")
                                   else None),
                    "after_bytes": (r.after_bytes
                                    if r.kind in ("stream_cut",
                                                  "engine_abort",
                                                  "stalled_reader")
                                    else None),
                    "seen": r.seen, "fires": r.fires,
                }
                for r in self._rules
            ]
