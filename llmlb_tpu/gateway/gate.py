"""Inference gate: counts in-flight inference and supports drain for updates.

Parity with reference inference_gate.rs:28-85: while rejecting, /v1/* returns
503 + Retry-After; `wait_for_idle` lets the updater drain; streaming bodies
count as in-flight until fully written (the reference wraps response bodies in
InFlightBody — here handlers hold the gate token across the whole stream).
"""

from __future__ import annotations

import asyncio
import contextlib


class InferenceGate:
    def __init__(self):
        self._in_flight = 0
        self._rejecting = False
        self._idle_event = asyncio.Event()
        self._idle_event.set()

    @property
    def in_flight(self) -> int:
        return self._in_flight

    @property
    def rejecting(self) -> bool:
        return self._rejecting

    def start_rejecting(self) -> None:
        self._rejecting = True

    def stop_rejecting(self) -> None:
        self._rejecting = False

    @contextlib.contextmanager
    def track(self):
        """Count a request in-flight for the duration of the with-block."""
        self._in_flight += 1
        self._idle_event.clear()
        try:
            yield
        finally:
            self._in_flight -= 1
            if self._in_flight == 0:
                self._idle_event.set()

    async def wait_for_idle(self, timeout_s: float | None = None) -> bool:
        try:
            await asyncio.wait_for(self._idle_event.wait(), timeout_s)
            return True
        except asyncio.TimeoutError:
            return False
