"""Best-effort state replication between gateway workers on one host.

Shared-nothing workers (gateway/worker.py) each hold their own copy of the
small mutable routing state: breaker states, TPS EMAs, the retry-budget
window, and (in LRU mode) prefix-affinity pins. This bus gossips those
deltas over local unix datagram sockets so a breaker tripped by one worker
ejects the endpoint on all of them within ~1 RTT, and a TPS sample measured
by one worker steers its siblings too.

Design constraints, in order:
  * **Correctness never depends on gossip.** Every message is advisory: a
    worker that misses updates only degrades steering/placement until its
    own in-band signals converge (LLMLB_GOSSIP=0 must be a safe mode).
  * **Last-writer-wins.** Messages carry a wall-clock stamp; receivers drop
    anything older than the state they already hold. Same-host wall clocks
    make this exact enough for ~millisecond propagation.
  * **Never block the hot path.** Sends are non-blocking datagram writes to
    every peer socket; a full or missing peer socket drops the message
    (counted) instead of waiting.

Each worker binds ``{dir}/w{index}.sock`` and publishes by iterating the
other ``w*.sock`` files in the directory — no membership protocol; a dead
worker's stale socket just eats an ECONNREFUSED (counted as a drop).
"""

from __future__ import annotations

import asyncio
import glob
import json
import logging
import os
import socket
import threading
import time
import typing

log = logging.getLogger("llmlb_tpu.gateway.gossip")

# Re-list the peer sockets at most this often: publishes between refreshes
# reuse the cached listing (workers churn at process granularity, not per
# request).
PEER_REFRESH_S = 2.0

# Tolerated message staleness: a datagram older than this is counted as a
# lag outlier but still applied (LWW stamps do per-key ordering).
LAG_WINDOW = 64  # samples kept for the lag gauge


class _Receiver(asyncio.DatagramProtocol):
    def __init__(self, bus: "GossipBus"):
        self.bus = bus

    def datagram_received(self, data: bytes, addr) -> None:
        self.bus._on_datagram(data)


class GossipBus:
    """Unix-datagram fan-out between the workers of one gateway instance.

    Handlers are registered per message kind and run on the receiving
    worker's event loop; they must be fast and must NOT publish back
    (receivers apply remote state via ``apply_remote_*`` entry points that
    never re-gossip, or a two-worker group would ping-pong forever).
    """

    def __init__(self, directory: str, index: int, expected_peers: int = 0):
        self.directory = directory
        self.index = index
        # Sibling count this bus should eventually see: while the cached
        # listing is SHORTER than this, every publish re-globs — a worker
        # that boots milliseconds before its siblings must not cache the
        # empty directory for PEER_REFRESH_S and silently drop its first
        # (often most important: registry/breaker) messages.
        self.expected_peers = expected_peers
        self.path = os.path.join(directory, f"w{index}.sock")
        self._handlers: dict[str, list[typing.Callable]] = {}
        self._send_sock: socket.socket | None = None
        self._transport: asyncio.DatagramTransport | None = None
        self._peers: list[str] = []
        self._peers_refreshed = 0.0
        self._lock = threading.Lock()
        # counters surfaced in /metrics (docs/monitoring/README.md)
        self.sent_total = 0
        self.received_total = 0
        self.send_errors_total = 0
        self._lag_samples: list[float] = []

    # ------------------------------------------------------------- lifecycle

    async def start(self) -> None:
        os.makedirs(self.directory, exist_ok=True)
        try:
            os.unlink(self.path)  # stale socket from a previous run
        except FileNotFoundError:
            pass
        recv = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        recv.bind(self.path)
        recv.setblocking(False)
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _Receiver(self), sock=recv
        )
        send = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        send.setblocking(False)
        self._send_sock = send
        log.info("gossip bus up at %s", self.path)

    def close(self) -> None:
        if self._transport is not None:
            self._transport.close()
            self._transport = None
        if self._send_sock is not None:
            self._send_sock.close()
            self._send_sock = None
        try:
            os.unlink(self.path)
        except OSError:
            pass

    # ------------------------------------------------------------ publishing

    def _peer_paths(self) -> list[str]:
        now = time.monotonic()
        if (now - self._peers_refreshed > PEER_REFRESH_S
                or len(self._peers) < self.expected_peers):
            self._peers = [
                p for p in glob.glob(os.path.join(self.directory, "w*.sock"))
                if p != self.path
            ]
            self._peers_refreshed = now
        return self._peers

    def publish(self, kind: str, data: dict) -> None:
        """Fire-and-forget to every peer. Callable from any thread (lease
        releases arrive from GC finalizers); plain sendto on a non-blocking
        datagram socket, no event-loop round trip."""
        sock = self._send_sock
        if sock is None:
            return
        payload = json.dumps(
            {"k": kind, "src": self.index, "ts": time.time(), "d": data},
            separators=(",", ":"),
        ).encode()
        with self._lock:
            peers = self._peer_paths()
            if log.isEnabledFor(logging.DEBUG):
                log.debug("gossip publish kind=%s to %d peers", kind,
                          len(peers))
            for peer in peers:
                try:
                    sock.sendto(payload, peer)
                    self.sent_total += 1
                except OSError:
                    # peer gone / queue full: best-effort means drop, and
                    # the peer's own in-band signals converge it later
                    self.send_errors_total += 1

    # -------------------------------------------------------------- receiving

    def subscribe(self, kind: str, handler: typing.Callable[[dict, dict], None]) -> None:
        """``handler(data, meta)`` with meta = {src, ts, lag_s}."""
        self._handlers.setdefault(kind, []).append(handler)

    def _on_datagram(self, raw: bytes) -> None:
        try:
            msg = json.loads(raw)
            kind = msg["k"]
            ts = float(msg["ts"])
        except (ValueError, KeyError, TypeError):
            return
        self.received_total += 1
        lag = max(0.0, time.time() - ts)
        self._lag_samples.append(lag)
        if len(self._lag_samples) > LAG_WINDOW:
            del self._lag_samples[: len(self._lag_samples) - LAG_WINDOW]
        meta = {"src": msg.get("src"), "ts": ts, "lag_s": lag}
        for handler in self._handlers.get(kind, ()):
            try:
                handler(msg.get("d") or {}, meta)
            except Exception:  # one bad handler must not poison the bus
                log.exception("gossip handler for %r failed", kind)

    # ------------------------------------------------------------- inspection

    def lag_seconds(self) -> float | None:
        """Mean one-way delay of recently received messages (the gossip-lag
        gauge); None until the first message arrives."""
        if not self._lag_samples:
            return None
        return sum(self._lag_samples) / len(self._lag_samples)

    def stats(self) -> dict:
        with self._lock:
            peers = len(self._peer_paths())
        return {
            "sent_total": self.sent_total,
            "received_total": self.received_total,
            "send_errors_total": self.send_errors_total,
            "lag_s": self.lag_seconds(),
            "peers": peers,
        }


def default_gossip_dir(port: int) -> str:
    """One bus per gateway instance: scope the socket dir by listen port so
    two gateways on one host never cross-gossip."""
    base = os.environ.get("LLMLB_GOSSIP_DIR")
    if base:
        return base
    data_dir = os.path.expanduser(
        os.environ.get("LLMLB_DATA_DIR", "~/.llmlb") or "~/.llmlb"
    )
    return os.path.join(data_dir, "gossip", str(port))
