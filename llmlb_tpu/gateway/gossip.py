"""Best-effort state replication between gateway workers — same host and
across hosts.

Shared-nothing workers (gateway/worker.py) each hold their own copy of the
small mutable routing state: breaker states, TPS EMAs, the retry-budget
window, prefix-affinity pins, adapter residency, the prefix-heat map, and
(in global mode) rate-limit spend. This bus gossips those deltas over local
unix datagram sockets to same-host siblings and — when ``LLMLB_GOSSIP_BIND``
is set — over a UDP mesh to the workers of OTHER gateway hosts, with a TCP
fallback for payloads too large for one datagram.

Design constraints, in order:
  * **Correctness never depends on gossip.** Every message is advisory: a
    worker that misses updates only degrades steering/placement until its
    own in-band signals converge (LLMLB_GOSSIP=0 must be a safe mode, and
    a partitioned mesh must degrade to per-worker convergence, never
    worse — tests/gateway/test_multiworker.py pins both).
  * **No wall clocks in conflict resolution.** Messages carry a per-origin
    Lamport sequence number; receivers keep a ``(seq, origin)`` version per
    state key and drop anything not newer. Wall stamps ride the envelope
    for the lag gauge ONLY — clock skew across hosts silently resurrected
    stale breaker state under the old wall-stamp LWW (the PR 10 deadline
    rule, applied to gossip).
  * **Versioned wire format.** Every message kind is a dataclass in
    ``MESSAGE_TYPES`` with its own wire version; unknown inbound fields and
    version mismatches refuse loudly (scripts/check_gossip_wire.py probes
    every declared field, so adding one without wire coverage is a test
    failure — the test_plan_wire discipline).
  * **Never block the hot path.** Sends are non-blocking datagram writes;
    a full or missing peer drops the message (counted) instead of waiting.
    The TCP fallback runs on the event loop, never inline in publish().

Membership: same-host siblings are discovered by globbing ``{dir}/w*.sock``
as before. Mesh peers come from three sources merged at each refresh —
static seeds (``LLMLB_GOSSIP_PEERS``), the shared registry database (each
host advertises its mesh address into the gateway settings table, so a
host that can reach the DB finds the fleet without config), and addresses
learned from inbound ``hello`` heartbeats. A peer silent past
``PARTITION_SUSPECT_S`` flips the ``gossip_partition_suspected`` gauge —
the operator signal that the fleet is converging per-worker.
"""

from __future__ import annotations

import asyncio
import dataclasses
import glob
import json
import logging
import os
import random
import socket
import struct
import threading
import time
import typing

log = logging.getLogger("llmlb_tpu.gateway.gossip")

# Re-list the peer sockets at most this often: publishes between refreshes
# reuse the cached listing (workers churn at process granularity, not per
# request).
PEER_REFRESH_S = 2.0

LAG_WINDOW = 64  # samples kept for the lag gauge

# Mesh payloads above this ride the TCP fallback: one datagram must fit a
# single unfragmented-ish UDP packet budget (prefix-heat maps and batched
# rl_spend flushes can outgrow it; unix datagrams on the same host are not
# subject to the limit but use the same threshold for one code path).
UDP_MAX_BYTES = 60_000
TCP_MAX_BYTES = 16 << 20  # refuse anything larger on the fallback listener
TCP_CONNECT_TIMEOUT_S = 2.0

# Mesh liveness: heartbeat cadence and the silence window after which a
# known peer is counted as suspected-partitioned.
HELLO_INTERVAL_S = 2.0
PARTITION_SUSPECT_S = 10.0

# Key prefix in the gateway settings table under which each host persists
# its advertised mesh address (membership from the registry DB).
MEMBER_KEY_PREFIX = "gossip.member."

# A version is a (seq, origin) tuple: per-origin Lamport sequence number
# first, origin id as the deterministic tiebreak. Tuple comparison IS the
# supersedes relation — see `newer`.
Version = typing.Tuple[int, str]


class GossipWireError(ValueError):
    """A gossip payload that must not be applied: unknown kind, version
    mismatch, unknown field (a newer peer's extension must version-bump,
    never silently drop), or malformed envelope."""


def newer(candidate: Version | None, current: Version | None) -> bool:
    """True when `candidate` supersedes `current` (None = never stamped).
    Lexicographic on (seq, origin): Lamport order first, origin id as a
    total-order tiebreak so two workers never disagree about a winner."""
    if candidate is None:
        return False
    if current is None:
        return True
    return tuple(candidate) > tuple(current)


class SeqClock:
    """Per-process Lamport clock: `tick` stamps every locally originated
    message/state change, `witness` folds in every received stamp, so any
    state change CAUSED by a remote observation outranks it. Thread-safe —
    publishes arrive from GC finalizers and executor threads."""

    __slots__ = ("_seq", "_lock")

    def __init__(self, start: int = 0):
        self._seq = int(start)
        self._lock = threading.Lock()

    def tick(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    def witness(self, remote_seq: int) -> None:
        with self._lock:
            if remote_seq > self._seq:
                self._seq = remote_seq

    def peek(self) -> int:
        with self._lock:
            return self._seq


# --------------------------------------------------------------- wire format
#
# One frozen dataclass per message kind; KIND/VERSION are class attributes,
# every field must be JSON-safe. encode/decode are the ONLY paths on/off the
# wire — scripts/check_gossip_wire.py round-trips auto-probed non-default
# values for every declared field through them, so a field added here
# without surviving the wire is a tier-1 failure.


@dataclasses.dataclass(frozen=True)
class HelloMsg:
    """Mesh heartbeat + membership advertisement. `nonce` is a per-process
    random id so a restarted host (same advertise addr, reset SeqClock) is
    recognized and its per-origin dedupe state dropped."""

    KIND: typing.ClassVar[str] = "hello"
    VERSION: typing.ClassVar[int] = 1

    advertise: str = ""
    index: int = 0
    nonce: int = 0


@dataclasses.dataclass(frozen=True)
class TpsMsg:
    """One endpoint TPS EMA observation (balancer._maybe_gossip_tps)."""

    KIND: typing.ClassVar[str] = "tps"
    VERSION: typing.ClassVar[int] = 1

    eid: str = ""
    model: str = ""
    kind: str = "decode_tps"
    ema: float = 0.0
    samples: int = 1


@dataclasses.dataclass(frozen=True)
class TpsClearMsg:
    """Endpoint went offline: drop its TPS state everywhere."""

    KIND: typing.ClassVar[str] = "tps_clear"
    VERSION: typing.ClassVar[int] = 1

    eid: str = ""


@dataclasses.dataclass(frozen=True)
class AffinityMsg:
    """LRU prefix-affinity pin (balancer._gossip_affinity)."""

    KIND: typing.ClassVar[str] = "affinity"
    VERSION: typing.ClassVar[int] = 1

    model: str = ""
    hash: str = ""
    eid: str = ""


@dataclasses.dataclass(frozen=True)
class BreakerMsg:
    """Breaker transition. Ships the REMAINING open interval, not the
    deadline — wall deadlines don't cross process (or host) clocks; the
    receiver rebuilds open_until on its own monotonic clock."""

    KIND: typing.ClassVar[str] = "breaker"
    VERSION: typing.ClassVar[int] = 1

    eid: str = ""
    to: str = ""
    reason: str = ""
    remaining_s: float = 0.0


@dataclasses.dataclass(frozen=True)
class RetrySpendMsg:
    """Retry-budget spends witnessed by one worker (`n` batched)."""

    KIND: typing.ClassVar[str] = "retry_spend"
    VERSION: typing.ClassVar[int] = 1

    n: int = 1


@dataclasses.dataclass(frozen=True)
class RegistryMsg:
    """The shared registry DB mutated: reload caches."""

    KIND: typing.ClassVar[str] = "registry"
    VERSION: typing.ClassVar[int] = 1

    hint: str = ""


@dataclasses.dataclass(frozen=True)
class RlSpendMsg:
    """Batched rate-limit spend deltas for the GLOBAL token buckets:
    {tenant_key: [requests, tokens]} consumed since the last flush.
    Receivers charge their local buckets by the delta — admission then
    approximates the fleet-wide limit instead of limit×workers
    (docs/resilience.md)."""

    KIND: typing.ClassVar[str] = "rl_spend"
    VERSION: typing.ClassVar[int] = 1

    spends: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class ResidencyMsg:
    """Event-driven adapter residency: the health checker observed this
    endpoint's loaded-adapter set change (health._sync_lora_models) — the
    per-probe poll becomes a push, so siblings steer LoRA traffic within
    one gossip hop instead of one probe interval."""

    KIND: typing.ClassVar[str] = "residency"
    VERSION: typing.ClassVar[int] = 1

    eid: str = ""
    adapters: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class HeatMsg:
    """Prefix-heat deltas: {prefix_hash: [eid, hits]} — which endpoint
    actually holds which hot prefix cached, so rendezvous affinity steers
    by real cache contents (balancer, LLMLB_AFFINITY_HEAT)."""

    KIND: typing.ClassVar[str] = "heat"
    VERSION: typing.ClassVar[int] = 1

    model: str = ""
    entries: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class MigrateMsg:
    """Rebalancer directive (gateway/rebalance.py, primary worker only):
    every worker holding live streams on `eid` should move up to
    `max_streams` of them to `target` (empty = each worker re-selects).
    Advisory like all gossip — a worker that misses it just keeps serving
    from the overloaded engine until the next directive."""

    KIND: typing.ClassVar[str] = "migrate"
    VERSION: typing.ClassVar[int] = 1

    eid: str = ""
    target: str = ""
    reason: str = "hotspot"
    max_streams: int = 1
    directive_id: int = 0


MESSAGE_TYPES: dict[str, type] = {
    cls.KIND: cls
    for cls in (
        HelloMsg, TpsMsg, TpsClearMsg, AffinityMsg, BreakerMsg,
        RetrySpendMsg, RegistryMsg, RlSpendMsg, ResidencyMsg, HeatMsg,
        MigrateMsg,
    )
}


def encode_message(kind: str, data: dict, *, origin: str, seq: int,
                   ts: float | None = None) -> bytes:
    """The ONE path onto the wire. Raises GossipWireError for an unknown
    kind or a field the message type does not declare — a publish site
    that outgrows its dataclass fails loudly at the sender, where the bug
    is, not as a silent drop at every receiver."""
    cls = MESSAGE_TYPES.get(kind)
    if cls is None:
        raise GossipWireError(f"unknown gossip kind {kind!r}")
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(data) - names
    if unknown:
        raise GossipWireError(
            f"gossip {kind!r} does not declare field(s) "
            f"{', '.join(sorted(unknown))} — extend {cls.__name__} "
            "(and its wire probes) first"
        )
    msg = cls(**data)
    envelope = {
        "v": cls.VERSION,
        "k": kind,
        "o": origin,
        "s": int(seq),
        # wall stamp is DIAGNOSTIC (lag gauge) — never conflict resolution
        "ts": time.time() if ts is None else float(ts),
        "d": dataclasses.asdict(msg),
    }
    return json.dumps(envelope, separators=(",", ":")).encode()


def decode_message(raw: bytes | dict) -> tuple[str, dict, dict]:
    """The ONE path off the wire: → (kind, data, meta) with
    meta = {origin, seq, ver, ts, lag_s}. Raises GossipWireError for
    anything that must not be applied; the bus counts and drops."""
    if isinstance(raw, (bytes, bytearray)):
        try:
            env = json.loads(raw)
        except (ValueError, UnicodeDecodeError) as e:
            raise GossipWireError(f"gossip envelope is not JSON: {e}")
    else:
        env = raw
    if not isinstance(env, dict):
        raise GossipWireError("gossip envelope must be a JSON object")
    kind = env.get("k")
    cls = MESSAGE_TYPES.get(kind) if isinstance(kind, str) else None
    if cls is None:
        raise GossipWireError(f"unknown gossip kind {kind!r}")
    if env.get("v") != cls.VERSION:
        raise GossipWireError(
            f"gossip {kind!r} version {env.get('v')!r} != {cls.VERSION} "
            "(mixed-version fleet: upgrade in lockstep or bump the kind)"
        )
    origin = env.get("o")
    seq = env.get("s")
    if not isinstance(origin, str) or not origin:
        raise GossipWireError(f"gossip {kind!r}: 'o' must be a non-empty str")
    if not isinstance(seq, int) or isinstance(seq, bool) or seq < 0:
        raise GossipWireError(f"gossip {kind!r}: 's' must be a non-negative int")
    d = env.get("d")
    if d is None:
        d = {}
    if not isinstance(d, dict):
        raise GossipWireError(f"gossip {kind!r}: 'd' must be an object")
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(d) - names
    if unknown:
        raise GossipWireError(
            f"gossip {kind!r} carries unknown field(s) "
            f"{', '.join(sorted(unknown))} — a newer peer must bump "
            f"{cls.__name__}.VERSION, never rely on silent drops"
        )
    try:
        msg = cls(**d)
    except TypeError as e:
        raise GossipWireError(f"gossip {kind!r}: {e}")
    try:
        ts = float(env.get("ts", 0.0))
    except (TypeError, ValueError):
        raise GossipWireError(f"gossip {kind!r}: bad 'ts'")
    meta = {
        "origin": origin,
        "seq": seq,
        "ver": (seq, origin),
        "ts": ts,
        "lag_s": 0.0,  # filled by the receiving bus
    }
    return kind, dataclasses.asdict(msg), meta


# ------------------------------------------------------------ fault injection


GOSSIP_FAULT_KINDS = ("drop", "delay", "partition")


@dataclasses.dataclass
class GossipFaultRule:
    """One transport-injection rule, the faults.py discipline applied to
    the gossip boundary (LLMLB_GOSSIP_FAULTS is a JSON list of these)::

        {"kind": "drop",             # or delay | partition
         "message": "breaker",       # message kind, "*" matches all
         "peer": "w1",               # destination origin/address substring,
                                     # "*" matches every peer
         "every_n": 2,               # fire on every Nth matching send…
         "probability": 0.5,         # …or with seeded probability
         "delay_s": 0.2,             # kind=delay: added delivery delay
         "groups": [["w0"],["w1"]],  # kind=partition: origins in different
                                     # groups cannot reach each other
         "max_fires": 10}            # optional cap, then rule is inert

    `partition` ignores every_n/probability — it is a topology statement,
    deterministic by construction. Everything else fires via `every_n`
    counters or one seeded RNG, so chaos tests replay bit-for-bit.
    """

    kind: str
    message: str = "*"
    peer: str = "*"
    every_n: int | None = None
    probability: float | None = None
    delay_s: float = 0.0
    groups: list = dataclasses.field(default_factory=list)
    max_fires: int | None = None
    # runtime counters (not part of the config surface)
    seen: int = 0
    fires: int = 0

    def __post_init__(self):
        if self.kind not in GOSSIP_FAULT_KINDS:
            raise ValueError(
                f"unknown gossip fault kind {self.kind!r} (expected one of "
                f"{', '.join(GOSSIP_FAULT_KINDS)})"
            )

    def matches(self, message: str, peer: str) -> bool:
        if self.message != "*" and self.message != message:
            return False
        if self.peer == "*":
            return True
        return self.peer in peer

    def partitioned(self, src: str, dst: str) -> bool:
        """True when src and dst sit in DIFFERENT declared groups. Origins
        not named in any group are unaffected (they see everyone)."""
        src_g = dst_g = None
        for i, group in enumerate(self.groups):
            members = [str(m) for m in group]
            if any(m in src for m in members):
                src_g = i
            if any(m in dst for m in members):
                dst_g = i
        return src_g is not None and dst_g is not None and src_g != dst_g


class GossipFaults:
    """Rule table + deterministic firing state for the gossip transport.
    Consulted once per (message, destination) at send time — receive-side
    injection would double-fire the counters for loopback-free buses."""

    def __init__(self, rules: list[GossipFaultRule] | None = None,
                 seed: int = 0):
        self._lock = threading.Lock()
        self._rules: list[GossipFaultRule] = list(rules or [])
        self._rng = random.Random(seed)

    @classmethod
    def from_env(cls) -> "GossipFaults | None":
        raw = os.environ.get("LLMLB_GOSSIP_FAULTS")
        if not raw:
            return None
        try:
            spec = json.loads(raw)
            rules = [GossipFaultRule(**r) for r in spec]
        except (ValueError, TypeError) as e:
            raise ValueError(
                f"LLMLB_GOSSIP_FAULTS is not a valid rule list: {e}"
            )
        seed = int(os.environ.get("LLMLB_FAULTS_SEED", "0") or 0)
        return cls(rules, seed=seed)

    def add_rule(self, rule: GossipFaultRule) -> GossipFaultRule:
        with self._lock:
            self._rules.append(rule)
        return rule

    def remove_rule(self, rule: GossipFaultRule) -> None:
        with self._lock:
            if rule in self._rules:
                self._rules.remove(rule)

    def clear(self) -> None:
        with self._lock:
            self._rules.clear()

    def decide(self, message: str, src: str, dst: str) -> tuple[bool, float]:
        """→ (drop, delay_s) for one send to one destination. Partition
        rules are pure topology; drop/delay advance deterministic per-rule
        counters exactly once per matching send."""
        drop = False
        delay = 0.0
        with self._lock:
            for rule in self._rules:
                if rule.kind == "partition":
                    if rule.partitioned(src, dst):
                        rule.fires += 1
                        drop = True
                    continue
                if not rule.matches(message, dst):
                    continue
                if rule.max_fires is not None and rule.fires >= rule.max_fires:
                    continue
                rule.seen += 1
                if rule.probability is not None:
                    fire = self._rng.random() < rule.probability
                else:
                    n = rule.every_n or 1
                    fire = rule.seen % n == 0
                if not fire:
                    continue
                rule.fires += 1
                if rule.kind == "drop":
                    drop = True
                else:
                    delay = max(delay, rule.delay_s)
        return drop, delay

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [
                {
                    "kind": r.kind, "message": r.message, "peer": r.peer,
                    "every_n": r.every_n, "probability": r.probability,
                    "delay_s": r.delay_s if r.kind == "delay" else None,
                    "groups": r.groups if r.kind == "partition" else None,
                    "seen": r.seen, "fires": r.fires,
                }
                for r in self._rules
            ]


# -------------------------------------------------------------------- mesh


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Cross-host transport config. `bind` empty (the default) keeps the
    bus unix-only — exactly the pre-mesh behavior."""

    bind: str = ""        # "host:port" UDP+TCP listen address
    advertise: str = ""   # address peers should dial; defaults to bind
    peers: tuple = ()     # static seed addresses ("host:port", ...)

    @classmethod
    def from_env(cls) -> "MeshConfig":
        bind = (os.environ.get("LLMLB_GOSSIP_BIND") or "").strip()
        advertise = (os.environ.get("LLMLB_GOSSIP_ADVERTISE") or "").strip()
        raw = os.environ.get("LLMLB_GOSSIP_PEERS") or ""
        peers = tuple(p.strip() for p in raw.split(",") if p.strip())
        return cls(bind=bind, advertise=advertise or bind, peers=peers)

    @property
    def enabled(self) -> bool:
        return bool(self.bind)


def parse_addr(addr: str) -> tuple[str, int] | None:
    """'host:port' → (host, port); None for anything malformed (a bad peer
    entry must not take the bus down)."""
    host, sep, port = addr.rpartition(":")
    if not sep or not host:
        return None
    try:
        return host.strip("[]"), int(port)
    except ValueError:
        return None


class _Receiver(asyncio.DatagramProtocol):
    def __init__(self, bus: "GossipBus", transport_name: str):
        self.bus = bus
        self.name = transport_name

    def datagram_received(self, data: bytes, addr) -> None:
        self.bus._on_datagram(data, via=self.name)


class GossipBus:
    """Datagram fan-out between gateway workers: unix sockets to same-host
    siblings, UDP (TCP above UDP_MAX_BYTES) to mesh peers.

    Handlers are registered per message kind and run on the receiving
    worker's event loop; they must be fast and must NOT publish back
    (receivers apply remote state via ``apply_remote_*`` entry points that
    never re-gossip, or a two-worker group would ping-pong forever).
    """

    def __init__(self, directory: str, index: int, expected_peers: int = 0,
                 *, mesh: MeshConfig | None = None,
                 faults: GossipFaults | None = None,
                 membership: typing.Callable[[], dict] | None = None,
                 register: typing.Callable[[str, str], None] | None = None):
        self.directory = directory
        self.index = index
        # Sibling count this bus should eventually see: while the cached
        # listing is SHORTER than this, every publish re-globs — a worker
        # that boots milliseconds before its siblings must not cache the
        # empty directory for PEER_REFRESH_S and silently drop its first
        # (often most important: registry/breaker) messages.
        self.expected_peers = expected_peers
        self.mesh = mesh or MeshConfig()
        self.faults = faults
        # membership() → {origin: advertise_addr} from the shared registry
        # DB; register(origin, advertise) persists OUR address there.
        self._membership = membership
        self._register = register
        self.path = os.path.join(directory, f"w{index}.sock")
        # Origin id: globally unique per worker process. Same-host siblings
        # are "w{i}"; mesh workers prefix the advertised address so two
        # hosts' worker-0s never collide.
        if self.mesh.enabled and self.mesh.advertise:
            self.origin = f"{self.mesh.advertise}#w{index}"
        else:
            self.origin = f"w{index}"
        self.clock = SeqClock()
        self.nonce = random.getrandbits(63)
        self._handlers: dict[str, list[typing.Callable]] = {}
        self._send_sock: socket.socket | None = None
        self._udp_sock: socket.socket | None = None
        self._transport: asyncio.DatagramTransport | None = None
        self._udp_transport: asyncio.DatagramTransport | None = None
        self._tcp_server: asyncio.AbstractServer | None = None
        self._hello_task: asyncio.Task | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._peers: list[str] = []
        self._peers_refreshed = 0.0
        # mesh peers: addr "host:port" → {"last_seen": monotonic|None,
        # "origin": str|None, "nonce": int|None}; seeded from config +
        # registry membership, refined by hello heartbeats.
        self._mesh_peers: dict[str, dict] = {}
        self._mesh_refreshed = 0.0
        # per-(origin, kind) high-water marks: drop duplicated/reordered
        # datagrams for kinds where replays are not idempotent; reset when
        # a peer's hello nonce changes (process restart → fresh clock).
        self._origin_seq: dict[tuple[str, str], int] = {}
        self._lock = threading.Lock()
        self.on_heartbeat: list[typing.Callable[[], None]] = []
        # optional per-message lag observer (app_state wires the gossip
        # delay histogram here); must never raise into the receive path
        self.on_lag: typing.Callable[[float], None] | None = None
        # counters surfaced in /metrics (docs/monitoring/README.md)
        self.sent_total = 0
        self.received_total = 0
        self.send_errors_total = 0
        self.recv_rejected_total = 0
        self.fault_dropped_total = 0
        self._lag_samples: list[float] = []

    # ------------------------------------------------------------- lifecycle

    async def start(self) -> None:
        os.makedirs(self.directory, exist_ok=True)
        try:
            os.unlink(self.path)  # stale socket from a previous run
        except FileNotFoundError:
            pass
        recv = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        recv.bind(self.path)
        recv.setblocking(False)
        loop = asyncio.get_running_loop()
        self._loop = loop
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _Receiver(self, "unix"), sock=recv
        )
        send = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        send.setblocking(False)
        self._send_sock = send
        if self.mesh.enabled:
            await self._start_mesh(loop)
        log.info("gossip bus up at %s%s", self.path,
                 f" + mesh {self.mesh.bind}" if self.mesh.enabled else "")

    async def _start_mesh(self, loop: asyncio.AbstractEventLoop) -> None:
        addr = parse_addr(self.mesh.bind)
        if addr is None:
            log.warning("LLMLB_GOSSIP_BIND %r is not host:port; "
                        "mesh disabled", self.mesh.bind)
            return
        udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        udp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        udp.bind(addr)
        udp.setblocking(False)
        self._udp_transport, _ = await loop.create_datagram_endpoint(
            lambda: _Receiver(self, "udp"), sock=udp
        )
        out = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        out.setblocking(False)
        self._udp_sock = out
        # TCP fallback listener on the same port: oversize payloads arrive
        # as one length-prefixed frame per connection.
        self._tcp_server = await asyncio.start_server(
            self._on_tcp_conn, host=addr[0], port=addr[1],
            reuse_address=True,
        )
        for peer in self.mesh.peers:
            if peer and peer != self.mesh.advertise:
                self._mesh_peers.setdefault(
                    peer, {"last_seen": None, "origin": None, "nonce": None})
        self._hello_task = loop.create_task(self._hello_loop())

    def close(self) -> None:
        if self._hello_task is not None:
            self._hello_task.cancel()
            self._hello_task = None
        if self._transport is not None:
            self._transport.close()
            self._transport = None
        if self._udp_transport is not None:
            self._udp_transport.close()
            self._udp_transport = None
        if self._tcp_server is not None:
            self._tcp_server.close()
            self._tcp_server = None
        for sk in (self._send_sock, self._udp_sock):
            if sk is not None:
                sk.close()
        self._send_sock = None
        self._udp_sock = None
        try:
            os.unlink(self.path)
        except OSError:
            pass

    # ------------------------------------------------------------ publishing

    def next_version(self) -> Version:
        """Allocate a fresh (seq, origin) version: callers stamp local state
        with it and pass seq back into publish(), so the wire stamp and the
        local stamp are THE SAME version — a delayed echo of an older
        remote update can never outrank the local transition it raced."""
        return (self.clock.tick(), self.origin)

    def _peer_paths(self) -> list[str]:
        now = time.monotonic()
        if (now - self._peers_refreshed > PEER_REFRESH_S
                or len(self._peers) < self.expected_peers):
            self._peers = [
                p for p in glob.glob(os.path.join(self.directory, "w*.sock"))
                if p != self.path
            ]
            self._peers_refreshed = now
        return self._peers

    def _mesh_addrs(self) -> list[str]:
        """Current mesh destinations: config seeds ∪ registry membership ∪
        hello-learned, minus ourselves."""
        now = time.monotonic()
        if (self._membership is not None
                and now - self._mesh_refreshed > PEER_REFRESH_S):
            self._mesh_refreshed = now
            try:
                members = self._membership() or {}
            except Exception:  # registry briefly unavailable: keep cache
                log.debug("gossip membership refresh failed", exc_info=True)
                members = {}
            for origin, addr in members.items():
                if not addr or addr == self.mesh.advertise:
                    continue
                entry = self._mesh_peers.setdefault(
                    addr, {"last_seen": None, "origin": None, "nonce": None})
                entry.setdefault("origin", origin)
        return list(self._mesh_peers)

    def publish(self, kind: str, data: dict, *, seq: int | None = None) -> Version:
        """Fire-and-forget to every peer; returns the (seq, origin) version
        the message carried. Callable from any thread (lease releases
        arrive from GC finalizers); plain sendto on non-blocking sockets,
        no event-loop round trip (the TCP fallback hops to the loop)."""
        if seq is None:
            seq = self.clock.tick()
        version = (seq, self.origin)
        payload = encode_message(kind, data, origin=self.origin, seq=seq)
        sock = self._send_sock
        if sock is None:
            return version
        with self._lock:
            peers = self._peer_paths()
            mesh_addrs = self._mesh_addrs() if self.mesh.enabled else []
            if log.isEnabledFor(logging.DEBUG):
                log.debug("gossip publish kind=%s to %d unix + %d mesh "
                          "peers", kind, len(peers), len(mesh_addrs))
            for peer in peers:
                # destination origin for fault matching: the sibling index
                # embedded in its socket name ({dir}/w{i}.sock)
                dst = os.path.basename(peer).rsplit(".", 1)[0]
                if not self._fault_gate(kind, dst, payload, peer, unix=True):
                    continue
                self._sendto_unix(sock, payload, peer)
            for addr in mesh_addrs:
                entry = self._mesh_peers.get(addr) or {}
                dst = entry.get("origin") or addr
                if not self._fault_gate(kind, dst, payload, addr, unix=False):
                    continue
                self._send_mesh(payload, addr)
        return version

    def _fault_gate(self, kind: str, dst: str, payload: bytes,
                    dest, *, unix: bool) -> bool:
        """Consult the fault table for one destination: False = suppressed
        here (dropped or rescheduled after a delay)."""
        if self.faults is None:
            return True
        drop, delay = self.faults.decide(kind, self.origin, dst)
        if drop:
            self.fault_dropped_total += 1
            return False
        if delay > 0:
            timer = threading.Timer(
                delay, self._deliver_delayed, (payload, dest, unix))
            timer.daemon = True
            timer.start()
            return False
        return True

    def _deliver_delayed(self, payload: bytes, dest, unix: bool) -> None:
        with self._lock:
            if unix:
                if self._send_sock is not None:
                    self._sendto_unix(self._send_sock, payload, dest)
            else:
                self._send_mesh(payload, dest)

    def _sendto_unix(self, sock: socket.socket, payload: bytes,
                     peer: str) -> None:
        try:
            sock.sendto(payload, peer)
            self.sent_total += 1
        except OSError:
            # peer gone / queue full: best-effort means drop, and the
            # peer's own in-band signals converge it later
            self.send_errors_total += 1

    def _send_mesh(self, payload: bytes, addr: str) -> None:
        parsed = parse_addr(addr)
        if parsed is None:
            self.send_errors_total += 1
            return
        if len(payload) > UDP_MAX_BYTES:
            # oversize → one-shot TCP frame, off the hot path on the loop
            loop = self._loop
            if loop is None or loop.is_closed():
                self.send_errors_total += 1
                return
            loop.call_soon_threadsafe(
                lambda: loop.create_task(self._tcp_send(parsed, payload)))
            return
        if self._udp_sock is None:
            return
        try:
            self._udp_sock.sendto(payload, parsed)
            self.sent_total += 1
        except OSError:
            self.send_errors_total += 1

    async def _tcp_send(self, addr: tuple[str, int], payload: bytes) -> None:
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(addr[0], addr[1]),
                timeout=TCP_CONNECT_TIMEOUT_S,
            )
            writer.write(struct.pack(">I", len(payload)) + payload)
            await writer.drain()
            writer.close()
            self.sent_total += 1
        except (OSError, asyncio.TimeoutError):
            self.send_errors_total += 1

    # -------------------------------------------------------------- receiving

    def subscribe(self, kind: str,
                  handler: typing.Callable[[dict, dict], None]) -> None:
        """``handler(data, meta)`` with
        meta = {origin, seq, ver, ts, lag_s}. ``ver`` is the (seq, origin)
        tuple receivers stamp per-key state with (see `newer`)."""
        self._handlers.setdefault(kind, []).append(handler)

    async def _on_tcp_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            header = await reader.readexactly(4)
            (size,) = struct.unpack(">I", header)
            if size > TCP_MAX_BYTES:
                self.recv_rejected_total += 1
                return
            raw = await reader.readexactly(size)
        except (asyncio.IncompleteReadError, OSError):
            self.recv_rejected_total += 1
            return
        finally:
            writer.close()
        self._on_datagram(raw, via="tcp")

    def _on_datagram(self, raw: bytes, via: str = "unix") -> None:
        try:
            kind, data, meta = decode_message(raw)
        except GossipWireError as e:
            self.recv_rejected_total += 1
            log.debug("gossip rejected (%s): %s", via, e)
            return
        origin = meta["origin"]
        if origin == self.origin:
            return  # our own message looped back via a seed list
        seq = meta["seq"]
        self.clock.witness(seq)
        self.received_total += 1
        lag = max(0.0, time.time() - meta["ts"])
        meta["lag_s"] = lag
        self._lag_samples.append(lag)
        if len(self._lag_samples) > LAG_WINDOW:
            del self._lag_samples[: len(self._lag_samples) - LAG_WINDOW]
        if self.on_lag is not None:
            try:
                self.on_lag(lag)
            except Exception:  # allow-silent: a metrics observer must not
                pass           # poison message delivery
        if kind == HelloMsg.KIND:
            self._note_hello(origin, data)
        elif via in ("udp", "tcp"):
            self._note_mesh_alive(origin)
        # per-origin duplicate/reorder suppression for non-idempotent kinds:
        # a replayed datagram must not double-charge buckets or budgets.
        # (Reset when the peer's hello nonce changes — see _note_hello.)
        if kind in (RlSpendMsg.KIND, RetrySpendMsg.KIND, MigrateMsg.KIND):
            last = self._origin_seq.get((origin, kind))
            if last is not None and seq <= last:
                return
            self._origin_seq[(origin, kind)] = seq
        for handler in self._handlers.get(kind, ()):
            try:
                handler(data, meta)
            except Exception:  # one bad handler must not poison the bus
                log.exception("gossip handler for %r failed", kind)

    def _note_hello(self, origin: str, data: dict) -> None:
        advertise = data.get("advertise") or ""
        nonce = int(data.get("nonce") or 0)
        if advertise and advertise != self.mesh.advertise:
            entry = self._mesh_peers.setdefault(
                advertise, {"last_seen": None, "origin": None, "nonce": None})
            entry["last_seen"] = time.monotonic()
            entry["origin"] = origin
            if entry["nonce"] is not None and entry["nonce"] != nonce:
                # peer restarted: its SeqClock reset — drop dedupe marks so
                # its fresh (low) sequence numbers are not mistaken for
                # replays of the previous incarnation
                for key in [k for k in self._origin_seq if k[0] == origin]:
                    del self._origin_seq[key]
            entry["nonce"] = nonce

    def _note_mesh_alive(self, origin: str) -> None:
        for entry in self._mesh_peers.values():
            if entry.get("origin") == origin:
                entry["last_seen"] = time.monotonic()
                return

    async def _hello_loop(self) -> None:
        """Mesh heartbeat: advertise membership (registry + wire), surface
        partition suspicion, and give batched publishers (rl_spend) a flush
        edge via on_heartbeat."""
        while True:
            try:
                if self._register is not None:
                    try:
                        self._register(self.origin, self.mesh.advertise)
                    except Exception:
                        log.debug("gossip membership register failed",
                                  exc_info=True)
                self.publish(HelloMsg.KIND, {
                    "advertise": self.mesh.advertise,
                    "index": self.index,
                    "nonce": self.nonce,
                })
                for hook in list(self.on_heartbeat):
                    try:
                        hook()
                    except Exception:
                        log.exception("gossip heartbeat hook failed")
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("gossip hello tick failed")
            await asyncio.sleep(HELLO_INTERVAL_S)

    # ------------------------------------------------------------- inspection

    def lag_seconds(self) -> float | None:
        """Mean one-way delay of recently received messages (the gossip-lag
        gauge); None until the first message arrives. Wall-clock based —
        diagnostic only, never ordering (see module docstring)."""
        if not self._lag_samples:
            return None
        return sum(self._lag_samples) / len(self._lag_samples)

    def mesh_peer_count(self) -> int:
        return len(self._mesh_peers)

    def partition_suspected(self) -> bool:
        """True when a mesh peer we HAVE heard from goes silent past the
        suspicion window (never-seen seeds are config, not partitions)."""
        if not self.mesh.enabled:
            return False
        now = time.monotonic()
        for entry in self._mesh_peers.values():
            seen = entry.get("last_seen")
            if seen is not None and now - seen > PARTITION_SUSPECT_S:
                return True
        return False

    def stats(self) -> dict:
        with self._lock:
            peers = len(self._peer_paths())
            mesh_peers = len(self._mesh_addrs()) if self.mesh.enabled else 0
        return {
            "origin": self.origin,
            "sent_total": self.sent_total,
            "received_total": self.received_total,
            "send_errors_total": self.send_errors_total,
            "recv_rejected_total": self.recv_rejected_total,
            "fault_dropped_total": self.fault_dropped_total,
            "lag_s": self.lag_seconds(),
            "peers": peers,
            "mesh_peers": mesh_peers,
            "partition_suspected": self.partition_suspected(),
            "seq": self.clock.peek(),
        }


def default_gossip_dir(port: int) -> str:
    """One bus per gateway instance: scope the socket dir by listen port so
    two gateways on one host never cross-gossip."""
    base = os.environ.get("LLMLB_GOSSIP_DIR")
    if base:
        return base
    data_dir = os.path.expanduser(
        os.environ.get("LLMLB_DATA_DIR", "~/.llmlb") or "~/.llmlb"
    )
    return os.path.join(data_dir, "gossip", str(port))
