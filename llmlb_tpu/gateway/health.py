"""Pull-based endpoint health checker.

State machine parity with reference health/endpoint_checker.rs: default 30 s
interval (:43), 5 s probe timeout (:40), offline after 2 consecutive failures
(:46), pending→offline immediately on first failure (:580); on recovery the
type is re-detected and models auto-synced (:333-377,:426); TPS state cleared on
failure so recovered endpoints re-learn (:313-317); every check persisted.

TPU extension: tpu/xllm endpoints are probed at /api/health and their chip/HBM
telemetry flows into the registry (the reference read GPU fields, :515).
"""

from __future__ import annotations

import asyncio
import logging
import time

import aiohttp

from llmlb_tpu.gateway.balancer import LoadManager
from llmlb_tpu.gateway.db import Database
from llmlb_tpu.gateway.detection import detect_endpoint_type
from llmlb_tpu.gateway.events import DashboardEventBus
from llmlb_tpu.gateway.model_sync import sync_endpoint_models
from llmlb_tpu.gateway.registry import EndpointRegistry
from llmlb_tpu.gateway.types import (
    AcceleratorInfo,
    Endpoint,
    EndpointStatus,
    EndpointType,
    HealthCheckResult,
)

log = logging.getLogger("llmlb_tpu.gateway.health")

OFFLINE_AFTER_FAILURES = 2  # parity: endpoint_checker.rs:46


def _as_int(v, default: int = 0) -> int:
    try:
        return int(v)
    except (TypeError, ValueError):
        return default


def _parse_telemetry(body: dict) -> AcceleratorInfo:
    """Tolerant parse of an engine /api/health body. Malformed fields degrade
    to zeros rather than raising — a bad payload from one endpoint must never
    abort the whole health cycle (check_all gathers without return_exceptions)."""
    from llmlb_tpu.disagg import ROLES

    tpu = body.get("tpu") or body.get("gpu")
    tpu = tpu if isinstance(tpu, dict) else {}
    engine = body.get("engine")
    engine = engine if isinstance(engine, dict) else {}
    util = tpu.get("utilization")
    disagg = body.get("disagg")
    disagg = disagg if isinstance(disagg, dict) else {}
    role = disagg.get("role")
    # Graceful drain advertisement (docs/deployment.md): a draining engine
    # keeps answering probes with 200 (so its models never 404) but flags
    # itself here — selection drops it within one probe interval.
    drain = body.get("draining")
    drain = drain if isinstance(drain, dict) else {}
    draining = (body.get("status") == "draining"
                or bool(drain.get("draining")))
    try:
        drain_remaining = max(0.0, float(drain.get("remaining_s") or 0.0))
    except (TypeError, ValueError):
        drain_remaining = 0.0
    # Multi-LoRA advertisement (docs/lora.md): resident adapter names,
    # re-read every probe like the disagg role above.
    lora = body.get("lora")
    lora = lora if isinstance(lora, dict) else {}
    lora_loaded = lora_available = None
    if lora.get("enabled"):
        lora_loaded = tuple(
            str(n) for n in (lora.get("resident") or ())
        )
        lora_available = tuple(
            str(n) for n in (lora.get("available") or ())
        )
    return AcceleratorInfo(
        role=role if role in ROLES else None,
        draining=draining,
        drain_remaining_s=drain_remaining,
        lora_loaded=lora_loaded,
        lora_available=lora_available,
        accelerator=tpu.get("accelerator") or ("tpu" if "tpu" in body else None),
        chip_count=_as_int(tpu.get("chip_count")),
        hbm_used_bytes=_as_int(tpu.get("hbm_used_bytes")),
        hbm_total_bytes=_as_int(tpu.get("hbm_total_bytes")),
        utilization=util if isinstance(util, (int, float)) else None,
        queue_depth=_as_int(engine.get("queued")),
        active_slots=_as_int(engine.get("active_slots")),
        num_slots=_as_int(engine.get("num_slots")),
        sampled_at=time.time(),
    )


class EndpointHealthChecker:
    def __init__(
        self,
        registry: EndpointRegistry,
        load_manager: LoadManager,
        db: Database,
        session: aiohttp.ClientSession,
        events: DashboardEventBus | None = None,
        interval_s: float = 30.0,
        timeout_s: float = 5.0,
        resilience=None,
    ):
        self.registry = registry
        self.load_manager = load_manager
        self.db = db
        self.session = session
        self.events = events
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        # ResilienceManager | None: in-band breaker state reconciles with
        # this pull checker — a good probe fast-forwards an open breaker to
        # half-open, a recovered-from-offline endpoint gets a fresh breaker.
        self.resilience = resilience
        # GossipBus | None (wired by app_state): resident-adapter changes
        # push to sibling workers the moment a probe observes them, instead
        # of each sibling waiting out its own registry reload.
        self.gossip = None
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.create_task(self._monitor_loop(), name="health-checker")

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _monitor_loop(self) -> None:
        while True:
            try:
                await self.check_all()
            except Exception:
                log.exception("health check cycle failed")
            await asyncio.sleep(self.interval_s)

    async def check_all(self) -> list[HealthCheckResult]:
        endpoints = self.registry.list_all()
        if not endpoints:
            return []
        return list(
            await asyncio.gather(*(self.check_endpoint(ep) for ep in endpoints))
        )

    # ------------------------------------------------------------------ probe

    async def _probe(self, ep: Endpoint) -> HealthCheckResult:
        """One HTTP probe. tpu/xllm: /api/health (telemetry) with /v1/models
        fallback; everything else: /v1/models."""
        headers = {}
        if ep.api_key:
            headers["Authorization"] = f"Bearer {ep.api_key}"
        start = time.monotonic()

        async def get(path: str) -> tuple[int, dict | None]:
            async with self.session.get(
                ep.url + path,
                headers=headers,
                timeout=aiohttp.ClientTimeout(total=self.timeout_s),
            ) as resp:
                try:
                    body = await resp.json(content_type=None)
                except Exception:
                    body = None
                return resp.status, body if isinstance(body, dict) else None

        try:
            accelerator = None
            models_payload = None
            if ep.endpoint_type in (EndpointType.TPU, EndpointType.XLLM):
                try:
                    status, body = await get("/api/health")
                except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                    status, body = 0, None
                if status == 200 and body:
                    accelerator = _parse_telemetry(body)
                else:
                    status, models_payload = await get("/v1/models")
            else:
                status, models_payload = await get("/v1/models")

            latency_ms = (time.monotonic() - start) * 1000.0
            ok = status == 200
            return HealthCheckResult(
                endpoint_id=ep.id, ok=ok, latency_ms=latency_ms,
                error=None if ok else f"HTTP {status}",
                accelerator=accelerator, models_payload=models_payload,
            )
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            return HealthCheckResult(
                endpoint_id=ep.id, ok=False,
                latency_ms=(time.monotonic() - start) * 1000.0,
                error=f"{type(e).__name__}: {e}",
            )

    # ------------------------------------------------------------ state logic

    async def check_endpoint(self, ep: Endpoint) -> HealthCheckResult:
        result = await self._probe(ep)
        prev_status = ep.status

        if result.ok:
            recovered = prev_status in (
                EndpointStatus.OFFLINE, EndpointStatus.ERROR, EndpointStatus.PENDING
            )
            self.registry.update_status(
                ep.id, EndpointStatus.ONLINE,
                latency_ms=result.latency_ms,
                accelerator=result.accelerator,
                consecutive_failures=0,
            )
            if self.resilience is not None:
                if recovered:
                    # the engine restarted; in-band failure history is stale
                    self.resilience.reset(ep.id)
                else:
                    # good probe: open breaker fast-forwards to half-open so
                    # the next real request (not the 30 s timer) decides
                    self.resilience.note_probe(ep.id, True)
            if recovered:
                await self._on_recovery(ep)
            self._sync_lora_models(ep, result.accelerator)
        else:
            failures = ep.consecutive_failures + 1
            if prev_status == EndpointStatus.PENDING:
                new_status = EndpointStatus.OFFLINE  # pending fails fast (:580)
            elif failures >= OFFLINE_AFTER_FAILURES:
                new_status = EndpointStatus.OFFLINE
            else:
                new_status = prev_status  # one strike: stay online
            self.registry.update_status(
                ep.id, new_status, consecutive_failures=failures
            )
            if self.resilience is not None:
                self.resilience.note_probe(ep.id, False)
            if new_status == EndpointStatus.OFFLINE:
                # recovered endpoints must re-measure TPS (:313-317)
                self.load_manager.clear_tps_for_endpoint(ep.id)

        self.db.record_health_check(
            ep.id, result.ok, result.latency_ms, result.error, result.checked_at
        )
        new_ep = self.registry.get(ep.id)
        if self.events and new_ep and new_ep.status != prev_status:
            self.events.publish(
                "EndpointStatusChanged",
                {
                    "endpoint_id": ep.id,
                    "name": ep.name,
                    "from": prev_status.value,
                    "to": new_ep.status.value,
                },
            )
        if self.events and result.accelerator:
            self.events.publish(
                "TelemetryUpdated",
                {"endpoint_id": ep.id, "tpu": vars(result.accelerator)},
            )
        return result

    def _sync_lora_models(self, ep: Endpoint, acc) -> None:
        """Mirror a probe's resident-adapter advertisement into
        `base:adapter` model entries (docs/lora.md). Model sync proper runs
        only at registration/recovery, but adapters hot-load and evict at
        request rate — this keeps find_by_model("base:adapter") fresh
        within one probe interval, the disagg-role re-parse precedent.
        No-op (and no DB churn) when the resident set is unchanged."""
        if acc is None or acc.lora_loaded is None:
            return
        from llmlb_tpu.gateway.types import Capability, EndpointModel

        models = self.registry.models_for(ep.id)
        base = [m for m in models if ":" not in m.model_id]
        lora_base = [m for m in base if Capability.LORA in m.capabilities]
        if not lora_base:
            return
        wanted: dict[str, EndpointModel] = {}
        for m in lora_base:
            for name in acc.lora_loaded:
                mid = f"{m.model_id}:{name}"
                wanted[mid] = EndpointModel(
                    endpoint_id=ep.id,
                    model_id=mid,
                    canonical_name=f"{m.canonical_name}:{name}",
                    capabilities=list(m.capabilities),
                    context_length=m.context_length,
                )
        current = {m.model_id for m in models if ":" in m.model_id}
        if current == set(wanted):
            return
        self.registry.sync_models(ep.id, base + list(wanted.values()))
        # Event-driven residency (docs/lora.md): the resident set CHANGED —
        # push it so siblings (and mesh peers) patch their caches now, one
        # gossip hop instead of one probe/reload interval.
        if self.gossip is not None:
            self.gossip.publish("residency", {
                "eid": ep.id,
                "adapters": {name: 1 for name in acc.lora_loaded},
            })

    async def _on_recovery(self, ep: Endpoint) -> None:
        """Re-detect type (it may have been swapped) and resync models."""
        try:
            detected = await detect_endpoint_type(
                ep.base_url, self.session, timeout=self.timeout_s, api_key=ep.api_key
            )
            if detected != ep.endpoint_type:
                log.info(
                    "endpoint %s type changed %s -> %s",
                    ep.name, ep.endpoint_type.value, detected.value,
                )
                self.registry.update_type(ep.id, detected)
                ep.endpoint_type = detected
        except Exception:  # allow-silent: re-detection is opportunistic;
            pass           # the model resync below still runs and logs
        try:
            await sync_endpoint_models(ep, self.registry, self.session)
        except Exception as e:
            log.warning("model sync on recovery failed for %s: %s", ep.name, e)
