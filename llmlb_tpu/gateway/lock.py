"""Single-instance lockfile per port, with stale-PID detection.

Parity with reference lock/mod.rs (acquire :298, stale detection via
is_process_running :225, stop-by-PID :262-380).
"""

from __future__ import annotations

import json
import os
import signal
import time


def _lock_dir() -> str:
    d = os.path.expanduser(os.environ.get("LLMLB_DATA_DIR", "~/.llmlb"))
    os.makedirs(d, exist_ok=True)
    return d


def _lock_path(port: int) -> str:
    return os.path.join(_lock_dir(), f"llmlb-{port}.lock")


def _pid_running(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


class ServerLock:
    def __init__(self, port: int, path: str):
        self.port = port
        self.path = path

    @classmethod
    def acquire(cls, port: int) -> "ServerLock":
        path = _lock_path(port)
        if os.path.exists(path):
            try:
                with open(path) as f:
                    info = json.load(f)
                if _pid_running(int(info.get("pid", -1))):
                    raise RuntimeError(
                        f"another llmlb instance (pid {info['pid']}) already "
                        f"holds port {port}"
                    )
            except (ValueError, OSError):
                pass  # stale/corrupt lockfile: fall through and replace
        with open(path, "w") as f:
            json.dump({"pid": os.getpid(), "port": port, "ts": time.time()}, f)
        return cls(port, path)

    def release(self) -> None:
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass

    @staticmethod
    def status(port: int) -> dict | None:
        path = _lock_path(port)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                info = json.load(f)
        except (ValueError, OSError):
            return None
        if not _pid_running(int(info.get("pid", -1))):
            return None
        return info

    @staticmethod
    def stop(port: int) -> bool:
        info = ServerLock.status(port)
        if info is None:
            return False
        try:
            os.kill(int(info["pid"]), signal.SIGTERM)
            return True
        except (ProcessLookupError, PermissionError):
            return False
