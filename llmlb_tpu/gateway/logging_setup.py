"""Logging subsystem: stderr + daily-rotated file sink with retention cleanup.

Parity with reference logging.rs:41-182 (tracing-subscriber dual sinks:
stderr layer + daily-rotated non-blocking file layer under ~/.llmlb/logs,
env-filtered, old-file cleanup). Python counterpart: logging with a
TimedRotatingFileHandler under ``log_dir`` (default ``~/.llmlb_tpu/logs``),
level from ``LLMLB_LOG_LEVEL``, and rotated files beyond the retention count
deleted at rollover. The active file path is exposed for the dashboard
log-tail API (reference api/logs.rs:52).
"""

from __future__ import annotations

import logging
import logging.handlers
import os

LOG_FILENAME = "llmlb.log"
DEFAULT_RETENTION = 14  # rotated files kept, parity with cleanup loop

# Every line carries the worker id (``w0`` .. ``wN-1``): with --workers N
# the processes' stderr interleaves on one console, and an untagged line
# from worker 3 is indistinguishable from worker 0's. Override with
# LLMLB_LOG_FORMAT (standard logging %-format; the extra field is
# ``%(worker)s``). Documented in docs/configuration.md.
DEFAULT_LOG_FORMAT = (
    "%(asctime)s %(levelname)-7s w%(worker)s %(name)s: %(message)s"
)

_active_log_path: str | None = None
_factory_installed = False


def _install_worker_field() -> None:
    """Stamp every LogRecord with this process's worker index via the
    record factory (handler filters would miss records emitted before the
    handlers exist, and third-party handlers added later)."""
    global _factory_installed
    if _factory_installed:
        return
    _factory_installed = True
    old_factory = logging.getLogRecordFactory()

    def factory(*args, **kwargs):
        record = old_factory(*args, **kwargs)
        if not hasattr(record, "worker"):
            record.worker = os.environ.get("LLMLB_WORKER_INDEX", "0")
        return record

    logging.setLogRecordFactory(factory)


def default_log_dir() -> str:
    return os.environ.get(
        "LLMLB_LOG_DIR",
        os.path.join(os.path.expanduser("~"), ".llmlb_tpu", "logs"),
    )


def active_log_path() -> str | None:
    """Path of the live log file, or None when file logging is disabled."""
    return _active_log_path


def init_logging(
    log_dir: str | None = None,
    *,
    level: str | None = None,
    retention: int | None = None,
    file_sink: bool = True,
) -> str | None:
    """Install stderr + rotating-file handlers on the root logger.

    Returns the active log file path (None if the file sink is disabled or
    the directory can't be created). Idempotent: re-running replaces the
    handlers rather than stacking duplicates.
    """
    global _active_log_path

    level_name = (level or os.environ.get("LLMLB_LOG_LEVEL") or "INFO").upper()
    log_level = getattr(logging, level_name, logging.INFO)
    retention = retention if retention is not None else int(
        os.environ.get("LLMLB_LOG_RETENTION", DEFAULT_RETENTION)
    )

    _install_worker_field()
    root = logging.getLogger()
    root.setLevel(log_level)
    fmt = logging.Formatter(
        os.environ.get("LLMLB_LOG_FORMAT") or DEFAULT_LOG_FORMAT
    )

    for h in list(root.handlers):
        if getattr(h, "_llmlb_sink", False):
            root.removeHandler(h)
            try:
                h.close()
            except Exception:  # allow-silent: closing a dead log sink
                pass

    stderr = logging.StreamHandler()
    stderr.setFormatter(fmt)
    stderr._llmlb_sink = True
    root.addHandler(stderr)

    _active_log_path = None
    if file_sink:
        directory = log_dir or default_log_dir()
        try:
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(directory, LOG_FILENAME)
            fileh = logging.handlers.TimedRotatingFileHandler(
                path, when="midnight", backupCount=retention, utc=True,
                delay=True,
            )
            fileh.setFormatter(fmt)
            fileh._llmlb_sink = True
            root.addHandler(fileh)
            _active_log_path = path
        except OSError as e:
            root.warning("file log sink disabled: %s", e)
    return _active_log_path


def tail_log(lines: int = 200, path: str | None = None) -> list[str]:
    """Last N lines of the active log file (log-tail API, api/logs.rs:52-73).
    Reads a bounded window from the end so huge files stay cheap."""
    p = path or _active_log_path
    if not p or not os.path.isfile(p):
        return []
    lines = max(1, min(lines, 5000))
    window = 256 * 1024
    with open(p, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(max(0, size - window))
        chunk = f.read()
    text = chunk.decode("utf-8", "replace")
    out = text.splitlines()
    if size > window and out:
        out = out[1:]  # first line may be torn by the window cut
    return out[-lines:]
