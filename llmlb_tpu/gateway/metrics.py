"""Gateway-wide Prometheus metrics: the request-path figures the balancer
cannot see from inside one engine.

Same dependency-free idiom as EngineMetrics (llmlb_tpu/engine/metrics.py):
plain counters and bucketed histograms behind one lock, rendered in
Prometheus text exposition at GET /metrics. Histograms are labeled
per (model, endpoint) so a slow request can be attributed to queueing vs
the engine, and to WHICH engine — the per-phase breakdown every serving
paper tunes against, now observable at the gateway layer.

Series:
  llmlb_gateway_requests_total{route,status}   counter
  llmlb_gateway_errors_total{route}            counter (status >= 400)
  llmlb_gateway_retries_total{api}             counter (admission re-attempts)
  llmlb_gateway_queue_timeouts_total{model}    counter
  llmlb_gateway_ttft_seconds{model,endpoint}   histogram
  llmlb_gateway_e2e_seconds{model,endpoint}    histogram
  llmlb_gateway_queue_wait_seconds{model,endpoint} histogram
plus scrape-time gauges (active requests, admission queue depth, event-bus
drops, trace-buffer size) injected by the /metrics handler.
"""

from __future__ import annotations

import threading
from collections import defaultdict

from llmlb_tpu.engine.metrics import Histogram

# Gateway-side latency edges: TTFT spans engine prefill plus proxy overhead
# (tens of ms to tens of seconds for queued long prompts); queue wait spans
# sub-ms fast-path admissions to the 30 s queue timeout.
TTFT_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
E2E_BUCKETS = (0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
               60.0, 120.0)
QUEUE_WAIT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                      5.0, 10.0, 30.0)


def _escape(value: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class GatewayMetrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._requests: dict[tuple[str, int], int] = defaultdict(int)
        self._errors: dict[str, int] = defaultdict(int)
        self._retries: dict[str, int] = defaultdict(int)
        self._queue_timeouts: dict[str, int] = defaultdict(int)
        # (model, endpoint) -> Histogram
        self._ttft: dict[tuple[str, str], Histogram] = {}
        self._e2e: dict[tuple[str, str], Histogram] = {}
        self._queue_wait: dict[tuple[str, str], Histogram] = {}

    # ------------------------------------------------------------ recorders

    def record_request(self, route: str, status: int) -> None:
        with self._lock:
            self._requests[(route, status)] += 1
            if status >= 400:
                self._errors[route] += 1

    def record_retry(self, api: str) -> None:
        """One admission re-attempt after parking on the queue, labeled by
        API kind ('chat', 'completion', ...) — the admission queue sits below
        route matching and never sees the route pattern."""
        with self._lock:
            self._retries[api] += 1

    def record_queue_timeout(self, model: str) -> None:
        with self._lock:
            self._queue_timeouts[model] += 1

    def _observe(self, table: dict, buckets: tuple[float, ...],
                 model: str, endpoint: str, seconds: float) -> None:
        with self._lock:
            hist = table.get((model, endpoint))
            if hist is None:
                hist = table[(model, endpoint)] = Histogram(buckets)
            hist.observe(seconds)

    def record_ttft(self, model: str, endpoint: str, seconds: float) -> None:
        self._observe(self._ttft, TTFT_BUCKETS, model, endpoint, seconds)

    def record_e2e(self, model: str, endpoint: str, seconds: float) -> None:
        self._observe(self._e2e, E2E_BUCKETS, model, endpoint, seconds)

    def record_queue_wait(self, model: str, endpoint: str,
                          seconds: float) -> None:
        self._observe(self._queue_wait, QUEUE_WAIT_BUCKETS, model, endpoint,
                      seconds)

    # ----------------------------------------------------------- exposition

    def summary(self) -> dict:
        """Compact JSON figures (bench tooling + dashboard overview)."""
        with self._lock:
            def pcts(table: dict) -> dict:
                merged: Histogram | None = None
                for hist in table.values():
                    if merged is None:
                        merged = Histogram(hist.edges)
                    for i, c in enumerate(hist.counts):
                        merged.counts[i] += c
                    merged.total += hist.total
                    merged.n += hist.n
                    merged.max = max(merged.max, hist.max)
                if merged is None:
                    return {"p50": None, "p99": None, "count": 0}
                return {"p50": merged.percentile(50),
                        "p99": merged.percentile(99), "count": merged.n}

            return {
                "requests_total": sum(self._requests.values()),
                "errors_total": sum(self._errors.values()),
                "retries_total": sum(self._retries.values()),
                "queue_timeouts_total": sum(self._queue_timeouts.values()),
                "ttft_s": pcts(self._ttft),
                "e2e_s": pcts(self._e2e),
                "queue_wait_s": pcts(self._queue_wait),
            }

    def render(self, *, gauges: dict[str, float] | None = None,
               counters: dict[str, float] | None = None) -> str:
        """Prometheus text exposition. `gauges`/`counters` hold scrape-time
        figures owned elsewhere (load manager, admission queue, event bus)."""
        with self._lock:
            lines = ["# TYPE llmlb_gateway_requests_total counter"]
            for (route, status), n in sorted(self._requests.items()):
                lines.append(
                    f'llmlb_gateway_requests_total{{route="{_escape(route)}",'
                    f'status="{status}"}} {n}'
                )
            lines.append("# TYPE llmlb_gateway_errors_total counter")
            for route, n in sorted(self._errors.items()):
                lines.append(
                    f'llmlb_gateway_errors_total{{route="{_escape(route)}"}} {n}'
                )
            lines.append("# TYPE llmlb_gateway_retries_total counter")
            for api, n in sorted(self._retries.items()):
                lines.append(
                    f'llmlb_gateway_retries_total{{api="{_escape(api)}"}} {n}'
                )
            lines.append("# TYPE llmlb_gateway_queue_timeouts_total counter")
            for model, n in sorted(self._queue_timeouts.items()):
                lines.append(
                    f'llmlb_gateway_queue_timeouts_total'
                    f'{{model="{_escape(model)}"}} {n}'
                )
            for name, table in (
                ("llmlb_gateway_ttft_seconds", self._ttft),
                ("llmlb_gateway_e2e_seconds", self._e2e),
                ("llmlb_gateway_queue_wait_seconds", self._queue_wait),
            ):
                lines.append(f"# TYPE {name} histogram")
                for (model, endpoint), hist in sorted(table.items()):
                    labels = (f'model="{_escape(model)}",'
                              f'endpoint="{_escape(endpoint)}"')
                    cumulative = 0
                    for i, edge in enumerate(hist.edges):
                        cumulative += hist.counts[i]
                        lines.append(
                            f'{name}_bucket{{{labels},le="{edge}"}} '
                            f'{cumulative}'
                        )
                    cumulative += hist.counts[-1]
                    lines.append(
                        f'{name}_bucket{{{labels},le="+Inf"}} {cumulative}'
                    )
                    lines.append(f"{name}_sum{{{labels}}} {hist.total}")
                    lines.append(f"{name}_count{{{labels}}} {hist.n}")
            for cname, value in sorted((counters or {}).items()):
                lines.append(f"# TYPE {cname} counter")
                lines.append(f"{cname} {value}")
            for gname, value in sorted((gauges or {}).items()):
                lines.append(f"# TYPE {gname} gauge")
                lines.append(f"{gname} {value}")
            return "\n".join(lines) + "\n"
