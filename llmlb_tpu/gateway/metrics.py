"""Gateway-wide Prometheus metrics: the request-path figures the balancer
cannot see from inside one engine.

Same dependency-free idiom as EngineMetrics (llmlb_tpu/engine/metrics.py):
plain counters and bucketed histograms behind one lock, rendered in
Prometheus text exposition at GET /metrics. Histograms are labeled
per (model, endpoint) so a slow request can be attributed to queueing vs
the engine, and to WHICH engine — the per-phase breakdown every serving
paper tunes against, now observable at the gateway layer.

Series:
  llmlb_gateway_requests_total{route,status}   counter
  llmlb_gateway_errors_total{route}            counter (status >= 400)
  llmlb_gateway_retries_total{api}             counter (admission re-attempts)
  llmlb_gateway_queue_timeouts_total{model}    counter
  llmlb_gateway_ttft_seconds{model,endpoint}   histogram
  llmlb_gateway_e2e_seconds{model,endpoint}    histogram
  llmlb_gateway_queue_wait_seconds{model,endpoint} histogram
resilience-layer series (gateway/resilience.py):
  llmlb_gateway_failover_retries_total{model,reason}     counter
  llmlb_gateway_failover_recoveries_total{model}         counter
  llmlb_gateway_retry_budget_exhausted_total             counter
  llmlb_gateway_breaker_transitions_total{endpoint,to}   counter
  llmlb_gateway_breaker_state{endpoint}                  gauge (0/1/2)
  llmlb_gateway_stream_interruptions_total{model,endpoint} counter
  llmlb_gateway_faults_injected_total{kind}              counter
fleet-federation series (gateway/rebalance.py, gateway/gossip.py):
  llmlb_gateway_rebalance_migrations_total{reason,outcome} counter
  llmlb_gateway_gossip_delay_seconds                     histogram
  (plus gossip_peers / gossip_partition_suspected scrape-time gauges
   injected by the /metrics handler, docs/monitoring/README.md)
SLO goodput series (targets from SloConfig, docs/profiling.md):
  llmlb_gateway_slo_eligible_total{model}   counter (requests judged)
  llmlb_gateway_slo_met_total{model}        counter (met every target)
  llmlb_gateway_slo_ttft_miss_total{model}  counter
  llmlb_gateway_slo_itl_miss_total{model}   counter
  llmlb_gateway_goodput_ratio{model}        gauge (met / eligible)
overload-protection series (docs/scheduling.md):
  llmlb_gateway_slo_priority_eligible_total{priority}  counter
  llmlb_gateway_slo_priority_met_total{priority}       counter
  llmlb_gateway_goodput_by_priority{priority}          gauge
  llmlb_gateway_ratelimit_rejections_total{reason}     counter (429s)
  llmlb_gateway_deadline_shed_total{model}             counter
  llmlb_gateway_stream_write_timeouts_total{model}     counter
plus scrape-time gauges (active requests, admission queue depth, event-bus
drops, trace-buffer size) injected by the /metrics handler.
"""

from __future__ import annotations

import re
import threading
from collections import defaultdict

from llmlb_tpu.engine.metrics import Histogram

# Sample lines of a Prometheus text exposition: `name value`,
# `name{labels} value`, with optional trailing timestamp. The label block
# is matched greedily to the LAST closing brace before the value — a '}'
# inside a label value (legal; only \ " \n are escaped) must not truncate
# the block or the injected label would land mid-string.
_SAMPLE_LINE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{.*\})?( .*)$"
)


def label_exposition(text: str, label: str, value: str) -> str:
    """Inject one label into every sample line of an exposition.

    Multi-worker /metrics: each worker's series carry worker="N" so a
    scrape (which SO_REUSEPORT hands to ONE arbitrary worker) stays
    attributable after the serving worker merges its siblings' spooled
    expositions — sum by (...) in PromQL aggregates, by (worker) splits.
    """
    pair = f'{label}="{value}"'
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        m = _SAMPLE_LINE.match(line)
        if m is None:
            out.append(line)
            continue
        name, labels, rest = m.group(1), m.group(2), m.group(3)
        if labels:
            out.append(f"{name}{{{labels[1:-1]},{pair}}}{rest}")
        else:
            out.append(f"{name}{{{pair}}}{rest}")
    return "\n".join(out) + ("\n" if text.endswith("\n") else "")

# Gateway-side latency edges: TTFT spans engine prefill plus proxy overhead
# (tens of ms to tens of seconds for queued long prompts); queue wait spans
# sub-ms fast-path admissions to the 30 s queue timeout.
TTFT_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
E2E_BUCKETS = (0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
               60.0, 120.0)
QUEUE_WAIT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                      5.0, 10.0, 30.0)
# One-way gossip delivery delay: sub-ms on a unix socket, tens of ms across
# hosts, seconds when a delay fault or congested mesh is in play.
GOSSIP_LAG_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                      1.0, 2.5, 5.0)


def _escape(value: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class GatewayMetrics:
    def __init__(self, slo=None):
        # `slo` is a config.SloConfig (None: goodput accounting inert —
        # the series still render, at zero, so dashboards never 404)
        self.slo = slo
        self._lock = threading.Lock()
        self._requests: dict[tuple[str, int], int] = defaultdict(int)
        self._errors: dict[str, int] = defaultdict(int)
        self._retries: dict[str, int] = defaultdict(int)
        self._queue_timeouts: dict[str, int] = defaultdict(int)
        # (model, endpoint) -> Histogram
        self._ttft: dict[tuple[str, str], Histogram] = {}
        self._e2e: dict[tuple[str, str], Histogram] = {}
        self._queue_wait: dict[tuple[str, str], Histogram] = {}
        # resilience layer (gateway/resilience.py)
        self._failover_retries: dict[tuple[str, str], int] = defaultdict(int)
        self._failover_recoveries: dict[str, int] = defaultdict(int)
        self._retry_budget_exhausted = 0
        self._breaker_transitions: dict[tuple[str, str], int] = defaultdict(int)
        self._breaker_state: dict[str, int] = {}
        self._stream_interruptions: dict[tuple[str, str], int] = defaultdict(int)
        self._faults_injected: dict[str, int] = defaultdict(int)
        # fleet federation (gateway/rebalance.py): proactive live-stream
        # migrations by (reason=hotspot|drain|restart, outcome=success|
        # aborted|refused|skipped) — distinct from stream_resumes, which
        # counts REACTIVE failure recovery
        self._rebalance_migrations: dict[tuple[str, str], int] = defaultdict(int)
        # one-way gossip delivery delay per received message (wall-clock
        # derived, diagnostic only — see gossip.py module docstring)
        self._gossip_lag = Histogram(GOSSIP_LAG_BUCKETS)
        # structured outputs (llmlb_tpu/structured): requests that asked for
        # grammar-constrained decoding, by kind, and requests rejected 400
        # at gateway-side validation (malformed / unsupported schema)
        self._structured_requests: dict[str, int] = defaultdict(int)
        self._structured_rejected = 0
        # multi-LoRA adapter routing (docs/lora.md): requests that named an
        # adapter, by route — "hot" (an endpoint already had it resident),
        # "load" (fell back to a lora-capable endpoint, triggering a
        # hot-load), "rejected" (400: malformed field or unserveable
        # adapter)
        self._lora_requests: dict[str, int] = defaultdict(int)
        # disaggregated prefill/decode (docs/disaggregation.md): two-phase
        # handoffs the proxy orchestrated, by outcome — "adopted" (a decode
        # pool endpoint took the stream) or "self" (no adopter free; the
        # prefill endpoint continued its own stream)
        self._handoffs: dict[str, int] = defaultdict(int)
        # SLO goodput accounting: per-model attainment counters against the
        # SloConfig targets; goodput_ratio renders as met/eligible
        self._slo_eligible: dict[str, int] = defaultdict(int)
        self._slo_met: dict[str, int] = defaultdict(int)
        self._slo_ttft_miss: dict[str, int] = defaultdict(int)
        self._slo_itl_miss: dict[str, int] = defaultdict(int)
        # goodput BY PRIORITY CLASS (docs/scheduling.md): the figure that
        # shows overload protection working — high-priority goodput holding
        # while low-priority traffic absorbs the squeeze
        self._slo_prio_eligible: dict[str, int] = defaultdict(int)
        self._slo_prio_met: dict[str, int] = defaultdict(int)
        # overload protection (docs/scheduling.md): requests refused by the
        # per-key token buckets, requests shed because their deadline had
        # already passed, and streams aborted by the write timeout
        # (stalled/slow-loris clients)
        self._ratelimit_rejections: dict[str, int] = defaultdict(int)
        self._deadline_shed: dict[str, int] = defaultdict(int)
        self._stream_write_timeouts: dict[str, int] = defaultdict(int)
        # durable streams (gateway/replay.py, docs/resilience.md): mid-stream
        # cuts replayed onto another engine, by outcome — "success" (the
        # continuation spliced into the client stream), or why the gateway
        # gave up and emitted the terminal error frame instead ("exhausted"
        # attempts, "budget" refused, "no_endpoint", "failed" resume POST)
        self._stream_resumes: dict[str, int] = defaultdict(int)
        # committed tokens replayed onto the resuming engine (the work the
        # failover saved the client from losing)
        self._stream_resumed_tokens: dict[str, int] = defaultdict(int)

    # ------------------------------------------------------------ recorders

    def record_request(self, route: str, status: int) -> None:
        with self._lock:
            self._requests[(route, status)] += 1
            if status >= 400:
                self._errors[route] += 1

    def record_retry(self, api: str) -> None:
        """One admission re-attempt after parking on the queue, labeled by
        API kind ('chat', 'completion', ...) — the admission queue sits below
        route matching and never sees the route pattern."""
        with self._lock:
            self._retries[api] += 1

    def record_queue_timeout(self, model: str) -> None:
        with self._lock:
            self._queue_timeouts[model] += 1

    # --------------------------------------------------- resilience recorders

    def record_failover_retry(self, model: str, reason: str) -> None:
        """One in-band failover retry: the request is being re-run against a
        different endpoint after `reason` (connect_error/timeout/http_5xx/
        http_429/stream_pre_byte)."""
        with self._lock:
            self._failover_retries[(model, reason)] += 1

    def record_failover_recovery(self, model: str) -> None:
        """A request that failed on >= 1 endpoint ultimately succeeded —
        the failure the client never saw."""
        with self._lock:
            self._failover_recoveries[model] += 1

    def record_retry_budget_exhausted(self) -> None:
        with self._lock:
            self._retry_budget_exhausted += 1

    def record_breaker_transition(self, endpoint: str, to_state: str) -> None:
        with self._lock:
            self._breaker_transitions[(endpoint, to_state)] += 1

    def set_breaker_state(self, endpoint: str, code: int) -> None:
        """Current breaker state per endpoint: 0=closed, 1=half_open, 2=open."""
        with self._lock:
            self._breaker_state[endpoint] = code

    def clear_breaker_state(self, endpoint: str) -> None:
        """Endpoint deleted: stop exporting its state gauge (a frozen open
        reading would alert on a nonexistent endpoint forever). Transition
        counters stay — they are history, not state."""
        with self._lock:
            self._breaker_state.pop(endpoint, None)

    def record_stream_interruption(self, model: str, endpoint: str) -> None:
        with self._lock:
            self._stream_interruptions[(model, endpoint)] += 1

    def record_fault_injected(self, kind: str) -> None:
        with self._lock:
            self._faults_injected[kind] += 1

    def record_rebalance_migration(self, reason: str, outcome: str) -> None:
        """One proactive migration attempt resolved by the rebalancer;
        reason is hotspot / drain / restart, outcome is success (stream now
        lives on the target), refused (target would not adopt; stream stayed
        put), aborted (mid-flight failure, fell back to the reactive resume
        path) or skipped (budget / window guard)."""
        with self._lock:
            self._rebalance_migrations[(reason, outcome)] += 1

    def observe_gossip_lag(self, seconds: float) -> None:
        """One-way delivery delay of one received gossip message."""
        with self._lock:
            self._gossip_lag.observe(max(0.0, seconds))

    def record_structured_request(self, kind: str) -> None:
        """One request asking for constrained decoding; `kind` is
        json_object / json_schema / tool_call."""
        with self._lock:
            self._structured_requests[kind] += 1

    def record_structured_rejected(self) -> None:
        """Gateway-side validation refused a structured request (400)."""
        with self._lock:
            self._structured_rejected += 1

    def record_lora_route(self, route: str) -> None:
        """One adapter-naming request routed: hot / load / rejected
        (docs/lora.md)."""
        with self._lock:
            self._lora_requests[route] += 1

    def record_handoff(self, outcome: str) -> None:
        """One orchestrated prefill→decode handoff; outcome is "adopted"
        (decode-capable endpoint took the stream) or "self" (fallback:
        the prefill endpoint adopted its own payload)."""
        with self._lock:
            self._handoffs[outcome] += 1

    def record_ratelimit_rejection(self, reason: str) -> None:
        """One 429 from the per-key token buckets; reason is 'requests'
        (rps bucket) or 'tokens' (tokens/minute bucket)."""
        with self._lock:
            self._ratelimit_rejections[reason] += 1

    def record_deadline_shed(self, model: str) -> None:
        """A request shed at the gateway because its deadline had already
        passed (queue wait ate the budget) — no prefill was burned."""
        with self._lock:
            self._deadline_shed[model] += 1

    def record_stream_write_timeout(self, model: str) -> None:
        """A stream aborted because the client stopped draining it for
        longer than the write timeout (slow-loris protection)."""
        with self._lock:
            self._stream_write_timeouts[model] += 1

    def record_stream_resume(self, outcome: str) -> None:
        """One mid-stream resume attempt resolved; outcome is "success"
        (continuation spliced) or the give-up reason (exhausted / budget /
        no_endpoint / failed)."""
        with self._lock:
            self._stream_resumes[outcome] += 1

    def record_stream_resumed_tokens(self, model: str, n: int) -> None:
        """Committed tokens replayed onto the resuming engine."""
        if n <= 0:
            return
        with self._lock:
            self._stream_resumed_tokens[model] += n

    def record_slo(self, model: str, ttft_s: float | None,
                   itl_mean_s: float | None,
                   priority: str | None = None) -> None:
        """Judge one SUCCESSFUL inference request against its model's SLO
        targets. `ttft_s` is client-observed time to first byte/response;
        `itl_mean_s` is the mean inter-token gap over the stream (None for
        non-streaming or single-token responses — only the TTFT target
        applies then). Failed requests are never goodput, but they are
        already counted by errors_total; this ledger answers the narrower
        'of the requests that succeeded, how many were fast enough'."""
        if self.slo is None or not self.slo.enabled or ttft_s is None:
            return
        ttft_target, itl_target = self.slo.targets_for(model)
        ttft_miss = ttft_s > ttft_target
        itl_miss = itl_mean_s is not None and itl_mean_s > itl_target
        with self._lock:
            self._slo_eligible[model] += 1
            if ttft_miss:
                self._slo_ttft_miss[model] += 1
            if itl_miss:
                self._slo_itl_miss[model] += 1
            if not (ttft_miss or itl_miss):
                self._slo_met[model] += 1
            if priority is not None:
                self._slo_prio_eligible[priority] += 1
                if not (ttft_miss or itl_miss):
                    self._slo_prio_met[priority] += 1

    def _observe(self, table: dict, buckets: tuple[float, ...],
                 model: str, endpoint: str, seconds: float) -> None:
        with self._lock:
            hist = table.get((model, endpoint))
            if hist is None:
                hist = table[(model, endpoint)] = Histogram(buckets)
            hist.observe(seconds)

    def record_ttft(self, model: str, endpoint: str, seconds: float) -> None:
        self._observe(self._ttft, TTFT_BUCKETS, model, endpoint, seconds)

    def record_e2e(self, model: str, endpoint: str, seconds: float) -> None:
        self._observe(self._e2e, E2E_BUCKETS, model, endpoint, seconds)

    def record_queue_wait(self, model: str, endpoint: str,
                          seconds: float) -> None:
        self._observe(self._queue_wait, QUEUE_WAIT_BUCKETS, model, endpoint,
                      seconds)

    # ----------------------------------------------------------- exposition

    def summary(self) -> dict:
        """Compact JSON figures (bench tooling + dashboard overview)."""
        with self._lock:
            def pcts(table: dict) -> dict:
                merged: Histogram | None = None
                for hist in table.values():
                    if merged is None:
                        merged = Histogram(hist.edges)
                    for i, c in enumerate(hist.counts):
                        merged.counts[i] += c
                    merged.total += hist.total
                    merged.n += hist.n
                    merged.max = max(merged.max, hist.max)
                if merged is None:
                    return {"p50": None, "p99": None, "count": 0}
                return {"p50": merged.percentile(50),
                        "p99": merged.percentile(99), "count": merged.n}

            return {
                "requests_total": sum(self._requests.values()),
                "errors_total": sum(self._errors.values()),
                "retries_total": sum(self._retries.values()),
                "queue_timeouts_total": sum(self._queue_timeouts.values()),
                "failover_retries_total": sum(self._failover_retries.values()),
                "failover_recoveries_total":
                    sum(self._failover_recoveries.values()),
                "stream_interruptions_total":
                    sum(self._stream_interruptions.values()),
                "faults_injected_total": sum(self._faults_injected.values()),
                "structured_requests_total":
                    sum(self._structured_requests.values()),
                "structured_rejected_total": self._structured_rejected,
                "lora_requests_total": sum(self._lora_requests.values()),
                "handoffs_total": sum(self._handoffs.values()),
                "slo_eligible_total": sum(self._slo_eligible.values()),
                "slo_met_total": sum(self._slo_met.values()),
                "ratelimit_rejections_total":
                    sum(self._ratelimit_rejections.values()),
                "deadline_shed_total": sum(self._deadline_shed.values()),
                "stream_write_timeouts_total":
                    sum(self._stream_write_timeouts.values()),
                "stream_resumes": dict(self._stream_resumes),
                "rebalance_migrations": {
                    f"{reason}/{outcome}": n
                    for (reason, outcome), n
                    in sorted(self._rebalance_migrations.items())
                },
                "stream_resumed_tokens_total":
                    sum(self._stream_resumed_tokens.values()),
                "goodput_by_priority": {
                    prio: round(self._slo_prio_met.get(prio, 0) / n, 4)
                    for prio, n in self._slo_prio_eligible.items() if n
                },
                "goodput_ratio": (
                    round(sum(self._slo_met.values())
                          / sum(self._slo_eligible.values()), 4)
                    if self._slo_eligible else None
                ),
                "ttft_s": pcts(self._ttft),
                "e2e_s": pcts(self._e2e),
                "queue_wait_s": pcts(self._queue_wait),
            }

    def render(self, *, gauges: dict[str, float] | None = None,
               counters: dict[str, float] | None = None) -> str:
        """Prometheus text exposition. `gauges`/`counters` hold scrape-time
        figures owned elsewhere (load manager, admission queue, event bus)."""
        with self._lock:
            lines = ["# TYPE llmlb_gateway_requests_total counter"]
            for (route, status), n in sorted(self._requests.items()):
                lines.append(
                    f'llmlb_gateway_requests_total{{route="{_escape(route)}",'
                    f'status="{status}"}} {n}'
                )
            lines.append("# TYPE llmlb_gateway_errors_total counter")
            for route, n in sorted(self._errors.items()):
                lines.append(
                    f'llmlb_gateway_errors_total{{route="{_escape(route)}"}} {n}'
                )
            lines.append("# TYPE llmlb_gateway_retries_total counter")
            for api, n in sorted(self._retries.items()):
                lines.append(
                    f'llmlb_gateway_retries_total{{api="{_escape(api)}"}} {n}'
                )
            lines.append("# TYPE llmlb_gateway_queue_timeouts_total counter")
            for model, n in sorted(self._queue_timeouts.items()):
                lines.append(
                    f'llmlb_gateway_queue_timeouts_total'
                    f'{{model="{_escape(model)}"}} {n}'
                )
            lines.append(
                "# TYPE llmlb_gateway_failover_retries_total counter"
            )
            for (model, reason), n in sorted(self._failover_retries.items()):
                lines.append(
                    f'llmlb_gateway_failover_retries_total'
                    f'{{model="{_escape(model)}",reason="{_escape(reason)}"}}'
                    f' {n}'
                )
            lines.append(
                "# TYPE llmlb_gateway_failover_recoveries_total counter"
            )
            for model, n in sorted(self._failover_recoveries.items()):
                lines.append(
                    f'llmlb_gateway_failover_recoveries_total'
                    f'{{model="{_escape(model)}"}} {n}'
                )
            lines.append(
                "# TYPE llmlb_gateway_retry_budget_exhausted_total counter"
            )
            lines.append(
                f"llmlb_gateway_retry_budget_exhausted_total "
                f"{self._retry_budget_exhausted}"
            )
            lines.append(
                "# TYPE llmlb_gateway_breaker_transitions_total counter"
            )
            for (endpoint, to), n in sorted(self._breaker_transitions.items()):
                lines.append(
                    f'llmlb_gateway_breaker_transitions_total'
                    f'{{endpoint="{_escape(endpoint)}",to="{_escape(to)}"}}'
                    f' {n}'
                )
            lines.append("# TYPE llmlb_gateway_breaker_state gauge")
            for endpoint, code in sorted(self._breaker_state.items()):
                lines.append(
                    f'llmlb_gateway_breaker_state'
                    f'{{endpoint="{_escape(endpoint)}"}} {code}'
                )
            lines.append(
                "# TYPE llmlb_gateway_stream_interruptions_total counter"
            )
            for (model, endpoint), n in sorted(
                self._stream_interruptions.items()
            ):
                lines.append(
                    f'llmlb_gateway_stream_interruptions_total'
                    f'{{model="{_escape(model)}",'
                    f'endpoint="{_escape(endpoint)}"}} {n}'
                )
            lines.append("# TYPE llmlb_gateway_faults_injected_total counter")
            for kind, n in sorted(self._faults_injected.items()):
                lines.append(
                    f'llmlb_gateway_faults_injected_total'
                    f'{{kind="{_escape(kind)}"}} {n}'
                )
            lines.append(
                "# TYPE llmlb_gateway_rebalance_migrations_total counter"
            )
            for (reason, outcome), n in sorted(
                self._rebalance_migrations.items()
            ):
                lines.append(
                    f'llmlb_gateway_rebalance_migrations_total'
                    f'{{reason="{_escape(reason)}",'
                    f'outcome="{_escape(outcome)}"}} {n}'
                )
            lines.append("# TYPE llmlb_gateway_gossip_delay_seconds histogram")
            if self._gossip_lag.n > 0:
                cumulative = 0
                for i, edge in enumerate(self._gossip_lag.edges):
                    cumulative += self._gossip_lag.counts[i]
                    lines.append(
                        f'llmlb_gateway_gossip_delay_seconds_bucket'
                        f'{{le="{edge}"}} {cumulative}'
                    )
                cumulative += self._gossip_lag.counts[-1]
                lines.append(
                    f'llmlb_gateway_gossip_delay_seconds_bucket'
                    f'{{le="+Inf"}} {cumulative}'
                )
                lines.append(
                    f"llmlb_gateway_gossip_delay_seconds_sum "
                    f"{self._gossip_lag.total}"
                )
                lines.append(
                    f"llmlb_gateway_gossip_delay_seconds_count "
                    f"{self._gossip_lag.n}"
                )
            lines.append(
                "# TYPE llmlb_gateway_structured_requests_total counter"
            )
            for kind, n in sorted(self._structured_requests.items()):
                lines.append(
                    f'llmlb_gateway_structured_requests_total'
                    f'{{kind="{_escape(kind)}"}} {n}'
                )
            lines.append(
                "# TYPE llmlb_gateway_structured_rejected_total counter"
            )
            lines.append(
                f"llmlb_gateway_structured_rejected_total "
                f"{self._structured_rejected}"
            )
            lines.append(
                "# TYPE llmlb_gateway_lora_requests_total counter"
            )
            for route, n in sorted(self._lora_requests.items()):
                lines.append(
                    f'llmlb_gateway_lora_requests_total'
                    f'{{route="{_escape(route)}"}} {n}'
                )
            lines.append(
                "# TYPE llmlb_gateway_handoffs_total counter"
            )
            for outcome, n in sorted(self._handoffs.items()):
                lines.append(
                    f'llmlb_gateway_handoffs_total'
                    f'{{outcome="{_escape(outcome)}"}} {n}'
                )
            for fam, table in (
                ("llmlb_gateway_slo_eligible_total", self._slo_eligible),
                ("llmlb_gateway_slo_met_total", self._slo_met),
                ("llmlb_gateway_slo_ttft_miss_total", self._slo_ttft_miss),
                ("llmlb_gateway_slo_itl_miss_total", self._slo_itl_miss),
            ):
                lines.append(f"# TYPE {fam} counter")
                for model, n in sorted(table.items()):
                    lines.append(f'{fam}{{model="{_escape(model)}"}} {n}')
            lines.append("# TYPE llmlb_gateway_goodput_ratio gauge")
            for model, eligible in sorted(self._slo_eligible.items()):
                if eligible > 0:
                    ratio = self._slo_met.get(model, 0) / eligible
                    lines.append(
                        f'llmlb_gateway_goodput_ratio'
                        f'{{model="{_escape(model)}"}} {round(ratio, 6)}'
                    )
            for fam, table in (
                ("llmlb_gateway_slo_priority_eligible_total",
                 self._slo_prio_eligible),
                ("llmlb_gateway_slo_priority_met_total", self._slo_prio_met),
            ):
                lines.append(f"# TYPE {fam} counter")
                for prio, n in sorted(table.items()):
                    lines.append(
                        f'{fam}{{priority="{_escape(prio)}"}} {n}'
                    )
            lines.append(
                "# TYPE llmlb_gateway_goodput_by_priority gauge"
            )
            for prio, eligible in sorted(self._slo_prio_eligible.items()):
                if eligible > 0:
                    ratio = self._slo_prio_met.get(prio, 0) / eligible
                    lines.append(
                        f'llmlb_gateway_goodput_by_priority'
                        f'{{priority="{_escape(prio)}"}} {round(ratio, 6)}'
                    )
            lines.append(
                "# TYPE llmlb_gateway_ratelimit_rejections_total counter"
            )
            for reason, n in sorted(self._ratelimit_rejections.items()):
                lines.append(
                    f'llmlb_gateway_ratelimit_rejections_total'
                    f'{{reason="{_escape(reason)}"}} {n}'
                )
            lines.append("# TYPE llmlb_gateway_deadline_shed_total counter")
            for model, n in sorted(self._deadline_shed.items()):
                lines.append(
                    f'llmlb_gateway_deadline_shed_total'
                    f'{{model="{_escape(model)}"}} {n}'
                )
            lines.append(
                "# TYPE llmlb_gateway_stream_write_timeouts_total counter"
            )
            for model, n in sorted(self._stream_write_timeouts.items()):
                lines.append(
                    f'llmlb_gateway_stream_write_timeouts_total'
                    f'{{model="{_escape(model)}"}} {n}'
                )
            lines.append(
                "# TYPE llmlb_gateway_stream_resumes_total counter"
            )
            for outcome, n in sorted(self._stream_resumes.items()):
                lines.append(
                    f'llmlb_gateway_stream_resumes_total'
                    f'{{outcome="{_escape(outcome)}"}} {n}'
                )
            lines.append(
                "# TYPE llmlb_gateway_stream_resumed_tokens_total counter"
            )
            for model, n in sorted(self._stream_resumed_tokens.items()):
                lines.append(
                    f'llmlb_gateway_stream_resumed_tokens_total'
                    f'{{model="{_escape(model)}"}} {n}'
                )
            for name, table in (
                ("llmlb_gateway_ttft_seconds", self._ttft),
                ("llmlb_gateway_e2e_seconds", self._e2e),
                ("llmlb_gateway_queue_wait_seconds", self._queue_wait),
            ):
                lines.append(f"# TYPE {name} histogram")
                for (model, endpoint), hist in sorted(table.items()):
                    labels = (f'model="{_escape(model)}",'
                              f'endpoint="{_escape(endpoint)}"')
                    cumulative = 0
                    for i, edge in enumerate(hist.edges):
                        cumulative += hist.counts[i]
                        lines.append(
                            f'{name}_bucket{{{labels},le="{edge}"}} '
                            f'{cumulative}'
                        )
                    cumulative += hist.counts[-1]
                    lines.append(
                        f'{name}_bucket{{{labels},le="+Inf"}} {cumulative}'
                    )
                    lines.append(f"{name}_sum{{{labels}}} {hist.total}")
                    lines.append(f"{name}_count{{{labels}}} {hist.n}")
            for cname, value in sorted((counters or {}).items()):
                lines.append(f"# TYPE {cname} counter")
                lines.append(f"{cname} {value}")
            for gname, value in sorted((gauges or {}).items()):
                lines.append(f"# TYPE {gname} gauge")
                lines.append(f"{gname} {value}")
            return "\n".join(lines) + "\n"
