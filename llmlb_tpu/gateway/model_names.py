"""Canonical ↔ engine-local model name mapping.

Same job as reference models/mapping.rs:22-422 (resolve_canonical_any :422,
resolve_engine_name :302): runtimes name the same model differently ("llama3:8b"
on Ollama vs "meta-llama/Meta-Llama-3-8B-Instruct" as a HF repo vs a GGUF file
name on LM Studio). The gateway exposes one canonical name and rewrites the
`model` field to the engine-local alias before proxying. Table-driven with
quantization-suffix parsing; unknown names canonicalize to themselves.
"""

from __future__ import annotations

import re

# canonical -> {endpoint_type_value: engine alias}
_KNOWN: dict[str, dict[str, str]] = {
    "meta-llama/Meta-Llama-3-8B-Instruct": {
        "ollama": "llama3:8b",
        "lm_studio": "meta-llama-3-8b-instruct",
        "tpu": "llama-3-8b",
    },
    "meta-llama/Llama-3.1-8B-Instruct": {
        "ollama": "llama3.1:8b",
        "tpu": "llama-3.1-8b",
    },
    "Qwen/Qwen2.5-0.5B-Instruct": {
        "ollama": "qwen2.5:0.5b",
        "tpu": "qwen2.5-0.5b",
    },
    "mistralai/Mistral-7B-Instruct-v0.3": {
        "ollama": "mistral:7b",
    },
    "openai/whisper-large-v3": {
        "tpu": "whisper-large-v3",
    },
    "stabilityai/stable-diffusion-xl-base-1.0": {
        "tpu": "sdxl",
    },
    "openai/gpt-oss-20b": {
        "ollama": "gpt-oss:20b",
    },
    "meta-llama/Llama-3.2-1B-Instruct": {
        "ollama": "llama3.2:1b",
        "tpu": "llama-3.2-1b",
    },
    "meta-llama/Llama-3.2-3B-Instruct": {
        "ollama": "llama3.2:3b",
    },
    "meta-llama/Llama-2-7b-chat-hf": {
        "ollama": "llama2:7b",
    },
    "mistralai/Mixtral-8x7B-Instruct-v0.1": {
        "ollama": "mixtral:8x7b",
        "tpu": "mixtral-8x7b",
    },
    "Qwen/Qwen2.5-7B-Instruct": {
        "ollama": "qwen2.5:7b",
    },
    "Qwen/Qwen2.5-Coder-7B-Instruct": {
        "ollama": "qwen2.5-coder:7b",
    },
    "google/gemma-2-9b-it": {
        "ollama": "gemma2:9b",
    },
    "microsoft/Phi-3-mini-4k-instruct": {
        "ollama": "phi3:mini",
    },
    "deepseek-ai/DeepSeek-R1-Distill-Qwen-7B": {
        "ollama": "deepseek-r1:7b",
    },
    "TinyLlama/TinyLlama-1.1B-Chat-v1.0": {
        "ollama": "tinyllama:1.1b",
        "tpu": "tinyllama-1.1b",
    },
    "BAAI/bge-m3": {
        "ollama": "bge-m3",
    },
    "nomic-ai/nomic-embed-text-v1.5": {
        "ollama": "nomic-embed-text",
    },
}

# family token -> HF org, for repo guessing on unknown names
# (same job as the reference's HF-repo guess tables, models/mapping.rs)
_FAMILY_ORGS = [
    ("llama", "meta-llama"),
    ("tinyllama", "TinyLlama"),
    ("mixtral", "mistralai"),
    ("mistral", "mistralai"),
    ("qwen", "Qwen"),
    ("gemma", "google"),
    ("phi", "microsoft"),
    ("deepseek", "deepseek-ai"),
    ("whisper", "openai"),
    ("gpt-oss", "openai"),
    ("stable-diffusion", "stabilityai"),
    ("sdxl", "stabilityai"),
    ("bge", "BAAI"),
    ("nomic-embed", "nomic-ai"),
]

_ALIAS_TO_CANONICAL: dict[str, str] = {}
for canonical, aliases in _KNOWN.items():
    _ALIAS_TO_CANONICAL[canonical.lower()] = canonical
    for alias in aliases.values():
        _ALIAS_TO_CANONICAL[alias.lower()] = canonical

_QUANT_SUFFIX = re.compile(
    r"[-_.](q[2-8](_[a-z0-9_]+)?|fp16|f16|bf16|int[48]|awq|gptq|gguf)$", re.I
)


def strip_quant_suffix(name: str) -> str:
    prev = None
    while prev != name:
        prev = name
        name = _QUANT_SUFFIX.sub("", name)
    return name


def to_canonical(name: str) -> str:
    """Resolve any alias (exact, case-insensitive, quant-stripped) to canonical;
    unknown names are their own canonical form."""
    if not name:
        return name
    hit = _ALIAS_TO_CANONICAL.get(name.lower())
    if hit:
        return hit
    stripped = strip_quant_suffix(name)
    hit = _ALIAS_TO_CANONICAL.get(stripped.lower())
    return hit or name


def to_engine_name(canonical: str, endpoint_type: str) -> str:
    """Engine-local alias for an endpoint type; falls back to the canonical."""
    aliases = _KNOWN.get(canonical)
    if aliases and endpoint_type in aliases:
        return aliases[endpoint_type]
    return canonical


def parse_engine_tag(name: str) -> dict:
    """Decompose an engine-style name ('llama3.1:8b-instruct-q4_K_M' or a
    GGUF filename) into family / size / variant / quant — the shape the
    reference's quant-suffix parser produces (api/model_name.rs)."""
    base = name
    if base.lower().endswith(".gguf"):
        base = base[:-5]
    quant = None
    m = _QUANT_SUFFIX.search(base)
    if m:
        quant = m.group(1)
        base = strip_quant_suffix(base)
    family, _, tag = base.partition(":")
    size = None
    variant = []
    for part in re.split(r"[-_.]", tag) if tag else []:
        if re.fullmatch(r"\d+(\.\d+)?[bBmM]", part):
            size = part.lower()
        elif part:
            variant.append(part.lower())
    return {
        "family": family.lower(),
        "size": size,
        "variant": "-".join(variant) or None,
        "quant": quant.lower() if quant else None,
    }


def guess_hf_repo(name: str) -> str | None:
    """Best-effort HF repo id for a bare model name: exact/alias table first,
    then family→org heuristics (catalog + download-flow helper)."""
    canonical = to_canonical(name)
    if "/" in canonical:
        return canonical
    lowered = strip_quant_suffix(canonical.lower().removesuffix(".gguf"))
    for token, org in _FAMILY_ORGS:
        if lowered.startswith(token) or f"-{token}" in lowered:
            bare = lowered.replace(":", "-")
            return f"{org}/{bare}"
    return None
