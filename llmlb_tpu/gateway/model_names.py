"""Canonical ↔ engine-local model name mapping.

Same job as reference models/mapping.rs:22-422 (resolve_canonical_any :422,
resolve_engine_name :302): runtimes name the same model differently ("llama3:8b"
on Ollama vs "meta-llama/Meta-Llama-3-8B-Instruct" as a HF repo vs a GGUF file
name on LM Studio). The gateway exposes one canonical name and rewrites the
`model` field to the engine-local alias before proxying. Table-driven with
quantization-suffix parsing; unknown names canonicalize to themselves.
"""

from __future__ import annotations

import re

# canonical -> {endpoint_type_value: engine alias}
_KNOWN: dict[str, dict[str, str]] = {
    "meta-llama/Meta-Llama-3-8B-Instruct": {
        "ollama": "llama3:8b",
        "lm_studio": "meta-llama-3-8b-instruct",
        "tpu": "llama-3-8b",
    },
    "meta-llama/Llama-3.1-8B-Instruct": {
        "ollama": "llama3.1:8b",
        "tpu": "llama-3.1-8b",
    },
    "Qwen/Qwen2.5-0.5B-Instruct": {
        "ollama": "qwen2.5:0.5b",
        "tpu": "qwen2.5-0.5b",
    },
    "mistralai/Mistral-7B-Instruct-v0.3": {
        "ollama": "mistral:7b",
    },
    "openai/whisper-large-v3": {
        "tpu": "whisper-large-v3",
    },
    "stabilityai/stable-diffusion-xl-base-1.0": {
        "tpu": "sdxl",
    },
    "openai/gpt-oss-20b": {
        "ollama": "gpt-oss:20b",
    },
}

_ALIAS_TO_CANONICAL: dict[str, str] = {}
for canonical, aliases in _KNOWN.items():
    _ALIAS_TO_CANONICAL[canonical.lower()] = canonical
    for alias in aliases.values():
        _ALIAS_TO_CANONICAL[alias.lower()] = canonical

_QUANT_SUFFIX = re.compile(
    r"[-_.](q[2-8](_[a-z0-9_]+)?|fp16|f16|bf16|int[48]|awq|gptq|gguf)$", re.I
)


def strip_quant_suffix(name: str) -> str:
    prev = None
    while prev != name:
        prev = name
        name = _QUANT_SUFFIX.sub("", name)
    return name


def to_canonical(name: str) -> str:
    """Resolve any alias (exact, case-insensitive, quant-stripped) to canonical;
    unknown names are their own canonical form."""
    if not name:
        return name
    hit = _ALIAS_TO_CANONICAL.get(name.lower())
    if hit:
        return hit
    stripped = strip_quant_suffix(name)
    hit = _ALIAS_TO_CANONICAL.get(stripped.lower())
    return hit or name


def to_engine_name(canonical: str, endpoint_type: str) -> str:
    """Engine-local alias for an endpoint type; falls back to the canonical."""
    aliases = _KNOWN.get(canonical)
    if aliases and endpoint_type in aliases:
        return aliases[endpoint_type]
    return canonical


def guess_hf_repo(name: str) -> str | None:
    """Best-effort HF repo id for a bare model name (catalog helper)."""
    canonical = to_canonical(name)
    if "/" in canonical:
        return canonical
    return None
