"""Model sync: pull an endpoint's model list and refresh the registry.

Parity with reference sync/ (sync_models_with_type sync/mod.rs:104, response
parsing sync/parser.rs:78, capability heuristics sync/capabilities.rs:47-57):
fetches /v1/models (OpenAI shape) or /api/tags (Ollama shape), maps engine
names to canonical names, detects capabilities from name heuristics, and
replaces the endpoint's model set in the registry.
"""

from __future__ import annotations

import logging

import aiohttp

from llmlb_tpu.gateway.model_names import to_canonical
from llmlb_tpu.gateway.registry import EndpointRegistry
from llmlb_tpu.gateway.types import Capability, Endpoint, EndpointModel, EndpointType

log = logging.getLogger("llmlb_tpu.gateway.sync")


def capabilities_from_meta(meta: dict) -> list[Capability] | None:
    """Explicit capability advertisement in a /v1/models entry (our tpu://
    engine emits this — engine/server.py list_models). Takes precedence over
    name heuristics; unknown capability strings are ignored."""
    raw = meta.get("capabilities")
    if not isinstance(raw, list):
        return None
    out = []
    for item in raw:
        try:
            out.append(Capability(str(item)))
        except ValueError:
            continue
    return out or None


def detect_capabilities(model_name: str) -> list[Capability]:
    """Name-based capability heuristics (parity: sync/capabilities.rs:47-57)."""
    lowered = model_name.lower()
    if "embed" in lowered or lowered.startswith("bge-") or "-bge" in lowered:
        return [Capability.EMBEDDINGS]
    if "whisper" in lowered:
        return [Capability.AUDIO_TRANSCRIPTION]
    if any(t in lowered for t in ("tts", "speech", "vibevoice", "bark")):
        return [Capability.AUDIO_SPEECH]
    if any(t in lowered for t in ("stable-diffusion", "sdxl", "sd-", "flux")):
        return [Capability.IMAGE_GENERATION]
    return [Capability.CHAT_COMPLETION]


def parse_models_response(body: dict) -> list[dict]:
    """Accept both OpenAI ({"data": [{"id": ...}]}) and Ollama ({"models":
    [{"name"|"model": ...}]}) shapes (parity: sync/parser.rs:78)."""
    models = []
    if isinstance(body.get("data"), list):
        for item in body["data"]:
            if isinstance(item, dict) and item.get("id"):
                models.append({"id": str(item["id"]), "meta": item})
    elif isinstance(body.get("models"), list):
        for item in body["models"]:
            if not isinstance(item, dict):
                continue
            name = item.get("name") or item.get("model")
            if name:
                models.append({"id": str(name), "meta": item})
    return models


async def fetch_endpoint_models(
    endpoint: Endpoint,
    session: aiohttp.ClientSession,
    timeout: float = 10.0,
) -> list[EndpointModel]:
    path = "/api/tags" if endpoint.endpoint_type == EndpointType.OLLAMA else "/v1/models"
    headers = {}
    if endpoint.api_key:
        headers["Authorization"] = f"Bearer {endpoint.api_key}"
    async with session.get(
        endpoint.url + path,
        headers=headers,
        timeout=aiohttp.ClientTimeout(total=timeout),
    ) as resp:
        if resp.status != 200:
            raise RuntimeError(f"{path} returned {resp.status}")
        body = await resp.json(content_type=None)
    if not isinstance(body, dict):
        raise RuntimeError(f"unexpected {path} payload")

    out = []
    for m in parse_models_response(body):
        engine_name = m["id"]
        context_length = None
        meta = m.get("meta") or {}
        for key in ("context_length", "max_context_length", "max_model_len"):
            if isinstance(meta.get(key), int):
                context_length = meta[key]
                break
        out.append(
            EndpointModel(
                endpoint_id=endpoint.id,
                model_id=engine_name,
                canonical_name=to_canonical(engine_name),
                capabilities=(
                    capabilities_from_meta(meta) or detect_capabilities(engine_name)
                ),
                context_length=context_length,
            )
        )
    return out


async def sync_endpoint_models(
    endpoint: Endpoint,
    registry: EndpointRegistry,
    session: aiohttp.ClientSession,
    timeout: float = 10.0,
) -> tuple[int, int]:
    """Returns (added, removed) vs the previous registry state."""
    from llmlb_tpu.gateway.engine_metadata import enrich_context_lengths

    models = await fetch_endpoint_models(endpoint, session, timeout)
    # per-engine metadata probes (Ollama /api/show etc.) fill in context
    # lengths the /v1/models listing did not carry
    await enrich_context_lengths(endpoint, models, session)
    before = {m.model_id for m in registry.models_for(endpoint.id)}
    after = {m.model_id for m in models}
    registry.sync_models(endpoint.id, models)
    return len(after - before), len(before - after)
