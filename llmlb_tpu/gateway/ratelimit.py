"""Per-API-key token-bucket rate limiting (docs/scheduling.md).

One greedy tenant must not be able to wreck p99 latency for everyone: the
gateway refuses its excess load with 429 + an honest Retry-After computed
from the bucket's refill rate, instead of queuing it in front of everyone
else's work. Two buckets per tenant:

- requests/second (burst-capped): debited 1 at admission.
- tokens/minute: the PROMPT estimate is debited at admission; completion
  tokens are debited after the response finishes (the bucket may go
  negative — a tenant that just streamed a huge completion throttles its
  own NEXT request, not the one already running).

State is worker-local, never gossiped. In a multi-worker gateway each
worker enforces ``limit / workers`` — conservative like retry budgets: the
group as a whole can never admit more than the configured rate, and
SO_REUSEPORT's accept spreading makes the per-worker share an even split
in practice (docs/deployment.md).

No reference counterpart: the reference gateway admits whoever shows up
first (ROADMAP open item 5 names this as the missing overload story).
"""

from __future__ import annotations

import threading
import time

from llmlb_tpu.gateway.config import RateLimitConfig


class TokenBucket:
    """Classic token bucket. ``take`` is check-and-debit; ``charge`` is an
    unconditional post-paid debit that may drive the level negative."""

    def __init__(self, rate_per_s: float, burst: float):
        self.rate = max(0.0, rate_per_s)
        self.burst = max(1.0, burst)
        self.level = self.burst
        self.ts = time.monotonic()

    def _refill(self, now: float) -> None:
        if self.rate <= 0:
            return
        self.level = min(self.burst, self.level + (now - self.ts) * self.rate)
        self.ts = now

    def take(self, cost: float, now: float | None = None) -> float:
        """Debit ``cost`` if covered; returns 0.0 on success, else the
        seconds until the bucket refills enough (the Retry-After figure)."""
        now = time.monotonic() if now is None else now
        self._refill(now)
        if self.level >= cost:
            self.level -= cost
            return 0.0
        if self.rate <= 0:
            return 60.0  # burst-only bucket that cannot refill: back off
        return (cost - self.level) / self.rate

    def charge(self, cost: float, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        self._refill(now)
        self.level -= cost  # may go negative: throttles the next take


class RateVerdict:
    __slots__ = ("allowed", "retry_after_s", "reason")

    def __init__(self, allowed: bool, retry_after_s: float = 0.0,
                 reason: str | None = None):
        self.allowed = allowed
        self.retry_after_s = retry_after_s
        self.reason = reason  # "requests" | "tokens"


_ALLOW = RateVerdict(True)


class RateLimiter:
    """Tenant-keyed bucket pairs. Thread-safe; zero work when disabled."""

    # A tenant idle this long has full buckets anyway: drop its entry so
    # the map does not grow one pair per key ever seen.
    IDLE_EVICT_S = 900.0

    def __init__(self, config: RateLimitConfig, workers: int = 1):
        self.config = config
        self.workers = max(1, int(workers))
        self._lock = threading.Lock()
        # tenant id -> (rps bucket | None, tpm bucket | None, last_used)
        self._buckets: dict[str, list] = {}

    @property
    def enabled(self) -> bool:
        return self.config.enabled

    def _limits_for(self, name: str | None) -> tuple[float, float, float]:
        """(rps, burst, tpm) for a tenant, overrides by key name first. A
        key PRESENT in the override wins even at 0 ("unlimited" — how a
        trusted key is exempted from the global default); an ABSENT key
        inherits the global. Divided by the worker count: each worker
        enforces its share."""
        cfg = self.config
        rps, burst, tpm = cfg.requests_per_s, cfg.burst, cfg.tokens_per_min
        ov = cfg.overrides.get(name or "")
        if ov is not None:
            rps = float(ov["rps"]) if "rps" in ov else rps
            burst = float(ov["burst"]) if "burst" in ov else burst
            tpm = float(ov["tpm"]) if "tpm" in ov else tpm
        w = self.workers
        return rps / w, (burst / w if burst > 0 else 0.0), tpm / w

    def _pair(self, tenant: str, name: str | None):
        got = self._buckets.get(tenant)
        if got is not None:
            got[2] = time.monotonic()
            return got
        rps, burst, tpm = self._limits_for(name)
        rps_bucket = (TokenBucket(rps, burst or max(1.0, 2 * rps))
                      if rps > 0 else None)
        tpm_bucket = (TokenBucket(tpm / 60.0, tpm) if tpm > 0 else None)
        got = [rps_bucket, tpm_bucket, time.monotonic()]
        self._buckets[tenant] = got
        if len(self._buckets) > 4096:
            self._evict_idle()
        return got

    def _evict_idle(self) -> None:
        cutoff = time.monotonic() - self.IDLE_EVICT_S
        for t in [t for t, b in self._buckets.items() if b[2] < cutoff]:
            del self._buckets[t]

    def acquire(self, tenant: str, name: str | None = None,
                est_tokens: int = 0) -> RateVerdict:
        """Admission check for one request: 1 from the request bucket plus
        the prompt-token estimate from the token bucket. Refusal debits
        nothing (a 429'd request consumed no engine work)."""
        if not self.enabled:
            return _ALLOW
        with self._lock:
            rps_bucket, tpm_bucket, _ = self._pair(tenant, name)
            if rps_bucket is not None:
                wait = rps_bucket.take(1.0)
                if wait > 0:
                    return RateVerdict(False, wait, "requests")
            if tpm_bucket is not None:
                wait = tpm_bucket.take(float(max(0, est_tokens)))
                if wait > 0:
                    if rps_bucket is not None:
                        rps_bucket.level += 1.0  # roll back the paired debit
                    return RateVerdict(False, wait, "tokens")
        return _ALLOW

    def charge_tokens(self, tenant: str, tokens: int,
                      name: str | None = None) -> None:
        """Post-paid debit of completion tokens (post-response truth the
        admission estimate could not know)."""
        if not self.enabled or tokens <= 0:
            return
        with self._lock:
            _, tpm_bucket, _ = self._pair(tenant, name)
            if tpm_bucket is not None:
                tpm_bucket.charge(float(tokens))

    def snapshot(self) -> dict:
        """Live figures for /api/health."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "tenants_tracked": len(self._buckets),
                "workers_divisor": self.workers,
                "defaults": {
                    "rps": self.config.requests_per_s,
                    "burst": self.config.burst,
                    "tpm": self.config.tokens_per_min,
                },
                "overrides": dict(self.config.overrides),
            }
