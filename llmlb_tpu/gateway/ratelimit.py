"""Per-API-key token-bucket rate limiting (docs/scheduling.md).

One greedy tenant must not be able to wreck p99 latency for everyone: the
gateway refuses its excess load with 429 + an honest Retry-After computed
from the bucket's refill rate, instead of queuing it in front of everyone
else's work. Two buckets per tenant:

- requests/second (burst-capped): debited 1 at admission.
- tokens/minute: the PROMPT estimate is debited at admission; completion
  tokens are debited after the response finishes (the bucket may go
  negative — a tenant that just streamed a huge completion throttles its
  own NEXT request, not the one already running).

Two enforcement modes:

- **Local share** (no gossip): each worker enforces ``limit / workers`` —
  conservative like retry budgets: the group as a whole can never admit
  more than the configured rate, and SO_REUSEPORT's accept spreading makes
  the per-worker share an even split in practice (docs/deployment.md).
- **Global buckets** (gossip attached via ``attach_gossip``): every worker
  holds FULL-limit buckets and batches its admissions into ``rl_spend``
  gossip; receivers charge their own buckets by the delta (unconditionally
  — levels may go negative), so a tenant at rps=N is admitted ≈N across
  the whole fleet instead of N×workers. Gossip loss only makes the limit
  temporarily more generous, never unsafe for correctness — and the bus
  dropping entirely degrades to independent full-limit workers, which the
  operator sees on the gossip_partition_suspected gauge. LLMLB_GOSSIP=0
  keeps the conservative local-share mode.

No reference counterpart: the reference gateway admits whoever shows up
first (ROADMAP open item 5 names this as the missing overload story).
"""

from __future__ import annotations

import threading
import time

from llmlb_tpu.gateway.config import RateLimitConfig

# Global mode: batch locally admitted spends and flush to the bus at most
# this often (plus the bus heartbeat as a floor when traffic is idle) — one
# datagram per interval per worker, not one per request.
RL_SPEND_FLUSH_S = 0.25


class TokenBucket:
    """Classic token bucket. ``take`` is check-and-debit; ``charge`` is an
    unconditional post-paid debit that may drive the level negative."""

    def __init__(self, rate_per_s: float, burst: float):
        self.rate = max(0.0, rate_per_s)
        self.burst = max(1.0, burst)
        self.level = self.burst
        self.ts = time.monotonic()

    def _refill(self, now: float) -> None:
        if self.rate <= 0:
            return
        self.level = min(self.burst, self.level + (now - self.ts) * self.rate)
        self.ts = now

    def take(self, cost: float, now: float | None = None) -> float:
        """Debit ``cost`` if covered; returns 0.0 on success, else the
        seconds until the bucket refills enough (the Retry-After figure)."""
        now = time.monotonic() if now is None else now
        self._refill(now)
        if self.level >= cost:
            self.level -= cost
            return 0.0
        if self.rate <= 0:
            return 60.0  # burst-only bucket that cannot refill: back off
        return (cost - self.level) / self.rate

    def charge(self, cost: float, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        self._refill(now)
        self.level -= cost  # may go negative: throttles the next take


class RateVerdict:
    __slots__ = ("allowed", "retry_after_s", "reason")

    def __init__(self, allowed: bool, retry_after_s: float = 0.0,
                 reason: str | None = None):
        self.allowed = allowed
        self.retry_after_s = retry_after_s
        self.reason = reason  # "requests" | "tokens"


_ALLOW = RateVerdict(True)


class RateLimiter:
    """Tenant-keyed bucket pairs. Thread-safe; zero work when disabled."""

    # A tenant idle this long has full buckets anyway: drop its entry so
    # the map does not grow one pair per key ever seen.
    IDLE_EVICT_S = 900.0

    def __init__(self, config: RateLimitConfig, workers: int = 1):
        self.config = config
        self.workers = max(1, int(workers))
        self._lock = threading.Lock()
        # tenant id -> (rps bucket | None, tpm bucket | None, last_used)
        self._buckets: dict[str, list] = {}
        # Global mode (attach_gossip): the bus, plus spends admitted here
        # since the last flush — tenant -> [requests, tokens, key_name].
        self.gossip = None
        self._pending: dict[str, list] = {}
        self._last_flush = time.monotonic()
        self.remote_spends_applied = 0  # datagrams folded in (snapshot)

    @property
    def enabled(self) -> bool:
        return self.config.enabled

    @property
    def global_mode(self) -> bool:
        return self.gossip is not None

    def attach_gossip(self, bus) -> None:
        """Switch to fleet-global buckets: full limits locally, admissions
        replicated as rl_spend deltas. Resets tracked tenants — their
        buckets were sized for the per-worker share."""
        with self._lock:
            self.gossip = bus
            self._buckets.clear()
            self._pending.clear()
        bus.subscribe("rl_spend",
                      lambda d, m: self.apply_remote_spend(d["spends"]))
        # traffic-idle flush floor: pending spends never wait past one
        # heartbeat even if this worker admits nothing else
        bus.on_heartbeat.append(self.flush_spends)

    def _limits_for(self, name: str | None) -> tuple[float, float, float]:
        """(rps, burst, tpm) for a tenant, overrides by key name first. A
        key PRESENT in the override wins even at 0 ("unlimited" — how a
        trusted key is exempted from the global default); an ABSENT key
        inherits the global. Local-share mode divides by the worker count
        (each worker enforces its share); global mode uses full limits and
        relies on gossiped spends."""
        cfg = self.config
        rps, burst, tpm = cfg.requests_per_s, cfg.burst, cfg.tokens_per_min
        ov = cfg.overrides.get(name or "")
        if ov is not None:
            rps = float(ov["rps"]) if "rps" in ov else rps
            burst = float(ov["burst"]) if "burst" in ov else burst
            tpm = float(ov["tpm"]) if "tpm" in ov else tpm
        w = 1 if self.global_mode else self.workers
        return rps / w, (burst / w if burst > 0 else 0.0), tpm / w

    def _pair(self, tenant: str, name: str | None):
        got = self._buckets.get(tenant)
        if got is not None:
            got[2] = time.monotonic()
            return got
        rps, burst, tpm = self._limits_for(name)
        rps_bucket = (TokenBucket(rps, burst or max(1.0, 2 * rps))
                      if rps > 0 else None)
        tpm_bucket = (TokenBucket(tpm / 60.0, tpm) if tpm > 0 else None)
        got = [rps_bucket, tpm_bucket, time.monotonic()]
        self._buckets[tenant] = got
        if len(self._buckets) > 4096:
            self._evict_idle()
        return got

    def _evict_idle(self) -> None:
        cutoff = time.monotonic() - self.IDLE_EVICT_S
        for t in [t for t, b in self._buckets.items() if b[2] < cutoff]:
            del self._buckets[t]

    def acquire(self, tenant: str, name: str | None = None,
                est_tokens: int = 0) -> RateVerdict:
        """Admission check for one request: 1 from the request bucket plus
        the prompt-token estimate from the token bucket. Refusal debits
        nothing (a 429'd request consumed no engine work)."""
        if not self.enabled:
            return _ALLOW
        with self._lock:
            rps_bucket, tpm_bucket, _ = self._pair(tenant, name)
            if rps_bucket is not None:
                wait = rps_bucket.take(1.0)
                if wait > 0:
                    return RateVerdict(False, wait, "requests")
            if tpm_bucket is not None:
                wait = tpm_bucket.take(float(max(0, est_tokens)))
                if wait > 0:
                    if rps_bucket is not None:
                        rps_bucket.level += 1.0  # roll back the paired debit
                    return RateVerdict(False, wait, "tokens")
            if self.global_mode:
                self._note_spend_locked(tenant, name, 1, max(0, est_tokens))
        self.flush_spends()
        return _ALLOW

    def charge_tokens(self, tenant: str, tokens: int,
                      name: str | None = None) -> None:
        """Post-paid debit of completion tokens (post-response truth the
        admission estimate could not know)."""
        if not self.enabled or tokens <= 0:
            return
        with self._lock:
            _, tpm_bucket, _ = self._pair(tenant, name)
            if tpm_bucket is not None:
                tpm_bucket.charge(float(tokens))
            if self.global_mode:
                self._note_spend_locked(tenant, name, 0, tokens)
        self.flush_spends()

    # ---------------------------------------------------- global replication

    def _note_spend_locked(self, tenant: str, name: str | None,
                           reqs: int, tokens: int) -> None:
        entry = self._pending.setdefault(tenant, [0, 0, name or ""])
        entry[0] += reqs
        entry[1] += tokens

    def flush_spends(self, force: bool = False) -> None:
        """Publish batched spend deltas when the interval elapsed (or
        forced by tests/shutdown). Never called with the lock held —
        publish writes to sockets."""
        g = self.gossip
        if g is None:
            return
        now = time.monotonic()
        with self._lock:
            if not self._pending:
                return
            if not force and now - self._last_flush < RL_SPEND_FLUSH_S:
                return
            self._last_flush = now
            pending, self._pending = self._pending, {}
        g.publish("rl_spend", {
            "spends": {t: list(v) for t, v in pending.items()},
        })

    def apply_remote_spend(self, spends: dict) -> None:
        """A sibling's admitted spends: unconditional charges against our
        own full-limit buckets (levels may go negative — exactly how the
        post-paid completion debit already works), so the NEXT local
        admission sees fleet-wide consumption. Never re-gossips."""
        if not self.enabled or not isinstance(spends, dict):
            return
        with self._lock:
            self.remote_spends_applied += 1
            for tenant, value in spends.items():
                if not (isinstance(value, (list, tuple)) and len(value) >= 2):
                    continue
                reqs, tokens = float(value[0]), float(value[1])
                name = (str(value[2]) or None) if len(value) > 2 else None
                rps_bucket, tpm_bucket, _ = self._pair(str(tenant), name)
                if rps_bucket is not None and reqs > 0:
                    rps_bucket.charge(reqs)
                if tpm_bucket is not None and tokens > 0:
                    tpm_bucket.charge(tokens)

    def snapshot(self) -> dict:
        """Live figures for /api/health."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "tenants_tracked": len(self._buckets),
                "global": self.global_mode,
                "workers_divisor": 1 if self.global_mode else self.workers,
                "remote_spends_applied": self.remote_spends_applied,
                "defaults": {
                    "rps": self.config.requests_per_s,
                    "burst": self.config.burst,
                    "tpm": self.config.tokens_per_min,
                },
                "overrides": dict(self.config.overrides),
            }
