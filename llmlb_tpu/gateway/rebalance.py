"""Proactive live-stream rebalancing (ROADMAP item 3, the fleet half).

PR 12 made moving a LIVE stream a gateway decision (`/v1/resume`) and PR 17
made it O(bytes) via KV page shipping — but only *failure* pulled that
trigger. This module adds the planner: a loop in the elected primary worker
that watches per-endpoint occupancy, queue depth, TPS EMAs and SLO goodput,
and when engine A is overloaded while engine B sits idle, migrates live
streams A→B through the existing resume + KV-export path while the client
keeps streaming. Drain, rolling restart, autoscale-down and hot-spot
dissipation are all the same mechanism with a different `reason` label:
an engine that advertises draining gets evacuated; an overloaded one gets
bled down to the hysteresis band.

Split of responsibilities:

- ``Rebalancer`` (primary worker only): scores endpoints, applies hysteresis
  and the migration budget, and issues directives — locally to its own
  ``StreamDirectory`` and over gossip (``migrate``) so sibling workers move
  their streams too. Directives are advisory like all gossip: a worker that
  misses one just keeps serving from the hot engine until the next tick.
- ``StreamDirectory`` (every worker): the worker's live streams by gateway
  request id. The streaming pump (api_openai) checks its handle at frame
  boundaries and performs the actual migration; a refused or failed adopt
  aborts instantly to the reactive failover path with the origin unharmed.

Safety rails (docs/resilience.md):
  hysteresis bands   — migrate only when source ≥ high AND target ≤ low for
                       consecutive ticks; a source between bands is left
                       alone, so load noise cannot thrash streams.
  migration budget   — at most `max_concurrent` in flight and `per_minute`
                       stream moves per minute, fleet-directive-side.
  per-stream window  — the same stream is never migrated twice within
                       `stream_window_s`.
  SLO gate           — hot-spot migrations are skipped entirely while the
                       fleet's goodput ratio is healthy and the hot engine
                       has no queue: visible pain first, churn second.
``LLMLB_REBALANCE=0`` disables registration and the loop — bit-compatible
with the pre-rebalancer gateway.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import threading
import time
from collections import deque

from llmlb_tpu.gateway.config import env_bool, env_float, env_int

log = logging.getLogger("llmlb_tpu.gateway.rebalance")

# Consecutive ticks a source must hold above the high band before a hotspot
# directive fires — one noisy probe sample must not move a stream.
HYSTERESIS_TICKS = 2

# When telemetry gives no slot count, assume this capacity for the
# occupancy score (matches the engine default of 8 decode slots).
DEFAULT_SLOTS = 8


@dataclasses.dataclass(frozen=True)
class RebalanceConfig:
    enabled: bool = True
    interval_s: float = 2.0
    # occupancy bands: (active_slots + queued) / num_slots
    high_water: float = 0.85
    low_water: float = 0.4
    max_concurrent: int = 2
    per_minute: int = 6
    stream_window_s: float = 60.0
    # hot-spot migrations are suppressed while fleet goodput holds at or
    # above this ratio AND the hot engine has an empty queue (1.0 = always
    # willing to migrate; SLO accounting disabled = gate inert)
    min_goodput: float = 0.98

    @classmethod
    def from_env(cls) -> "RebalanceConfig":
        return cls(
            enabled=env_bool("LLMLB_REBALANCE", True),
            interval_s=env_float("LLMLB_REBALANCE_INTERVAL", 2.0),
            high_water=env_float("LLMLB_REBALANCE_HIGH", 0.85),
            low_water=env_float("LLMLB_REBALANCE_LOW", 0.4),
            max_concurrent=env_int("LLMLB_REBALANCE_MAX_CONCURRENT", 2),
            per_minute=env_int("LLMLB_REBALANCE_PER_MINUTE", 6),
            stream_window_s=env_float("LLMLB_REBALANCE_STREAM_WINDOW", 60.0),
            min_goodput=env_float("LLMLB_REBALANCE_MIN_GOODPUT", 0.98),
        )


class StreamHandle:
    """One live stream this worker is pumping. The pump owns the handle;
    the directory (gossip/rebalancer side) only ever sets `pending` —
    single-writer per field, so a directive racing a natural finish cannot
    corrupt anything: the pump simply never looks again."""

    __slots__ = ("rid", "model", "endpoint_id", "started_at", "migrations",
                 "last_migrate_at", "pending", "migrating", "done")

    def __init__(self, rid: str, model: str, endpoint_id: str):
        self.rid = rid
        self.model = model
        self.endpoint_id = endpoint_id
        self.started_at = time.monotonic()
        self.migrations = 0
        self.last_migrate_at = 0.0
        # (target_eid, reason, directive_id) | None — set by the directory,
        # claimed by the pump at a frame boundary
        self.pending: tuple | None = None
        self.migrating = False  # claimed and in flight
        self.done = False


class StreamDirectory:
    """Live streams on THIS worker, keyed by gateway request id. The pump
    registers on stream start and unregisters in its finally block; the
    rebalancer (local tick or a gossiped directive) marks handles pending."""

    def __init__(self, config: RebalanceConfig | None = None):
        self.config = config or RebalanceConfig.from_env()
        self._lock = threading.Lock()
        self._streams: dict[str, StreamHandle] = {}

    def register(self, rid: str, model: str,
                 endpoint_id: str) -> StreamHandle | None:
        if not self.config.enabled:
            return None  # LLMLB_REBALANCE=0: invisible, bit-compatible
        handle = StreamHandle(rid, model, endpoint_id)
        with self._lock:
            self._streams[rid] = handle
        return handle

    def unregister(self, handle: StreamHandle | None) -> None:
        """Stream finished (naturally or not). A directive that raced the
        finish dies here un-acted-on — no orphaned lease, no accounting."""
        if handle is None:
            return
        handle.done = True
        handle.pending = None
        with self._lock:
            self._streams.pop(handle.rid, None)

    def claim(self, handle: StreamHandle) -> tuple | None:
        """Pump-side: atomically take a pending directive (returns
        (target, reason, directive_id) or None). The claim moves the handle
        into `migrating` until note_outcome resolves it."""
        with self._lock:
            pending = handle.pending
            if pending is None or handle.done:
                return None
            handle.pending = None
            handle.migrating = True
            return pending

    def note_outcome(self, handle: StreamHandle, *, success: bool,
                     target: str | None = None) -> None:
        """Pump-side: migration resolved. Success re-homes the handle; any
        outcome stamps the window so the next directive skips this stream."""
        with self._lock:
            handle.migrating = False
            handle.last_migrate_at = time.monotonic()
            if success and target:
                handle.endpoint_id = target
                handle.migrations += 1

    def apply_directive(self, eid: str, target: str, reason: str,
                        max_streams: int, directive_id: int) -> int:
        """Mark up to `max_streams` eligible streams on `eid` pending
        migration to `target`; returns how many were marked. Eligible =
        live, not already pending/migrating, outside the per-stream
        window. Oldest first — the longest stream has the most KV to lose
        to a crash and the most to gain from an idle engine."""
        if max_streams <= 0:
            return 0
        now = time.monotonic()
        window = self.config.stream_window_s
        marked = 0
        with self._lock:
            candidates = sorted(
                (h for h in self._streams.values()
                 if h.endpoint_id == eid and not h.done
                 and h.pending is None and not h.migrating
                 and now - h.last_migrate_at > window),
                key=lambda h: h.started_at,
            )
            for h in candidates[:max_streams]:
                h.pending = (target, reason, directive_id)
                marked += 1
        return marked

    def inflight(self) -> int:
        with self._lock:
            return sum(1 for h in self._streams.values()
                       if h.pending is not None or h.migrating)

    def counts(self) -> dict[str, int]:
        """Live streams per endpoint (rebalancer scoring input)."""
        with self._lock:
            out: dict[str, int] = {}
            for h in self._streams.values():
                out[h.endpoint_id] = out.get(h.endpoint_id, 0) + 1
            return out

    def snapshot(self) -> dict:
        with self._lock:
            by_endpoint: dict[str, int] = {}
            for h in self._streams.values():
                by_endpoint[h.endpoint_id] = by_endpoint.get(
                    h.endpoint_id, 0) + 1
            return {
                "streams": len(self._streams),
                "inflight_migrations": sum(
                    1 for h in self._streams.values()
                    if h.pending is not None or h.migrating
                ),
                "by_endpoint": by_endpoint,
            }


class Rebalancer:
    """The planner loop (primary worker only — the single-writer discipline
    that already scopes the health checker and maintenance there)."""

    def __init__(self, registry, load_manager, directory: StreamDirectory,
                 *, metrics=None, gossip=None,
                 config: RebalanceConfig | None = None):
        self.registry = registry
        self.load_manager = load_manager
        self.directory = directory
        self.metrics = metrics
        self.gossip = gossip
        self.config = config or RebalanceConfig.from_env()
        self._task: asyncio.Task | None = None
        self._over: dict[str, int] = {}       # eid -> consecutive hot ticks
        self._issued: deque[float] = deque()  # per-minute budget (monotonic)
        self._directive_seq = 0
        self.directives_total = 0
        self.skipped_budget_total = 0

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self.config.enabled and self._task is None:
            self._task = asyncio.create_task(self._loop(), name="rebalancer")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.interval_s)
            try:
                self.tick()
            except Exception:
                log.exception("rebalance tick failed")

    # --------------------------------------------------------------- scoring

    def _resumable(self) -> list:
        from llmlb_tpu.gateway.replay import RESUMABLE_ENDPOINT_TYPES

        return [
            ep for ep in self.registry.list_online()
            if ep.endpoint_type.value in RESUMABLE_ENDPOINT_TYPES
        ]

    def score(self, ep) -> float:
        """Occupancy pressure: (busy slots + queued) / capacity. Telemetry
        (engine /api/health via the probe loop) when fresh, gateway-side
        active counts otherwise — both advisory, the bands absorb noise."""
        acc = getattr(ep, "accelerator", None)
        if acc is not None and getattr(acc, "num_slots", 0):
            return (acc.active_slots + acc.queue_depth) / max(
                1, acc.num_slots)
        return self.load_manager.active_count(ep.id) / float(DEFAULT_SLOTS)

    def _goodput_degraded(self) -> bool:
        """True only when SLO accounting has a measurement AND it is below
        the gate — unknown goodput never justifies churn."""
        if self.metrics is None:
            return False
        try:
            ratio = self.metrics.summary().get("goodput_ratio")
        except Exception:
            return False
        return ratio is not None and ratio < self.config.min_goodput

    # ------------------------------------------------------------ directives

    def _budget_allows(self, n: int) -> int:
        """Clamp a wanted stream count to the budget; 0 = skip. Charges
        nothing — `_charge` runs after the directive actually issues."""
        now = time.monotonic()
        while self._issued and now - self._issued[0] > 60.0:
            self._issued.popleft()
        room_minute = self.config.per_minute - len(self._issued)
        room_concurrent = self.config.max_concurrent - self.directory.inflight()
        return max(0, min(n, room_minute, room_concurrent))

    def _charge(self, n: int) -> None:
        now = time.monotonic()
        for _ in range(n):
            self._issued.append(now)

    def _issue(self, src_eid: str, target_eid: str, reason: str,
               n: int) -> int:
        granted = self._budget_allows(n)
        if granted <= 0:
            self.skipped_budget_total += 1
            if self.metrics is not None:
                self.metrics.record_rebalance_migration(reason, "skipped")
            return 0
        self._directive_seq += 1
        directive_id = self._directive_seq
        # local streams first (gossip never loops back to ourselves)...
        marked = self.directory.apply_directive(
            src_eid, target_eid, reason, granted, directive_id)
        # ...then every sibling worker, same budget figure: each worker
        # moves at most `granted` of ITS streams — the budget is per
        # directive, deliberately conservative against double counting.
        if self.gossip is not None:
            self.gossip.publish("migrate", {
                "eid": src_eid,
                "target": target_eid,
                "reason": reason,
                "max_streams": granted,
                "directive_id": directive_id,
            })
        self._charge(max(1, marked))
        self.directives_total += 1
        log.info("rebalance directive #%d: %s -> %s (%s, up to %d streams, "
                 "%d marked locally)", directive_id, src_eid, target_eid,
                 reason, granted, marked)
        return granted

    def evacuate(self, eid: str, reason: str = "drain",
                 target: str | None = None) -> int:
        """Move every stream off `eid` (budget-paced): the drain runbook,
        rolling restarts and autoscale-down all enter here — repeatedly, one
        tick at a time, until the endpoint is empty."""
        eps = [ep for ep in self._resumable() if ep.id != eid]
        if not eps:
            return 0
        if target is None:
            target = min(eps, key=self.score).id
        return self._issue(eid, target, reason,
                           self.config.max_concurrent)

    # ------------------------------------------------------------------ tick

    def tick(self) -> None:
        """One planning pass. Public (not just the loop's callee) so tests
        and the bench drive it deterministically."""
        eps = self._resumable()
        if len(eps) < 2:
            return
        scores = {ep.id: self.score(ep) for ep in eps}

        # 1) evacuation: an engine advertising draining (rolling restart,
        #    autoscale-down, operator drain) gets its streams moved NOW —
        #    proactively, not when its connections die.
        draining = [ep for ep in eps
                    if getattr(ep.accelerator, "draining", False)]
        for ep in draining:
            healthy = [e for e in eps if e.id != ep.id
                       and not getattr(e.accelerator, "draining", False)]
            if not healthy:
                continue
            target = min(healthy, key=lambda e: scores[e.id])
            self._issue(ep.id, target.id, "drain",
                        self.config.max_concurrent)

        # 2) hot-spot dissipation, hysteresis-banded.
        candidates = [ep for ep in eps if ep not in draining]
        if len(candidates) < 2:
            return
        src = max(candidates, key=lambda e: scores[e.id])
        if scores[src.id] >= self.config.high_water:
            self._over[src.id] = self._over.get(src.id, 0) + 1
        else:
            self._over.pop(src.id, None)
            return
        if self._over[src.id] < HYSTERESIS_TICKS:
            return
        targets = [e for e in candidates if e.id != src.id
                   and scores[e.id] <= self.config.low_water]
        if not targets:
            return
        # no queue on the hot engine and no measured SLO pain: high
        # occupancy is just good utilization — leave the streams alone
        src_queue = getattr(src.accelerator, "queue_depth", 0) or 0
        if src_queue == 0 and not self._goodput_degraded():
            return
        # fastest idle engine wins the stream: prefer the lowest score,
        # break ties toward the higher decode TPS EMA for the hot model mix
        target = min(targets, key=lambda e: (round(scores[e.id], 3),
                                             -self._tps_hint(e.id)))
        self._issue(src.id, target.id, "hotspot", 1)
        self._over.pop(src.id, None)  # re-arm hysteresis after acting

    def _tps_hint(self, eid: str) -> float:
        """Best decode TPS EMA observed for an endpoint across models —
        tiebreak only, so staleness is harmless."""
        try:
            snap = self.load_manager.tps_snapshot()
        except Exception:
            return 0.0
        best = 0.0
        for key, s in snap.items():
            if key.startswith(f"{eid}:"):
                best = max(best, float(s.get("ema_tps") or 0.0))
        return best

    def snapshot(self) -> dict:
        return {
            "enabled": self.config.enabled,
            "directives_total": self.directives_total,
            "skipped_budget_total": self.skipped_budget_total,
            "inflight": self.directory.inflight(),
            "bands": {"high": self.config.high_water,
                      "low": self.config.low_water},
        }
