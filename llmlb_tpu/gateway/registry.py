"""EndpointRegistry: in-memory endpoint/model cache with SQLite write-through.

Parity with reference registry/endpoints.rs:80-608 (find_by_model :209,
list_online_by_capability :169, update_status :282, sync_models :483): every
read is served from memory; every mutation writes DB then cache under one lock.
"""

from __future__ import annotations

import threading
import time

from llmlb_tpu.gateway.db import Database
from llmlb_tpu.gateway.types import (
    AcceleratorInfo,
    Capability,
    Endpoint,
    EndpointModel,
    EndpointStatus,
    EndpointType,
)


class EndpointRegistry:
    def __init__(self, db: Database):
        self.db = db
        self._lock = threading.RLock()
        self._endpoints: dict[str, Endpoint] = {}
        self._models: dict[str, list[EndpointModel]] = {}  # endpoint_id -> models
        # Called (no args) after every durable mutation; app_state wires it
        # to the gossip bus in multi-worker mode so sibling workers reload
        # their cache from the shared DB (~1 RTT instead of never — each
        # worker's cache is otherwise only seeded at its own boot).
        self.on_mutate = None
        self._load()

    def _load(self) -> None:
        with self._lock:
            self._endpoints = {ep.id: ep for ep in self.db.list_endpoints()}
            self._models = {}
            for m in self.db.list_endpoint_models():
                self._models.setdefault(m.endpoint_id, []).append(m)

    def reload(self) -> None:
        """Re-seed the cache from the DB (a sibling worker mutated it).
        Transient cache-only fields (breaker_state) are re-mirrored by the
        resilience layer on its next transition; never fires on_mutate."""
        self._load()

    def _notify_mutation(self) -> None:
        cb = self.on_mutate
        if cb is not None:
            try:
                cb()
            except Exception:  # allow-silent: a broken listener must not
                pass           # poison registry mutations

    # ------------------------------------------------------------------ CRUD

    def add(self, endpoint: Endpoint) -> Endpoint:
        with self._lock:
            for existing in self._endpoints.values():
                if existing.url == endpoint.url:
                    raise ValueError(f"endpoint URL already registered: {endpoint.url}")
            self.db.upsert_endpoint(endpoint)
            self._endpoints[endpoint.id] = endpoint
        self._notify_mutation()
        return endpoint

    def update(self, endpoint: Endpoint) -> None:
        with self._lock:
            endpoint.updated_at = time.time()
            self.db.upsert_endpoint(endpoint)
            self._endpoints[endpoint.id] = endpoint
        self._notify_mutation()

    def remove(self, endpoint_id: str) -> bool:
        with self._lock:
            if endpoint_id not in self._endpoints:
                return False
            self.db.delete_endpoint(endpoint_id)
            self._endpoints.pop(endpoint_id, None)
            self._models.pop(endpoint_id, None)
        self._notify_mutation()
        return True

    def get(self, endpoint_id: str) -> Endpoint | None:
        with self._lock:
            return self._endpoints.get(endpoint_id)

    def list_all(self) -> list[Endpoint]:
        with self._lock:
            return list(self._endpoints.values())

    def list_online(self) -> list[Endpoint]:
        with self._lock:
            return [
                ep for ep in self._endpoints.values()
                if ep.status == EndpointStatus.ONLINE
            ]

    # ----------------------------------------------------------------- status

    def update_status(
        self,
        endpoint_id: str,
        status: EndpointStatus,
        latency_ms: float | None = None,
        accelerator: AcceleratorInfo | None = None,
        consecutive_failures: int | None = None,
    ) -> Endpoint | None:
        with self._lock:
            ep = self._endpoints.get(endpoint_id)
            if ep is None:
                return None
            status_changed = ep.status != status
            ep.status = status
            if latency_ms is not None:
                ep.latency_ms = latency_ms
            if accelerator is not None:
                ep.accelerator = accelerator
            if consecutive_failures is not None:
                ep.consecutive_failures = consecutive_failures
            ep.last_checked_at = time.time()
            ep.updated_at = time.time()
            self.db.upsert_endpoint(ep)
        # notify siblings on status flips only — every 30 s probe rewrites
        # latency/telemetry, and a reload per probe per worker is pure churn
        # (stale telemetry between flips degrades steering, not correctness)
        if status_changed:
            self._notify_mutation()
        return ep

    def set_breaker_state(self, endpoint_id: str, state: str) -> None:
        """Mirror the in-band circuit breaker's state onto the cached
        endpoint (resilience.py calls this on every transition). Cache-only
        on purpose: breaker state is runtime truth, not configuration, so it
        must not round-trip through the DB."""
        with self._lock:
            ep = self._endpoints.get(endpoint_id)
            if ep is not None:
                ep.breaker_state = state

    def update_type(self, endpoint_id: str, endpoint_type: EndpointType) -> None:
        with self._lock:
            ep = self._endpoints.get(endpoint_id)
            if ep is None:
                return
            ep.endpoint_type = endpoint_type
            ep.updated_at = time.time()
            self.db.upsert_endpoint(ep)
        self._notify_mutation()

    # ----------------------------------------------------------------- models

    def sync_models(self, endpoint_id: str, models: list[EndpointModel]) -> None:
        with self._lock:
            self.db.replace_endpoint_models(endpoint_id, models)
            self._models[endpoint_id] = list(models)
        self._notify_mutation()

    def apply_residency(self, endpoint_id: str, adapters: list[str]) -> None:
        """Patch the cached `base:adapter` model entries for an endpoint to
        exactly `adapters` — the gossip fast path for adapter residency
        (health._sync_lora_models pushes changes the moment a probe sees
        them). Cache-only like set_breaker_state: the primary's sync_models
        already persisted the truth to the shared DB, and a full reload
        rides the `registry` gossip behind this message anyway — this just
        closes the window where a sibling routes on a stale resident set."""
        with self._lock:
            models = self._models.get(endpoint_id)
            if not models:
                return
            base = [m for m in models if ":" not in m.model_id]
            lora_base = [m for m in base
                         if Capability.LORA in m.capabilities]
            if not lora_base:
                return
            wanted: dict[str, EndpointModel] = {}
            for m in lora_base:
                for name in adapters:
                    mid = f"{m.model_id}:{name}"
                    wanted[mid] = EndpointModel(
                        endpoint_id=endpoint_id,
                        model_id=mid,
                        canonical_name=f"{m.canonical_name}:{name}",
                        capabilities=list(m.capabilities),
                        context_length=m.context_length,
                    )
            self._models[endpoint_id] = base + list(wanted.values())

    def models_for(self, endpoint_id: str) -> list[EndpointModel]:
        with self._lock:
            return list(self._models.get(endpoint_id, []))

    def all_models(self) -> list[EndpointModel]:
        with self._lock:
            return [m for ms in self._models.values() for m in ms]

    def canonical_model_names(self) -> list[str]:
        with self._lock:
            seen: dict[str, None] = {}
            for ms in self._models.values():
                for m in ms:
                    seen.setdefault(m.canonical_name)
            return list(seen)

    def find_by_model(
        self, canonical_name: str, capability: Capability | None = None
    ) -> list[tuple[Endpoint, EndpointModel]]:
        """Online endpoints serving a model (optionally with a capability)."""
        with self._lock:
            out = []
            for ep in self._endpoints.values():
                if ep.status != EndpointStatus.ONLINE:
                    continue
                for m in self._models.get(ep.id, []):
                    if m.canonical_name != canonical_name and m.model_id != canonical_name:
                        continue
                    if capability is not None and capability not in m.capabilities:
                        continue
                    out.append((ep, m))
                    break
            return out

    def list_online_by_capability(
        self, capability: Capability
    ) -> list[tuple[Endpoint, EndpointModel]]:
        with self._lock:
            out = []
            for ep in self._endpoints.values():
                if ep.status != EndpointStatus.ONLINE:
                    continue
                for m in self._models.get(ep.id, []):
                    if capability in m.capabilities:
                        out.append((ep, m))
                        break
            return out
