"""Durable streams: per-stream replay state + continuation splicing.

PR 4 drew the line at "mid-stream failures are not retried — bytes already
left". This module moves that line: while an SSE stream flows through the
gateway, a `ReplayState` accumulates the token ids the engine has committed
(shipped as interleaved ``data: {"object": "llmlb.replay", "tokens": [...]}``
frames when the gateway arms a stream with ``llmlb_replay: true``) plus the
exact completion text already forwarded to the client. When the engine dies
mid-stream, the proxy re-runs endpoint selection and POSTs the ORIGINAL chat
body + the committed ids to the new engine's ``/v1/resume`` — the PR 11
adopt/replay path, so the continuation is token-identical for greedy and
seeded streams — then SPLICES the resumed stream into the same client
response with `ChunkSplicer`: the duplicated prefix (the adopter re-emits the
full text) is dropped, the second role delta is stripped, and the client sees
one uninterrupted stream with exactly one terminal frame.

Why token ids and not text: replaying re-tokenized text would not land KV at
the same absolute positions; replaying the committed ids does (chunk-prefill
of prompt+committed — engine/scheduler.ParkedState semantics), which is what
makes the continuation bit-identical. The ids the gateway missed between the
last replay frame and the cut are simply regenerated: generation is
deterministic given the committed prefix for greedy/seeded sampling, and for
unseeded stochastic streams the engine ships each frame's ids BEFORE the text
they produced, so the replayed ids always cover every character the client
has seen.
"""

from __future__ import annotations

import json

REPLAY_OBJECT = "llmlb.replay"

# Endpoint types whose engines speak /v1/resume (the in-tree JAX engine).
# Everything else streams through the historical byte-for-byte path and a
# mid-stream cut stays terminal, exactly as before this module existed.
RESUMABLE_ENDPOINT_TYPES = ("tpu",)


class FrameSplitter:
    """Split an SSE byte stream into complete frames at ``\\n\\n`` boundaries.

    The armed pump forwards whole frames only: a cut that lands mid-frame
    must not leak a partial event to the client (the resumed stream re-emits
    that frame's text, and the splice counts only forwarded characters)."""

    __slots__ = ("_buf",)

    def __init__(self):
        self._buf = b""

    def push(self, chunk: bytes) -> list[bytes]:
        """Complete frames (terminator included) arrived so far."""
        self._buf += chunk
        frames: list[bytes] = []
        while True:
            idx = self._buf.find(b"\n\n")
            if idx < 0:
                return frames
            frames.append(self._buf[: idx + 2])
            self._buf = self._buf[idx + 2:]


def is_done_frame(frame: bytes) -> bool:
    """Exact terminal-frame test: a ``data:`` line whose payload is the
    literal ``[DONE]`` — a substring test would false-positive on completion
    CONTENT that happens to contain the text \"[DONE]\"."""
    for line in frame.split(b"\n"):
        line = line.strip()
        if (line.startswith(b"data:")
                and line[len(b"data:"):].strip() == b"[DONE]"):
            return True
    return False


def parse_data_frame(frame: bytes) -> dict | None:
    """The JSON payload of one SSE frame's ``data:`` line, or None for
    non-data frames, ``[DONE]``, and unparseable payloads."""
    for line in frame.split(b"\n"):
        line = line.strip()
        if not line.startswith(b"data:"):
            continue
        data = line[len(b"data:"):].strip()
        if not data or data == b"[DONE]":
            return None
        try:
            payload = json.loads(data)
        except (ValueError, UnicodeDecodeError):
            return None
        return payload if isinstance(payload, dict) else None
    return None


class ReplayState:
    """Everything one armed stream needs to continue on another engine:
    the engine-bound request body, the committed token ids, and the exact
    client-visible characters already forwarded (content and tool-call
    arguments tracked separately — they are distinct delta channels)."""

    def __init__(self, payload: dict, *, capability=None, api_kind=None,
                 tenant: str | None = None, weight: float = 1.0,
                 deadline_at: float | None = None, rid: str | None = None,
                 prefix_hash: str | None = None, max_attempts: int = 2):
        # the body as forwarded to the FIRST engine; `model` is rewritten to
        # each resume target's engine-local name at acquire time
        self.payload = dict(payload)
        self.payload.pop("committed_ids", None)
        self.capability = capability
        self.api_kind = api_kind
        self.tenant = tenant
        self.weight = weight
        self.deadline_at = deadline_at
        self.rid = rid
        self.prefix_hash = prefix_hash
        self.max_attempts = max(0, int(max_attempts))
        self.attempts = 0
        self.committed: list[int] = []
        # set at each resume: the NEXT replay frame replaces the ledger
        # instead of extending it (see mark_ledger_stale)
        self._ledger_stale = False
        self.sent_content = 0
        self.sent_args = 0
        self.tool_open_sent = False
        self.resumes = 0  # successful splices on this stream
        # identity of the stream as the client first saw it: continuation
        # chunks are re-stamped with these so the splice is seamless
        self.completion_id: str | None = None
        self.created: int | None = None
        # the endpoint currently serving this stream: a resume first asks
        # it (POST /v1/kv/export) for the parked stream's serialized KV
        # pages so the adopter can land bytes instead of replaying — a
        # dead origin just fails the fetch fast and replay proceeds
        self.origin = None

    # ------------------------------------------------------------- accounting

    def note_openai_chunk(self, obj: dict) -> bool:
        """Account one upstream data-frame payload. Returns False for
        replay frames (gateway-internal — never forwarded to the client),
        True for client-relevant chunks."""
        if obj.get("object") == REPLAY_OBJECT:
            toks = obj.get("tokens")
            if isinstance(toks, list):
                if self._ledger_stale:
                    # first frame from an adopter: it re-reports the FULL
                    # committed sequence, superseding the pre-resume ledger
                    self.committed = []
                    self._ledger_stale = False
                self.committed.extend(int(t) for t in toks)
            return False
        if self.completion_id is None and isinstance(obj.get("id"), str):
            self.completion_id = obj["id"]
            created = obj.get("created")
            if isinstance(created, int):
                self.created = created
        for choice in obj.get("choices") or []:
            if not isinstance(choice, dict):
                continue
            delta = choice.get("delta") or {}
            content = delta.get("content")
            if isinstance(content, str):
                self.sent_content += len(content)
            for tc in delta.get("tool_calls") or []:
                if not isinstance(tc, dict):
                    continue
                if tc.get("id") or (tc.get("function") or {}).get("name"):
                    self.tool_open_sent = True
                args = (tc.get("function") or {}).get("arguments")
                if isinstance(args, str):
                    self.sent_args += len(args)
        return True

    def mark_ledger_stale(self) -> None:
        """Called at each resume: the adopter's replay frames re-report the
        full committed sequence (replayed ids first, continuation after), so
        a SECOND cut replays from the fresh ledger. The swap is LAZY — it
        happens at the adopter's first replay frame, not here — so a cut
        landing before any frame arrives still resumes from the previous
        ledger, which by the ships-tokens-before-text contract covers every
        character the client has seen."""
        self._ledger_stale = True

    def resume_body(self, engine_model: str | None,
                    kv_pages: dict | None = None) -> dict:
        body = dict(self.payload)
        if engine_model:
            body["model"] = engine_model
        body["committed_ids"] = list(self.committed)
        body["stream"] = True
        body["llmlb_replay"] = True
        if kv_pages is not None:
            # serialized KV pages fetched from the draining origin's
            # /v1/kv/export: the adopter lands them instead of replaying
            # the prefill (engine/kv_transfer.py); incompatible payloads
            # fall back engine-side, never here
            body["kv_pages"] = kv_pages
        return body


def _drop_prefix(text: str, skip: int) -> tuple[str, int]:
    if skip <= 0:
        return text, 0
    if skip >= len(text):
        return "", skip - len(text)
    return text[skip:], 0


class ChunkSplicer:
    """Rewrites a resumed upstream's chunks so the client stream continues
    seamlessly: the second role delta is stripped, the re-emitted completion
    prefix (content and tool-call arguments the client already has) is
    dropped, a duplicate forced-tool-call opening (id+name) is suppressed,
    and every chunk is re-stamped with the original stream's id/created.
    Forwarded characters are counted back into the ReplayState so a second
    cut splices against the up-to-date offsets."""

    def __init__(self, replay: ReplayState):
        self.replay = replay
        self.skip_content = replay.sent_content
        self.skip_args = replay.sent_args
        self.suppress_tool_open = replay.tool_open_sent

    def splice(self, obj: dict) -> dict | None:
        """Spliced chunk dict to forward, or None when nothing in this chunk
        is new to the client (pure duplicate / role-only chunk)."""
        out = dict(obj)
        if self.replay.completion_id is not None:
            out["id"] = self.replay.completion_id
        if self.replay.created is not None:
            out["created"] = self.replay.created
        meaningful = isinstance(out.get("usage"), dict)
        choices_out = []
        for choice in out.get("choices") or []:
            if not isinstance(choice, dict):
                choices_out.append(choice)
                continue
            choice = dict(choice)
            delta = dict(choice.get("delta") or {})
            delta.pop("role", None)  # exactly one role delta per stream
            content = delta.get("content")
            if isinstance(content, str) and content:
                keep, self.skip_content = _drop_prefix(content,
                                                       self.skip_content)
                delta["content"] = keep
                self.replay.sent_content += len(keep)
                if keep:
                    meaningful = True
            tool_calls = delta.get("tool_calls")
            if isinstance(tool_calls, list) and tool_calls:
                spliced_tcs = []
                for tc in tool_calls:
                    if not isinstance(tc, dict):
                        continue
                    tc = dict(tc)
                    fn = dict(tc.get("function") or {})
                    if self.suppress_tool_open:
                        # the client already holds the opening tool delta
                        # from the first engine (its call id is canonical)
                        tc.pop("id", None)
                        tc.pop("type", None)
                        fn.pop("name", None)
                    elif tc.get("id") or fn.get("name"):
                        self.replay.tool_open_sent = True
                        self.suppress_tool_open = True
                        meaningful = True
                    args = fn.get("arguments")
                    if isinstance(args, str) and args:
                        keep, self.skip_args = _drop_prefix(args,
                                                            self.skip_args)
                        fn["arguments"] = keep
                        self.replay.sent_args += len(keep)
                        if keep:
                            meaningful = True
                    tc["function"] = fn
                    if tc.get("id") or fn.get("name") or fn.get("arguments"):
                        spliced_tcs.append(tc)
                if spliced_tcs:
                    delta["tool_calls"] = spliced_tcs
                else:
                    delta.pop("tool_calls", None)
            if choice.get("finish_reason"):
                meaningful = True
            choice["delta"] = delta
            choices_out.append(choice)
        out["choices"] = choices_out
        return out if meaningful else None


def encode_chunk_frame(obj: dict) -> bytes:
    """One spliced chunk back onto the wire as an SSE data frame."""
    return b"data: " + json.dumps(obj, separators=(",", ":")).encode() + b"\n\n"
