"""Gateway resilience layer: in-band failover + per-endpoint circuit breaking.

Before this layer, any single upstream hiccup was a client-visible 502: the
proxy gave up on the first connect error or non-200, and a dead endpoint kept
receiving traffic until the pull-based health checker (30 s interval x 2
strikes) noticed — up to ~60 s of guaranteed failures. This module closes
both gaps with in-band signals:

* **Failover retries** (`FailoverController`): a failed attempt re-runs
  endpoint selection excluding every endpoint that already failed this
  request, with capped exponential backoff + jitter, under a global
  `RetryBudget` (retries capped as a fraction of recent request volume so a
  melting fleet is not amplified by its own failover traffic). Non-streamed
  requests and streams that fail *before the first byte reaches the client*
  are retryable; mid-stream failures are not (bytes already left).

* **Passive health / circuit breaker** (`ResilienceManager`): per-endpoint
  closed -> open -> half-open breakers fed by in-band outcomes, including
  stream interruptions. Tripping ejects the endpoint from `select`/
  `try_admit` immediately (the LoadManager consults `allow()`); after the
  open interval one half-open probe request is admitted and its outcome
  closes or re-opens (doubled interval, capped) the breaker. The pull
  checker reconciles: a successful out-of-band probe fast-forwards an open
  breaker to half-open, and a recovered-from-offline endpoint starts with a
  fresh breaker.

* **Fault-aware upstream POST** (`upstream_post`): the single choke point
  every proxy path uses to talk to an endpoint, where faults.py rules are
  applied — so all of the above is testable deterministically.

Prior art: the retry-budget idea follows Finagle/Envoy `retry_budget`
(ratio + min floor over a sliding window); the breaker is the standard
consecutive-failure trip with exponential open intervals.
"""

from __future__ import annotations

import asyncio
import enum
import math
import random
import threading
import time

import aiohttp

from llmlb_tpu.gateway.config import ResilienceConfig
from llmlb_tpu.gateway.faults import (
    EngineAbortResponse,
    InjectedHTTPResponse,
    StreamCutResponse,
)
from llmlb_tpu.gateway.gossip import SeqClock, newer

RETRYABLE_EXCEPTIONS = (aiohttp.ClientError, asyncio.TimeoutError, OSError)


class BreakerState(str, enum.Enum):
    CLOSED = "closed"
    HALF_OPEN = "half_open"
    OPEN = "open"


# /metrics gauge encoding (llmlb_gateway_breaker_state{endpoint=...})
BREAKER_STATE_CODE = {
    BreakerState.CLOSED: 0,
    BreakerState.HALF_OPEN: 1,
    BreakerState.OPEN: 2,
}


# A half-open probe that never reports an outcome (handler crash, leaked
# lease) must not wedge the breaker in half_open forever: its slot is
# reclaimed after this long. Generous — longer than the default 300 s
# inference timeout, so a legitimately slow probe stream is not double-run.
HALF_OPEN_PROBE_TIMEOUT_S = 600.0


class _Breaker:
    """Per-endpoint breaker record. All mutation under the manager's lock."""

    __slots__ = ("state", "consecutive_failures", "opened_at", "open_until",
                 "trip_streak", "probes_in_flight", "probe_started_at",
                 "last_failure_reason", "last_change_ver")

    def __init__(self):
        self.state = BreakerState.CLOSED
        self.consecutive_failures = 0
        self.opened_at = 0.0
        self.open_until = 0.0
        self.trip_streak = 0  # consecutive trips; doubles the open interval
        self.probes_in_flight = 0
        self.probe_started_at = 0.0
        self.last_failure_reason: str | None = None
        # (seq, origin) stamp of the last applied transition (local or
        # remote): the seq-LWW ordering key for cross-worker gossip — wall
        # stamps skewed across hosts and could resurrect a stale OPEN
        # (gossip.newer); None until the first transition.
        self.last_change_ver: tuple | None = None


class RetryBudget:
    """Sliding-window retry budget: retries are allowed while the retry
    count stays under max(min_floor, ratio * recent requests). Envoy's
    `retry_budget` semantics, windowed rather than token-bucketed so the
    figure shown in /api/health is directly interpretable."""

    def __init__(self, ratio: float, min_retries: int, window_s: float):
        self.ratio = ratio
        self.min_retries = min_retries
        self.window_s = window_s
        self._lock = threading.Lock()
        self._requests: list[float] = []
        self._retries: list[float] = []
        # Called (spend count is rare — failures only) after a successful
        # local spend; app_state wires this to gossip so sibling workers
        # count the retry against their own window too. Request volume
        # stays worker-local on purpose: replicating every request would
        # put a datagram on the bus per request, and a per-worker request
        # denominator only makes the budget MORE conservative.
        self.on_spend = None

    def _trim(self, now: float) -> None:
        cutoff = now - self.window_s
        for series in (self._requests, self._retries):
            # windows are short and appends ordered; linear trim from the left
            i = 0
            while i < len(series) and series[i] < cutoff:
                i += 1
            if i:
                del series[:i]

    def note_request(self, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._trim(now)
            self._requests.append(now)

    def allowed(self, now: float | None = None) -> int:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._trim(now)
            return max(self.min_retries,
                       int(self.ratio * len(self._requests)))

    def try_spend(self, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._trim(now)
            cap = max(self.min_retries, int(self.ratio * len(self._requests)))
            if len(self._retries) >= cap:
                return False
            self._retries.append(now)
        cb = self.on_spend
        if cb is not None:
            try:
                cb()
            except Exception:  # allow-silent: gossip publish is best-effort
                pass
        return True

    def note_remote_spend(self) -> None:
        """A sibling worker spent a retry: count it against this window so
        the fleet-wide retry volume honors one budget, not N."""
        with self._lock:
            self._trim(time.monotonic())
            self._retries.append(time.monotonic())

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            self._trim(now)
            cap = max(self.min_retries, int(self.ratio * len(self._requests)))
            return {
                "window_s": self.window_s,
                "requests_in_window": len(self._requests),
                "retries_in_window": len(self._retries),
                "retries_allowed": cap,
            }


class ResilienceManager:
    """Per-endpoint breakers + the global retry budget.

    Wired into the LoadManager as `load_manager.resilience`: selection
    filters candidates through `allow()` and reports admissions via
    `on_admit()` (which consumes half-open probe slots). Proxy paths report
    outcomes via `record_success()`/`record_failure()`. Thread-safe — lease
    releases can arrive from GC finalizer threads.
    """

    def __init__(self, config: ResilienceConfig | None = None, *,
                 metrics=None, events=None, registry=None):
        self.config = config or ResilienceConfig()
        self.metrics = metrics  # GatewayMetrics | None
        self.events = events  # DashboardEventBus | None
        self.registry = registry  # EndpointRegistry | None
        self.budget = RetryBudget(
            self.config.retry_budget_ratio,
            self.config.retry_budget_min,
            self.config.retry_budget_window_s,
        )
        self._lock = threading.Lock()
        self._breakers: dict[str, _Breaker] = {}
        # GossipBus | None (set by app_state): transitions replicate to
        # sibling workers so a breaker tripped here ejects the endpoint
        # everywhere within ~1 RTT. Advisory — with gossip off, every worker
        # still converges on its own in-band failures.
        self.gossip = None
        self._applying_remote = False  # loop guard: remote applies don't re-gossip
        self._local_clock = SeqClock()  # version source when no bus attached

    def _next_ver(self):
        g = self.gossip
        if g is not None:
            return g.next_version()
        return (self._local_clock.tick(), "local")

    # ------------------------------------------------------------ transitions

    def _transition(self, endpoint_id: str, b: _Breaker,
                    to: BreakerState, reason: str | None = None) -> None:
        """Caller holds the lock. The sinks (metrics/events/registry) use
        their own locks and never call back into this manager, so invoking
        them under our lock cannot deadlock."""
        frm = b.state
        if frm == to:
            return
        b.state = to
        ver = self._next_ver()
        b.last_change_ver = ver
        if to == BreakerState.OPEN:
            now = time.monotonic()
            b.opened_at = now
            interval = min(
                self.config.breaker_open_max_s,
                self.config.breaker_open_s * (2 ** b.trip_streak),
            )
            b.open_until = now + interval
            b.trip_streak += 1
            b.probes_in_flight = 0
        elif to == BreakerState.HALF_OPEN:
            b.probes_in_flight = 0
        else:  # CLOSED
            b.consecutive_failures = 0
            b.trip_streak = 0
            b.probes_in_flight = 0
        name = endpoint_id
        if self.registry is not None:
            ep = self.registry.get(endpoint_id)
            if ep is not None:
                name = ep.name
            self.registry.set_breaker_state(endpoint_id, to.value)
        if self.metrics is not None:
            self.metrics.record_breaker_transition(name, to.value)
            self.metrics.set_breaker_state(
                name, BREAKER_STATE_CODE[to]
            )
        if self.events is not None:
            self.events.publish("BreakerStateChanged", {
                "endpoint_id": endpoint_id,
                "name": name,
                "from": frm.value,
                "to": to.value,
                "reason": reason,
            })
        if self.gossip is not None and not self._applying_remote:
            # the wire version IS the local stamp (seq=ver[0]): a delayed
            # echo of an older remote transition can never outrank this one
            self.gossip.publish("breaker", {
                "eid": endpoint_id,
                "to": to.value,
                "reason": reason,
                # ship the remaining open interval, not the deadline —
                # peers rebuild the deadline on their own monotonic clock
                "remaining_s": (
                    round(max(0.0, b.open_until - time.monotonic()), 3)
                    if to == BreakerState.OPEN else 0.0
                ),
            }, seq=ver[0])

    def apply_remote_breaker(self, endpoint_id: str, to: str,
                             remaining_s: float, reason: str | None,
                             ver: tuple) -> None:
        """A sibling worker's breaker transition, applied seq-LWW.

        OPEN ejects the endpoint here with the peer's remaining interval (so
        the whole group reopens together); CLOSED/HALF_OPEN relax a local
        open breaker (the peer had direct probe evidence). Purely advisory:
        a dropped message only delays ejection until this worker's own
        failures trip its local breaker, and correctness (request outcomes,
        retries) never consults the peer state directly."""
        if not self.config.enabled:
            return
        try:
            target = BreakerState(to)
        except ValueError:
            return
        if (self.registry is not None
                and self.registry.get(endpoint_id) is None):
            return  # deleted endpoint: never resurrect its breaker
        ver = tuple(ver)
        with self._lock:
            b = self._breakers.setdefault(endpoint_id, _Breaker())
            if not newer(ver, b.last_change_ver):
                return  # stale: this worker already knows something newer
            self._applying_remote = True
            try:
                if target == BreakerState.OPEN:
                    if b.state != BreakerState.OPEN:
                        self._transition(endpoint_id, b, BreakerState.OPEN,
                                         f"gossip: {reason}")
                        # override the locally computed interval with the
                        # tripping worker's remaining window
                        b.open_until = time.monotonic() + max(0.0, remaining_s)
                elif target == BreakerState.HALF_OPEN:
                    if b.state == BreakerState.OPEN:
                        self._transition(endpoint_id, b,
                                         BreakerState.HALF_OPEN,
                                         f"gossip: {reason}")
                elif b.state != BreakerState.CLOSED:
                    self._transition(endpoint_id, b, BreakerState.CLOSED,
                                     f"gossip: {reason}")
                # adopt the WIRE version: this worker's state now equals the
                # sender's, so anything newer than the sender's stamp (and
                # only that) should supersede it here too
                b.last_change_ver = ver
            finally:
                self._applying_remote = False

    # -------------------------------------------------------------- selection

    def allow(self, endpoint_id: str, now: float | None = None) -> bool:
        """May this endpoint receive a request right now? Open breakers past
        their interval lazily move to half-open here, so expiry needs no
        timer task."""
        if not self.config.enabled:
            return True
        now = time.monotonic() if now is None else now
        with self._lock:
            b = self._breakers.get(endpoint_id)
            if b is None or b.state == BreakerState.CLOSED:
                return True
            if b.state == BreakerState.OPEN:
                if now < b.open_until:
                    return False
                self._transition(endpoint_id, b, BreakerState.HALF_OPEN,
                                 "open interval elapsed")
            if (b.probes_in_flight > 0
                    and now - b.probe_started_at > HALF_OPEN_PROBE_TIMEOUT_S):
                # outcome never arrived (crashed handler, leaked lease):
                # reclaim the slot instead of wedging half-open forever
                b.probes_in_flight = 0
            return b.probes_in_flight < self.config.breaker_half_open_probes

    def on_admit(self, endpoint_id: str) -> None:
        """An admission actually landed on this endpoint; in half-open that
        consumes the probe slot so only N probes fly at once."""
        with self._lock:
            b = self._breakers.get(endpoint_id)
            if b is not None and b.state == BreakerState.HALF_OPEN:
                b.probes_in_flight += 1
                b.probe_started_at = time.monotonic()

    # --------------------------------------------------------------- outcomes

    def record_success(self, endpoint_id: str) -> None:
        with self._lock:
            b = self._breakers.get(endpoint_id)
            if b is None:
                return
            if b.state == BreakerState.HALF_OPEN:
                self._transition(endpoint_id, b, BreakerState.CLOSED,
                                 "probe succeeded")
            elif b.state == BreakerState.CLOSED:
                b.consecutive_failures = 0
            # OPEN: a straggler success (request admitted pre-trip) is not
            # probe evidence; wait for the half-open probe.

    def record_failure(self, endpoint_id: str, reason: str = "error") -> None:
        if not self.config.enabled:
            return
        if (self.registry is not None
                and self.registry.get(endpoint_id) is None):
            # in-flight failure for an endpoint deleted mid-request: do not
            # resurrect its breaker (forget() already ran — a revived entry
            # would export an uncleable state gauge under the raw id)
            return
        with self._lock:
            b = self._breakers.setdefault(endpoint_id, _Breaker())
            b.last_failure_reason = reason
            if b.state == BreakerState.HALF_OPEN:
                self._transition(endpoint_id, b, BreakerState.OPEN,
                                 f"probe failed: {reason}")
            elif b.state == BreakerState.CLOSED:
                b.consecutive_failures += 1
                if (b.consecutive_failures
                        >= self.config.breaker_failure_threshold):
                    self._transition(endpoint_id, b, BreakerState.OPEN,
                                     f"failure threshold: {reason}")

    # ------------------------------------------------- pull-checker reconcile

    def note_probe(self, endpoint_id: str, ok: bool) -> None:
        """Out-of-band health-probe outcome (health.py). A successful probe
        fast-forwards an open breaker to half-open — the next real request
        is the in-band probe; a failed probe while half-open re-opens."""
        with self._lock:
            b = self._breakers.get(endpoint_id)
            if b is None:
                return
            if ok and b.state == BreakerState.OPEN:
                self._transition(endpoint_id, b, BreakerState.HALF_OPEN,
                                 "health probe succeeded")
            elif not ok and b.state == BreakerState.HALF_OPEN:
                self._transition(endpoint_id, b, BreakerState.OPEN,
                                 "health probe failed")

    def reset(self, endpoint_id: str) -> None:
        """Endpoint recovered offline->online via the pull checker: start
        with a fresh breaker (the engine restarted; history is stale)."""
        with self._lock:
            b = self._breakers.get(endpoint_id)
            if b is not None and b.state != BreakerState.CLOSED:
                self._transition(endpoint_id, b, BreakerState.CLOSED,
                                 "endpoint recovered")
            self._breakers.pop(endpoint_id, None)

    def forget(self, endpoint_id: str, endpoint_name: str | None = None) -> None:
        """Endpoint removed from the registry. Clears the /metrics state
        gauge too (the caller passes the name — the registry entry is
        already gone), or an endpoint deleted while open would pin the
        GatewayBreakerOpen alert forever."""
        with self._lock:
            self._breakers.pop(endpoint_id, None)
        if self.metrics is not None and endpoint_name is not None:
            self.metrics.clear_breaker_state(endpoint_name)

    # ------------------------------------------------------------- inspection

    def state_of(self, endpoint_id: str) -> BreakerState:
        with self._lock:
            b = self._breakers.get(endpoint_id)
            return b.state if b is not None else BreakerState.CLOSED

    def breaker_info(self, endpoint_id: str) -> dict:
        now = time.monotonic()
        with self._lock:
            b = self._breakers.get(endpoint_id)
            if b is None:
                return {"state": BreakerState.CLOSED.value,
                        "consecutive_failures": 0, "retry_after_s": 0.0}
            return {
                "state": b.state.value,
                "consecutive_failures": b.consecutive_failures,
                "trip_streak": b.trip_streak,
                "last_failure_reason": b.last_failure_reason,
                "retry_after_s": (
                    round(max(0.0, b.open_until - now), 3)
                    if b.state == BreakerState.OPEN else 0.0
                ),
            }

    def soonest_reopen_s(self, endpoint_ids: list[str]) -> float | None:
        """Seconds until the first of these breakers admits traffic again;
        None when at least one admits traffic right now."""
        now = time.monotonic()
        waits: list[float] = []
        for eid in endpoint_ids:
            if self.allow(eid, now):
                return None
            with self._lock:
                b = self._breakers.get(eid)
                waits.append(max(0.0, b.open_until - now) if b else 0.0)
        return min(waits) if waits else None


# ----------------------------------------------------------------- failover


class PreStreamFailure:
    """Sentinel returned by the streaming proxies when the upstream stream
    died before any byte reached the client — the one stream failure that
    is safe to fail over (the client saw nothing)."""

    def __init__(self, error: str):
        self.error = error


def book_stream_outcome(state, failover, endpoint, model, *,
                        upstream_failed: bool, completed: bool) -> None:
    """Common outcome booking for the streaming proxies' finally blocks.
    An upstream cut feeds the breaker + per-endpoint stats + the
    interruption metric; a clean completion is a success; a client
    disconnect with the upstream still healthy counts as endpoint-alive —
    every stream must resolve its outcome or a half-open probe slot would
    leak and wedge the breaker."""
    if upstream_failed:
        if failover is not None:
            failover.record_failure(endpoint, None, "stream_interrupted",
                                    stream_interrupted=True)
        else:
            state.load_manager.note_endpoint_failure(
                endpoint.id, stream_interruption=True)
            state.metrics.record_stream_interruption(model, endpoint.name)
    elif failover is not None:
        if completed:
            failover.record_success(endpoint)
        else:
            failover.record_alive(endpoint)


def backoff_delay(retry_index: int, config: ResilienceConfig,
                  rng: random.Random | None = None) -> float:
    """Capped exponential backoff with full jitter over the upper half:
    delay in [cap/2, cap] of min(backoff_cap, base * 2^(retry-1))."""
    r = rng.random() if rng is not None else random.random()
    cap = min(config.backoff_cap_s,
              config.backoff_base_s * (2 ** max(0, retry_index - 1)))
    return cap * (0.5 + 0.5 * r)


class FailoverController:
    """Drives one client request's attempt loop across endpoints.

    Usage (per proxy path)::

        fo = FailoverController(state, model, trace=trace)
        while True:
            selection = await select(..., exclude=fo.failed_ids)
            ...post...
            on failure:
                fo.record_failure(endpoint, lease, reason)
                if await fo.should_retry(reason):
                    continue
                return <502 / normalized error>
            on success:
                fo.record_success(endpoint)
    """

    def __init__(self, state, model: str, *, trace=None, candidates_fn=None):
        self.state = state
        self.model = model
        self.trace = trace
        self.attempt = 1
        self.retried = False
        self.failed_ids: set[str] = set()
        # () -> list[Endpoint]: the request's full candidate pool. A retry is
        # only worth its backoff when an endpoint we have NOT yet failed on
        # remains — otherwise fail fast with the normalized 502 (a single
        # dead endpoint must not park the client on the queue first).
        self.candidates_fn = candidates_fn
        resilience = state.resilience
        self.config = (resilience.config if resilience is not None
                       else ResilienceConfig())
        if resilience is not None:
            resilience.budget.note_request()

    def record_failure(self, endpoint, lease, reason: str, *,
                       stream_interrupted: bool = False) -> None:
        """Book one failed attempt everywhere it must land: lease release,
        breaker, per-endpoint balancer stats, TPS reset is NOT done here
        (the pull checker owns that on offline).

        429 is retryable (this request fails over to a peer) but does NOT
        feed the breaker: a saturated endpoint is alive, and tripping
        breakers on saturation converts an overload spike into a cascade
        of hard ejections (Envoy's outlier detection excludes 429 for the
        same reason)."""
        if lease is not None:
            lease.fail()
        self.failed_ids.add(endpoint.id)
        if self.state.resilience is not None and reason != "http_429":
            self.state.resilience.record_failure(endpoint.id, reason)
        self.state.load_manager.note_endpoint_failure(
            endpoint.id, stream_interruption=stream_interrupted
        )
        if stream_interrupted:
            self.state.metrics.record_stream_interruption(
                self.model, endpoint.name
            )

    def record_success(self, endpoint) -> None:
        if self.state.resilience is not None:
            self.state.resilience.record_success(endpoint.id)
        self.state.load_manager.note_endpoint_success(endpoint.id)
        if self.retried:
            self.state.metrics.record_failover_recovery(self.model)

    def record_alive(self, endpoint) -> None:
        """The endpoint responded, but the request did not succeed for a
        reason that is not endpoint sickness (non-retryable 4xx, malformed
        200 body, client disconnect). Liveness evidence for the breaker —
        crucially, it resolves a half-open probe — without counting a
        request success or a failover recovery."""
        if self.state.resilience is not None:
            self.state.resilience.record_success(endpoint.id)

    async def should_retry(self, reason: str) -> bool:
        """True = the caller may re-select and retry (budget spent, backoff
        already slept, attempt count advanced)."""
        resilience = self.state.resilience
        if resilience is None or not self.config.enabled:
            return False
        if self.attempt >= self.config.max_attempts:
            return False
        if self.candidates_fn is not None and not any(
            ep.id not in self.failed_ids for ep in self.candidates_fn()
        ):
            return False
        if not resilience.budget.try_spend():
            self.state.metrics.record_retry_budget_exhausted()
            return False
        self.state.metrics.record_failover_retry(self.model, reason)
        if self.trace is not None:
            self.trace.mark("failover", attempt=self.attempt, reason=reason)
        delay = backoff_delay(self.attempt, self.config)
        self.attempt += 1
        self.retried = True
        if delay > 0:
            await asyncio.sleep(delay)
        return True


# ------------------------------------------------------- upstream HTTP edge


async def upstream_post(state, endpoint, path: str, *, json=None, data=None,
                        headers=None, timeout=None):
    """The one POST every proxy path uses to reach an endpoint. Applies
    fault-injection rules (faults.py) at this boundary: added latency,
    connect-refused, synthetic HTTP status, or a stream cut after K bytes —
    each counted in /metrics so a chaos run is observable."""
    from llmlb_tpu.gateway.faults import UPSTREAM_KINDS

    faults = state.faults
    fired = (faults.decide(endpoint, path, kinds=UPSTREAM_KINDS)
             if faults is not None else ())
    cut_rule = None
    abort_rule = None
    for rule in fired:
        state.metrics.record_fault_injected(rule.kind)
        if rule.kind == "latency" and rule.latency_ms > 0:
            await asyncio.sleep(rule.latency_ms / 1000.0)
        elif rule.kind == "connect_refused":
            raise aiohttp.ClientConnectionError(
                f"fault injected: connect refused ({endpoint.name})"
            )
        elif rule.kind == "http":
            return InjectedHTTPResponse(rule.status)
        elif rule.kind == "stream_cut":
            cut_rule = rule
        elif rule.kind == "engine_abort":
            abort_rule = rule
    resp = await state.http.post(
        endpoint.url + path, json=json, data=data, headers=headers,
        timeout=timeout,
    )
    if abort_rule is not None:
        # connection reset after K delivered bytes, no partial event, no
        # prior error frame — the killed-engine signature the mid-stream
        # resume path recovers from (docs/resilience.md)
        return EngineAbortResponse(resp, abort_rule.after_bytes)
    if cut_rule is not None:
        return StreamCutResponse(resp, cut_rule.after_bytes)
    return resp


# ------------------------------------------------------------- Retry-After


def retry_after_seconds(state, model: str | None,
                        capability=None) -> int:
    """Retry-After for a 503: if every endpoint serving the model is
    draining, the soonest drain completion (a replacement engine should be
    registering about then — docs/deployment.md); if every endpoint is
    breaker-open, the soonest breaker reopen; otherwise a fraction of the
    queue timeout (capacity should free up well before a full timeout)."""
    resilience = state.resilience
    if model:
        pairs = state.registry.find_by_model(model, capability)
        eps = [ep for ep, _ in pairs]
        draining = [ep for ep in eps
                    if ep.accelerator is not None and ep.accelerator.draining]
        if eps and len(draining) == len(eps):
            wait = min(ep.accelerator.drain_remaining_s for ep in draining)
            return max(1, min(60, math.ceil(wait)))
        if eps and resilience is not None:
            wait = resilience.soonest_reopen_s([ep.id for ep in eps])
            if wait is not None:
                return max(1, math.ceil(wait))
    queue_timeout = state.load_manager.queue_config.queue_timeout_s
    return max(1, min(30, math.ceil(queue_timeout / 4)))
