"""Request-body sanitization for stored history records.

The reference SPECS this contract (tests/contract/
openai_request_sanitization_spec.rs: inline base64 media must never land in
request_history) but ships the test ignored ("TDD RED: request history
sanitization not implemented"). Here it is implemented: `data:` URLs
(image_url), `input_audio.data` / `b64_json` payloads, and any long
base64-looking string under a media key are replaced with a size-preserving
redaction marker before the record is stored, and oversized bodies are
wrapped with a truncation envelope so the stored column stays valid JSON.
"""

from __future__ import annotations

import json
import re
from typing import Any

# Keys whose long base64 string values are inline media payloads. file_data
# carries Responses-API inline files; image_url appears both as an object
# ({"url": ...}) and as a bare string in the Responses API.
_MEDIA_KEYS = frozenset({"data", "b64_json", "audio", "image", "file_data"})
_REDACT_MIN_LEN = 256  # short values (format tags, tiny fixtures) pass through
_BASE64ISH = re.compile(r"^[A-Za-z0-9+/=_\-\s]+$")

MAX_STORED_BODY_BYTES = 32 * 1024


def _redact_data_url(value: str) -> str:
    """Keep only the media-type head of a data: URL. A malformed one with no
    comma must not leak through (base64 never contains commas, so the head
    is safe to keep only when a comma terminates it)."""
    if "," in value:
        head = value.split(",", 1)[0]
        return f"{head},<redacted {len(value)} bytes>"
    return f"data:<redacted {len(value)} bytes>"


def _walk(obj: Any) -> Any:
    if isinstance(obj, dict):
        out = {}
        for key, value in obj.items():
            if isinstance(value, str) and len(value) >= _REDACT_MIN_LEN:
                if value.startswith("data:"):
                    # inline data URL under ANY key (url, image_url string
                    # form, file_data, ...)
                    out[key] = _redact_data_url(value)
                    continue
                if key in _MEDIA_KEYS and _BASE64ISH.fullmatch(value):
                    # media keys redact only base64-looking payloads; a long
                    # plain-text value under a generic "data" key survives
                    # for the dashboard detail view
                    out[key] = f"<redacted {len(value)} bytes>"
                    continue
            out[key] = _walk(value)
        return out
    if isinstance(obj, list):
        return [_walk(item) for item in obj]
    return obj


def sanitize_request_body(body: Any) -> str | None:
    """JSON text safe to persist in request_history.request_body: inline
    media redacted, size bounded in BYTES, always valid JSON (or None when
    the body isn't JSON-serializable)."""
    try:
        text = json.dumps(_walk(body), ensure_ascii=False)
    except (TypeError, ValueError):
        return None
    encoded = text.encode("utf-8")
    if len(encoded) > MAX_STORED_BODY_BYTES:
        prefix = encoded[:MAX_STORED_BODY_BYTES // 2].decode("utf-8", "ignore")
        return json.dumps({
            "_truncated": True,
            "_original_bytes": len(encoded),
            "prefix": prefix,
        }, ensure_ascii=False)
    return text
