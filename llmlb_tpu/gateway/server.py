"""Gateway server entry: bootstrap state, serve, graceful shutdown.

Parity with reference server.rs (axum serve + graceful shutdown on signals)
and main.rs/cli (serve/stop/status subcommands; the single-instance lock lives
in lock.py).

Multi-worker serving (``--workers N`` / ``LLMLB_WORKERS``): a supervisor
forks N shared-nothing gateway processes that share the listen port via
SO_REUSEPORT; see gateway/worker.py and docs/deployment.md. The elected
primary (worker 0) runs the health checker, maintenance, the update
manager's background tasks, and the tray.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
import time

from aiohttp import web

from llmlb_tpu.gateway.app import create_app
from llmlb_tpu.gateway.app_state import build_app_state
from llmlb_tpu.gateway.config import ServerConfig, env_bool
from llmlb_tpu.gateway.gate import InferenceGate  # noqa: F401  (re-export)
from llmlb_tpu.gateway.lock import ServerLock
from llmlb_tpu.gateway.update import UpdateManager
from llmlb_tpu.gateway.worker import (
    WorkerInfo,
    current_worker,
    run_supervisor,
    supports_reuse_port,
    worker_count_from_env,
)

log = logging.getLogger("llmlb_tpu.gateway.server")


def maybe_install_uvloop() -> bool:
    """Opt-in uvloop (LLMLB_UVLOOP=1): a drop-in libuv event loop worth
    ~2-3x on the pure proxy path. Graceful fallback — uvloop is not a
    dependency of this repo, so absence logs and keeps the stdlib loop."""
    if not env_bool("LLMLB_UVLOOP", False):
        return False
    try:
        import uvloop  # type: ignore[import-not-found]
    except ImportError:
        log.warning("LLMLB_UVLOOP=1 but uvloop is not installed; "
                    "using the stdlib asyncio event loop")
        return False
    asyncio.set_event_loop_policy(uvloop.EventLoopPolicy())
    log.info("uvloop event loop policy installed")
    return True


async def run_server(config: ServerConfig | None = None, *,
                     worker: WorkerInfo | None = None,
                     acquire_lock: bool = True) -> None:
    config = config or ServerConfig.from_env()
    worker = worker or current_worker()
    os.makedirs(os.path.dirname(config.database_url) or ".", exist_ok=True)

    from llmlb_tpu.native import ensure_native_built

    ensure_native_built()  # blocking make belongs here, not in a request path

    # In multi-worker mode the supervisor holds the instance lock for the
    # whole group; forked workers must not fight over it.
    lock = ServerLock.acquire(config.port) if acquire_lock else None
    state = await build_app_state(config, worker=worker)
    stop_event = asyncio.Event()

    from llmlb_tpu import __version__

    # Real self-update wiring when LLMLB_UPDATE_REPO/ARTIFACT are set:
    # restart = graceful exit, the supervisor re-execs the (new) artifact.
    state.update_manager = UpdateManager.from_env(
        state.gate, state.http, __version__, events=state.events,
        drain_timeout_s=config.update_drain_timeout_s,
        restart_cb=stop_event.set,
    )
    # Background update checks run on the elected primary only; an apply
    # landing on any worker still drains and exits that worker, which takes
    # the whole group down for the external supervisor to re-exec
    # (docs/deployment.md).
    if worker.is_primary:
        state.update_manager.start_background_tasks()
    app = create_app(state)

    # Short shutdown grace: idle keep-alive connections must not delay a
    # supervisor restart (observed: default 60 s stalls the update re-exec).
    # Access logging is OFF on the proxy hot path by default: one formatted
    # log line per request costs more than the rest of the accounting
    # combined at high request rates (LLMLB_ACCESS_LOG=1 re-enables).
    access_log = (logging.getLogger("aiohttp.access")
                  if env_bool("LLMLB_ACCESS_LOG", False) else None)
    runner = web.AppRunner(app, shutdown_timeout=5.0, access_log=access_log)
    await runner.setup()
    site = web.TCPSite(
        runner, config.host, config.port,
        # N workers bind the same (host, port); the kernel load-balances
        # accepted connections across their accept queues.
        reuse_port=True if worker.multi else None,
    )
    await site.start()
    log.info("llmlb_tpu gateway listening on %s:%d (worker %d/%d)",
             config.host, config.port, worker.index, worker.count)

    probe_host = config.host
    if probe_host in ("0.0.0.0", "::", ""):
        probe_host = "127.0.0.1"
    elif ":" in probe_host:  # bare IPv6 address needs brackets in a URL
        probe_host = f"[{probe_host}]"

    # Tray equivalent (reference gui/tray.rs, win/mac only): opt-in on these
    # headless TPU hosts; menu/notifications surface at /api/system/tray.
    # One tray per gateway instance, not per worker.
    if worker.is_primary and os.environ.get(
        "LLMLB_TRAY", "0"
    ).lower() in ("1", "true"):
        from llmlb_tpu.gateway.tray import TrayController

        state.tray = TrayController(
            f"http://{probe_host}:{config.port}/dashboard",
            state.update_manager,
            events=state.events,
            quit_cb=stop_event.set,
        )
        await state.tray.start()

    async def self_health() -> bool:
        try:
            async with state.http.get(
                f"http://{probe_host}:{config.port}/health", timeout=2
            ) as r:
                return r.status == 200
        except Exception:
            return False

    # If we just restarted into a freshly applied update, watch health for
    # 30 s and roll back from .bak on failure (reference post-restart watch).
    # Primary-only: one watcher per instance decides the rollback.
    watch_task = (
        asyncio.create_task(
            state.update_manager.post_restart_watch(self_health)
        )
        if worker.is_primary else None
    )

    hard_stop = asyncio.Event()
    first_signal_at = 0.0

    def on_signal() -> None:
        nonlocal first_signal_at
        now = time.monotonic()
        if stop_event.is_set():
            # Second signal escalates to hard stop — but only when it is a
            # deliberate repeat, not a duplicate delivery of the first:
            # with --workers, a terminal Ctrl-C reaches each child via the
            # process group AND via the supervisor's forward (same for
            # systemd KillMode=control-group), microseconds apart. That
            # pair must drain gracefully, not abort in-flight streams.
            if now - first_signal_at > 0.5:
                hard_stop.set()
        else:
            first_signal_at = now
        stop_event.set()

    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, on_signal)
        except NotImplementedError:
            pass
    try:
        await stop_event.wait()
    finally:
        log.info("shutting down")
        if watch_task is not None:
            watch_task.cancel()
        if state.tray is not None:
            await state.tray.stop()
        await state.update_manager.stop_background_tasks()
        # Drain in-flight inference before tearing the server down: with the
        # 5 s shutdown grace above, an ordinary SIGTERM would otherwise cut
        # long-running generations mid-stream. Skipped after a FORCE apply
        # (its point is aborting wedged streams) and cut short by a second
        # signal. A NORMAL update apply has already drained, so the wait
        # returns immediately there.
        from llmlb_tpu.gateway.update import ApplyMode

        forced = getattr(
            state.update_manager, "last_apply_mode", None
        ) == ApplyMode.FORCE
        state.gate.start_rejecting()
        if not forced and not hard_stop.is_set():
            drain = asyncio.ensure_future(state.gate.wait_for_idle(30.0))
            bail = asyncio.ensure_future(hard_stop.wait())
            done, pending = await asyncio.wait(
                {drain, bail}, return_when=asyncio.FIRST_COMPLETED
            )
            for p in pending:
                p.cancel()
            if drain in done and not drain.result():
                log.warning("shutdown drain timeout with %d in flight",
                            state.gate.in_flight)
        await runner.cleanup()
        if lock is not None:
            lock.release()


def serve_multi_worker(config: ServerConfig, workers: int) -> None:
    """Supervisor path: hold the instance lock, build the native library
    once (N children racing `make` would step on each other), fork the
    workers, and wait. Each child re-inits logging with its worker id (the
    file sink stays primary-only — N TimedRotatingFileHandlers would race
    the midnight rotation) and runs the ordinary run_server."""
    from llmlb_tpu.gateway.logging_setup import init_logging
    from llmlb_tpu.native import ensure_native_built

    os.makedirs(os.path.dirname(config.database_url) or ".", exist_ok=True)
    ensure_native_built()
    lock = ServerLock.acquire(config.port)
    try:
        def child_main(worker: WorkerInfo) -> int:
            init_logging(file_sink=worker.is_primary)
            maybe_install_uvloop()
            asyncio.run(
                run_server(config, worker=worker, acquire_lock=False)
            )
            return 0

        code = run_supervisor(workers, child_main)
    finally:
        lock.release()
    if code:
        raise SystemExit(code)


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="llmlb", description="TPU-native LLM gateway")
    sub = parser.add_subparsers(dest="command")

    serve = sub.add_parser("serve", help="run the gateway")
    serve.add_argument("--host", default=None)
    serve.add_argument("--port", type=int, default=None)
    serve.add_argument(
        "--workers", type=int, default=None,
        help="number of gateway worker processes sharing the port via "
             "SO_REUSEPORT (default LLMLB_WORKERS or 1)",
    )

    sub.add_parser("status", help="check whether a gateway is running")
    stop = sub.add_parser("stop", help="stop a running gateway")
    stop.add_argument("--port", type=int, default=None)
    assistant = sub.add_parser(
        "assistant", help="API helper: sanitized curl, openapi, guides"
    )
    assistant.add_argument("assistant_args", nargs=argparse.REMAINDER)

    args = parser.parse_args(argv)
    if args.command == "assistant":
        from llmlb_tpu.gateway.assistant import main as assistant_main

        raise SystemExit(assistant_main(args.assistant_args))
    from llmlb_tpu.gateway.logging_setup import init_logging

    # stderr + daily-rotated file sink (reference logging.rs:41-182)
    init_logging()

    config = ServerConfig.from_env()
    if getattr(args, "host", None):
        config = config.__class__(**{**config.__dict__, "host": args.host})
    if getattr(args, "port", None):
        config = config.__class__(**{**config.__dict__, "port": args.port})

    if args.command in (None, "serve"):
        workers = worker_count_from_env(getattr(args, "workers", None))
        if workers > 1 and not supports_reuse_port():
            log.warning("--workers %d requested but SO_REUSEPORT is "
                        "unavailable on this platform; serving "
                        "single-process", workers)
            workers = 1
        if workers > 1:
            serve_multi_worker(config, workers)
        else:
            # Pin the 1-of-1 identity explicitly (and in the env, which
            # current_worker()/logging read): a lingering LLMLB_WORKERS=4
            # must not make this lone process bind with reuse_port or wait
            # for gossip siblings that will never exist.
            os.environ["LLMLB_WORKERS"] = "1"
            maybe_install_uvloop()
            asyncio.run(run_server(config, worker=WorkerInfo(0, 1)))
    elif args.command == "status":
        info = ServerLock.status(config.port)
        if info:
            print(f"running: pid={info['pid']} port={info['port']}")
        else:
            print("not running")
    elif args.command == "stop":
        if ServerLock.stop(config.port):
            print("stopped")
        else:
            print("not running")


if __name__ == "__main__":
    main()
