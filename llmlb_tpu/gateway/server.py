"""Gateway server entry: bootstrap state, serve, graceful shutdown.

Parity with reference server.rs (axum serve + graceful shutdown on signals)
and main.rs/cli (serve/stop/status subcommands; the single-instance lock lives
in lock.py).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal

from aiohttp import web

from llmlb_tpu.gateway.app import create_app
from llmlb_tpu.gateway.app_state import build_app_state
from llmlb_tpu.gateway.config import ServerConfig
from llmlb_tpu.gateway.gate import InferenceGate  # noqa: F401  (re-export)
from llmlb_tpu.gateway.lock import ServerLock
from llmlb_tpu.gateway.update import UpdateManager

log = logging.getLogger("llmlb_tpu.gateway.server")


async def run_server(config: ServerConfig | None = None) -> None:
    config = config or ServerConfig.from_env()
    os.makedirs(os.path.dirname(config.database_url) or ".", exist_ok=True)

    from llmlb_tpu.native import ensure_native_built

    ensure_native_built()  # blocking make belongs here, not in a request path

    lock = ServerLock.acquire(config.port)
    state = await build_app_state(config)
    state.update_manager = UpdateManager(
        state.gate, state.events, drain_timeout_s=config.update_drain_timeout_s
    )
    app = create_app(state)

    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, config.host, config.port)
    await site.start()
    log.info("llmlb_tpu gateway listening on %s:%d", config.host, config.port)

    stop_event = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop_event.set)
        except NotImplementedError:
            pass
    try:
        await stop_event.wait()
    finally:
        log.info("shutting down")
        await runner.cleanup()
        lock.release()


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="llmlb", description="TPU-native LLM gateway")
    sub = parser.add_subparsers(dest="command")

    serve = sub.add_parser("serve", help="run the gateway")
    serve.add_argument("--host", default=None)
    serve.add_argument("--port", type=int, default=None)

    sub.add_parser("status", help="check whether a gateway is running")
    stop = sub.add_parser("stop", help="stop a running gateway")
    stop.add_argument("--port", type=int, default=None)

    args = parser.parse_args(argv)
    from llmlb_tpu.gateway.logging_setup import init_logging

    # stderr + daily-rotated file sink (reference logging.rs:41-182)
    init_logging()

    config = ServerConfig.from_env()
    if getattr(args, "host", None):
        config = config.__class__(**{**config.__dict__, "host": args.host})
    if getattr(args, "port", None):
        config = config.__class__(**{**config.__dict__, "port": args.port})

    if args.command in (None, "serve"):
        asyncio.run(run_server(config))
    elif args.command == "status":
        info = ServerLock.status(config.port)
        if info:
            print(f"running: pid={info['pid']} port={info['port']}")
        else:
            print("not running")
    elif args.command == "stop":
        if ServerLock.stop(config.port):
            print("stopped")
        else:
            print("not running")


if __name__ == "__main__":
    main()
