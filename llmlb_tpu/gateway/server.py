"""Gateway server entry: bootstrap state, serve, graceful shutdown.

Parity with reference server.rs (axum serve + graceful shutdown on signals)
and main.rs/cli (serve/stop/status subcommands; the single-instance lock lives
in lock.py).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal

from aiohttp import web

from llmlb_tpu.gateway.app import create_app
from llmlb_tpu.gateway.app_state import build_app_state
from llmlb_tpu.gateway.config import ServerConfig
from llmlb_tpu.gateway.gate import InferenceGate  # noqa: F401  (re-export)
from llmlb_tpu.gateway.lock import ServerLock
from llmlb_tpu.gateway.update import UpdateManager

log = logging.getLogger("llmlb_tpu.gateway.server")


async def run_server(config: ServerConfig | None = None) -> None:
    config = config or ServerConfig.from_env()
    os.makedirs(os.path.dirname(config.database_url) or ".", exist_ok=True)

    from llmlb_tpu.native import ensure_native_built

    ensure_native_built()  # blocking make belongs here, not in a request path

    lock = ServerLock.acquire(config.port)
    state = await build_app_state(config)
    stop_event = asyncio.Event()

    from llmlb_tpu import __version__

    # Real self-update wiring when LLMLB_UPDATE_REPO/ARTIFACT are set:
    # restart = graceful exit, the supervisor re-execs the (new) artifact.
    state.update_manager = UpdateManager.from_env(
        state.gate, state.http, __version__, events=state.events,
        drain_timeout_s=config.update_drain_timeout_s,
        restart_cb=stop_event.set,
    )
    state.update_manager.start_background_tasks()
    app = create_app(state)

    # Short shutdown grace: idle keep-alive connections must not delay a
    # supervisor restart (observed: default 60 s stalls the update re-exec).
    runner = web.AppRunner(app, shutdown_timeout=5.0)
    await runner.setup()
    site = web.TCPSite(runner, config.host, config.port)
    await site.start()
    log.info("llmlb_tpu gateway listening on %s:%d", config.host, config.port)

    probe_host = config.host
    if probe_host in ("0.0.0.0", "::", ""):
        probe_host = "127.0.0.1"
    elif ":" in probe_host:  # bare IPv6 address needs brackets in a URL
        probe_host = f"[{probe_host}]"

    # Tray equivalent (reference gui/tray.rs, win/mac only): opt-in on these
    # headless TPU hosts; menu/notifications surface at /api/system/tray.
    if os.environ.get("LLMLB_TRAY", "0").lower() in ("1", "true"):
        from llmlb_tpu.gateway.tray import TrayController

        state.tray = TrayController(
            f"http://{probe_host}:{config.port}/dashboard",
            state.update_manager,
            events=state.events,
            quit_cb=stop_event.set,
        )
        await state.tray.start()

    async def self_health() -> bool:
        try:
            async with state.http.get(
                f"http://{probe_host}:{config.port}/health", timeout=2
            ) as r:
                return r.status == 200
        except Exception:
            return False

    # If we just restarted into a freshly applied update, watch health for
    # 30 s and roll back from .bak on failure (reference post-restart watch).
    watch_task = asyncio.create_task(
        state.update_manager.post_restart_watch(self_health)
    )

    hard_stop = asyncio.Event()

    def on_signal() -> None:
        if stop_event.is_set():
            hard_stop.set()  # second signal: skip the graceful drain
        stop_event.set()

    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, on_signal)
        except NotImplementedError:
            pass
    try:
        await stop_event.wait()
    finally:
        log.info("shutting down")
        watch_task.cancel()
        if state.tray is not None:
            await state.tray.stop()
        await state.update_manager.stop_background_tasks()
        # Drain in-flight inference before tearing the server down: with the
        # 5 s shutdown grace above, an ordinary SIGTERM would otherwise cut
        # long-running generations mid-stream. Skipped after a FORCE apply
        # (its point is aborting wedged streams) and cut short by a second
        # signal. A NORMAL update apply has already drained, so the wait
        # returns immediately there.
        from llmlb_tpu.gateway.update import ApplyMode

        forced = getattr(
            state.update_manager, "last_apply_mode", None
        ) == ApplyMode.FORCE
        state.gate.start_rejecting()
        if not forced and not hard_stop.is_set():
            drain = asyncio.ensure_future(state.gate.wait_for_idle(30.0))
            bail = asyncio.ensure_future(hard_stop.wait())
            done, pending = await asyncio.wait(
                {drain, bail}, return_when=asyncio.FIRST_COMPLETED
            )
            for p in pending:
                p.cancel()
            if drain in done and not drain.result():
                log.warning("shutdown drain timeout with %d in flight",
                            state.gate.in_flight)
        await runner.cleanup()
        lock.release()


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="llmlb", description="TPU-native LLM gateway")
    sub = parser.add_subparsers(dest="command")

    serve = sub.add_parser("serve", help="run the gateway")
    serve.add_argument("--host", default=None)
    serve.add_argument("--port", type=int, default=None)

    sub.add_parser("status", help="check whether a gateway is running")
    stop = sub.add_parser("stop", help="stop a running gateway")
    stop.add_argument("--port", type=int, default=None)
    assistant = sub.add_parser(
        "assistant", help="API helper: sanitized curl, openapi, guides"
    )
    assistant.add_argument("assistant_args", nargs=argparse.REMAINDER)

    args = parser.parse_args(argv)
    if args.command == "assistant":
        from llmlb_tpu.gateway.assistant import main as assistant_main

        raise SystemExit(assistant_main(args.assistant_args))
    from llmlb_tpu.gateway.logging_setup import init_logging

    # stderr + daily-rotated file sink (reference logging.rs:41-182)
    init_logging()

    config = ServerConfig.from_env()
    if getattr(args, "host", None):
        config = config.__class__(**{**config.__dict__, "host": args.host})
    if getattr(args, "port", None):
        config = config.__class__(**{**config.__dict__, "port": args.port})

    if args.command in (None, "serve"):
        asyncio.run(run_server(config))
    elif args.command == "status":
        info = ServerLock.status(config.port)
        if info:
            print(f"running: pid={info['pid']} port={info['port']}")
        else:
            print("not running")
    elif args.command == "stop":
        if ServerLock.stop(config.port):
            print("stopped")
        else:
            print("not running")


if __name__ == "__main__":
    main()
