"""Per-endpoint device/system probes for the admin API.

Parity with reference system_info/ (dispatch get_endpoint_system_info
mod.rs:31; llama.cpp /slots probe with /metrics fallback llamacpp.rs:40):
given an endpoint, ask ITS runtime what hardware/capacity sits behind it and
normalize the answer into one shape the dashboard can render. TPU engines
report chip/HBM telemetry (richer than the reference's GPU fields); llama.cpp
reports slot count and context sizes; Ollama reports loaded models and their
VRAM; xLLM-style engines report their /api/system body.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any

import aiohttp

from llmlb_tpu.gateway.types import Endpoint, EndpointType

log = logging.getLogger("llmlb_tpu.gateway.system_info")

PROBE_TIMEOUT_S = 5.0


async def _get_json(session: aiohttp.ClientSession, url: str,
                    headers: dict) -> Any | None:
    try:
        async with session.get(
            url, headers=headers,
            timeout=aiohttp.ClientTimeout(total=PROBE_TIMEOUT_S),
        ) as resp:
            if resp.status != 200:
                return None
            return await resp.json(content_type=None)
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError, ValueError):
        return None


async def _get_text(session: aiohttp.ClientSession, url: str,
                    headers: dict) -> str | None:
    try:
        async with session.get(
            url, headers=headers,
            timeout=aiohttp.ClientTimeout(total=PROBE_TIMEOUT_S),
        ) as resp:
            if resp.status != 200:
                return None
            return await resp.text()
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
        return None


async def _llama_cpp_info(ep: Endpoint, session, headers) -> dict | None:
    """/slots preferred (slot count + per-slot n_ctx), /metrics fallback —
    the reference's two-strategy probe (llamacpp.rs:40)."""
    slots = await _get_json(session, ep.url + "/slots", headers)
    if isinstance(slots, list) and slots:
        n_ctx = [s.get("n_ctx") for s in slots
                 if isinstance(s, dict) and isinstance(s.get("n_ctx"), int)]
        return {
            "device": "llama.cpp",
            "parallel_slots": len(slots),
            "n_ctx": max(n_ctx) if n_ctx else None,
            "busy_slots": sum(
                1 for s in slots
                if isinstance(s, dict) and s.get("is_processing")
            ),
            "source": "slots",
        }
    metrics = await _get_text(session, ep.url + "/metrics", headers)
    if metrics:
        kv_used = None
        for line in metrics.splitlines():
            if line.startswith("llamacpp:kv_cache_tokens"):
                try:
                    kv_used = float(line.split()[-1])
                except (ValueError, IndexError):
                    pass
        return {
            "device": "llama.cpp",
            "kv_cache_tokens": kv_used,
            "source": "metrics",
        }
    return None


async def _tpu_info(ep: Endpoint, session, headers) -> dict | None:
    body = await _get_json(session, ep.url + "/api/health", headers)
    if not isinstance(body, dict):
        return None
    tpu = body.get("tpu") if isinstance(body.get("tpu"), dict) else {}
    engine = body.get("engine") if isinstance(body.get("engine"), dict) else {}
    disagg = body.get("disagg") if isinstance(body.get("disagg"), dict) else {}
    return {
        "device": tpu.get("device_kind") or tpu.get("accelerator") or "tpu",
        "chip_count": tpu.get("chip_count"),
        "hbm_used_bytes": tpu.get("hbm_used_bytes"),
        "hbm_total_bytes": tpu.get("hbm_total_bytes"),
        "num_slots": engine.get("num_slots"),
        "active_slots": engine.get("active_slots"),
        "queued": engine.get("queued"),
        # disaggregation role + live handoff figures (docs/disaggregation.md)
        "role": disagg.get("role") or "both",
        "handoff_backlog": disagg.get("handoff_backlog"),
        "source": "api_health",
    }


async def _ollama_info(ep: Endpoint, session, headers) -> dict | None:
    version, ps = await asyncio.gather(
        _get_json(session, ep.url + "/api/version", headers),
        _get_json(session, ep.url + "/api/ps", headers),
    )
    if version is None and ps is None:
        return None
    loaded = []
    vram = 0
    vram_known = False
    models = (ps or {}).get("models") if isinstance(ps, dict) else None
    for m in models or []:
        if isinstance(m, dict):
            loaded.append(m.get("name"))
            if "size_vram" in m:
                vram_known = True
                vram += m.get("size_vram") or 0
    return {
        "device": "ollama",
        "version": (version or {}).get("version")
        if isinstance(version, dict) else None,
        "loaded_models": loaded,
        # 0 with the field present means "CPU-resident" (a real state);
        # None means the runtime never reported VRAM at all
        "vram_bytes": vram if vram_known else None,
        "source": "api_version+ps",
    }


async def _xllm_info(ep: Endpoint, session, headers) -> dict | None:
    body = await _get_json(session, ep.url + "/api/system", headers)
    if not isinstance(body, dict):
        return None
    return {"device": "xllm", "system": body, "source": "api_system"}


async def get_endpoint_system_info(
    ep: Endpoint, session: aiohttp.ClientSession
) -> dict | None:
    """Dispatch on endpoint type (system_info/mod.rs:31). None when the
    runtime exposes nothing usable."""
    headers = {}
    if ep.api_key:
        headers["Authorization"] = f"Bearer {ep.api_key}"
    if ep.endpoint_type == EndpointType.LLAMA_CPP:
        return await _llama_cpp_info(ep, session, headers)
    if ep.endpoint_type == EndpointType.TPU:
        return await _tpu_info(ep, session, headers)
    if ep.endpoint_type == EndpointType.OLLAMA:
        return await _ollama_info(ep, session, headers)
    if ep.endpoint_type == EndpointType.XLLM:
        return await _xllm_info(ep, session, headers)
    return None
