"""Token accounting for proxied traffic.

Parity with reference token/mod.rs: a streaming accumulator that line-splits
SSE as bytes pass through untouched, captures `usage` when the upstream
provides it (our tpu engine always does; so do OpenAI-compatible servers with
stream_options.include_usage), otherwise accumulates content text and falls
back to tiktoken cl100k_base estimation (token/mod.rs:217-223). A C++ twin of
the hot SSE line-splitter lives in native/ (used when built).
"""

from __future__ import annotations

import json
from functools import lru_cache


@lru_cache(maxsize=1)
def _encoder():
    import tiktoken

    return tiktoken.get_encoding("cl100k_base")


def estimate_tokens(text: str) -> int:
    if not text:
        return 0
    try:
        return len(_encoder().encode(text, disallowed_special=()))
    except Exception:
        # byte-pair estimate fallback: ~4 chars/token heuristic
        return max(1, len(text) // 4)


def extract_usage_from_response(body: dict) -> tuple[int, int] | None:
    usage = body.get("usage")
    if not isinstance(usage, dict):
        return None
    pt = usage.get("prompt_tokens", usage.get("input_tokens"))
    ct = usage.get("completion_tokens", usage.get("output_tokens"))
    if pt is None and ct is None:
        return None
    return int(pt or 0), int(ct or 0)


class StreamingTokenAccumulator:
    """Feed raw SSE bytes; get usage (reported or estimated) at stream end.

    When the C++ scanner (native/sse_scan.cpp) is available, the hot path is
    one native call per chunk; raw bytes are retained so the content-text
    estimation fallback can run in Python at finalize time only if the
    upstream never reported usage.
    """

    def __init__(self):
        self._buffer = b""
        self._content_parts: list[str] = []
        self._usage: tuple[int, int] | None = None
        self._chunks_seen = 0
        self._native = None
        self._raw: list[bytes] | None = None
        try:
            from llmlb_tpu.native import NativeSseScanner

            self._native = NativeSseScanner()
            self._raw = []
        except Exception:
            self._native = None

    def feed(self, chunk: bytes) -> None:
        if self._native is not None:
            self._native.feed(chunk)
            # retain raw bytes only until a usage object shows up — once the
            # upstream has reported, the estimation fallback can never run
            if self._raw is not None:
                if self._native.usage() is not None:
                    self._raw = None
                else:
                    self._raw.append(chunk)
            return
        self._feed_python(chunk)

    def _feed_python(self, chunk: bytes) -> None:
        self._buffer += chunk
        while b"\n" in self._buffer:
            line, self._buffer = self._buffer.split(b"\n", 1)
            self._feed_line(line.strip())

    def _feed_line(self, line: bytes) -> None:
        if not line.startswith(b"data:"):
            return
        data = line[len(b"data:"):].strip()
        if not data or data == b"[DONE]":
            return
        try:
            payload = json.loads(data)
        except (ValueError, UnicodeDecodeError):
            return
        if not isinstance(payload, dict):
            return
        self._chunks_seen += 1
        usage = extract_usage_from_response(payload)
        if usage is not None and usage != (0, 0):
            self._usage = usage
        for choice in payload.get("choices") or []:
            if not isinstance(choice, dict):
                continue
            delta = choice.get("delta") or {}
            content = delta.get("content")
            if isinstance(content, str):
                self._content_parts.append(content)
            text = choice.get("text")
            if isinstance(text, str):
                self._content_parts.append(text)
        # Responses-API streams: output_text deltas
        if payload.get("type") == "response.output_text.delta":
            delta = payload.get("delta")
            if isinstance(delta, str):
                self._content_parts.append(delta)

    def finalize(self, prompt_text: str = "") -> tuple[int, int, bool]:
        """Returns (prompt_tokens, completion_tokens, was_reported)."""
        if self._native is not None:
            usage = self._native.usage()
            if usage is not None:
                return usage[0], usage[1], True
            # no reported usage: replay retained bytes through the Python
            # parser (off the hot path) to estimate from content text
            raw, self._raw = self._raw or [], []
            self._native = None
            for chunk in raw:
                self._feed_python(chunk)
        if self._usage is not None:
            return self._usage[0], self._usage[1], True
        return (
            estimate_tokens(prompt_text),
            estimate_tokens("".join(self._content_parts)),
            False,
        )

    @property
    def chunks_seen(self) -> int:
        if self._native is not None:
            return self._native.frames
        return self._chunks_seen
