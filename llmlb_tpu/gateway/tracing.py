"""Request-lifecycle tracing: gateway → balancer → engine.

Every inbound request gets a trace id (a client-supplied ``X-Request-Id``
is reused when well-formed, otherwise one is minted), echoed on the
response so clients can correlate their logs with gateway traces.
Inference requests additionally record ordered spans — ``auth``,
``admission``, ``queue_wait``, ``endpoint_select``, ``proxy``,
``first_token``, ``decode``, ``done`` — with monotonic timestamps, and the
id is forwarded on the proxied call via ``X-Request-Id`` so the engine
scheduler's ``request_id`` joins the same trace. Completed traces live in
a bounded ring buffer served at ``GET /api/traces`` (+ ``/{id}``) and are
announced on the dashboard event bus as ``TraceCompleted`` events.

Cross-process timelines (docs/tracing.md): ``/api/traces/{id}?view=timeline``
joins the gateway's own spans with the flight-recorder events of EVERY
engine the request touched — the selection target plus any handoff
adopter and resume target named by span attrs — fetched live from each
engine's ``GET /api/requests/{id}/timeline`` and merged into one causally
ordered event list. ``?format=chrome`` exports the same merge as Chrome
trace-event JSON loadable in Perfetto (chrome://tracing).

Multi-worker lookup: SO_REUSEPORT hands ``/api/traces/{id}`` to an
arbitrary worker, which 404s when a sibling served the request. With a
spool directory configured (the gossip dir, automatic under multi-worker),
completed traces are spooled as one JSON file each and any worker answers
for any sibling — the PR 9 /metrics sibling-merge pattern.

No reference counterpart: the reference router only logs per-request
lines. This is the shared spine later perf PRs measure themselves
against — TTFT vs queue wait vs engine step time, per request.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import uuid
from collections import OrderedDict, deque

import aiohttp
from aiohttp import web

REQUEST_ID_HEADER = "X-Request-Id"

# Client-supplied ids are echoed into headers, logs, and label-adjacent
# places; anything outside this shape is replaced, not trusted.
_ID_RE = re.compile(r"^[A-Za-z0-9_.:\-]{1,128}$")

# Canonical lifecycle order, used by consumers (dashboard, tests) to lay
# spans out; traces may omit phases a request never reached.
SPAN_ORDER = ("auth", "admission", "queue_wait", "endpoint_select", "proxy",
              "first_token", "decode", "done")


def mint_request_id(raw: str | None) -> str:
    if raw and _ID_RE.match(raw):
        return raw
    return uuid.uuid4().hex


class TokenTimeline:
    """Bounded per-request token timing marks for streamed responses: one
    monotonic stamp per SSE data chunk that reached the client. Attached to
    the request's trace, it shows WHERE a slow stream stalled (a late first
    mark = prefill/queueing; a gap mid-stream = a slow step, page-pool
    eviction, or an engine hiccup) — the per-request view the ITL histogram
    averages away. Cost: one clock read + one list append per chunk, capped
    at MAX_MARKS; marks beyond the cap keep counting but record nothing."""

    MAX_MARKS = 256

    def __init__(self):
        self.marks: list[float] = []
        self.count = 0

    def mark(self) -> None:
        self.count += 1
        if len(self.marks) < self.MAX_MARKS:
            self.marks.append(time.monotonic())

    def payload(self, trace_t0: float) -> dict:
        """JSON block for the trace: offsets from request arrival (ms),
        plus the largest inter-mark gap — the stall, pre-located."""
        marks_ms = [round((m - trace_t0) * 1000.0, 3) for m in self.marks]
        max_gap = 0.0
        for a, b in zip(marks_ms, marks_ms[1:]):
            max_gap = max(max_gap, b - a)
        return {
            "chunks": self.count,
            "truncated": self.count > len(self.marks),
            "first_ms": marks_ms[0] if marks_ms else None,
            "last_ms": marks_ms[-1] if marks_ms else None,
            "max_gap_ms": round(max_gap, 3),
            "marks_ms": marks_ms,
        }


class RequestTrace:
    """Ordered spans over one request's lifetime. Touched only from the
    event loop; durations come from one monotonic clock."""

    def __init__(self, trace_id: str, method: str, path: str):
        self.trace_id = trace_id
        self.method = method
        self.path = path
        self.started_at = time.time()
        self.t0 = time.monotonic()
        self.model: str | None = None
        self.endpoint_id: str | None = None
        self.endpoint_name: str | None = None
        self.status: int | None = None
        self.error: str | None = None
        self.duration_ms: float | None = None
        self.spans: list[dict] = []
        self._open: dict[str, int] = {}  # name -> index into spans
        # sampled streamed-token timeline (TokenTimeline.payload shape)
        self.token_timeline: dict | None = None

    # --------------------------------------------------------------- spans

    def begin(self, name: str) -> None:
        if name in self._open:
            return
        self._open[name] = len(self.spans)
        self.spans.append({
            "name": name,
            "start_ms": round((time.monotonic() - self.t0) * 1000.0, 3),
            "duration_ms": None,
        })

    def end(self, name: str) -> None:
        idx = self._open.pop(name, None)
        if idx is None:
            return
        span = self.spans[idx]
        now_ms = (time.monotonic() - self.t0) * 1000.0
        span["duration_ms"] = round(max(0.0, now_ms - span["start_ms"]), 3)

    def mark(self, name: str, **attrs) -> None:
        """Point-in-time span (duration 0)."""
        span = {
            "name": name,
            "start_ms": round((time.monotonic() - self.t0) * 1000.0, 3),
            "duration_ms": 0.0,
        }
        if attrs:
            span["attrs"] = attrs
        self.spans.append(span)

    def add_span(self, name: str, *, start_monotonic: float,
                 duration_s: float, **attrs) -> None:
        """Span with caller-measured bounds (e.g. queue_wait from the
        admission queue's own waited_s)."""
        span = {
            "name": name,
            "start_ms": round((start_monotonic - self.t0) * 1000.0, 3),
            "duration_ms": round(max(0.0, duration_s) * 1000.0, 3),
        }
        if attrs:
            span["attrs"] = attrs
        self.spans.append(span)

    def set_endpoint(self, endpoint) -> None:
        self.endpoint_id = endpoint.id
        self.endpoint_name = endpoint.name

    # -------------------------------------------------------------- finish

    def finish(self, status: int, error: str | None = None) -> None:
        now_ms = (time.monotonic() - self.t0) * 1000.0
        for name in list(self._open):
            self.end(name)
        self.status = status
        self.error = error
        self.duration_ms = round(now_ms, 3)
        self.spans.sort(key=lambda s: s["start_ms"])
        self.spans.append({
            "name": "done", "start_ms": round(now_ms, 3), "duration_ms": 0.0,
        })

    def attach_timeline(self, timeline: "TokenTimeline") -> None:
        if timeline.count:
            self.token_timeline = timeline.payload(self.t0)

    def to_dict(self) -> dict:
        d = {
            "trace_id": self.trace_id,
            "method": self.method,
            "path": self.path,
            "started_at": self.started_at,
            "model": self.model,
            "endpoint_id": self.endpoint_id,
            "endpoint_name": self.endpoint_name,
            "status": self.status,
            "error": self.error,
            "duration_ms": self.duration_ms,
            "spans": self.spans,
        }
        if self.token_timeline is not None:
            d["token_timeline"] = self.token_timeline
        return d


class TraceStore:
    """Bounded ring of completed traces + the in-flight set. Thread-safe:
    completion may be observed from bench/scrape threads.

    `spool_dir` (multi-worker): completed traces are additionally written
    as one JSON file each so sibling workers sharing the directory can
    answer `/api/traces/{id}` for requests they never served."""

    SPOOL_RETENTION_S = 600.0
    _SPOOL_PRUNE_EVERY = 64

    def __init__(self, capacity: int = 256, events=None,
                 timeline_interval: int | None = None,
                 spool_dir: str | None = None):
        self.capacity = max(1, capacity)
        self._events = events  # DashboardEventBus | None
        self._lock = threading.Lock()
        self._active: "OrderedDict[str, RequestTrace]" = OrderedDict()
        self._done: deque[RequestTrace] = deque(maxlen=self.capacity)
        self.spool_dir = spool_dir
        self.spool_errors_total = 0
        self._spool_writes = 0
        # token-timeline sampling: every Nth streamed request carries marks
        # (1 = all streams, 0 = none; LLMLB_TRACE_TIMELINE_SAMPLE)
        if timeline_interval is None:
            import os

            try:
                timeline_interval = int(
                    os.environ.get("LLMLB_TRACE_TIMELINE_SAMPLE", "1")
                )
            except ValueError:
                timeline_interval = 1
        self.timeline_interval = max(0, timeline_interval)
        self._timeline_seen = 0

    def sample_timeline(self) -> bool:
        """Decide (round-robin over streamed requests) whether this stream
        records a TokenTimeline — bounded cost under sampling pressure."""
        if self.timeline_interval <= 0:
            return False
        with self._lock:
            self._timeline_seen += 1
            return (self._timeline_seen - 1) % self.timeline_interval == 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._done)

    def start(self, trace_id: str, method: str, path: str) -> RequestTrace:
        trace = RequestTrace(trace_id, method, path)
        with self._lock:
            # A reused client id replaces any stale in-flight entry rather
            # than leaking it.
            self._active[trace.trace_id] = trace
            while len(self._active) > self.capacity:
                self._active.popitem(last=False)
        return trace

    def finish(self, trace: RequestTrace, status: int,
               error: str | None = None) -> None:
        trace.finish(status, error)
        with self._lock:
            # Identity check: a reused client id may have replaced this
            # trace's slot with a newer in-flight trace — don't evict it.
            if self._active.get(trace.trace_id) is trace:
                del self._active[trace.trace_id]
            self._done.append(trace)
        if self.spool_dir:
            self._spool(trace)
        if self._events is not None:
            self._events.publish("TraceCompleted", {
                "trace_id": trace.trace_id,
                "path": trace.path,
                "model": trace.model,
                "endpoint_id": trace.endpoint_id,
                "status": status,
                "duration_ms": trace.duration_ms,
            })

    def get(self, trace_id: str) -> dict | None:
        with self._lock:
            trace = self._active.get(trace_id)
            if trace is not None:
                d = trace.to_dict()
                d["in_flight"] = True
                return d
            for t in self._done:
                if t.trace_id == trace_id:
                    d = t.to_dict()
                    d["in_flight"] = False
                    return d
        # sibling-worker fallback: a spooled trace another worker finished
        return self._read_spool(trace_id)

    # -------------------------------------------------------------- spooling

    def _spool_path(self, trace_id: str) -> str:
        return os.path.join(self.spool_dir, f"trace-{trace_id}.json")

    def _spool(self, trace: RequestTrace) -> None:
        """Write one completed trace atomically (tmp + rename: a sibling's
        concurrent read never sees a torn file). Spool failures count, not
        crash — the in-memory ring stays authoritative."""
        try:
            os.makedirs(self.spool_dir, exist_ok=True)
            path = self._spool_path(trace.trace_id)
            tmp = f"{path}.{os.getpid()}.tmp"
            body = trace.to_dict()
            body["in_flight"] = False
            with open(tmp, "w") as f:
                json.dump(body, f, separators=(",", ":"))
            os.replace(tmp, path)
        except (OSError, TypeError, ValueError):
            self.spool_errors_total += 1
            return
        self._spool_writes += 1
        if self._spool_writes % self._SPOOL_PRUNE_EVERY == 0:
            self._prune_spool()

    def _prune_spool(self) -> None:
        horizon = time.time() - self.SPOOL_RETENTION_S
        try:
            names = os.listdir(self.spool_dir)
        except OSError:
            return
        for name in names:
            if not name.startswith("trace-"):
                continue
            p = os.path.join(self.spool_dir, name)
            try:
                if os.path.getmtime(p) < horizon:
                    os.unlink(p)
            except OSError:
                continue  # allow-silent: a sibling's sweep got there first

    def _read_spool(self, trace_id: str) -> dict | None:
        if not self.spool_dir or not _ID_RE.match(trace_id):
            return None
        try:
            with open(self._spool_path(trace_id)) as f:
                body = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(body, dict) or body.get("trace_id") != trace_id:
            return None
        body["spooled"] = True
        return body

    def list(self, limit: int = 100) -> list[dict]:
        """Most-recent-first completed traces (non-positive limit: none)."""
        if limit <= 0:
            return []
        with self._lock:
            out = [t.to_dict() for t in list(self._done)[-limit:]]
        out.reverse()
        return out


def observe_first_token(state, trace, model: str, endpoint_name: str,
                        started: float, *, streaming: bool = False) -> None:
    """First-byte-from-upstream bookkeeping, applied identically by every
    proxy path: records the gateway TTFT histogram and the ``first_token``
    trace mark; on streams also opens the ``decode`` span. Non-streaming
    callers invoke it at the response boundary, where first token and
    end-to-end coincide."""
    state.metrics.record_ttft(model, endpoint_name,
                              time.monotonic() - started)
    if trace is not None:
        trace.mark("first_token")
        if streaming:
            trace.begin("decode")


# ------------------------------------------------------- cross-process join

# Per-engine timeline fetch budget: a dead engine must not stall the whole
# view — its absence is reported in the `sources` block instead.
TIMELINE_FETCH_TIMEOUT_S = 3.0

# Cross-process happens-before edges the wall-clock merge must not flip:
# clock skew between hosts can stamp the adopting engine's event earlier
# than the emitting engine's. Same-source pairs are never repaired — the
# per-process seq already orders those exactly (and a park/resume cycle
# can legitimately repeat).
_CAUSAL_EDGES = (
    ("handoff_emitted", "adopted"),
    ("staged", "adopted"),
    ("parked", "resumed"),
)


def endpoints_touched(trace: dict) -> list[str]:
    """Endpoint names the trace's spans record, in first-touch order: the
    selection target (`endpoint_select`), any handoff adopter
    (`handoff_adopt`), and any failover resume target (`stream_resume`)."""
    names: list[str] = []
    for span in trace.get("spans") or []:
        ep = (span.get("attrs") or {}).get("endpoint")
        if ep and ep not in names:
            names.append(ep)
    if not names and trace.get("endpoint_name"):
        names.append(trace["endpoint_name"])
    return names


def _gateway_events(trace: dict) -> list[dict]:
    """The trace's own spans re-expressed in flight-recorder event shape
    (wall-clock ts = started_at + the span's monotonic offset), so the
    proxy-side lifecycle interleaves with the engines' events."""
    base = float(trace.get("started_at") or 0.0)
    events = []
    for n, span in enumerate(trace.get("spans") or []):
        ev: dict = {
            "seq": n + 1,
            "ts": round(base + float(span.get("start_ms") or 0.0) / 1000.0, 6),
            "src": "gateway",
            "event": span["name"],
            "request_id": trace["trace_id"],
        }
        if span.get("duration_ms"):
            ev["duration_s"] = round(span["duration_ms"] / 1000.0, 6)
        if span.get("attrs"):
            ev["attrs"] = span["attrs"]
        events.append(ev)
    return events


async def fetch_engine_timelines(
    state, trace: dict,
) -> tuple[list[dict], list[dict]]:
    """Fetch `GET /api/requests/{id}/timeline` from every engine the trace
    names. Returns (events, sources): events carry an `endpoint` label on
    top of their engine-side `src`; sources reports per-engine fetch
    outcomes so a missing engine is visible, not silent."""
    by_name = {e.name: e for e in state.registry.list_all()}
    events: list[dict] = []
    sources: list[dict] = []
    seen: set[tuple] = set()  # spool siblings can return duplicate events
    for name in endpoints_touched(trace):
        info: dict = {"endpoint": name, "ok": False}
        ep = by_name.get(name)
        if ep is None:
            info["error"] = "endpoint not registered"
            sources.append(info)
            continue
        url = (ep.url.rstrip("/")
               + f"/api/requests/{trace['trace_id']}/timeline")
        try:
            timeout = aiohttp.ClientTimeout(total=TIMELINE_FETCH_TIMEOUT_S)
            async with state.http.get(url, timeout=timeout) as resp:
                if resp.status == 200:
                    body = await resp.json()
                else:
                    info["error"] = f"HTTP {resp.status}"
                    sources.append(info)
                    continue
        except Exception as e:  # noqa: BLE001 — any fetch failure reports
            info["error"] = str(e) or type(e).__name__
            sources.append(info)
            continue
        fetched = 0
        for ev in (body.get("events") or []):
            if not isinstance(ev, dict):
                continue
            key = (ev.get("src"), ev.get("seq"))
            if key in seen:
                continue
            seen.add(key)
            ev = dict(ev)
            ev["endpoint"] = name
            events.append(ev)
            fetched += 1
        info.update(ok=True, events=fetched, source=body.get("source"))
        sources.append(info)
    return events, sources


def _event_sort_key(ev: dict):
    return (float(ev.get("ts") or 0.0), str(ev.get("src") or ""),
            int(ev.get("seq") or 0))


def repair_causal_order(events: list[dict]) -> None:
    """Clamp cross-source effect events that wall-clock skew stamped
    before their cause (handoff emit → adopt, stage → adopt, park →
    resume): the effect's ts moves just past the latest other-source
    cause and the event is flagged `ts_adjusted`. In-place; re-sorts."""
    changed = False
    for cause_name, effect_name in _CAUSAL_EDGES:
        causes = [e for e in events if e.get("event") == cause_name]
        if not causes:
            continue
        for ev in events:
            if ev.get("event") != effect_name:
                continue
            prior = [c for c in causes if c.get("src") != ev.get("src")]
            if not prior:
                continue
            cmax = max(float(c.get("ts") or 0.0) for c in prior)
            if float(ev.get("ts") or 0.0) < cmax:
                ev["ts"] = round(cmax + 1e-6, 6)
                ev["ts_adjusted"] = True
                changed = True
    if changed:
        events.sort(key=_event_sort_key)


def merge_timeline(trace: dict, engine_events: list[dict],
                   sources: list[dict]) -> dict:
    """One ordered cross-process timeline: gateway spans + every fetched
    engine event, sorted by (wall ts, source, per-source seq) with causal
    repair for skewed cross-process edges."""
    events = _gateway_events(trace) + engine_events
    events.sort(key=_event_sort_key)
    repair_causal_order(events)
    return {
        "trace_id": trace["trace_id"],
        "endpoints": endpoints_touched(trace),
        "sources": sources,
        "events": events,
        "event_count": len(events),
    }


def chrome_trace(timeline: dict) -> dict:
    """Chrome trace-event JSON (Perfetto / chrome://tracing): one pid per
    process (gateway + each engine source), complete `X` slices for
    duration-bearing events, `i` instants for the rest. Timestamps are
    microseconds from the earliest event."""
    events = timeline.get("events") or []
    t0 = min((float(e.get("ts") or 0.0) for e in events), default=0.0)
    pids: dict[str, int] = {}
    out: list[dict] = []

    def pid_for(ev: dict) -> int:
        src = str(ev.get("src") or "?")
        label = (f"{ev['endpoint']} ({src})"
                 if ev.get("endpoint") else src)
        if src not in pids:
            pids[src] = len(pids) + 1
            out.append({"ph": "M", "name": "process_name", "pid": pids[src],
                        "tid": 0, "args": {"name": label}})
        return pids[src]

    for ev in events:
        pid = pid_for(ev)
        args = dict(ev.get("attrs") or {})
        args["request_id"] = ev.get("request_id")
        if ev.get("ts_adjusted"):
            args["ts_adjusted"] = True
        ts_us = round((float(ev.get("ts") or 0.0) - t0) * 1e6, 3)
        rec = {"name": ev.get("event"), "pid": pid, "tid": 0,
               "ts": ts_us, "cat": "llmlb", "args": args}
        if ev.get("duration_s"):
            rec.update(ph="X", dur=round(float(ev["duration_s"]) * 1e6, 3))
        else:
            rec.update(ph="i", s="p")
        out.append(rec)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# ------------------------------------------------------------------ handlers


async def list_traces(request: web.Request) -> web.Response:
    state = request.app["state"]
    try:
        limit = min(int(request.query.get("limit", 100)), 500)
    except ValueError:
        return web.json_response({"error": "limit must be an integer"},
                                 status=400)
    return web.json_response({"traces": state.traces.list(limit)})


async def get_trace(request: web.Request) -> web.Response:
    """GET /api/traces/{id} — one trace. `?view=timeline` joins the
    gateway spans with every touched engine's flight-recorder events into
    one causally ordered cross-process timeline; `?format=chrome` exports
    that merge as Chrome trace-event JSON (Perfetto-loadable)."""
    state = request.app["state"]
    trace = state.traces.get(request.match_info["trace_id"])
    if trace is None:
        return web.json_response({"error": "trace not found"}, status=404)
    want_chrome = request.query.get("format") == "chrome"
    want_timeline = request.query.get("view") == "timeline" or want_chrome
    if not want_timeline:
        return web.json_response(trace)
    engine_events, sources = await fetch_engine_timelines(state, trace)
    timeline = merge_timeline(trace, engine_events, sources)
    if want_chrome:
        return web.json_response(chrome_trace(timeline))
    body = dict(trace)
    body["timeline"] = timeline
    return web.json_response(body)
