"""Request-lifecycle tracing: gateway → balancer → engine.

Every inbound request gets a trace id (a client-supplied ``X-Request-Id``
is reused when well-formed, otherwise one is minted), echoed on the
response so clients can correlate their logs with gateway traces.
Inference requests additionally record ordered spans — ``auth``,
``admission``, ``queue_wait``, ``endpoint_select``, ``proxy``,
``first_token``, ``decode``, ``done`` — with monotonic timestamps, and the
id is forwarded on the proxied call via ``X-Request-Id`` so the engine
scheduler's ``request_id`` joins the same trace. Completed traces live in
a bounded ring buffer served at ``GET /api/traces`` (+ ``/{id}``) and are
announced on the dashboard event bus as ``TraceCompleted`` events.

No reference counterpart: the reference router only logs per-request
lines. This is the shared spine later perf PRs measure themselves
against — TTFT vs queue wait vs engine step time, per request.
"""

from __future__ import annotations

import re
import threading
import time
import uuid
from collections import OrderedDict, deque

from aiohttp import web

REQUEST_ID_HEADER = "X-Request-Id"

# Client-supplied ids are echoed into headers, logs, and label-adjacent
# places; anything outside this shape is replaced, not trusted.
_ID_RE = re.compile(r"^[A-Za-z0-9_.:\-]{1,128}$")

# Canonical lifecycle order, used by consumers (dashboard, tests) to lay
# spans out; traces may omit phases a request never reached.
SPAN_ORDER = ("auth", "admission", "queue_wait", "endpoint_select", "proxy",
              "first_token", "decode", "done")


def mint_request_id(raw: str | None) -> str:
    if raw and _ID_RE.match(raw):
        return raw
    return uuid.uuid4().hex


class TokenTimeline:
    """Bounded per-request token timing marks for streamed responses: one
    monotonic stamp per SSE data chunk that reached the client. Attached to
    the request's trace, it shows WHERE a slow stream stalled (a late first
    mark = prefill/queueing; a gap mid-stream = a slow step, page-pool
    eviction, or an engine hiccup) — the per-request view the ITL histogram
    averages away. Cost: one clock read + one list append per chunk, capped
    at MAX_MARKS; marks beyond the cap keep counting but record nothing."""

    MAX_MARKS = 256

    def __init__(self):
        self.marks: list[float] = []
        self.count = 0

    def mark(self) -> None:
        self.count += 1
        if len(self.marks) < self.MAX_MARKS:
            self.marks.append(time.monotonic())

    def payload(self, trace_t0: float) -> dict:
        """JSON block for the trace: offsets from request arrival (ms),
        plus the largest inter-mark gap — the stall, pre-located."""
        marks_ms = [round((m - trace_t0) * 1000.0, 3) for m in self.marks]
        max_gap = 0.0
        for a, b in zip(marks_ms, marks_ms[1:]):
            max_gap = max(max_gap, b - a)
        return {
            "chunks": self.count,
            "truncated": self.count > len(self.marks),
            "first_ms": marks_ms[0] if marks_ms else None,
            "last_ms": marks_ms[-1] if marks_ms else None,
            "max_gap_ms": round(max_gap, 3),
            "marks_ms": marks_ms,
        }


class RequestTrace:
    """Ordered spans over one request's lifetime. Touched only from the
    event loop; durations come from one monotonic clock."""

    def __init__(self, trace_id: str, method: str, path: str):
        self.trace_id = trace_id
        self.method = method
        self.path = path
        self.started_at = time.time()
        self.t0 = time.monotonic()
        self.model: str | None = None
        self.endpoint_id: str | None = None
        self.endpoint_name: str | None = None
        self.status: int | None = None
        self.error: str | None = None
        self.duration_ms: float | None = None
        self.spans: list[dict] = []
        self._open: dict[str, int] = {}  # name -> index into spans
        # sampled streamed-token timeline (TokenTimeline.payload shape)
        self.token_timeline: dict | None = None

    # --------------------------------------------------------------- spans

    def begin(self, name: str) -> None:
        if name in self._open:
            return
        self._open[name] = len(self.spans)
        self.spans.append({
            "name": name,
            "start_ms": round((time.monotonic() - self.t0) * 1000.0, 3),
            "duration_ms": None,
        })

    def end(self, name: str) -> None:
        idx = self._open.pop(name, None)
        if idx is None:
            return
        span = self.spans[idx]
        now_ms = (time.monotonic() - self.t0) * 1000.0
        span["duration_ms"] = round(max(0.0, now_ms - span["start_ms"]), 3)

    def mark(self, name: str, **attrs) -> None:
        """Point-in-time span (duration 0)."""
        span = {
            "name": name,
            "start_ms": round((time.monotonic() - self.t0) * 1000.0, 3),
            "duration_ms": 0.0,
        }
        if attrs:
            span["attrs"] = attrs
        self.spans.append(span)

    def add_span(self, name: str, *, start_monotonic: float,
                 duration_s: float, **attrs) -> None:
        """Span with caller-measured bounds (e.g. queue_wait from the
        admission queue's own waited_s)."""
        span = {
            "name": name,
            "start_ms": round((start_monotonic - self.t0) * 1000.0, 3),
            "duration_ms": round(max(0.0, duration_s) * 1000.0, 3),
        }
        if attrs:
            span["attrs"] = attrs
        self.spans.append(span)

    def set_endpoint(self, endpoint) -> None:
        self.endpoint_id = endpoint.id
        self.endpoint_name = endpoint.name

    # -------------------------------------------------------------- finish

    def finish(self, status: int, error: str | None = None) -> None:
        now_ms = (time.monotonic() - self.t0) * 1000.0
        for name in list(self._open):
            self.end(name)
        self.status = status
        self.error = error
        self.duration_ms = round(now_ms, 3)
        self.spans.sort(key=lambda s: s["start_ms"])
        self.spans.append({
            "name": "done", "start_ms": round(now_ms, 3), "duration_ms": 0.0,
        })

    def attach_timeline(self, timeline: "TokenTimeline") -> None:
        if timeline.count:
            self.token_timeline = timeline.payload(self.t0)

    def to_dict(self) -> dict:
        d = {
            "trace_id": self.trace_id,
            "method": self.method,
            "path": self.path,
            "started_at": self.started_at,
            "model": self.model,
            "endpoint_id": self.endpoint_id,
            "endpoint_name": self.endpoint_name,
            "status": self.status,
            "error": self.error,
            "duration_ms": self.duration_ms,
            "spans": self.spans,
        }
        if self.token_timeline is not None:
            d["token_timeline"] = self.token_timeline
        return d


class TraceStore:
    """Bounded ring of completed traces + the in-flight set. Thread-safe:
    completion may be observed from bench/scrape threads."""

    def __init__(self, capacity: int = 256, events=None,
                 timeline_interval: int | None = None):
        self.capacity = max(1, capacity)
        self._events = events  # DashboardEventBus | None
        self._lock = threading.Lock()
        self._active: "OrderedDict[str, RequestTrace]" = OrderedDict()
        self._done: deque[RequestTrace] = deque(maxlen=self.capacity)
        # token-timeline sampling: every Nth streamed request carries marks
        # (1 = all streams, 0 = none; LLMLB_TRACE_TIMELINE_SAMPLE)
        if timeline_interval is None:
            import os

            try:
                timeline_interval = int(
                    os.environ.get("LLMLB_TRACE_TIMELINE_SAMPLE", "1")
                )
            except ValueError:
                timeline_interval = 1
        self.timeline_interval = max(0, timeline_interval)
        self._timeline_seen = 0

    def sample_timeline(self) -> bool:
        """Decide (round-robin over streamed requests) whether this stream
        records a TokenTimeline — bounded cost under sampling pressure."""
        if self.timeline_interval <= 0:
            return False
        with self._lock:
            self._timeline_seen += 1
            return (self._timeline_seen - 1) % self.timeline_interval == 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._done)

    def start(self, trace_id: str, method: str, path: str) -> RequestTrace:
        trace = RequestTrace(trace_id, method, path)
        with self._lock:
            # A reused client id replaces any stale in-flight entry rather
            # than leaking it.
            self._active[trace.trace_id] = trace
            while len(self._active) > self.capacity:
                self._active.popitem(last=False)
        return trace

    def finish(self, trace: RequestTrace, status: int,
               error: str | None = None) -> None:
        trace.finish(status, error)
        with self._lock:
            # Identity check: a reused client id may have replaced this
            # trace's slot with a newer in-flight trace — don't evict it.
            if self._active.get(trace.trace_id) is trace:
                del self._active[trace.trace_id]
            self._done.append(trace)
        if self._events is not None:
            self._events.publish("TraceCompleted", {
                "trace_id": trace.trace_id,
                "path": trace.path,
                "model": trace.model,
                "endpoint_id": trace.endpoint_id,
                "status": status,
                "duration_ms": trace.duration_ms,
            })

    def get(self, trace_id: str) -> dict | None:
        with self._lock:
            trace = self._active.get(trace_id)
            if trace is not None:
                d = trace.to_dict()
                d["in_flight"] = True
                return d
            for t in self._done:
                if t.trace_id == trace_id:
                    d = t.to_dict()
                    d["in_flight"] = False
                    return d
        return None

    def list(self, limit: int = 100) -> list[dict]:
        """Most-recent-first completed traces (non-positive limit: none)."""
        if limit <= 0:
            return []
        with self._lock:
            out = [t.to_dict() for t in list(self._done)[-limit:]]
        out.reverse()
        return out


def observe_first_token(state, trace, model: str, endpoint_name: str,
                        started: float, *, streaming: bool = False) -> None:
    """First-byte-from-upstream bookkeeping, applied identically by every
    proxy path: records the gateway TTFT histogram and the ``first_token``
    trace mark; on streams also opens the ``decode`` span. Non-streaming
    callers invoke it at the response boundary, where first token and
    end-to-end coincide."""
    state.metrics.record_ttft(model, endpoint_name,
                              time.monotonic() - started)
    if trace is not None:
        trace.mark("first_token")
        if streaming:
            trace.begin("decode")


# ------------------------------------------------------------------ handlers


async def list_traces(request: web.Request) -> web.Response:
    state = request.app["state"]
    try:
        limit = min(int(request.query.get("limit", 100)), 500)
    except ValueError:
        return web.json_response({"error": "limit must be an integer"},
                                 status=400)
    return web.json_response({"traces": state.traces.list(limit)})


async def get_trace(request: web.Request) -> web.Response:
    state = request.app["state"]
    trace = state.traces.get(request.match_info["trace_id"])
    if trace is None:
        return web.json_response({"error": "trace not found"}, status=404)
    return web.json_response(trace)
