"""System-tray equivalent: a menu-model controller over the update lifecycle.

Parity with reference gui/tray.rs:37-135 — the reference builds a win/mac
tray-icon whose menu shows "Open Dashboard", an update line that tracks the
UpdateManager state (notify on available, click-to-apply), and the configured
update schedule; tray events are proxied into the update manager.

This build targets Linux TPU hosts, where there is no desktop shell, so the
tray is split into a platform-neutral controller (menu model + event-bus
subscription + action dispatch — everything gui/tray.rs does besides drawing)
and a pluggable backend. The shipped `HeadlessTrayBackend` records menu state
and notifications and logs them (queryable in tests and over
`/api/system/tray`); a GUI backend need only implement `update_menu`/`notify`.
Enable with LLMLB_TRAY=1 (the reference compiles the tray only on win/mac;
headless is our "unsupported platform" analogue, not a stub of the logic).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Callable

log = logging.getLogger("llmlb_tpu.gateway.tray")


class HeadlessTrayBackend:
    """Backend that records the menu model and notifications.

    Stands in for tray-icon on hosts with no display server; the controller
    logic above it is identical to what a GUI backend would drive.
    """

    def __init__(self, max_notifications: int = 50):
        self.menu: list[dict[str, Any]] = []
        self.notifications: list[dict[str, Any]] = []
        self._max = max_notifications

    def update_menu(self, items: list[dict[str, Any]]) -> None:
        self.menu = items

    def notify(self, title: str, body: str) -> None:
        self.notifications.append(
            {"title": title, "body": body, "ts": time.time()}
        )
        del self.notifications[:-self._max]
        log.info("tray notification: %s — %s", title, body)


class TrayController:
    """Builds the tray menu from update state and dispatches menu actions.

    Mirrors the reference's menu composition (gui/tray.rs:37-135): a static
    "Open Dashboard" entry, a dynamic update entry whose label/enabled state
    follow the UpdateManager state machine, a read-only schedule line, and
    Quit. `activate(item_id)` is the click path the reference proxies into
    the update manager.
    """

    def __init__(
        self,
        dashboard_url: str,
        update_manager,
        events=None,
        backend=None,
        quit_cb: Callable[[], None] | None = None,
        open_url_cb: Callable[[str], None] | None = None,
    ):
        self.dashboard_url = dashboard_url
        self.update = update_manager
        self.events = events
        self.backend = backend or HeadlessTrayBackend()
        self.quit_cb = quit_cb
        # Opening a browser is a platform side effect; injectable so servers
        # and tests never spawn one.
        self.open_url_cb = open_url_cb or (
            lambda url: log.info("open dashboard: %s", url)
        )
        self._task: asyncio.Task | None = None
        self._sub_id: int | None = None
        self._notified_version: str | None = None
        self.refresh()

    # ------------------------------------------------------------- menu model

    def _update_item(self) -> dict[str, Any]:
        st = self.update.status() if self.update else {"state": "up_to_date"}
        state = st.get("state", "up_to_date")
        version = st.get("available_version")
        if state == "available" and version:
            return {"id": "update", "label": f"Update to {version} available — apply",
                    "enabled": True}
        if state == "draining":
            return {"id": "update", "label": "Update: draining in-flight requests…",
                    "enabled": False}
        if state == "applying":
            return {"id": "update", "label": "Update: applying…", "enabled": False}
        if state == "failed":
            err = (st.get("error") or "unknown error")[:80]
            return {"id": "update", "label": f"Update failed: {err} — retry check",
                    "enabled": True}
        return {"id": "update", "label": "Check for updates", "enabled": True}

    def _schedule_item(self) -> dict[str, Any]:
        sched = (self.update.status().get("schedule")
                 if self.update else None) or {}
        mode = sched.get("mode", "immediate")
        if mode == "at_time" and sched.get("at_time"):
            when = time.strftime("%H:%M", time.localtime(sched["at_time"]))
            label = f"Update schedule: at {when}"
        elif mode == "on_idle":
            label = "Update schedule: when idle"
        else:
            label = "Update schedule: immediate"
        return {"id": "schedule", "label": label, "enabled": False}

    def menu_model(self) -> list[dict[str, Any]]:
        return [
            {"id": "open_dashboard", "label": "Open Dashboard", "enabled": True},
            self._update_item(),
            self._schedule_item(),
            {"id": "quit", "label": "Quit", "enabled": True},
        ]

    def refresh(self) -> None:
        self.backend.update_menu(self.menu_model())

    # ---------------------------------------------------------------- actions

    async def activate(self, item_id: str) -> dict[str, Any]:
        """Dispatch a menu click (the reference's tray→update-manager proxy)."""
        if item_id == "open_dashboard":
            self.open_url_cb(self.dashboard_url)
            return {"ok": True}
        if item_id == "update":
            st = self.update.status()
            if st.get("state") == "available" and st.get("available_version"):
                started = self.update.request_apply()
                self.refresh()
                return {"ok": started, "action": "apply"}
            result = await self.update.check(force=True)
            self.refresh()
            return {"ok": True, "action": "check", **{
                k: v for k, v in result.items() if k in ("available", "version")
            }}
        if item_id == "quit":
            if self.quit_cb:
                self.quit_cb()
            return {"ok": True}
        return {"ok": False, "error": f"unknown item {item_id!r}"}

    # ----------------------------------------------------- event subscription

    async def start(self) -> None:
        """Follow UpdateStateChanged on the event bus: refresh the menu and
        raise a notification when an update becomes available or fails."""
        if self.events is None:
            return
        self._sub_id, queue = self.events.subscribe()
        self._task = asyncio.create_task(self._pump(queue), name="tray-events")

    async def _pump(self, queue: asyncio.Queue) -> None:
        while True:
            event = await queue.get()
            try:
                if event.get("type") != "UpdateStateChanged":
                    continue
                data = event.get("data") or {}
                state, version = data.get("state"), data.get("version")
                if (state == "available" and version
                        and version != self._notified_version):
                    self._notified_version = version
                    self.backend.notify(
                        "Update available",
                        f"Version {version} is ready to apply from the tray "
                        "menu.",
                    )
                elif state == "failed":
                    self.backend.notify(
                        "Update failed",
                        str(self.update.status().get("error") or "see logs"),
                    )
                self.refresh()
            except Exception:
                # one bad event must not kill tray notifications for good
                log.exception("tray event handling failed; continuing")

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                # allow-silent: a pump that died earlier must not abort the
                # server's shutdown sequence (drain + update stop follow us)
                pass
            self._task = None
        if self.events is not None and self._sub_id is not None:
            self.events.unsubscribe(self._sub_id)
            self._sub_id = None

    def status(self) -> dict[str, Any]:
        """Queryable tray state for /api/system/tray and tests."""
        return {
            "menu": self.backend.menu,
            "notifications": getattr(self.backend, "notifications", []),
        }
