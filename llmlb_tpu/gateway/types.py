"""Core gateway domain types.

Counterpart of the reference's types/endpoint.rs + common/auth.rs, re-designed:
the `TPU` endpoint type is first-class (detection priority #1) and telemetry
fields are accelerator-generic (chip/HBM) rather than CUDA-specific
(reference types/endpoint.rs:308-379 carries GPU VRAM fields).
"""

from __future__ import annotations

import dataclasses
import enum
import time
import uuid


class EndpointType(str, enum.Enum):
    TPU = "tpu"  # in-tree JAX engine — ours, probed first
    XLLM = "xllm"
    OLLAMA = "ollama"
    VLLM = "vllm"
    LM_STUDIO = "lm_studio"
    LLAMA_CPP = "llama_cpp"
    OPENAI_COMPATIBLE = "openai_compatible"


class EndpointStatus(str, enum.Enum):
    PENDING = "pending"
    ONLINE = "online"
    OFFLINE = "offline"
    ERROR = "error"


class Capability(str, enum.Enum):
    CHAT_COMPLETION = "chat_completion"
    EMBEDDINGS = "embeddings"
    IMAGE_GENERATION = "image_generation"
    AUDIO_TRANSCRIPTION = "audio_transcription"
    AUDIO_SPEECH = "audio_speech"
    # Grammar-constrained decoding (response_format json_schema / forced
    # tool_choice). Advertised by tpu:// engines in /v1/models; the gateway
    # steers constrained requests to endpoints that have it.
    STRUCTURED_OUTPUTS = "structured_outputs"
    # Disaggregated prefill/decode roles (docs/disaggregation.md): tpu://
    # engines advertise which phase(s) they serve on the /v1/models
    # capability list; the balancer steers prefill-heavy requests toward
    # PREFILL-capable endpoints and handoff adoption toward DECODE-capable
    # ones. Engines running --role both/split advertise both.
    PREFILL = "prefill"
    DECODE = "decode"
    # Multi-LoRA serving (docs/lora.md): a tpu:// engine started with
    # --lora-dir advertises "lora" on its base model entry ("I can hot-load
    # any adapter in my store") and one extra model entry per RESIDENT
    # adapter (`base:adapter`). The balancer routes adapter traffic to
    # endpoints where it is already hot and falls back to any lora-capable
    # endpoint — triggering a hot-load — before 404ing.
    LORA = "lora"


class Role(str, enum.Enum):
    ADMIN = "admin"
    VIEWER = "viewer"


class Permission(str, enum.Enum):
    """API-key permission scopes (parity: reference common/auth.rs:59-97)."""

    OPENAI_INFERENCE = "openai.inference"
    OPENAI_MODELS_READ = "openai.models.read"
    ENDPOINTS_READ = "endpoints.read"
    ENDPOINTS_MANAGE = "endpoints.manage"
    USERS_MANAGE = "users.manage"
    INVITATIONS_MANAGE = "invitations.manage"
    LOGS_READ = "logs.read"
    METRICS_READ = "metrics.read"
    REGISTRY_READ = "registry.read"


class TpsApiKind(str, enum.Enum):
    """Which API family a TPS measurement belongs to."""

    CHAT = "chat"
    COMPLETION = "completion"
    RESPONSES = "responses"
    EMBEDDINGS = "embeddings"
    OTHER = "other"


@dataclasses.dataclass
class AcceleratorInfo:
    """Chip/HBM + engine-load telemetry reported by an endpoint's health probe.

    The scheduler (balancer.select_endpoint) folds these into placement:
    HBM pressure and engine queue depth demote an endpoint relative to its
    measured TPS (the reference read GPU fields for display only,
    health/endpoint_checker.rs:515 — acting on them is a TPU-native extension).
    """

    accelerator: str | None = None  # "tpu" | "gpu" | ...
    chip_count: int = 0
    hbm_used_bytes: int = 0
    hbm_total_bytes: int = 0
    utilization: float | None = None
    # Engine load figures (tpu:// engines report these in /api/health):
    queue_depth: int = 0  # requests waiting for a slot
    active_slots: int = 0
    num_slots: int = 0
    # Disaggregation role from the engine's /api/health disagg block
    # (docs/disaggregation.md): "both" | "split" | "prefill" | "decode";
    # None for endpoints that do not advertise one (treated as "both").
    # Re-parsed on every probe, so a restarted engine whose role changed
    # re-routes within one probe interval.
    role: str | None = None
    # Graceful drain advertisement (docs/deployment.md): a draining engine
    # still answers probes (status stays online — its models must not 404)
    # but is ejected from selection (balancer._permitted) within one probe
    # interval; `drain_remaining_s` feeds the gateway's Retry-After when
    # every endpoint for a model is draining.
    draining: bool = False
    drain_remaining_s: float = 0.0
    # Multi-LoRA advertisement from the engine's /api/health lora block
    # (docs/lora.md): None when the endpoint does not serve adapters;
    # otherwise the RESIDENT (hot) adapter names. Re-parsed every probe —
    # the health checker mirrors it into `base:adapter` model entries so
    # adapter routing sees hot-loads/evictions within one probe interval.
    lora_loaded: tuple[str, ...] | None = None
    # Every SERVABLE adapter in the endpoint's store (resident or not).
    # Lets the gateway refuse an adapter NO endpoint could hot-load with a
    # clean 400 naming the field, instead of proxying to a certain
    # engine-side 400 (which the resilience layer normalizes to 502). An
    # adapter dropped into a store propagates here within one probe.
    lora_available: tuple[str, ...] | None = None
    sampled_at: float = 0.0  # when the probe captured this; 0 = never

    @property
    def hbm_pressure(self) -> float | None:
        if self.hbm_total_bytes <= 0:
            return None
        return self.hbm_used_bytes / self.hbm_total_bytes


@dataclasses.dataclass
class Endpoint:
    name: str
    base_url: str
    id: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex)
    api_key: str | None = None
    endpoint_type: EndpointType = EndpointType.OPENAI_COMPATIBLE
    status: EndpointStatus = EndpointStatus.PENDING
    latency_ms: float | None = None
    consecutive_failures: int = 0
    # In-band circuit-breaker state (gateway/resilience.py), mirrored here by
    # the registry so every endpoint listing carries it. Transient: not
    # persisted — a restarted gateway starts with closed breakers.
    breaker_state: str = "closed"
    accelerator: AcceleratorInfo = dataclasses.field(default_factory=AcceleratorInfo)
    created_at: float = dataclasses.field(default_factory=time.time)
    updated_at: float = dataclasses.field(default_factory=time.time)
    last_checked_at: float | None = None

    @property
    def url(self) -> str:
        return self.base_url.rstrip("/")


@dataclasses.dataclass
class EndpointModel:
    endpoint_id: str
    model_id: str  # engine-local name (e.g. "llama3:8b" on ollama)
    canonical_name: str  # canonical name exposed by the gateway
    capabilities: list[Capability] = dataclasses.field(
        default_factory=lambda: [Capability.CHAT_COMPLETION]
    )
    context_length: int | None = None
    created_at: float = dataclasses.field(default_factory=time.time)


@dataclasses.dataclass
class HealthCheckResult:
    endpoint_id: str
    ok: bool
    latency_ms: float | None
    error: str | None = None
    accelerator: AcceleratorInfo | None = None
    models_payload: dict | None = None  # /v1/models body captured by the probe
    checked_at: float = dataclasses.field(default_factory=time.time)
