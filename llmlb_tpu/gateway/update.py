"""Self-update manager: state machine + drain-aware apply.

Parity with reference update/ (state machine mod.rs:59-123, background tasks
:807-905, drain via InferenceGate, scheduling schedule.rs:17-43, post-apply
health watch + rollback). The binary-swap mechanics differ (we restart the
Python process via an operator-provided hook or exit-for-supervisor), but the
externally observable lifecycle — check → available → draining (503s on /v1/*)
→ applying → restart — and the admin API shape are preserved.
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import logging
import time

from llmlb_tpu.gateway.events import DashboardEventBus
from llmlb_tpu.gateway.gate import InferenceGate

log = logging.getLogger("llmlb_tpu.gateway.update")


class UpdateState(str, enum.Enum):
    UP_TO_DATE = "up_to_date"
    AVAILABLE = "available"
    DRAINING = "draining"
    APPLYING = "applying"
    FAILED = "failed"


class ApplyMode(str, enum.Enum):
    NORMAL = "normal"  # wait for in-flight inference to drain
    FORCE = "force"  # abort in-flight


@dataclasses.dataclass
class ScheduleConfig:
    mode: str = "immediate"  # immediate | on_idle | at_time
    at_time: float | None = None


class UpdateManager:
    def __init__(
        self,
        gate: InferenceGate,
        events: DashboardEventBus | None = None,
        drain_timeout_s: float = 300.0,
        apply_hook=None,  # async callable that performs the actual swap/restart
        check_hook=None,  # async callable returning {"version": ..} | None
    ):
        self.gate = gate
        self.events = events
        self.drain_timeout_s = drain_timeout_s
        self.apply_hook = apply_hook
        self.check_hook = check_hook
        self.state = UpdateState.UP_TO_DATE
        self.available_version: str | None = None
        self.error: str | None = None
        self.schedule = ScheduleConfig()
        self.history: list[dict] = []
        self.last_check_at: float | None = None
        self._apply_task: asyncio.Task | None = None

    def _set_state(self, state: UpdateState) -> None:
        self.state = state
        if self.events:
            self.events.publish(
                "UpdateStateChanged",
                {"state": state.value, "version": self.available_version},
            )

    def status(self) -> dict:
        return {
            "state": self.state.value,
            "available_version": self.available_version,
            "error": self.error,
            "last_check_at": self.last_check_at,
            "schedule": dataclasses.asdict(self.schedule),
            "history": self.history[-10:],
        }

    async def check(self) -> dict:
        """Query for an available update (hourly in reference; on-demand here —
        this environment has no egress, so the default check_hook is None)."""
        self.last_check_at = time.time()
        if self.check_hook is None:
            return {"available": False}
        try:
            info = await self.check_hook()
        except Exception as e:
            self.error = str(e)
            return {"available": False, "error": str(e)}
        if info and info.get("version"):
            self.available_version = info["version"]
            self._set_state(UpdateState.AVAILABLE)
            return {"available": True, "version": info["version"]}
        self._set_state(UpdateState.UP_TO_DATE)
        return {"available": False}

    def request_apply(self, mode: ApplyMode = ApplyMode.NORMAL) -> bool:
        if self._apply_task and not self._apply_task.done():
            return False
        self._apply_task = asyncio.create_task(self._apply_flow(mode))
        return True

    async def _apply_flow(self, mode: ApplyMode) -> None:
        """drain → apply → (restart handled by hook). Reference §3.4 call stack."""
        started = time.time()
        self._set_state(UpdateState.DRAINING)
        self.gate.start_rejecting()  # /v1/* now 503 + Retry-After
        try:
            if mode == ApplyMode.NORMAL:
                drained = await self.gate.wait_for_idle(self.drain_timeout_s)
                if not drained:
                    log.warning(
                        "drain timeout after %.0fs with %d in flight; proceeding",
                        self.drain_timeout_s, self.gate.in_flight,
                    )
            self._set_state(UpdateState.APPLYING)
            if self.apply_hook is not None:
                await self.apply_hook()
            self.history.append({
                "version": self.available_version,
                "mode": mode.value,
                "started_at": started,
                "finished_at": time.time(),
                "ok": True,
            })
            self._set_state(UpdateState.UP_TO_DATE)
            self.available_version = None
        except Exception as e:
            self.error = str(e)
            self.history.append({
                "version": self.available_version, "mode": mode.value,
                "started_at": started, "finished_at": time.time(),
                "ok": False, "error": str(e),
            })
            self._set_state(UpdateState.FAILED)
        finally:
            self.gate.stop_rejecting()

    def cancel_drain(self) -> bool:
        if self.state == UpdateState.DRAINING and self._apply_task:
            self._apply_task.cancel()
            self.gate.stop_rejecting()
            self._set_state(
                UpdateState.AVAILABLE if self.available_version
                else UpdateState.UP_TO_DATE
            )
            return True
        return False

    def set_schedule(self, mode: str, at_time: float | None = None) -> None:
        if mode not in ("immediate", "on_idle", "at_time"):
            raise ValueError(f"unknown schedule mode {mode!r}")
        self.schedule = ScheduleConfig(mode=mode, at_time=at_time)
