"""Self-update manager: state machine + drain-aware apply + rollback watch.

Parity with reference update/ (state machine mod.rs:59-123, background tasks
:807-905, check/download :965+, drain via InferenceGate, scheduling
schedule.rs:17-90, post-restart health watch + rollback README.md:160-166).
The swap unit is an operator-configured artifact (update_source.py) rather
than a Rust binary, but the externally observable lifecycle — hourly check →
available → download w/ progress → draining (503s on /v1/*) → applying →
restart → 30 s health watch with `.bak` rollback — and the admin API shape
are preserved. Hooks remain injectable for tests; the defaults are the real
GitHub + artifact-swap implementations when configured via
LLMLB_UPDATE_REPO / LLMLB_UPDATE_ARTIFACT.
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import json
import logging
import os
import tempfile
import time

from llmlb_tpu.gateway.events import DashboardEventBus
from llmlb_tpu.gateway.gate import InferenceGate

log = logging.getLogger("llmlb_tpu.gateway.update")

CHECK_INTERVAL_S = 3600.0  # parity: hourly background check
POST_RESTART_WATCH_S = 30.0  # parity: 30 s health watch after restart
SCHEDULE_TICK_S = 5.0


class UpdateState(str, enum.Enum):
    UP_TO_DATE = "up_to_date"
    AVAILABLE = "available"
    DRAINING = "draining"
    APPLYING = "applying"
    FAILED = "failed"


class ApplyMode(str, enum.Enum):
    NORMAL = "normal"  # wait for in-flight inference to drain
    FORCE = "force"  # abort in-flight


@dataclasses.dataclass
class ScheduleConfig:
    mode: str = "immediate"  # immediate | on_idle | at_time
    at_time: float | None = None


class UpdateManager:
    def __init__(
        self,
        gate: InferenceGate,
        events: DashboardEventBus | None = None,
        drain_timeout_s: float = 300.0,
        apply_hook=None,  # async callable that performs the actual swap/restart
        check_hook=None,  # async callable returning {"version": ..} | None
        source=None,  # GitHubUpdateSource (or compatible)
        applier=None,  # ArtifactSwapApplier (or compatible)
        restart_cb=None,  # sync/async: hand control to the supervisor
    ):
        self.gate = gate
        self.events = events
        self.drain_timeout_s = drain_timeout_s
        self.apply_hook = apply_hook
        self.check_hook = check_hook
        self.source = source
        self.applier = applier
        self.restart_cb = restart_cb
        self.state = UpdateState.UP_TO_DATE
        self.available_version: str | None = None
        self.available_asset_url: str | None = None
        self.downloaded_path: str | None = None
        self._downloaded_version: str | None = None
        self.download_progress: dict | None = None  # {"done": n, "total": n}
        self.error: str | None = None
        self.schedule = ScheduleConfig()
        self.history: list[dict] = []
        self.last_check_at: float | None = None
        self._apply_task: asyncio.Task | None = None
        self.last_apply_mode: ApplyMode | None = None
        self._bg_tasks: list[asyncio.Task] = []

    @classmethod
    def from_env(cls, gate: InferenceGate, http, current_version: str,
                 events: DashboardEventBus | None = None,
                 drain_timeout_s: float = 300.0,
                 restart_cb=None) -> "UpdateManager":
        """Build with the real GitHub + artifact-swap hooks when
        LLMLB_UPDATE_REPO / LLMLB_UPDATE_ARTIFACT are configured."""
        from llmlb_tpu.gateway.update_source import (
            ArtifactSwapApplier,
            GitHubUpdateSource,
        )

        repo = os.environ.get("LLMLB_UPDATE_REPO")
        artifact = os.environ.get("LLMLB_UPDATE_ARTIFACT")
        source = GitHubUpdateSource(
            http, repo, current_version,
            asset_match=os.environ.get("LLMLB_UPDATE_ASSET_MATCH", ""),
            api_base=os.environ.get(
                "LLMLB_UPDATE_API_BASE", "https://api.github.com"
            ),
        ) if repo else None
        applier = ArtifactSwapApplier(artifact) if artifact else None
        if repo and not artifact:
            log.warning(
                "LLMLB_UPDATE_REPO is set but LLMLB_UPDATE_ARTIFACT is not: "
                "update checks will run, but apply has nothing to swap and "
                "will fail rather than pretend to succeed"
            )
        return cls(
            gate, events, drain_timeout_s=drain_timeout_s,
            source=source, applier=applier, restart_cb=restart_cb,
        )

    def _set_state(self, state: UpdateState) -> None:
        self.state = state
        if self.events:
            self.events.publish(
                "UpdateStateChanged",
                {"state": state.value, "version": self.available_version},
            )

    def status(self) -> dict:
        return {
            "state": self.state.value,
            "available_version": self.available_version,
            "download_progress": self.download_progress,
            "error": self.error,
            "last_check_at": self.last_check_at,
            "schedule": dataclasses.asdict(self.schedule),
            "history": self.history[-10:],
        }

    async def check(self, force: bool = False) -> dict:
        """Query for an available update (hourly background + on demand).
        Priority: injected check_hook (tests) > GitHub source > none."""
        self.last_check_at = time.time()
        try:
            if self.check_hook is not None:
                info = await self.check_hook()
            elif self.source is not None:
                info = await self.source.check(force=force)
            else:
                return {"available": False}
        except Exception as e:
            self.error = str(e)
            return {"available": False, "error": str(e)}
        applying = self._apply_task is not None and not self._apply_task.done()
        if info and info.get("version"):
            if info["version"] in self._blocked_versions():
                log.warning(
                    "release %s was rolled back on this host; not offering it "
                    "again", info["version"],
                )
                if self.available_version == info["version"]:
                    self.available_version = None
                    self.available_asset_url = None
                if not applying and self.state == UpdateState.AVAILABLE:
                    self._set_state(UpdateState.UP_TO_DATE)
                return {"available": False, "blocked": info["version"]}
            self.available_version = info["version"]
            self.available_asset_url = info.get("asset_url")
            self.error = None  # a successful check clears stale errors
            if not applying:  # never stomp DRAINING/APPLYING mid-apply
                self._set_state(UpdateState.AVAILABLE)
            return {"available": True, **info}
        if not applying:
            self._set_state(UpdateState.UP_TO_DATE)
            self.error = None
        return {"available": False}

    # A version that failed its post-restart health watch is remembered on
    # disk so neither this process nor the restarted one re-offers it
    # (reference rollback semantics; prevents an apply/rollback flip-flop).
    def _blocklist_path(self) -> str | None:
        if self.applier is None:
            return None
        return os.path.join(self.applier.state_dir, "update_blocklist.json")

    def _blocked_versions(self) -> set[str]:
        path = self._blocklist_path()
        if not path:
            return set()
        try:
            with open(path) as f:
                return set(json.load(f))
        except FileNotFoundError:
            return set()
        except (OSError, ValueError) as e:
            log.warning("update blocklist at %s unreadable (%s); treating "
                        "as empty", path, e)
            return set()

    def _block_version(self, version: str | None) -> None:
        path = self._blocklist_path()
        if not path or not version:
            return
        blocked = self._blocked_versions() | {version}
        try:
            # atomic: a crash mid-write must not leave a truncated file that
            # silently reads back as an empty blocklist
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(sorted(blocked), f)
            os.replace(tmp, path)
        except OSError:
            log.warning("could not persist update blocklist at %s", path)

    _UNPINNED = object()  # sentinel: caller did not pin a release

    async def download(self, version=_UNPINNED, asset_url=_UNPINNED):
        """Fetch the asset to a staging path, publishing progress events
        (update/mod.rs download-with-progress). Callers on the apply path
        pass a pinned (version, asset_url) pair so a concurrent check()
        discovering a newer release can't relabel in-flight bytes — pinned
        values are authoritative, even when the pinned asset_url is None
        (no fallback to mutable instance state)."""
        if version is UpdateManager._UNPINNED:
            version = self.available_version
        if asset_url is UpdateManager._UNPINNED:
            asset_url = self.available_asset_url
        if self.source is None or not asset_url:
            return None
        # Cache is keyed by version: a staged download from a previous
        # release must never be applied under a newer version's label.
        if (self.downloaded_path
                and self._downloaded_version == version
                and os.path.isfile(self.downloaded_path)):
            return self.downloaded_path
        # Stage next to the artifact when possible (same filesystem, private
        # service dir); else a fresh 0700 tempdir — never a predictable path
        # in world-writable /tmp.
        if self.applier is not None:
            staging_dir = self.applier.state_dir
        else:
            staging_dir = tempfile.mkdtemp(prefix="llmlb-update-")
        staging = os.path.join(staging_dir, f"llmlb-update-{version}")

        def progress(done: int, total: int) -> None:
            self.download_progress = {"done": done, "total": total}
            # Throttle: one event per ~4 MiB. total==0 (chunked encoding)
            # must not bypass the throttle; the completion event below is
            # published unconditionally once the transfer finishes.
            if self.events and done % (1 << 22) < (1 << 16):
                self.events.publish("UpdateDownloadProgress", {
                    "version": version, "done": done, "total": total,
                })

        self.downloaded_path = await self.source.download(
            asset_url, staging, progress_cb=progress
        )
        self._downloaded_version = version
        done = (self.download_progress or {}).get("done", 0)
        self.download_progress = {"done": done, "total": done}
        if self.events:
            self.events.publish("UpdateDownloadProgress", {
                "version": version, "done": done, "total": done,
                "complete": True,
            })
        return self.downloaded_path

    def request_apply(self, mode: ApplyMode = ApplyMode.NORMAL) -> bool:
        if self._apply_task and not self._apply_task.done():
            return False
        self.last_apply_mode = mode
        self._apply_task = asyncio.create_task(self._apply_flow(mode))
        return True

    async def _apply_flow(self, mode: ApplyMode) -> None:
        """download → drain → apply → (restart). Reference §3.4 call stack."""
        started = time.time()
        # Pin the release being applied: a concurrent check() discovering a
        # newer version must not relabel this apply mid-flight.
        version = self.available_version
        asset_url = self.available_asset_url

        def fail(msg: str) -> None:
            self.error = msg
            self.history.append({
                "version": version, "mode": mode.value,
                "started_at": started, "finished_at": time.time(),
                "ok": False, "error": msg,
            })
            self._set_state(UpdateState.FAILED)
            # a failed FORCE apply must not leave shutdown drains disabled
            self.last_apply_mode = None

        # Everything that can fail without touching traffic happens BEFORE
        # the drain: the 503 window must cover only the swap itself.
        if version and version in self._blocked_versions():
            fail(f"release {version} was rolled back on this host")
            return
        if self.applier is not None and self.applier.read_marker():
            fail("previous update's post-restart health watch has not "
                 "completed; not stacking another apply")
            return
        staged = None
        if self.apply_hook is None:
            if self.applier is None:
                fail("no apply mechanism configured "
                     "(set LLMLB_UPDATE_ARTIFACT or an apply hook)")
                return
            try:
                staged = await self.download(version, asset_url)
            except Exception as e:
                fail(str(e))
                return
            if staged is None:
                fail(f"no downloadable asset for {version or 'update'}")
                return

        self._set_state(UpdateState.DRAINING)
        self.gate.start_rejecting()  # /v1/* now 503 + Retry-After
        try:
            if mode == ApplyMode.NORMAL:
                drained = await self.gate.wait_for_idle(self.drain_timeout_s)
                if not drained:
                    log.warning(
                        "drain timeout after %.0fs with %d in flight; proceeding",
                        self.drain_timeout_s, self.gate.in_flight,
                    )
            self._set_state(UpdateState.APPLYING)
            if self.apply_hook is not None:
                await self.apply_hook()
            else:
                self.applier.apply(staged, version)
                if self.restart_cb is not None:
                    r = self.restart_cb()
                    if asyncio.iscoroutine(r):
                        await r
            self.history.append({
                "version": version,
                "mode": mode.value,
                "started_at": started,
                "finished_at": time.time(),
                "ok": True,
            })
            self._set_state(UpdateState.UP_TO_DATE)
            self.error = None
            self.available_version = None
        except Exception as e:
            fail(str(e))
        finally:
            self.gate.stop_rejecting()

    def cancel_drain(self) -> bool:
        if self.state == UpdateState.DRAINING and self._apply_task:
            self._apply_task.cancel()
            self.gate.stop_rejecting()
            self._set_state(
                UpdateState.AVAILABLE if self.available_version
                else UpdateState.UP_TO_DATE
            )
            return True
        return False

    def set_schedule(self, mode: str, at_time: float | None = None) -> None:
        if mode not in ("immediate", "on_idle", "at_time"):
            raise ValueError(f"unknown schedule mode {mode!r}")
        self.schedule = ScheduleConfig(mode=mode, at_time=at_time)

    # ------------------------------------------------------- background tasks

    def start_background_tasks(
        self, check_interval_s: float = CHECK_INTERVAL_S
    ) -> None:
        """Hourly release check + schedule executor (update/mod.rs:807-905,
        schedule.rs:17-90)."""
        self._bg_tasks.append(asyncio.create_task(
            self._check_loop(check_interval_s), name="update-check"
        ))
        self._bg_tasks.append(asyncio.create_task(
            self._schedule_loop(), name="update-schedule"
        ))

    async def stop_background_tasks(self) -> None:
        for t in self._bg_tasks:
            t.cancel()
        for t in self._bg_tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass  # allow-silent: shutdown teardown of cancelled tasks
        self._bg_tasks.clear()

    async def _check_loop(self, interval_s: float) -> None:
        while True:
            try:
                await self.check()
            except Exception:
                log.exception("background update check failed")
            await asyncio.sleep(interval_s)

    async def _schedule_loop(self) -> None:
        """Fire a pending AVAILABLE update per the configured schedule:
        on_idle waits for zero in-flight inference; at_time waits for the
        wall clock. 'immediate' keeps apply operator-triggered (API parity:
        the reference's Immediate mode is what /update/apply does)."""
        while True:
            await asyncio.sleep(SCHEDULE_TICK_S)
            try:
                if self.state != UpdateState.AVAILABLE:
                    continue
                if self.applier is not None and self.applier.read_marker():
                    continue  # current update's health watch still pending
                mode = self.schedule.mode
                if mode == "on_idle" and self.gate.in_flight == 0:
                    log.info("on_idle schedule firing update apply")
                    self.request_apply(ApplyMode.NORMAL)
                elif (mode == "at_time" and self.schedule.at_time
                        and time.time() >= self.schedule.at_time):
                    log.info("at_time schedule firing update apply")
                    self.schedule = ScheduleConfig()  # one-shot
                    self.request_apply(ApplyMode.NORMAL)
            except Exception:
                log.exception("schedule loop failure")

    # ---------------------------------------------------- post-restart watch

    async def post_restart_watch(
        self, health_check, watch_s: float = POST_RESTART_WATCH_S,
        interval_s: float = 1.0,
    ) -> str:
        """After a restart with a pending-update marker: confirm the new
        version is healthy for `watch_s`, else roll back from `.bak`
        (reference 30 s health watch + auto-rollback). `health_check` is an
        async callable returning truthy when serving is healthy.

        Returns one of: "no_marker", "healthy", "rolled_back",
        "rollback_failed"."""
        if self.applier is None:
            return "no_marker"
        marker = self.applier.read_marker()
        if not marker:
            return "no_marker"
        deadline = time.monotonic() + watch_s
        healthy_streak = 0
        while time.monotonic() < deadline:
            try:
                ok = await health_check()
            except Exception:
                ok = False
            if ok:
                healthy_streak += 1
                if healthy_streak >= 3:  # stable, not a lucky first probe
                    self.applier.clear_marker()
                    self.history.append({
                        "version": marker.get("version"),
                        "post_restart": "healthy", "ts": time.time(),
                    })
                    log.info("update %s confirmed healthy",
                             marker.get("version"))
                    return "healthy"
            else:
                healthy_streak = 0
            await asyncio.sleep(interval_s)
        rolled = self.applier.rollback()
        # Remember the bad release on disk: the restarted process (and this
        # one) must not offer or re-apply it.
        self._block_version(marker.get("version"))
        self.history.append({
            "version": marker.get("version"),
            "post_restart": "rolled_back" if rolled else "rollback_failed",
            "ts": time.time(),
        })
        self._set_state(UpdateState.FAILED)
        self.error = (
            f"update {marker.get('version')} unhealthy after restart; "
            + ("rolled back" if rolled else "rollback failed (no .bak)")
        )
        log.error("%s", self.error)
        if rolled and self.restart_cb is not None:
            r = self.restart_cb()
            if asyncio.iscoroutine(r):
                await r
        return "rolled_back" if rolled else "rollback_failed"
