"""Self-update sources and appliers: GitHub Releases check, asset download
with progress, artifact swap with `.bak` rollback, restart marker.

Parity with reference update/mod.rs internals: release check with a 24 h
cache (:965+), asset download with progress reporting, platform apply that
keeps a `.bak` of the previous binary, and the 30 s post-restart health watch
with automatic rollback (README.md:160-166). The swap unit here is an
operator-configured artifact path (the deployable the supervisor re-execs —
a zipapp/venv tarball/binary), not a Rust binary, but the lifecycle and the
on-disk `.bak` + marker contract are the same.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time

import aiohttp

log = logging.getLogger("llmlb_tpu.gateway.update")

CHECK_CACHE_S = 24 * 3600.0  # parity: 24h release-check cache
MARKER_NAME = "update_pending.json"


def _version_tuple(v: str) -> tuple:
    parts = []
    for tok in v.lstrip("v").replace("-", ".").split("."):
        parts.append(int(tok) if tok.isdigit() else -1)
    return tuple(parts)


def is_newer(candidate: str, current: str) -> bool:
    try:
        return _version_tuple(candidate) > _version_tuple(current)
    except Exception:
        return candidate != current


class GitHubUpdateSource:
    """Release check + asset download against the GitHub Releases API."""

    def __init__(
        self,
        http: aiohttp.ClientSession,
        repo: str,
        current_version: str,
        asset_match: str = "",
        api_base: str = "https://api.github.com",
    ):
        self.http = http
        self.repo = repo
        self.current_version = current_version
        self.asset_match = asset_match  # substring an asset name must contain
        self.api_base = api_base.rstrip("/")
        self._cache: dict | None = None
        self._cache_at = 0.0

    async def check(self, force: bool = False) -> dict | None:
        """Latest-release probe; None when current is up to date. Results are
        cached for CHECK_CACHE_S unless force (update/mod.rs 24h cache)."""
        now = time.time()
        if not force and self._cache is not None and (
            now - self._cache_at < CHECK_CACHE_S
        ):
            release = self._cache
        else:
            url = f"{self.api_base}/repos/{self.repo}/releases/latest"
            async with self.http.get(
                url,
                headers={"Accept": "application/vnd.github+json"},
                timeout=aiohttp.ClientTimeout(total=30),
            ) as resp:
                if resp.status != 200:
                    raise RuntimeError(
                        f"release check failed: HTTP {resp.status}"
                    )
                release = await resp.json()
            self._cache, self._cache_at = release, now

        version = (release.get("tag_name") or "").strip()
        if not version or not is_newer(version, self.current_version):
            return None
        asset_url = None
        asset_name = None
        for asset in release.get("assets") or []:
            name = asset.get("name") or ""
            if self.asset_match in name:
                asset_url = asset.get("browser_download_url")
                asset_name = name
                break
        return {
            "version": version,
            "asset_url": asset_url,
            "asset_name": asset_name,
            "notes": (release.get("body") or "")[:2000],
        }

    async def download(
        self, url: str, dest_path: str, progress_cb=None,
        chunk_size: int = 1 << 16,
    ) -> str:
        """Stream the asset to dest_path, reporting (done, total) progress."""
        tmp = dest_path + ".part"
        async with self.http.get(
            url, timeout=aiohttp.ClientTimeout(total=3600, sock_read=120)
        ) as resp:
            if resp.status != 200:
                raise RuntimeError(f"asset download failed: HTTP {resp.status}")
            total = int(resp.headers.get("Content-Length") or 0)
            done = 0
            with open(tmp, "wb") as f:
                async for chunk in resp.content.iter_chunked(chunk_size):
                    f.write(chunk)
                    done += len(chunk)
                    if progress_cb:
                        progress_cb(done, total)
        os.replace(tmp, dest_path)
        return dest_path


class ArtifactSwapApplier:
    """Swap the deployable artifact in place, keeping `.bak` for rollback.

    apply(): current → current.bak, staged → current, write the restart
    marker. The supervisor (systemd/k8s/launchd) restarts the process; on
    next boot `post_restart_watch` clears the marker when healthy or rolls
    back from `.bak` when not (reference update apply + rollback flow).
    """

    def __init__(self, artifact_path: str, state_dir: str | None = None):
        self.artifact_path = artifact_path
        self.state_dir = state_dir or os.path.dirname(
            os.path.abspath(artifact_path)
        )
        os.makedirs(self.state_dir, exist_ok=True)

    @property
    def backup_path(self) -> str:
        return self.artifact_path + ".bak"

    @property
    def marker_path(self) -> str:
        return os.path.join(self.state_dir, MARKER_NAME)

    def apply(self, staged_path: str, version: str | None) -> None:
        if not os.path.isfile(staged_path):
            raise FileNotFoundError(staged_path)
        mode = None
        if os.path.isfile(self.artifact_path):
            shutil.copy2(self.artifact_path, self.backup_path)
            mode = os.stat(self.artifact_path).st_mode
        # shutil.move, not os.replace: the staging dir may be on another
        # filesystem (os.replace raises EXDEV across devices).
        shutil.move(staged_path, self.artifact_path)
        if mode is not None:
            os.chmod(self.artifact_path, mode)
        self.write_marker(version)

    def write_marker(self, version: str | None) -> None:
        with open(self.marker_path, "w") as f:
            json.dump({
                "version": version,
                "applied_at": time.time(),
                "artifact": self.artifact_path,
                "backup": self.backup_path,
            }, f)

    def read_marker(self) -> dict | None:
        try:
            with open(self.marker_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def clear_marker(self) -> None:
        try:
            os.unlink(self.marker_path)
        except OSError:
            pass

    def rollback(self) -> bool:
        """Restore the previous artifact from `.bak`. True if restored."""
        if not os.path.isfile(self.backup_path):
            return False
        os.replace(self.backup_path, self.artifact_path)
        self.clear_marker()
        return True
