"""Multi-worker serving: shared-nothing gateway processes on one port.

The reference router is a single Rust process that clears ~170k req/s; a
CPython gateway is GIL-bound near 1-2k req/s per process, so horizontal
scale on one host comes from N processes sharing the listen port via
SO_REUSEPORT (the kernel load-balances accepted connections across the
workers' accept queues). Each worker is shared-nothing: its own event loop,
LoadManager, breaker set, SQLite connection, and HTTP client. The small
mutable routing state replicates best-effort over the gossip bus
(gateway/gossip.py); correctness never depends on it.

Single-writer discipline for the things that must not run N times:
  * the pull health checker probes from exactly one elected worker
    (the primary, index 0) — otherwise N workers multiply probe load
    on every engine;
  * the hourly maintenance loop (history retention, audit verify) and the
    update manager's background tasks run on the primary only;
  * SQLite stays safe for the remaining cross-worker writes (request
    history, daily stats, audit batches) via WAL + busy_timeout and an
    atomic audit flush transaction (db.py / audit.py).

The supervisor (`run_supervisor`) forks N children and babysits them:
signals forward to the children, and the first unexpected child death
tears the group down (a supervisor like systemd restarts the whole unit —
per-worker respawn would silently mask crash loops).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import sys

log = logging.getLogger("llmlb_tpu.gateway.worker")

# Set by the supervisor in each forked child; single-process serving leaves
# them unset and current_worker() reports the 1-of-1 identity.
WORKER_INDEX_ENV = "LLMLB_WORKER_INDEX"
WORKER_COUNT_ENV = "LLMLB_WORKERS"


@dataclasses.dataclass(frozen=True)
class WorkerInfo:
    """This process's place in the worker group."""

    index: int = 0
    count: int = 1

    @property
    def is_primary(self) -> bool:
        """The elected worker: health checker, maintenance, updates."""
        return self.index == 0

    @property
    def multi(self) -> bool:
        return self.count > 1

    @property
    def label(self) -> str:
        return str(self.index)


def current_worker() -> WorkerInfo:
    """Worker identity from the environment (the supervisor sets it in each
    child); a plain single-process gateway is worker 0 of 1."""
    try:
        count = max(1, int(os.environ.get(WORKER_COUNT_ENV, "1")))
    except ValueError:
        count = 1
    try:
        index = int(os.environ.get(WORKER_INDEX_ENV, "0"))
    except ValueError:
        index = 0
    return WorkerInfo(index=max(0, min(index, count - 1)), count=count)


def worker_count_from_env(cli_value: int | None = None) -> int:
    """Resolve --workers / LLMLB_WORKERS (CLI wins); 0/absent means 1."""
    if cli_value is not None and cli_value > 0:
        return cli_value
    try:
        return max(1, int(os.environ.get(WORKER_COUNT_ENV, "1") or "1"))
    except ValueError:
        return 1


def run_supervisor(workers: int, child_main) -> int:
    """Fork `workers` children, each running ``child_main(WorkerInfo)``;
    forward SIGTERM/SIGINT; tear the group down when any child exits.
    Returns the exit code for the supervisor process. POSIX-only (fork +
    SO_REUSEPORT are both POSIX facilities; on platforms without them the
    caller runs single-process)."""
    pids: list[int] = []
    for i in range(workers):
        pid = os.fork()
        if pid == 0:
            # Child: die with the supervisor. Without PDEATHSIG, a
            # SIGKILLed (or crashed) supervisor leaves N orphan workers
            # holding the port forever — observed in practice.
            try:
                import ctypes

                libc = ctypes.CDLL(None, use_errno=True)
                libc.prctl(1, signal.SIGTERM)  # PR_SET_PDEATHSIG
            except (OSError, AttributeError):
                pass
            # Stamp identity into the env so every layer (logging,
            # metrics labels, gossip socket name) can read it without
            # plumbing the WorkerInfo through call sites that predate
            # multi-worker serving.
            os.environ[WORKER_INDEX_ENV] = str(i)
            os.environ[WORKER_COUNT_ENV] = str(workers)
            try:
                code = child_main(WorkerInfo(index=i, count=workers))
            except KeyboardInterrupt:
                code = 0
            except BaseException:  # a child must never unwind into the
                log.exception("worker %d crashed", i)  # supervisor's stack
                code = 1
            # never return into the supervisor's stack
            os._exit(code or 0)
        pids.append(pid)

    shutting_down = False

    def forward(signum, _frame):
        nonlocal shutting_down
        shutting_down = True
        for pid in pids:
            try:
                os.kill(pid, signum)
            except ProcessLookupError:
                pass

    old = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        old[sig] = signal.signal(sig, forward)

    log.info("supervisor: %d workers forked (pids %s)", workers, pids)
    exit_code = 0
    live = set(pids)
    try:
        while live:
            try:
                pid, status = os.wait()
            except InterruptedError:
                continue
            except ChildProcessError:
                break
            if pid not in live:
                continue
            live.discard(pid)
            code = os.waitstatus_to_exitcode(status)
            if code != 0:
                exit_code = exit_code or (code if code > 0 else 1)
            if live and not shutting_down:
                # one worker died on its own: take the group down rather
                # than limp along with silently reduced capacity
                log.warning(
                    "worker pid %d exited %s; stopping the group", pid, code
                )
                shutting_down = True
                for p in live:
                    try:
                        os.kill(p, signal.SIGTERM)
                    except ProcessLookupError:
                        pass
    finally:
        for sig, handler in old.items():
            signal.signal(sig, handler)
    return exit_code


def supports_reuse_port() -> bool:
    import socket

    return hasattr(socket, "SO_REUSEPORT") and sys.platform != "win32"
