"""Multi-LoRA serving (docs/lora.md): hundreds of per-tenant adapters over
one set of base weights.

- `store`: adapter discovery + safetensors loading (HF/PEFT layout) into the
  stacked host tensors the device pool rows take.
- `manager`: the device-resident adapter pool — LRU hot-load/evict keyed like
  the structured-outputs mask cache, refcounted so an adapter with active
  requests is never evicted, slot 0 reserved as the all-zero identity row.
- `api`: the request-surface contract shared by the gateway and the engine
  server — `lora` field / `model:adapter` suffix parsing with one notion of
  "valid", so both dialects 400 identically.

The batched grouped matmul lives in ops/lora.py (bgmv Pallas kernel + XLA
fallback); the model-side wiring is the `<name>_lora_a`/`<name>_lora_b`
param companions in models/llama.py.
"""

from llmlb_tpu.lora.api import (
    LORA_NAME_RE,
    adapter_from_body,
    split_model_adapter,
)
from llmlb_tpu.lora.manager import LoraManager
from llmlb_tpu.lora.store import (
    AdapterInfo,
    discover_adapters,
    load_adapter_tensors,
    lora_target_dims,
    save_adapter,
)

__all__ = [
    "AdapterInfo",
    "LORA_NAME_RE",
    "LoraManager",
    "adapter_from_body",
    "discover_adapters",
    "load_adapter_tensors",
    "lora_target_dims",
    "save_adapter",
    "split_model_adapter",
]
