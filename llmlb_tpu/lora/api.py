"""The LoRA request surface shared by the gateway and the engine server.

An adapter is selected two ways on BOTH dialects (docs/lora.md):

- model-name suffix: `"model": "llama-3-8b:acme-support"` — the part after
  the LAST colon names the adapter (cloud prefixes like `openai:`/
  `anthropic:` are consumed by the gateway BEFORE this parse ever runs);
- explicit field: `"lora": "acme-support"` with the bare base model name.

Both present and disagreeing is a 400. The gateway and the engine validate
with this one module (the `speculative`/`response_format` shape: shared
validator, per-layer 400 with the field named), so a malformed `lora` value
is refused identically at either layer; adapter EXISTENCE is the engine's
call (LoraManager.validate — the gateway only knows what endpoints
advertise).
"""

from __future__ import annotations

import re

# Adapter names reach file paths (store.discover_adapters scans
# directories by name), metrics labels, and model-name suffixes — one
# conservative charset for all three.
LORA_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._\-]{0,63}$")


def split_model_adapter(model: str | None) -> tuple[str | None, str | None]:
    """Split `base:adapter` on the LAST colon. Returns (base, adapter) —
    (model, None) when there is no adapter-shaped suffix. Purely
    syntactic: the caller decides whether the suffix really is an adapter
    (a registry may know the full string as a literal model name)."""
    if not model or not isinstance(model, str) or ":" not in model:
        return model, None
    base, _, cand = model.rpartition(":")
    if not base or not LORA_NAME_RE.match(cand):
        return model, None
    return base, cand


def adapter_from_body(body: dict) -> tuple[str | None, str | None]:
    """Resolve (base_model, adapter) from a chat-shaped body: the explicit
    `lora` field and/or the model-name suffix. Raises ValueError naming the
    `lora` field for malformed values or a field/suffix conflict — both
    layers map it to a 400 in their own dialect's error shape."""
    explicit = body.get("lora")
    if explicit is not None:
        if not isinstance(explicit, str) or not explicit:
            raise ValueError("'lora' must be a non-empty string naming an "
                             "adapter")
        if not LORA_NAME_RE.match(explicit):
            raise ValueError(
                "'lora' must match [A-Za-z0-9][A-Za-z0-9._-]{0,63}"
            )
    base, suffix = split_model_adapter(body.get("model"))
    if explicit is not None and suffix is not None and explicit != suffix:
        raise ValueError(
            f"'lora' ({explicit!r}) conflicts with the model-name suffix "
            f"({suffix!r}); use one or make them agree"
        )
    adapter = explicit or suffix
    if adapter is None:
        return body.get("model"), None
    return (base if suffix is not None else body.get("model")), adapter
