"""Gateway-side LoRA routing (docs/lora.md), the disagg/gateway.py sibling.

Resolution order for a request naming adapter `a` on model `m`:

1. HOT — some online endpoint advertises the `m:a` model entry (resident
   adapters are mirrored into model entries every health probe), so
   selection runs over exactly those endpoints: the adapter is already in
   their device pool and decode starts without a load.
2. LOAD — no endpoint has it hot, but some serve `m` with the `lora`
   capability: selection runs over those, and the chosen engine hot-loads
   the adapter at admission (one disk→device transfer, then it advertises
   hot within a probe interval).
3. Neither → 400 naming the `lora` field (the fleet cannot serve this
   adapter), EXCEPT when the adapter came only from a model-name suffix
   and the full string is itself a servable model — then it was never an
   adapter reference at all (`llama3:8b` on an ollama endpoint) and normal
   routing proceeds.

Validation of the field's SHAPE is shared with the engine server
(llmlb_tpu/lora/api.py), so both dialects 400 identically on malformed
values — the `speculative`/`response_format` validation pattern.
"""

from __future__ import annotations

import dataclasses

from llmlb_tpu.lora.api import adapter_from_body


@dataclasses.dataclass(frozen=True)
class LoraRoute:
    """How one adapter request routes."""

    adapter: str
    base_canonical: str  # canonical BASE model (affinity + accounting)
    canonical: str  # the model name selection runs over
    kind: str  # "hot" | "load"
    # capability the selection must require (None = leave unchanged);
    # set for "load" so only adapter-store-bearing endpoints are eligible
    capability: object | None = None


def lora_route_for(state, body: dict) -> LoraRoute | None:
    """Resolve a request's adapter reference against the live registry.
    None when the request references no adapter (or the "adapter" was a
    literal colon-model). Raises ValueError naming the `lora` field for
    malformed values and for adapters no online endpoint can serve."""
    from llmlb_tpu.gateway.model_names import to_canonical
    from llmlb_tpu.gateway.types import Capability

    model = body.get("model")
    explicit = body.get("lora")
    if explicit is None and (not isinstance(model, str)
                             or ":" not in model):
        return None
    base, adapter = adapter_from_body(body)  # raises on malformed/conflict
    if adapter is None:
        return None
    base_canonical = to_canonical(base) if base else ""
    # The adapter interpretation is only live when the BASE model has a
    # lora-capable endpoint: `llama3:8b` on an ollama fleet is a literal
    # model name, not adapter "8b" of model "llama3" — even though the
    # full string resolves. The explicit `lora` field is always an adapter
    # reference and refuses loudly when the fleet cannot serve it.
    if not state.registry.find_by_model(base_canonical, Capability.LORA):
        if explicit is None:
            return None  # literal colon-model; normal routing proceeds
        raise ValueError(
            f"'lora' adapter {adapter!r} is not available for model "
            f"{base or model!r}: no online endpoint serves it with an "
            "adapter store"
        )
    qualified = f"{base_canonical}:{adapter}"
    if state.registry.find_by_model(qualified):
        return LoraRoute(adapter=adapter, base_canonical=base_canonical,
                         canonical=qualified, kind="hot")
    # Cold-load route — but refuse outright when the fleet's advertised
    # stores say NO endpoint could load this adapter: a clean 400 naming
    # the field beats a proxied engine-side 400 (which the resilience
    # layer would normalize to 502). Endpoints without a fresh probe
    # advertisement (lora_available is None) are given the benefit of the
    # doubt — the engine is the authority and rescans its store on a miss.
    lora_eps = state.registry.find_by_model(base_canonical, Capability.LORA)
    advertised = [
        getattr(getattr(ep, "accelerator", None), "lora_available", None)
        for ep, _m in lora_eps
    ]
    if all(a is not None for a in advertised) and not any(
        adapter in a for a in advertised
    ):
        raise ValueError(
            f"'lora' adapter {adapter!r} is not available for model "
            f"{base or model!r}: no online endpoint's adapter store "
            "contains it"
        )
    return LoraRoute(adapter=adapter, base_canonical=base_canonical,
                     canonical=base_canonical, kind="load",
                     capability=Capability.LORA)


def forward_model_name(route: LoraRoute, engine_model: str | None,
                       fallback: str) -> str:
    """The model name the upstream engine should see: its own
    adapter-qualified entry on the hot path, `base:adapter` synthesized on
    the load path (the engine parses the suffix and hot-loads)."""
    if route.kind == "hot" and engine_model:
        return engine_model
    base = engine_model or fallback
    if base.endswith(f":{route.adapter}"):
        return base
    return f"{base}:{route.adapter}"
