"""Device-resident LoRA adapter pool: hot-load, LRU evict, refcounts.

The manager owns the `<name>_lora_a` / `<name>_lora_b` param companions the
model forward reads (stacked pools `[L, N+1, in, R]` / `[L, N+1, R, out]`):

- Row 0 is the RESERVED all-zero identity adapter — adapter-free requests
  carry index 0 and their delta is exactly 0.0, keeping them bit-identical
  to a LoRA-free engine (the `test_quantize_off_bit_identical` contract).
- Rows 1..N hold up to `max_adapters` resident adapters. A request's
  adapter hot-loads on first use (disk → host stack → one device row write
  per pool leaf) and is LRU-evicted only when NO request references it —
  acquired at submit, released at the request's terminal event, so queued
  and parked requests pin their adapter exactly like the PR 5 mask cache
  pins compiled masks with in-flight readers.

Thread-safety: acquire/release run on HTTP executor threads while the step
loop dispatches. Manager bookkeeping sits under one lock; the device row
writes are plain (non-donating) `at[].set` updates re-assigned into
`core.params` — in-flight dispatches keep their already-flattened arrays,
and no live request references a row mid-rewrite (eviction requires
refcount 0, and the row's new owner is only submittable after the write
returns).

HBM math (docs/lora.md): one resident adapter at rank R costs
`sum_targets L * R * (in + out) * 2 bytes` bf16 — ~56 MB for a llama-3-8b
all-target R=16 adapter, which is why PR 8's int8 base weights are what
make hundreds of resident adapters plausible.
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

from llmlb_tpu.lora.store import (
    AdapterInfo,
    discover_adapters,
    load_adapter_tensors,
    lora_target_dims,
)

log = logging.getLogger("llmlb_tpu.lora")

_LORA_A = "_lora_a"
_LORA_B = "_lora_b"


class LoraManager:
    """Adapter pool bookkeeping + the device pool leaves' single writer."""

    def __init__(
        self,
        cfg,
        *,
        lora_dir: str,
        max_adapters: int = 8,
        rank_cap: int = 16,
        targets: tuple[str, ...] = ("wq", "wk", "wv", "wo", "wg", "wu", "wd"),
        metrics=None,
    ):
        self.cfg = cfg
        self.lora_dir = lora_dir
        self.max_adapters = max(1, int(max_adapters))
        self.rank_cap = max(1, int(rank_cap))
        self.targets = tuple(targets)
        self.metrics = metrics
        self.core = None  # attached by EngineCore (owns the params dict)
        self._lock = threading.RLock()
        self.available: dict[str, AdapterInfo] = discover_adapters(
            lora_dir, rank_cap=self.rank_cap, allowed_targets=self.targets
        )
        # name -> pool row (1-based; row 0 is the identity adapter)
        self._resident: dict[str, int] = {}
        self._free_rows = list(range(1, self.max_adapters + 1))
        self._refcounts: dict[str, int] = {}
        self._acquired: dict[str, str] = {}  # request token -> adapter name
        self._last_used: dict[str, float] = {}
        self.loads_total = 0
        self.evictions_total = 0

    # -------------------------------------------------------------- pool init

    def init_pool_leaves(self, dtype) -> dict[str, np.ndarray]:
        """The zero pool leaves merged into the engine's param pytree at
        construction (sharded/placed with everything else). Host numpy —
        EngineCore device_puts them with the rest of the params."""
        dims = lora_target_dims(self.cfg, self.targets)
        n = self.max_adapters + 1  # + identity row 0
        layers = self.cfg.num_layers
        leaves: dict[str, np.ndarray] = {}
        for tgt, (in_dim, out_dim) in dims.items():
            leaves[f"{tgt}{_LORA_A}"] = np.zeros(
                (layers, n, in_dim, self.rank_cap), dtype
            )
            leaves[f"{tgt}{_LORA_B}"] = np.zeros(
                (layers, n, self.rank_cap, out_dim), dtype
            )
        return leaves

    def attach(self, core) -> None:
        self.core = core

    # ------------------------------------------------------------- validation

    def rescan(self) -> None:
        """Re-discover the adapter directory (new adapters appear without an
        engine restart; resident/refcounted state is preserved)."""
        with self._lock:
            fresh = discover_adapters(
                self.lora_dir, rank_cap=self.rank_cap,
                allowed_targets=self.targets,
            )
            # resident adapters keep the info they were loaded from
            for name in self._resident:
                if name in self.available:
                    fresh[name] = self.available[name]
            self.available = fresh

    def validate(self, name: str) -> AdapterInfo:
        """The servable AdapterInfo for `name`, or ValueError whose message
        names the `lora` field — the engine server maps it to a 400."""
        with self._lock:
            info = self.available.get(name)
            if info is None:
                self.rescan()
                info = self.available.get(name)
            if info is None:
                known = ", ".join(sorted(self.available)) or "none"
                raise ValueError(
                    f"'lora' names unknown adapter {name!r} "
                    f"(available: {known})"
                )
            if info.error is not None:
                raise ValueError(
                    f"'lora' adapter {name!r} is not servable: {info.error}"
                )
            return info

    # --------------------------------------------------------- acquire/release

    def acquire(self, name: str, token: str) -> int:
        """Pin adapter `name` for request `token` and return its pool row,
        hot-loading (and LRU-evicting) as needed. Idempotent per token.
        Raises ValueError (unknown/invalid adapter, or pool exhausted by
        active adapters) — the caller maps it to a client error."""
        with self._lock:
            prev = self._acquired.get(token)
            if prev == name:
                return self._resident[name]
            if prev is not None:
                self._release_name(prev)
                del self._acquired[token]
            info = self.validate(name)
            row = self._ensure_resident(info)
            self._acquired[token] = name
            self._refcounts[name] = self._refcounts.get(name, 0) + 1
            self._last_used[name] = time.monotonic()
            if self.metrics is not None:
                self.metrics.record_lora_request(name)
            return row

    def release(self, token: str) -> None:
        """Unpin whatever `token` acquired. Idempotent — terminal paths may
        fire more than once for one request."""
        with self._lock:
            name = self._acquired.pop(token, None)
            if name is not None:
                self._release_name(name)

    def _release_name(self, name: str) -> None:
        n = self._refcounts.get(name, 0)
        if n <= 1:
            self._refcounts.pop(name, None)
        else:
            self._refcounts[name] = n - 1

    def slot_of(self, name: str | None) -> int:
        """Pool row of a RESIDENT adapter (0 for None — the identity row).
        Callers hold a refcount via acquire, so the row cannot move.

        Deliberately LOCK-FREE: the step loop calls this per dispatch while
        an HTTP thread may hold the manager lock across a multi-second cold
        hot-load (disk read + device writes) — taking the lock here would
        stall every active stream behind that load. A plain GIL-atomic dict
        read is safe: an adapter is published to `_resident` only AFTER its
        rows are fully written, and the caller's refcount pins the entry."""
        if not name:
            return 0
        row = self._resident.get(name)
        if row is None:
            raise KeyError(f"adapter {name!r} is not resident")
        return row

    # ------------------------------------------------------------ load / evict

    def _ensure_resident(self, info: AdapterInfo) -> int:
        """Lock held. Return the adapter's pool row, loading it (evicting an
        idle LRU victim when the pool is full) if needed."""
        row = self._resident.get(info.name)
        if row is not None:
            return row
        if not self._free_rows:
            victim = self._evict_lru_locked()
            if victim is None:
                active = sorted(self._refcounts)
                raise ValueError(
                    f"'lora' adapter pool exhausted: all {self.max_adapters} "
                    f"resident adapters have active requests "
                    f"({', '.join(active)}); retry shortly or raise "
                    "--lora-max-adapters"
                )
        row = self._free_rows.pop(0)
        t0 = time.monotonic()
        self._write_rows(info, row)
        self._resident[info.name] = row
        self.loads_total += 1
        took = time.monotonic() - t0
        if self.metrics is not None:
            self.metrics.record_lora_load(took)
        log.info("lora: loaded adapter %r (rank %d, targets %s) into row %d "
                 "in %.3fs", info.name, info.rank,
                 "/".join(info.targets), row, took)
        return row

    def _evict_lru_locked(self) -> str | None:
        victim: str | None = None
        for name in self._resident:
            if self._refcounts.get(name, 0) > 0:
                continue
            if victim is None or (self._last_used.get(name, 0.0)
                                  < self._last_used.get(victim, 0.0)):
                victim = name
        if victim is None:
            return None
        row = self._resident.pop(victim)
        self._free_rows.append(row)
        self._last_used.pop(victim, None)
        self.evictions_total += 1
        if self.metrics is not None:
            self.metrics.record_lora_eviction()
        log.info("lora: evicted idle adapter %r from row %d", victim, row)
        # The vacated device rows are NOT zeroed: nothing references a row
        # without a refcount, and the next load overwrites it wholesale.
        return victim

    def _write_rows(self, info: AdapterInfo, row: int) -> None:
        """Write one adapter's factors into pool row `row` of every target
        leaf. Non-donating updates: in-flight dispatches flattened the old
        arrays already, and no request can reference this row until acquire
        returns."""
        assert self.core is not None, "LoraManager.attach(core) first"
        import jax.numpy as jnp

        host = load_adapter_tensors(
            info, self.cfg, pool_rank=self.rank_cap,
            dtype=np.dtype(self.cfg.dtype),
        )
        params = self.core.params
        for tgt in self.targets:
            a_key, b_key = f"{tgt}{_LORA_A}", f"{tgt}{_LORA_B}"
            pair = host.get(tgt)
            if pair is None:
                # target untouched by this adapter: zero the row (it may
                # hold a previous tenant's factors)
                a_upd = jnp.zeros(params[a_key].shape[2:],
                                  params[a_key].dtype)
                b_upd = jnp.zeros(params[b_key].shape[2:],
                                  params[b_key].dtype)
            else:
                a_upd, b_upd = jnp.asarray(pair[0]), jnp.asarray(pair[1])
            params[a_key] = params[a_key].at[:, row].set(a_upd)
            params[b_key] = params[b_key].at[:, row].set(b_upd)

    # ------------------------------------------------------------ introspection

    def resident_names(self) -> list[str]:
        with self._lock:
            return sorted(self._resident)

    def available_names(self) -> list[str]:
        with self._lock:
            return sorted(n for n, i in self.available.items()
                          if i.error is None)

    def info(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "dir": self.lora_dir,
                "max_adapters": self.max_adapters,
                "rank_cap": self.rank_cap,
                "targets": list(self.targets),
                "available": self.available_names(),
                "resident": sorted(self._resident),
                "active": {n: c for n, c in sorted(self._refcounts.items())},
                "loads_total": self.loads_total,
                "evictions_total": self.evictions_total,
            }
