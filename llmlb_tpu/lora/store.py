"""LoRA adapter files: discovery, validation, and host-side tensor loading.

Adapters live as subdirectories of `--lora-dir`, one per adapter, in the
HF/PEFT layout the fine-tune-then-serve loop produces (PAPERS.md: the
Gemma-on-TPU paper is exactly that loop):

    <lora_dir>/<adapter-name>/
        adapter_config.json        # {"r": 8, "lora_alpha": 16,
                                   #  "target_modules": ["q_proj", ...]}
        adapter_model.safetensors  # base_model.model.model.layers.{i}.
                                   #   self_attn.q_proj.lora_A.weight [r, in]
                                   #   ...lora_B.weight [out, r]

Discovery reads only the configs (cheap — validation without touching
tensors); `load_adapter_tensors` reads the safetensors on first use (the
manager's hot-load path) and returns per-target stacked host pairs
`a [L, in, R]` / `b [L, R, out]` in the model's [in, out] matmul layout:

- lora_A transposes to [in, r], lora_B to [r, out] (HF stores both as
  [out, in] like every nn.Linear weight);
- the PEFT scale alpha/r folds into B once at load — serving never
  multiplies it per step;
- rank pads up to the pool rank R with zero columns/rows (exact: the
  padded rank contributes 0 to the delta), so mixed-rank adapters share
  one pool;
- a layer/target the adapter does not touch stays zero — no delta there.

`save_adapter` writes the same layout (tests and the bench synthesize
adapters with it — it is NOT a training utility).
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

# HF/PEFT module names → our param-pytree projection names.
HF_TARGET_MAP = {
    "q_proj": "wq",
    "k_proj": "wk",
    "v_proj": "wv",
    "o_proj": "wo",
    "gate_proj": "wg",
    "up_proj": "wu",
    "down_proj": "wd",
}
_REVERSE_TARGET_MAP = {v: k for k, v in HF_TARGET_MAP.items()}

CONFIG_FILE = "adapter_config.json"
WEIGHTS_FILE = "adapter_model.safetensors"


def lora_target_dims(cfg, targets: tuple[str, ...]) -> dict[str, tuple[int, int]]:
    """(in_dim, out_dim) per LoRA-targetable projection of a model config —
    the pool row shapes. Matches the [in, out] layout of models/llama.py."""
    e = cfg.hidden_size
    d = cfg.head_dim_
    h, k = cfg.num_heads, cfg.num_kv_heads
    f = cfg.intermediate_size
    dims = {
        "wq": (e, h * d),
        "wk": (e, k * d),
        "wv": (e, k * d),
        "wo": (h * d, e),
        "wg": (e, f),
        "wu": (e, f),
        "wd": (f, e),
    }
    return {t: dims[t] for t in targets}


@dataclasses.dataclass
class AdapterInfo:
    """One discovered adapter. `error` is None when servable; otherwise the
    reason the engine must refuse it with a 400 naming the `lora` field."""

    name: str
    path: str
    rank: int = 0
    alpha: float = 0.0
    targets: tuple[str, ...] = ()
    error: str | None = None


def _read_config(path: str) -> dict:
    with open(os.path.join(path, CONFIG_FILE)) as f:
        return json.load(f)


def discover_adapters(
    lora_dir: str,
    *,
    rank_cap: int,
    allowed_targets: tuple[str, ...],
) -> dict[str, AdapterInfo]:
    """Scan `lora_dir` for adapter subdirectories. Config-only: invalid
    adapters (rank over the cap, unsupported target module, malformed
    config) are kept in the map WITH their error so a request naming one
    gets a specific 400 instead of a generic "unknown adapter"."""
    out: dict[str, AdapterInfo] = {}
    if not lora_dir or not os.path.isdir(lora_dir):
        return out
    for name in sorted(os.listdir(lora_dir)):
        path = os.path.join(lora_dir, name)
        if not os.path.isdir(path) or not os.path.exists(
            os.path.join(path, WEIGHTS_FILE)
        ):
            continue
        info = AdapterInfo(name=name, path=path)
        try:
            cfg = _read_config(path)
            rank = int(cfg.get("r", 0))
            alpha = float(cfg.get("lora_alpha", rank))
            raw_targets = cfg.get("target_modules") or []
            targets = []
            for m in raw_targets:
                tgt = HF_TARGET_MAP.get(str(m))
                if tgt is None:
                    raise ValueError(
                        f"unsupported target module {m!r} (supported: "
                        f"{', '.join(sorted(HF_TARGET_MAP))})"
                    )
                targets.append(tgt)
            unsupported = [t for t in targets if t not in allowed_targets]
            if unsupported:
                raise ValueError(
                    "target module(s) "
                    + ", ".join(_REVERSE_TARGET_MAP[t] for t in unsupported)
                    + " are not servable for this model family"
                )
            if rank < 1:
                raise ValueError(f"rank must be >= 1, got {rank}")
            if rank > rank_cap:
                raise ValueError(
                    f"rank {rank} exceeds the engine's rank cap {rank_cap} "
                    "(--lora-rank-cap)"
                )
            info.rank = rank
            info.alpha = alpha
            info.targets = tuple(targets)
        except FileNotFoundError:
            info.error = f"missing {CONFIG_FILE}"
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            info.error = str(e)
        out[name] = info
    return out


def load_adapter_tensors(
    info: AdapterInfo,
    cfg,
    *,
    pool_rank: int,
    dtype,
) -> dict[str, tuple[np.ndarray, np.ndarray]]:
    """Read one adapter's safetensors into stacked per-target host pairs
    `{target: (a [L, in, R], b [L, R, out])}` at the pool rank R. The PEFT
    alpha/r scale folds into B; absent layers/targets stay zero."""
    from llmlb_tpu.engine.weights import _close_shard, _open_shard

    dims = lora_target_dims(cfg, info.targets)
    layers = cfg.num_layers
    scale = info.alpha / info.rank if info.rank else 1.0
    out: dict[str, tuple[np.ndarray, np.ndarray]] = {}
    shard = _open_shard(os.path.join(info.path, WEIGHTS_FILE))
    try:
        # PEFT prefixes vary (base_model.model., base_model.model.model.,
        # plain model.); index every key ONCE by its stable
        # `layers.{i}.<module>.lora_{A|B}.weight` tail so the per-(layer,
        # target) lookups below are O(1) instead of a scan of every key —
        # this runs inside the hot-load path a cold adapter pays at
        # admission.
        by_tail: dict[str, str] = {}
        for key in shard.keys():
            idx = key.rfind("layers.")
            if idx >= 0:
                by_tail.setdefault(key[idx:], key)

        def find(layer: int, module: str, which: str) -> str | None:
            return by_tail.get(
                f"layers.{layer}.{module}.lora_{which}.weight"
            ) or by_tail.get(
                f"layers.{layer}.mlp.{module}.lora_{which}.weight"
            )

        for tgt in info.targets:
            in_dim, out_dim = dims[tgt]
            module = _REVERSE_TARGET_MAP[tgt]
            if tgt in ("wq", "wk", "wv", "wo"):
                module = f"self_attn.{module}"
            a = np.zeros((layers, in_dim, pool_rank), dtype)
            b = np.zeros((layers, pool_rank, out_dim), dtype)
            for i in range(layers):
                ka = find(i, module, "A")
                kb = find(i, module, "B")
                if ka is None or kb is None:
                    continue  # untouched layer: zero delta
                wa = np.asarray(shard.get_tensor(ka), np.float32)  # [r, in]
                wb = np.asarray(shard.get_tensor(kb), np.float32)  # [out, r]
                r = wa.shape[0]
                if r > pool_rank:
                    raise ValueError(
                        f"adapter {info.name!r} layer {i} {module} rank {r} "
                        f"exceeds the pool rank {pool_rank}"
                    )
                a[i, :, :r] = wa.T.astype(dtype)
                b[i, :r, :] = (wb.T * scale).astype(dtype)
            out[tgt] = (a, b)
    finally:
        _close_shard(shard)
    return out


def save_adapter(
    lora_dir: str,
    name: str,
    cfg,
    *,
    rank: int,
    alpha: float | None = None,
    targets: tuple[str, ...] = ("wq", "wk", "wv", "wo"),
    seed: int = 0,
    scale: float = 0.25,  # large enough that greedy streams visibly diverge
) -> str:
    """Write a synthetic adapter in the PEFT layout `discover_adapters`
    reads — the fixture-side of the contract (tests + bench_gateway's lora
    workload). Deterministic per (name, seed). Returns the adapter path."""
    from safetensors.numpy import save_file

    dims = lora_target_dims(cfg, targets)
    alpha = float(alpha if alpha is not None else rank)
    rng = np.random.default_rng(
        seed + int.from_bytes(name.encode()[:4].ljust(4, b"\0"), "big")
    )
    tensors: dict[str, np.ndarray] = {}
    for tgt in targets:
        in_dim, out_dim = dims[tgt]
        module = _REVERSE_TARGET_MAP[tgt]
        prefix = "self_attn." if tgt in ("wq", "wk", "wv", "wo") else "mlp."
        for i in range(cfg.num_layers):
            key = f"base_model.model.model.layers.{i}.{prefix}{module}"
            tensors[f"{key}.lora_A.weight"] = (
                rng.standard_normal((rank, in_dim)) * scale
            ).astype(np.float32)
            tensors[f"{key}.lora_B.weight"] = (
                rng.standard_normal((out_dim, rank)) * scale
            ).astype(np.float32)
    path = os.path.join(lora_dir, name)
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, CONFIG_FILE), "w") as f:
        json.dump({
            "r": rank,
            "lora_alpha": alpha,
            "target_modules": [_REVERSE_TARGET_MAP[t] for t in targets],
        }, f)
    save_file(tensors, os.path.join(path, WEIGHTS_FILE))
    return path
