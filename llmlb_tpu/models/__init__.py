from llmlb_tpu.models.llama import (
    LlamaConfig,
    init_params,
    param_shardings,
    kv_cache_shardings,
    init_kv_cache,
    prefill,
    prefill_into_slots,
    decode_step,
)

__all__ = [
    "LlamaConfig",
    "init_params",
    "param_shardings",
    "kv_cache_shardings",
    "init_kv_cache",
    "prefill",
    "prefill_into_slots",
    "decode_step",
]
