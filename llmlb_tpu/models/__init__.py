"""Model families served by the tpu:// engine.

`family_for(cfg)` resolves the function module (init_params / param_shardings /
kv_cache_shardings / init_kv_cache / prefill / prefill_into_slots / decode_step
— one shared serving contract) for a config, so the engine scheduler is
family-agnostic: dense Llama-class (llama.py) and sparse-MoE Mixtral-class
(mixtral.py) plug into the same continuous-batching loop.
"""

from llmlb_tpu.models.llama import (
    LlamaConfig,
    init_params,
    param_shardings,
    kv_cache_shardings,
    init_kv_cache,
    prefill,
    prefill_into_slots,
    decode_step,
)


def family_for(cfg):
    """Resolve the serving-function module for a model config."""
    from llmlb_tpu.models import llama, mixtral

    if isinstance(cfg, mixtral.MixtralConfig):
        return mixtral
    if isinstance(cfg, LlamaConfig):
        return llama
    raise TypeError(f"no model family for config type {type(cfg).__name__}")


__all__ = [
    "LlamaConfig",
    "family_for",
    "init_params",
    "param_shardings",
    "kv_cache_shardings",
    "init_kv_cache",
    "prefill",
    "prefill_into_slots",
    "decode_step",
]
