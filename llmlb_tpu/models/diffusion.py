"""Text-conditioned image diffusion (UNet + DDIM) — functional JAX.

Backs /v1/images/generations on the tpu:// engine. The reference proxies image
requests to endpoints advertising the ImageGeneration capability
(api/images.rs:158-182) and hosts no image model; this is the in-tree
TPU-native equivalent:

- Pixel-space ε-prediction UNet: NHWC convs (MXU-friendly), group norm, SiLU,
  residual blocks with time+text conditioning injected per block, one
  self-attention block at the bottleneck, skip connections on the up path.
- Text conditioning: byte-token embedding mean-pool → MLP, added to the
  sinusoidal timestep embedding (classifier-free guidance via a null
  embedding row).
- DDIM sampler: fixed step count under `lax.scan` — the whole sampling loop
  is one compiled program, no host round-trips per step.

Weights are framework-native (flat pytree in safetensors; save/load below) —
the compact architecture has no public HF counterpart.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class DiffusionConfig:
    img_size: int = 64
    channels: int = 3
    base_ch: int = 64
    ch_mults: tuple = (1, 2, 4)
    text_vocab: int = 256
    text_dim: int = 128
    max_text_len: int = 128
    train_steps: int = 1000
    dtype: Any = jnp.float32


def _group_norm(x, g, b, groups=8, eps=1e-5):
    n, h, w, c = x.shape
    groups = min(groups, c)
    xg = x.reshape(n, h, w, groups, c // groups)
    mean = xg.mean((1, 2, 4), keepdims=True)
    var = ((xg - mean) ** 2).mean((1, 2, 4), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    return xg.reshape(n, h, w, c) * g + b


def _conv(x, w, b, stride=1):
    out = lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return out + b


def init_params(cfg: DiffusionConfig, key: jax.Array) -> Params:
    ks = iter(jax.random.split(key, 128))

    def w(shape, fan_in):
        return (jax.random.normal(next(ks), shape, jnp.float32)
                * fan_in**-0.5).astype(cfg.dtype)

    def conv_p(cin, cout, k=3):
        return {"w": w((k, k, cin, cout), k * k * cin),
                "b": jnp.zeros((cout,), cfg.dtype)}

    def res_block(cin, cout):
        return {
            "n1g": jnp.ones((cin,), cfg.dtype), "n1b": jnp.zeros((cin,), cfg.dtype),
            "c1": conv_p(cin, cout),
            "emb_w": w((cfg.base_ch * 4, cout), cfg.base_ch * 4),
            "emb_b": jnp.zeros((cout,), cfg.dtype),
            "n2g": jnp.ones((cout,), cfg.dtype), "n2b": jnp.zeros((cout,), cfg.dtype),
            "c2": conv_p(cout, cout),
            "skip": conv_p(cin, cout, k=1) if cin != cout else None,
        }

    chs = [cfg.base_ch * m for m in cfg.ch_mults]
    emb_dim = cfg.base_ch * 4
    mid = chs[-1]
    params: Params = {
        "text_embed": w((cfg.text_vocab + 1, cfg.text_dim), cfg.text_dim),
        "null_text": w((cfg.text_dim,), cfg.text_dim),
        "text_w1": w((cfg.text_dim, emb_dim), cfg.text_dim),
        "text_b1": jnp.zeros((emb_dim,), cfg.dtype),
        "time_w1": w((cfg.base_ch, emb_dim), cfg.base_ch),
        "time_b1": jnp.zeros((emb_dim,), cfg.dtype),
        "emb_w2": w((emb_dim, emb_dim), emb_dim),
        "emb_b2": jnp.zeros((emb_dim,), cfg.dtype),
        "conv_in": conv_p(cfg.channels, chs[0]),
        "down": [], "down_samp": [],
        "mid1": res_block(mid, mid),
        "attn_g": jnp.ones((mid,), cfg.dtype),
        "attn_b": jnp.zeros((mid,), cfg.dtype),
        "attn_qkv": conv_p(mid, mid * 3, k=1),
        "attn_out": conv_p(mid, mid, k=1),
        "mid2": res_block(mid, mid),
        "up": [], "up_samp": [],
        "norm_out_g": jnp.ones((chs[0],), cfg.dtype),
        "norm_out_b": jnp.zeros((chs[0],), cfg.dtype),
        "conv_out": conv_p(chs[0], cfg.channels),
    }
    prev = chs[0]
    for ch in chs:
        params["down"].append(res_block(prev, ch))
        params["down_samp"].append(conv_p(ch, ch))  # stride-2 in forward
        prev = ch
    for ch in reversed(chs):
        params["up_samp"].append(conv_p(prev, ch))  # project before skip concat
        params["up"].append(res_block(ch + ch, ch))
        prev = ch
    return params


def _res(cfg, p, x, emb):
    h = jax.nn.silu(_group_norm(x, p["n1g"], p["n1b"]))
    h = _conv(h, p["c1"]["w"], p["c1"]["b"])
    h = h + (jax.nn.silu(emb) @ p["emb_w"] + p["emb_b"])[:, None, None, :]
    h = jax.nn.silu(_group_norm(h, p["n2g"], p["n2b"]))
    h = _conv(h, p["c2"]["w"], p["c2"]["b"])
    if p["skip"] is not None:
        x = _conv(x, p["skip"]["w"], p["skip"]["b"])
    return x + h


def _timestep_embedding(t: jnp.ndarray, dim: int) -> jnp.ndarray:
    half = dim // 2
    freqs = jnp.exp(-np.log(10000.0) * jnp.arange(half) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.sin(args), jnp.cos(args)], axis=-1)


def _text_condition(cfg, params, text_ids, text_lens):
    """[B, T] byte ids (+1 offset; 0 = pad) -> [B, text_dim] pooled embedding.
    text_lens == 0 selects the learned null embedding (CFG unconditional)."""
    emb = params["text_embed"][text_ids]  # [B, T, text_dim]
    valid = (jnp.arange(text_ids.shape[1])[None, :]
             < text_lens[:, None]).astype(emb.dtype)
    pooled = (emb * valid[..., None]).sum(1) / jnp.maximum(
        text_lens[:, None].astype(emb.dtype), 1.0
    )
    null = jnp.broadcast_to(params["null_text"], pooled.shape)
    return jnp.where((text_lens > 0)[:, None], pooled, null)


@partial(jax.jit, static_argnames=("cfg",))
def unet_eps(params: Params, cfg: DiffusionConfig,
             x: jnp.ndarray,  # [B, H, W, C] noisy image
             t: jnp.ndarray,  # [B] int32 timestep
             text_ids: jnp.ndarray,  # [B, T]
             text_lens: jnp.ndarray,  # [B]
             ) -> jnp.ndarray:
    """Predict the noise ε added at timestep t."""
    temb = _timestep_embedding(t, cfg.base_ch)
    emb = (temb @ params["time_w1"] + params["time_b1"])
    cond = _text_condition(cfg, params, text_ids, text_lens)
    emb = emb + (cond @ params["text_w1"] + params["text_b1"])
    emb = jax.nn.silu(emb) @ params["emb_w2"] + params["emb_b2"]

    h = _conv(x.astype(cfg.dtype), params["conv_in"]["w"], params["conv_in"]["b"])
    skips = []
    for blk, samp in zip(params["down"], params["down_samp"]):
        h = _res(cfg, blk, h, emb)
        skips.append(h)
        h = _conv(h, samp["w"], samp["b"], stride=2)

    h = _res(cfg, params["mid1"], h, emb)
    # bottleneck self-attention
    n, hh, ww, c = h.shape
    a = _group_norm(h, params["attn_g"], params["attn_b"])
    qkv = _conv(a, params["attn_qkv"]["w"], params["attn_qkv"]["b"])
    q, k, v = jnp.split(qkv.reshape(n, hh * ww, 3 * c), 3, axis=-1)
    att = jax.nn.softmax(
        jnp.einsum("nqc,nkc->nqk", q, k, preferred_element_type=jnp.float32)
        * c**-0.5, axis=-1
    ).astype(h.dtype)
    a = jnp.einsum("nqk,nkc->nqc", att, v).reshape(n, hh, ww, c)
    h = h + _conv(a, params["attn_out"]["w"], params["attn_out"]["b"])
    h = _res(cfg, params["mid2"], h, emb)

    for blk, samp in zip(params["up"], params["up_samp"]):
        skip = skips.pop()
        target = skip.shape[1]
        h = jax.image.resize(h, (n, target, target, h.shape[-1]), "nearest")
        h = _conv(h, samp["w"], samp["b"])
        h = _res(cfg, blk, jnp.concatenate([h, skip], axis=-1), emb)

    h = jax.nn.silu(_group_norm(h, params["norm_out_g"], params["norm_out_b"]))
    return _conv(h, params["conv_out"]["w"], params["conv_out"]["b"])


def _ddim_schedule(cfg: DiffusionConfig, n_steps: int):
    betas = np.linspace(1e-4, 0.02, cfg.train_steps, dtype=np.float64)
    alphas_bar = np.cumprod(1.0 - betas)
    ts = np.linspace(cfg.train_steps - 1, 0, n_steps).round().astype(np.int32)
    return jnp.asarray(ts), jnp.asarray(alphas_bar.astype(np.float32))


@partial(jax.jit, static_argnames=("cfg", "n_images", "n_steps", "guidance"))
def ddim_sample(params: Params, cfg: DiffusionConfig, key: jax.Array,
                text_ids: jnp.ndarray, text_lens: jnp.ndarray,
                n_images: int, n_steps: int = 20,
                guidance: float = 3.0) -> jnp.ndarray:
    """Generate [n, H, W, C] images in [-1, 1] with classifier-free guidance.
    The full sampler is one compiled scan — no host loop."""
    ts, alphas_bar = _ddim_schedule(cfg, n_steps)
    shape = (n_images, cfg.img_size, cfg.img_size, cfg.channels)
    x = jax.random.normal(key, shape, jnp.float32)
    text_ids = jnp.broadcast_to(text_ids, (n_images,) + text_ids.shape[1:])
    text_lens = jnp.broadcast_to(text_lens, (n_images,))
    zero_lens = jnp.zeros_like(text_lens)

    def step(x, i):
        t = ts[i]
        t_batch = jnp.full((n_images,), t, jnp.int32)
        eps_c = unet_eps(params, cfg, x, t_batch, text_ids, text_lens)
        eps_u = unet_eps(params, cfg, x, t_batch, text_ids, zero_lens)
        eps = eps_u + guidance * (eps_c - eps_u)
        a_t = alphas_bar[t]
        t_prev = jnp.where(i + 1 < n_steps, ts[jnp.minimum(i + 1, n_steps - 1)], -1)
        a_prev = jnp.where(t_prev >= 0, alphas_bar[jnp.maximum(t_prev, 0)], 1.0)
        x0 = (x - jnp.sqrt(1.0 - a_t) * eps) / jnp.sqrt(a_t)
        x0 = jnp.clip(x0, -1.0, 1.0)
        x = jnp.sqrt(a_prev) * x0 + jnp.sqrt(1.0 - a_prev) * eps
        return x, None

    x, _ = lax.scan(step, x, jnp.arange(n_steps))
    return jnp.clip(x, -1.0, 1.0)


# Checkpoint round-trip shares the flat-pytree safetensors format with tts.
def save_checkpoint(path: str, cfg: DiffusionConfig, params: Params) -> None:
    import json
    import os

    from safetensors.numpy import save_file

    flat = {}

    def add(prefix, leaf):
        if isinstance(leaf, dict):
            for k, v in leaf.items():
                add(f"{prefix}.{k}" if prefix else k, v)
        elif isinstance(leaf, list):
            for i, v in enumerate(leaf):
                add(f"{prefix}.{i}", v)
        elif leaf is None:
            return
        else:
            flat[prefix] = np.asarray(leaf)

    add("", params)
    os.makedirs(path, exist_ok=True)
    save_file(flat, os.path.join(path, "model.safetensors"))
    meta = {k: (list(v) if isinstance(v, tuple) else v)
            for k, v in dataclasses.asdict(cfg).items() if k != "dtype"}
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump({"model_type": "llmlb_tpu_diffusion", **meta}, f)


def load_checkpoint(path: str) -> tuple[DiffusionConfig, Params]:
    import json
    import os

    from safetensors.numpy import load_file

    with open(os.path.join(path, "config.json")) as f:
        meta = json.load(f)
    meta.pop("model_type", None)
    if "ch_mults" in meta:
        meta["ch_mults"] = tuple(meta["ch_mults"])
    cfg = DiffusionConfig(**meta)
    flat = load_file(os.path.join(path, "model.safetensors"))
    nested: dict = {}
    for key, value in flat.items():
        parts = key.split(".")
        node = nested
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(value)

    def fix(node, template):
        if isinstance(template, list):
            return [fix(node[str(i)], template[i]) for i in range(len(template))]
        if isinstance(template, dict):
            return {
                k: (None if template[k] is None else fix(node.get(k), template[k]))
                for k in template
            }
        return node

    template = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, fix(nested, template)
