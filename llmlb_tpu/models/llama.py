"""Llama-family decoder (Llama-2/3, Qwen-2/2.5, Mistral) — functional JAX.

TPU-first design decisions:
- Pure functions over a flat param pytree; no Module framework. Everything jits.
- All layers are *stacked* along a leading axis. Prefill/extend iterate them
  with `lax.scan` (one layer compiles once — prefill compile time stays flat
  even for 80-layer configs); decode UNROLLS the loop so each layer updates
  the donated KV cache in place at a static index — scanning the cache
  materialized full-cache copies per layer under the engine's burst scan
  (see _decode_impl).
- Serving-shaped entry points: `prefill` (bucketed [B, T] prompts into fresh KV
  slots) and `decode_step` ([B] one token per slot). Both have fully static
  shapes; raggedness is carried by `prompt_lens` / `seq_lens` masks.
- Sharding is expressed once in `param_shardings` / `kv_cache_shardings` using
  logical axes (parallel/sharding.py) — Megatron-style tp over heads/ffn/vocab,
  dp over the batch/slot axis.

The reference does no inference in-process (SURVEY.md L0: external runtimes over
HTTP); this model family is the in-tree `tpu://` engine's compute core per the
BASELINE.json north star. HF-format checkpoints load via engine/weights.py.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llmlb_tpu.ops.attention import (
    gqa_attention_decode,
    gqa_attention_extend,
    gqa_attention_prefill,
)
from llmlb_tpu.ops.norms import rms_norm
from llmlb_tpu.ops.rope import RopeScaling, apply_rope, rope_frequencies
from llmlb_tpu.parallel.mesh import validate_tp
from llmlb_tpu.parallel.sharding import ShardingRules, logical_to_sharding
from llmlb_tpu.quant import quantize_kv

Params = dict[str, Any]

# Int8-quantized projection weights ride the pytree as `<name>` (int8) +
# `<name>_scale` (f32 per output channel) pairs — llmlb_tpu/quant.
_SCALE = "_scale"

# Multi-LoRA adapter pools (llmlb_tpu/lora, docs/lora.md) ride the pytree as
# `<name>_lora_a` [L, N, IN, R] / `<name>_lora_b` [L, N, R, OUT] pairs —
# N stacked adapter slots over the base projection `<name>`, slot 0 all-zero
# (the no-adapter identity row). Like the quant scales they are companions:
# absent on LoRA-free engines, in which case every branch below compiles the
# original program bit for bit.
_LORA_A = "_lora_a"
_LORA_B = "_lora_b"
# Projections that can carry adapter deltas (attention always; the dense
# SwiGLU MLP optionally — MoE expert FFNs are out of scope, so mixtral
# engines serve attention-only adapters).
LORA_TARGETS = ("wq", "wk", "wv", "wo", "wg", "wu", "wd")


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int | None = None  # default hidden_size // num_heads
    rope_theta: float = 10000.0
    rope_scaling: RopeScaling | None = None
    rms_eps: float = 1e-5
    attention_bias: bool = False  # Qwen-2/2.5 use qkv bias
    tie_word_embeddings: bool = False
    max_position_embeddings: int = 8192
    dtype: Any = jnp.bfloat16

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @classmethod
    def from_hf_config(cls, hf: dict, dtype=jnp.bfloat16) -> "LlamaConfig":
        """Build from a HF `config.json` dict (llama / qwen2 / mistral archs)."""
        scaling = None
        rs = hf.get("rope_scaling")
        rope_type = rs.get("rope_type", rs.get("type")) if rs else None
        if rope_type not in (None, "default", "llama3"):
            raise NotImplementedError(
                f"rope_scaling type {rope_type!r} is not supported yet; "
                "refusing to load a checkpoint that would generate silently "
                "wrong logits beyond its original context window"
            )
        if rope_type == "llama3":
            scaling = RopeScaling(
                factor=rs.get("factor", 8.0),
                low_freq_factor=rs.get("low_freq_factor", 1.0),
                high_freq_factor=rs.get("high_freq_factor", 4.0),
                original_max_position=rs.get("original_max_position_embeddings", 8192),
            )
        model_type = hf.get("model_type", "llama")
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
            head_dim=hf.get("head_dim"),
            rope_theta=hf.get("rope_theta", 10000.0),
            rope_scaling=scaling,
            rms_eps=hf.get("rms_norm_eps", 1e-5),
            attention_bias=hf.get(
                "attention_bias", model_type in ("qwen2", "qwen2_moe")
            ),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            max_position_embeddings=hf.get("max_position_embeddings", 8192),
            dtype=dtype,
        )


# ---------------------------------------------------------------------------
# Params
# ---------------------------------------------------------------------------

def init_params(cfg: LlamaConfig, key: jax.Array) -> Params:
    """Random init (serving uses checkpoint weights; this backs tests/benches)."""
    d = cfg.head_dim_
    h, k_, e, f, l_ = cfg.num_heads, cfg.num_kv_heads, cfg.hidden_size, (
        cfg.intermediate_size
    ), cfg.num_layers
    keys = iter(jax.random.split(key, 16))

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * fan_in**-0.5).astype(
            cfg.dtype
        )

    params: Params = {
        "embed": w(next(keys), (cfg.vocab_size, e), e),
        "wq": w(next(keys), (l_, e, h * d), e),
        "wk": w(next(keys), (l_, e, k_ * d), e),
        "wv": w(next(keys), (l_, e, k_ * d), e),
        "wo": w(next(keys), (l_, h * d, e), h * d),
        "wg": w(next(keys), (l_, e, f), e),
        "wu": w(next(keys), (l_, e, f), e),
        "wd": w(next(keys), (l_, f, e), f),
        "ln_attn": jnp.ones((l_, e), cfg.dtype),
        "ln_mlp": jnp.ones((l_, e), cfg.dtype),
        "ln_final": jnp.ones((e,), cfg.dtype),
    }
    if cfg.attention_bias:
        params["bq"] = jnp.zeros((l_, h * d), cfg.dtype)
        params["bk"] = jnp.zeros((l_, k_ * d), cfg.dtype)
        params["bv"] = jnp.zeros((l_, k_ * d), cfg.dtype)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w(next(keys), (e, cfg.vocab_size), e)
    return params


def param_logical_axes(cfg: LlamaConfig) -> dict[str, tuple]:
    """Logical sharding axes per param leaf (see parallel/sharding.py)."""
    axes = {
        "embed": ("vocab", "embed"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "wg": ("layers", "embed", "ffn"),
        "wu": ("layers", "embed", "ffn"),
        "wd": ("layers", "ffn", "embed"),
        "ln_attn": ("layers", "embed"),
        "ln_mlp": ("layers", "embed"),
        "ln_final": ("embed",),
    }
    if cfg.attention_bias:
        axes["bq"] = ("layers", "heads")
        axes["bk"] = ("layers", "kv_heads")
        axes["bv"] = ("layers", "kv_heads")
    if not cfg.tie_word_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    # Per-output-channel int8 scales (present only on quantized pytrees;
    # extra sharding entries for absent leaves are never consulted). A
    # scale's axes are its weight's with the input (contraction) axis
    # dropped — the scale is per OUTPUT channel.
    for name in ("wq", "wk", "wv", "wo", "wg", "wu", "wd"):
        axes[name + _SCALE] = (axes[name][0], axes[name][2])
    # LoRA adapter pools (present only on LoRA-enabled engines): A keeps the
    # weight's input axis (rank axis replicated — ranks are tiny), B keeps
    # the output axis so the delta lands sharded exactly like the base
    # projection's output under tp.
    for name in LORA_TARGETS:
        w_axes = axes[name]
        axes[name + _LORA_A] = (w_axes[0], None, w_axes[1], None)
        axes[name + _LORA_B] = (w_axes[0], None, None, w_axes[2])
    return axes


def shard_rules_for(cfg: LlamaConfig, tp: int) -> ShardingRules:
    """Default rules; kv heads replicate when tp exceeds the kv head count."""
    validate_tp(cfg.num_heads, cfg.num_kv_heads, tp)
    if cfg.intermediate_size % tp != 0:
        raise ValueError(
            f"intermediate_size={cfg.intermediate_size} not divisible by tp={tp}"
        )
    kv_shardable = cfg.num_kv_heads % tp == 0
    return ShardingRules(kv_heads="tp" if kv_shardable else None)


def param_shardings(cfg: LlamaConfig, mesh: Mesh, rules: ShardingRules | None = None):
    rules = rules or shard_rules_for(cfg, mesh.shape["tp"])
    return {
        name: logical_to_sharding(mesh, rules, *axes)
        for name, axes in param_logical_axes(cfg).items()
    }


# ---------------------------------------------------------------------------
# KV cache (slot-based: [L, B_slots, S_capacity, K, D])
# ---------------------------------------------------------------------------

def init_kv_cache(
    cfg: LlamaConfig, num_slots: int, capacity: int, dtype=None
) -> tuple[jnp.ndarray, jnp.ndarray]:
    shape = (cfg.num_layers, num_slots, capacity, cfg.num_kv_heads, cfg.head_dim_)
    dtype = dtype or cfg.dtype
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def kv_cache_shardings(cfg: LlamaConfig, mesh: Mesh, rules: ShardingRules | None = None):
    rules = rules or shard_rules_for(cfg, mesh.shape["tp"])
    sharding = logical_to_sharding(
        mesh, rules, "layers", "batch", "seq", "kv_heads", "head_dim"
    )
    return (sharding, sharding)


# ---------------------------------------------------------------------------
# Paged KV cache (global pool: [L, P_pages, page_size, K, D] + block tables)
# ---------------------------------------------------------------------------

def init_kv_pages(
    cfg: LlamaConfig, num_pages: int, page_size: int, dtype=None,
    quantized: bool = False,
):
    """Global page pool shared by every slot: a slot's logical row is the
    concatenation of the pool pages its block table names. Page 0 is the
    engine's trash page (see engine/paging.py).

    `quantized` swaps each pool for an int8 layout: values [L, P, PS, K, D]
    int8 plus per-vector scales [L, P, PS, K] f32 riding the same page ids
    (one absmax scale per written (token, head) K/V vector). The pair
    travels as a {"q", "s"} pytree through the same serving signatures —
    every alloc/free/refcount/block-table decision stays byte-identical."""
    shape = (cfg.num_layers, num_pages, page_size, cfg.num_kv_heads,
             cfg.head_dim_)
    if quantized:
        def pool():
            return {"q": jnp.zeros(shape, jnp.int8),
                    "s": jnp.zeros(shape[:-1], jnp.float32)}

        return pool(), pool()
    dtype = dtype or cfg.dtype
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def kv_pages_shardings(cfg: LlamaConfig, mesh: Mesh,
                       rules: ShardingRules | None = None,
                       quantized: bool = False):
    """Pages are shared across slots, so the page axis cannot shard over dp
    the way dense slots do (one sequence's pages must stay co-resident);
    only the kv-head axis splits (tp), pages replicate over dp. Quantized
    pools shard their scale arrays along the same axes minus head_dim."""
    rules = rules or shard_rules_for(cfg, mesh.shape["tp"])
    sharding = logical_to_sharding(
        mesh, rules, "layers", None, "seq", "kv_heads", "head_dim"
    )
    if quantized:
        scale_sh = logical_to_sharding(
            mesh, rules, "layers", None, "seq", "kv_heads"
        )
        pool_sh = {"q": sharding, "s": scale_sh}
        return (pool_sh, dict(pool_sh))
    return (sharding, sharding)


def kv_pool_values(pool):
    """The value array of a KV page pool (the int8 member of a quantized
    {"q","s"} pair, or the pool itself when bf16)."""
    return pool["q"] if isinstance(pool, dict) else pool


def _write_pool(pool, page, off, kv):
    """Scatter K/V rows into pool cells [page, off] (leading layer axis
    already sliced away). Quantized pools take the int8 values plus the
    per-vector scales at the same indices — quantize-on-write."""
    if isinstance(pool, dict):
        q, s = quantize_kv(kv)
        return {"q": pool["q"].at[page, off].set(q),
                "s": pool["s"].at[page, off].set(s)}
    return pool.at[page, off].set(kv.astype(pool.dtype))


def _write_pool_layer(pool, layer_idx, page, off, kv):
    """Decode-path scatter at a static layer index of the full pool."""
    if isinstance(pool, dict):
        q, s = quantize_kv(kv)
        return {"q": pool["q"].at[layer_idx, page, off].set(q),
                "s": pool["s"].at[layer_idx, page, off].set(s)}
    return pool.at[layer_idx, page, off].set(kv.astype(pool.dtype))


def _pool_layer(pool, layer_idx):
    """One layer's slice of the pool (both members when quantized)."""
    if isinstance(pool, dict):
        return {"q": pool["q"][layer_idx], "s": pool["s"][layer_idx]}
    return pool[layer_idx]


def make_write_kv_pages(block_tables: jnp.ndarray, page_size: int):
    """KV write that scatters token rows through the block table into the
    global page pool — the paged counterpart of make_write_kv_slots.
    `positions` are logical per-row positions; page block_tables[b, p//PS],
    offset p%PS is the physical cell."""

    def write_kv(pool, kv, positions):
        page = jnp.take_along_axis(block_tables, positions // page_size,
                                   axis=1)  # [B, T]
        return _write_pool(pool, page, positions % page_size, kv)

    return write_kv


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _layer_stacked_names(cfg: LlamaConfig) -> list[str]:
    names = ["wq", "wk", "wv", "wo", "wg", "wu", "wd", "ln_attn", "ln_mlp"]
    if cfg.attention_bias:
        names += ["bq", "bk", "bv"]
    return names


def _with_scales(params: Params, names: list[str]) -> list[str]:
    """Extend a stacked-name list with the companions the pytree carries:
    `<name>_scale` (int8 quant) and `<name>_lora_a`/`<name>_lora_b` (LoRA
    adapter pools), so every per-layer slice sees them. On a plain pytree
    this is the identity — same names, same jit cache keys, bit-identical
    programs."""
    out = list(names)
    for n in names:
        for suffix in (_SCALE, _LORA_A, _LORA_B):
            if n + suffix in params:
                out.append(n + suffix)
    return out


def _proj(lp: Params, name: str, x: jnp.ndarray,
          lora_idx: jnp.ndarray | None = None) -> jnp.ndarray:
    """`x @ W` with on-the-fly int8 dequant when W is quantized: the int8
    -> bf16 convert fuses into the einsum's operand read (HBM moves int8
    bytes), accumulation is fp32 (`preferred_element_type`), and the
    per-output-channel scale applies to the OUTPUT — exact, because the
    scale is constant along the contraction axis. Unquantized weights take
    the original matmul untouched.

    With `lora_idx` ([B] int32 adapter pool rows) and this projection's
    adapter pools in the layer slice, each row's rank-R LoRA delta is added
    to the OUTPUT (ops/lora.py bgmv) — the int8 dequant path above is
    untouched, and row 0 (the all-zero identity adapter) adds exactly 0.0,
    keeping adapter-free rows bit-identical."""
    w = lp[name]
    scale = lp.get(name + _SCALE)
    if scale is None:
        if w.dtype == jnp.int8:
            raise TypeError(
                f"param {name!r} is int8 but its {name}{_SCALE} companion "
                "is missing from the layer slice"
            )
        y = x @ w
    else:
        y32 = jnp.einsum("...i,io->...o", x, w.astype(x.dtype),
                         preferred_element_type=jnp.float32)
        y = (y32 * scale).astype(x.dtype)
    if lora_idx is not None and name + _LORA_A in lp:
        from llmlb_tpu.ops.lora import lora_delta

        delta = lora_delta(x, lp[name + _LORA_A], lp[name + _LORA_B],
                           lora_idx)
        y = y + delta.astype(y.dtype)
    return y


def _qkv(cfg: LlamaConfig, lp: Params, x: jnp.ndarray, lora_idx=None):
    b, t, _ = x.shape
    d = cfg.head_dim_
    q = _proj(lp, "wq", x, lora_idx)
    k = _proj(lp, "wk", x, lora_idx)
    v = _proj(lp, "wv", x, lora_idx)
    if cfg.attention_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    return (
        q.reshape(b, t, cfg.num_heads, d),
        k.reshape(b, t, cfg.num_kv_heads, d),
        v.reshape(b, t, cfg.num_kv_heads, d),
    )


def _mlp(lp: Params, x: jnp.ndarray, lora_idx=None) -> jnp.ndarray:
    return _proj(
        lp, "wd",
        jax.nn.silu(_proj(lp, "wg", x, lora_idx))
        * _proj(lp, "wu", x, lora_idx),
        lora_idx,
    )


def _attn_block(cfg: LlamaConfig, lp: Params, x: jnp.ndarray, positions,
                inv_freq, attn_fn, lora_idx=None):
    """Shared pre-norm attention sub-block (every serving path uses this one
    skeleton: norm → qkv → rope → attn_fn → wo residual). `attn_fn(q, k, v)`
    supplies the attention flavor (dense prefill / cache decode / ring) and may
    capture caches via closure. Returns (x_out, roped_k, roped_v)."""
    b, t, _ = x.shape
    h = rms_norm(x, lp["ln_attn"], cfg.rms_eps)
    q, k, v = _qkv(cfg, lp, h, lora_idx)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    attn = attn_fn(q, k, v)
    return x + _proj(lp, "wo", attn.reshape(b, t, -1), lora_idx), k, v


def _unembed(cfg: LlamaConfig, params: Params, x: jnp.ndarray) -> jnp.ndarray:
    x = rms_norm(x, params["ln_final"], cfg.rms_eps)
    head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    return jnp.einsum(
        "be,ev->bv", x, head, preferred_element_type=jnp.float32
    )


def _default_mlp_fn(lp: Params, h: jnp.ndarray, token_valid,
                    lora_idx=None) -> jnp.ndarray:
    return _mlp(lp, h, lora_idx)


def _write_kv_fresh(cache, kv, positions):
    """KV write for prefill into fresh per-request slots (rows 0..B)."""
    return lax.dynamic_update_slice(cache, kv.astype(cache.dtype),
                                    (0, 0, 0, 0))


def make_write_kv_slots(slot_ids: jnp.ndarray):
    """KV write that scatters prompts into rows `slot_ids` of the engine's
    live slot cache — the continuous-batching insert path."""

    def write_kv(cache, kv, positions):
        return cache.at[slot_ids[:, None], positions].set(
            kv.astype(cache.dtype)
        )

    return write_kv


def _prefill_impl(params, cfg, input_ids, prompt_lens, cache_k, cache_v, write_kv,
                  *, stacked_names=None, mlp_fn=_default_mlp_fn,
                  lora_idx=None):
    """Shared prefill body for every model family.

    `write_kv(cache, new_kv, positions)` places K/V; `mlp_fn(lp, h,
    token_valid, lora_idx)` is the per-family feed-forward (dense SwiGLU
    here, routed experts for mixtral — token_valid marks non-padding tokens
    so MoE routing can ignore padding). `lora_idx` ([B] int32, optional)
    selects each row's adapter pool slot (docs/lora.md)."""
    b, t = input_ids.shape
    inv_freq = rope_frequencies(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None, :], (b, t))
    token_valid = positions < prompt_lens[:, None]  # [B, T]

    x = params["embed"][input_ids]  # [B, T, E]
    stacked = {n: params[n] for n in _with_scales(
        params, stacked_names or _layer_stacked_names(cfg))}

    def layer(carry_x, layer_in):
        lp, ck, cv = layer_in
        carry_x, k, v = _attn_block(
            cfg, lp, carry_x, positions, inv_freq,
            lambda q, k, v: gqa_attention_prefill(q, k, v, prompt_lens),
            lora_idx,
        )
        ck = write_kv(ck, k, positions)
        cv = write_kv(cv, v, positions)
        h = rms_norm(carry_x, lp["ln_mlp"], cfg.rms_eps)
        carry_x = carry_x + mlp_fn(lp, h, token_valid, lora_idx)
        return carry_x, (ck, cv)

    x, (cache_k, cache_v) = lax.scan(layer, x, (stacked, cache_k, cache_v))

    last = jnp.maximum(prompt_lens - 1, 0)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]  # [B, E]
    logits = _unembed(cfg, params, x_last)
    return logits, cache_k, cache_v


def _decode_impl(params, cfg, input_ids, seq_lens, cache_k, cache_v,
                 *, stacked_names=None, mlp_fn=_default_mlp_fn, window=None,
                 lora_idx=None):
    """Shared one-token decode body for every model family.

    The layer loop is UNROLLED (static layer indices) rather than a
    lax.scan with the caches as scan inputs/outputs. Scanning the cache
    slices it per layer and re-stacks the outputs into fresh buffers, and
    under the engine's k-step burst scan XLA materialized full-cache copies
    every layer — measured 40 ms/step on a v5e for a 2 GiB model whose
    weight-streaming bound is ~3 ms (bench_runs/MEASUREMENTS.md). Unrolled,
    each layer does one [B,1,K,D] scatter into the donated full cache at a
    static layer index and reads a static slice for attention, which XLA
    keeps in place. Decode programs are tiny, so L× code growth is cheap."""
    b = input_ids.shape[0]
    capacity = cache_k.shape[2]
    inv_freq = rope_frequencies(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)
    # Freed slots keep counting on device; clamp so their garbage writes stay
    # inside the (ignored) row instead of relying on scatter OOB semantics.
    write_pos = jnp.minimum(seq_lens, capacity - 1)
    positions = write_pos[:, None]  # [B, 1]
    batch_idx = jnp.arange(b)

    x = params["embed"][input_ids][:, None, :]  # [B, 1, E]
    names = _with_scales(params, stacked_names or _layer_stacked_names(cfg))

    for layer_idx in range(cfg.num_layers):
        lp = {n: params[n][layer_idx] for n in names}

        def attn_fn(q, k, v, layer_idx=layer_idx):
            nonlocal cache_k, cache_v  # write precedes attention over the cache
            cache_k = cache_k.at[layer_idx, batch_idx, write_pos].set(
                k[:, 0].astype(cache_k.dtype)
            )
            cache_v = cache_v.at[layer_idx, batch_idx, write_pos].set(
                v[:, 0].astype(cache_v.dtype)
            )
            return gqa_attention_decode(
                q, cache_k[layer_idx], cache_v[layer_idx], write_pos + 1,
                window=window,
            )

        x, _, _ = _attn_block(cfg, lp, x, positions, inv_freq, attn_fn,
                              lora_idx)
        h = rms_norm(x, lp["ln_mlp"], cfg.rms_eps)
        x = x + mlp_fn(lp, h, None, lora_idx)

    logits = _unembed(cfg, params, x[:, 0])
    return logits, cache_k, cache_v


@partial(jax.jit, static_argnames=("cfg", "mesh"),
         donate_argnames=("cache_k", "cache_v"))
def prefill(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jnp.ndarray,  # [B, T] int32, right-padded
    prompt_lens: jnp.ndarray,  # [B] int32
    cache_k: jnp.ndarray,  # [L, B, S, K, D] — fresh slots, written at [0:T]
    cache_v: jnp.ndarray,
    mesh: Mesh | None = None,  # unused (GSPMD shards via param placement);
    # accepted so all model families share one serving-call signature
    lora_idx: jnp.ndarray | None = None,  # [B] int32 adapter pool rows
):
    """Prefill B prompts into their KV slots. Returns (last_logits [B, V] fp32,
    cache_k, cache_v)."""
    return _prefill_impl(
        params, cfg, input_ids, prompt_lens, cache_k, cache_v, _write_kv_fresh,
        lora_idx=lora_idx,
    )


@partial(jax.jit, static_argnames=("cfg", "mesh"),
         donate_argnames=("cache_k", "cache_v"))
def prefill_into_slots(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jnp.ndarray,  # [B, T] int32, right-padded
    prompt_lens: jnp.ndarray,  # [B] int32
    slot_ids: jnp.ndarray,  # [B] int32 — target rows in the global slot cache
    cache_k: jnp.ndarray,  # [L, NUM_SLOTS, CAP, K, D] — the engine's live cache
    cache_v: jnp.ndarray,
    mesh: Mesh | None = None,  # unused; shared family signature
    lora_idx: jnp.ndarray | None = None,  # [B] int32 adapter pool rows
):
    """Prefill B prompts and scatter their KV into rows `slot_ids` of the live
    slot cache — the continuous-batching insert path (new requests land in freed
    slots while other slots keep decoding). Returns (last_logits [B, V] fp32,
    cache_k, cache_v)."""
    return _prefill_impl(
        params, cfg, input_ids, prompt_lens, cache_k, cache_v,
        make_write_kv_slots(slot_ids), lora_idx=lora_idx,
    )


def _prefill_extend_impl(params, cfg, input_ids, chunk_lens, start_pos, slot_ids,
                         cache_k, cache_v, *, stacked_names=None,
                         mlp_fn=_default_mlp_fn, all_logits=False, window=None,
                         lora_idx=None):
    """Shared chunked-prefill body: process a [B, T] chunk of prompt tokens
    whose slots already hold `start_pos` tokens of KV. Queries attend over the
    full slot row (earlier chunks + causal within this chunk). Backs long
    prompts that exceed the one-shot prefill buckets, and — with
    `all_logits=True` — the speculative verify step, which needs logits at
    EVERY chunk position, not just the last. `window` (static) bounds how
    much of the capacity axis attention reads, same contract as decode.

    Padding tokens (i >= chunk_lens) write garbage K/V at positions beyond the
    chunk; those cells sit past the valid range (masked by every later
    attention) and are overwritten in place when the sequence grows into them.
    """
    _, t = input_ids.shape
    capacity = cache_k.shape[2]
    inv_freq = rope_frequencies(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)
    offs = jnp.arange(t, dtype=jnp.int32)[None, :]
    positions = start_pos[:, None] + offs  # [B, T] global positions
    write_pos = jnp.minimum(positions, capacity - 1)
    token_valid = offs < chunk_lens[:, None]  # [B, T]

    x = params["embed"][input_ids]  # [B, T, E]
    stacked = {n: params[n] for n in _with_scales(
        params, stacked_names or _layer_stacked_names(cfg))}

    def layer(carry_x, layer_in):
        lp, ck, cv = layer_in

        def attn_fn(q, k, v):
            nonlocal ck, cv  # cache write precedes attention over the cache
            ck = ck.at[slot_ids[:, None], write_pos].set(k.astype(ck.dtype))
            cv = cv.at[slot_ids[:, None], write_pos].set(v.astype(cv.dtype))
            k_rows, v_rows = ck[slot_ids], cv[slot_ids]
            if window is not None and window < capacity:
                k_rows = lax.slice_in_dim(k_rows, 0, window, axis=1)
                v_rows = lax.slice_in_dim(v_rows, 0, window, axis=1)
            return gqa_attention_extend(
                q, k_rows, v_rows, positions, chunk_lens
            )

        carry_x, _, _ = _attn_block(cfg, lp, carry_x, positions, inv_freq,
                                    attn_fn, lora_idx)
        h = rms_norm(carry_x, lp["ln_mlp"], cfg.rms_eps)
        carry_x = carry_x + mlp_fn(lp, h, token_valid, lora_idx)
        return carry_x, (ck, cv)

    x, (cache_k, cache_v) = lax.scan(layer, x, (stacked, cache_k, cache_v))

    if all_logits:
        b = x.shape[0]
        logits = _unembed(cfg, params, x.reshape(b * t, -1)).reshape(b, t, -1)
        return logits, cache_k, cache_v
    last = jnp.maximum(chunk_lens - 1, 0)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]  # [B, E]
    logits = _unembed(cfg, params, x_last)
    return logits, cache_k, cache_v


@partial(jax.jit, static_argnames=("cfg", "mesh"),
         donate_argnames=("cache_k", "cache_v"))
def prefill_extend_slots(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jnp.ndarray,  # [B, T] int32, right-padded chunk
    chunk_lens: jnp.ndarray,  # [B] int32 — valid tokens in this chunk
    start_pos: jnp.ndarray,  # [B] int32 — tokens already in the slot's cache
    slot_ids: jnp.ndarray,  # [B] int32 — target rows in the global slot cache
    cache_k: jnp.ndarray,  # [L, NUM_SLOTS, CAP, K, D]
    cache_v: jnp.ndarray,
    mesh: Mesh | None = None,  # unused; shared family signature
    lora_idx: jnp.ndarray | None = None,  # [B] int32 adapter pool rows
):
    """Chunked prefill: append a chunk of prompt tokens to slots that already
    hold `start_pos` tokens, attending over everything so far. Lets the engine
    serve prompts far beyond the one-shot prefill buckets while decode steps
    interleave between chunks. Returns (chunk-last logits [B, V] fp32, caches).
    """
    return _prefill_extend_impl(
        params, cfg, input_ids, chunk_lens, start_pos, slot_ids,
        cache_k, cache_v, lora_idx=lora_idx,
    )


@partial(jax.jit, static_argnames=("cfg", "mesh"),
         donate_argnames=("cache_k", "cache_v"))
def prefill_into_pages(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jnp.ndarray,  # [B, T] int32, right-padded
    prompt_lens: jnp.ndarray,  # [B] int32
    block_tables: jnp.ndarray,  # [B, PPN] int32 — target pages per prompt
    cache_k: jnp.ndarray,  # [L, P, PS, K, D] — the engine's live page pool
    cache_v: jnp.ndarray,
    mesh: Mesh | None = None,  # unused; shared family signature
    lora_idx: jnp.ndarray | None = None,  # [B] int32 adapter pool rows
):
    """Prefill B prompts and scatter their KV through the block tables into
    the global page pool — the paged counterpart of prefill_into_slots.
    Returns (last_logits [B, V] fp32, cache_k, cache_v).

    HANDOFF CONTRACT (docs/disaggregation.md): this entry point (and the
    extend/CP variants) is handoff-shaped — row i of `last_logits` is the
    FINAL-position logits of prompt i, and every KV row lands at its
    absolute token position. Split mode stages exactly this logits row
    for a later decode-pool adoption (the first token samples from it),
    and the cross-process replay depends on position-exact KV so the
    adopted continuation is token-identical. A family that fused
    prefill+sample, or wrote KV at relative positions, would break both."""
    return _prefill_impl(
        params, cfg, input_ids, prompt_lens, cache_k, cache_v,
        make_write_kv_pages(block_tables, kv_pool_values(cache_k).shape[2]),
        lora_idx=lora_idx,
    )


def _prefill_extend_paged_impl(params, cfg, input_ids, chunk_lens, start_pos,
                               block_tables, cache_k, cache_v, *,
                               stacked_names=None, mlp_fn=_default_mlp_fn,
                               all_logits=False, window=None, lora_idx=None):
    """Paged counterpart of _prefill_extend_impl: the chunk's KV scatters
    through the block table into the page pool and attention reads the pool
    via ops.attention.paged_attention_extend. Padding tokens write garbage
    past the chunk — into this row's own later pages or the trash page
    (unallocated table entries), never another row's cells. `all_logits`
    returns logits at every chunk position (the speculative verify step);
    `window` (static) bounds the attention sweep to whole pages covering it,
    same contract as paged decode."""
    from llmlb_tpu.ops.attention import paged_attention_extend

    _, t = input_ids.shape
    ps = kv_pool_values(cache_k).shape[2]
    ppn = block_tables.shape[1]
    capacity = ppn * ps
    inv_freq = rope_frequencies(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)
    offs = jnp.arange(t, dtype=jnp.int32)[None, :]
    positions = start_pos[:, None] + offs  # [B, T] global positions
    write_pos = jnp.minimum(positions, capacity - 1)
    page = jnp.take_along_axis(block_tables, write_pos // ps, axis=1)
    off = write_pos % ps
    token_valid = offs < chunk_lens[:, None]  # [B, T]
    # attention sweeps only the pages covering `window` (writes keep the full
    # table: write_pos clamps into capacity, not the window)
    read_tables = block_tables
    if window is not None and -(-window // ps) < ppn:
        read_tables = lax.slice_in_dim(
            block_tables, 0, max(1, -(-window // ps)), axis=1
        )

    x = params["embed"][input_ids]  # [B, T, E]
    stacked = {n: params[n] for n in _with_scales(
        params, stacked_names or _layer_stacked_names(cfg))}

    def layer(carry_x, layer_in):
        lp, ck, cv = layer_in

        def attn_fn(q, k, v):
            nonlocal ck, cv  # pool write precedes attention over the pool
            ck = _write_pool(ck, page, off, k)
            cv = _write_pool(cv, page, off, v)
            return paged_attention_extend(
                q, ck, cv, read_tables, positions, chunk_lens
            )

        carry_x, _, _ = _attn_block(cfg, lp, carry_x, positions, inv_freq,
                                    attn_fn, lora_idx)
        h = rms_norm(carry_x, lp["ln_mlp"], cfg.rms_eps)
        carry_x = carry_x + mlp_fn(lp, h, token_valid, lora_idx)
        return carry_x, (ck, cv)

    x, (cache_k, cache_v) = lax.scan(layer, x, (stacked, cache_k, cache_v))

    if all_logits:
        b = x.shape[0]
        logits = _unembed(cfg, params, x.reshape(b * t, -1)).reshape(b, t, -1)
        return logits, cache_k, cache_v
    last = jnp.maximum(chunk_lens - 1, 0)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]  # [B, E]
    logits = _unembed(cfg, params, x_last)
    return logits, cache_k, cache_v


@partial(jax.jit, static_argnames=("cfg", "mesh"),
         donate_argnames=("cache_k", "cache_v"))
def prefill_extend_pages(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jnp.ndarray,  # [B, T] int32, right-padded chunk
    chunk_lens: jnp.ndarray,  # [B] int32 — valid tokens in this chunk
    start_pos: jnp.ndarray,  # [B] int32 — tokens already in the row's pages
    block_tables: jnp.ndarray,  # [B, PPN] int32
    cache_k: jnp.ndarray,  # [L, P, PS, K, D]
    cache_v: jnp.ndarray,
    mesh: Mesh | None = None,  # unused; shared family signature
    lora_idx: jnp.ndarray | None = None,  # [B] int32 adapter pool rows
):
    """Paged chunked prefill: append a chunk of prompt tokens to rows that
    already hold `start_pos` tokens, attending over everything so far
    through the block tables. Same contract as prefill_extend_slots."""
    return _prefill_extend_paged_impl(
        params, cfg, input_ids, chunk_lens, start_pos, block_tables,
        cache_k, cache_v, lora_idx=lora_idx,
    )


@partial(jax.jit, static_argnames=("cfg", "mesh", "window"),
         donate_argnames=("cache_k", "cache_v"))
def verify_step(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jnp.ndarray,  # [B, K+1] int32 — last committed token + drafts
    chunk_lens: jnp.ndarray,  # [B] int32 — 1 + draft count per row
    start_pos: jnp.ndarray,  # [B] int32 — committed tokens in the row's cache
    slot_ids: jnp.ndarray,  # [B] int32 — target rows (engine passes arange)
    cache_k: jnp.ndarray,  # [L, NUM_SLOTS, CAP, K, D]
    cache_v: jnp.ndarray,
    mesh: Mesh | None = None,  # unused; shared family signature
    window: int | None = None,  # static context-window bucket
    lora_idx: jnp.ndarray | None = None,  # [B] int32 adapter pool rows
):
    """Speculative verification over the dense slot cache: one extend-style
    dispatch scores the last committed token plus up to K draft tokens,
    returning logits at EVERY chunk position ([B, K+1, V] fp32) so the
    scheduler can sample each position and accept the longest matching
    draft prefix. KV for all chunk positions is written; rejected-suffix
    cells become garbage past the rolled-back length (standard contract)."""
    return _prefill_extend_impl(
        params, cfg, input_ids, chunk_lens, start_pos, slot_ids,
        cache_k, cache_v, all_logits=True, window=window, lora_idx=lora_idx,
    )


@partial(jax.jit, static_argnames=("cfg", "mesh", "window"),
         donate_argnames=("cache_k", "cache_v"))
def verify_step_paged(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jnp.ndarray,  # [B, K+1] int32 — last committed token + drafts
    chunk_lens: jnp.ndarray,  # [B] int32 — 1 + draft count per row
    start_pos: jnp.ndarray,  # [B] int32 — committed tokens in the row's pages
    block_tables: jnp.ndarray,  # [B, PPN] int32
    cache_k: jnp.ndarray,  # [L, P, PS, K, D]
    cache_v: jnp.ndarray,
    mesh: Mesh | None = None,  # unused; shared family signature
    window: int | None = None,  # static context-window bucket
    lora_idx: jnp.ndarray | None = None,  # [B] int32 adapter pool rows
):
    """Paged speculative verification: same contract as verify_step with the
    slot cache swapped for the page pool + block tables — the K+1-token
    ragged extend the paged attention kernels were built for."""
    return _prefill_extend_paged_impl(
        params, cfg, input_ids, chunk_lens, start_pos, block_tables,
        cache_k, cache_v, all_logits=True, window=window, lora_idx=lora_idx,
    )


def _decode_paged_impl(params, cfg, input_ids, seq_lens, cache_k, cache_v,
                       block_tables, *, stacked_names=None,
                       mlp_fn=_default_mlp_fn, window=None, lora_idx=None):
    """Paged counterpart of _decode_impl (same unrolled layer loop — see
    that docstring for why decode never scans the cache). Each layer's
    one-token KV lands at page block_tables[b, pos//PS], offset pos%PS;
    freed/parked rows clamp into their own last cell or the trash page
    (their block-table rows are zeroed on free), so garbage writes can
    never land in a page another row owns."""
    from llmlb_tpu.ops.attention import paged_attention_decode

    b = input_ids.shape[0]
    ps = kv_pool_values(cache_k).shape[2]
    capacity = block_tables.shape[1] * ps
    inv_freq = rope_frequencies(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)
    write_pos = jnp.minimum(seq_lens, capacity - 1)
    positions = write_pos[:, None]  # [B, 1]
    batch_idx = jnp.arange(b)
    page = block_tables[batch_idx, write_pos // ps]  # [B]
    off = write_pos % ps

    x = params["embed"][input_ids][:, None, :]  # [B, 1, E]
    names = _with_scales(params, stacked_names or _layer_stacked_names(cfg))

    for layer_idx in range(cfg.num_layers):
        lp = {n: params[n][layer_idx] for n in names}

        def attn_fn(q, k, v, layer_idx=layer_idx):
            nonlocal cache_k, cache_v  # write precedes attention over the pool
            cache_k = _write_pool_layer(cache_k, layer_idx, page, off,
                                        k[:, 0])
            cache_v = _write_pool_layer(cache_v, layer_idx, page, off,
                                        v[:, 0])
            return paged_attention_decode(
                q, _pool_layer(cache_k, layer_idx),
                _pool_layer(cache_v, layer_idx), block_tables,
                write_pos + 1, window=window,
            )

        x, _, _ = _attn_block(cfg, lp, x, positions, inv_freq, attn_fn,
                              lora_idx)
        h = rms_norm(x, lp["ln_mlp"], cfg.rms_eps)
        x = x + mlp_fn(lp, h, None, lora_idx)

    logits = _unembed(cfg, params, x[:, 0])
    return logits, cache_k, cache_v


@partial(jax.jit, static_argnames=("cfg", "mesh", "window"),
         donate_argnames=("cache_k", "cache_v"))
def decode_step_paged(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jnp.ndarray,  # [B] int32 — previous sampled token per row
    seq_lens: jnp.ndarray,  # [B] int32 — tokens already in the row's pages
    cache_k: jnp.ndarray,  # [L, P, PS, K, D]
    cache_v: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, PPN] int32
    mesh: Mesh | None = None,  # unused; shared family signature
    window: int | None = None,  # static context-window bucket (≥ max seq+1)
    lora_idx: jnp.ndarray | None = None,  # [B] int32 adapter pool rows
):
    """One paged decode step across all rows. Returns (logits [B, V] fp32,
    caches). Same contract as decode_step with the dense slot cache swapped
    for the page pool + block tables."""
    return _decode_paged_impl(params, cfg, input_ids, seq_lens, cache_k,
                              cache_v, block_tables, window=window,
                              lora_idx=lora_idx)


@partial(jax.jit, static_argnames=("cfg",))
def encode(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jnp.ndarray,  # [B, T] int32, right-padded
    prompt_lens: jnp.ndarray,  # [B] int32
) -> jnp.ndarray:
    """Text-embedding forward: full transformer pass (no KV writes), masked
    mean-pool over valid tokens, L2-normalize. Returns [B, E] fp32.

    Serves /v1/embeddings on the tpu:// engine — the reference only proxies
    embeddings to external runtimes (api/openai.rs /v1/embeddings handler);
    here the same decoder weights double as the embedding model, the common
    practice for serving stacks without a dedicated embedder.
    """
    b, t = input_ids.shape
    inv_freq = rope_frequencies(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None, :], (b, t))

    x = params["embed"][input_ids]
    stacked = {n: params[n]
               for n in _with_scales(params, _layer_stacked_names(cfg))}

    def layer(carry_x, lp):
        carry_x, _, _ = _attn_block(
            cfg, lp, carry_x, positions, inv_freq,
            lambda q, k, v: gqa_attention_prefill(q, k, v, prompt_lens),
        )
        h = rms_norm(carry_x, lp["ln_mlp"], cfg.rms_eps)
        carry_x = carry_x + _mlp(lp, h)
        return carry_x, None

    x, _ = lax.scan(layer, x, stacked)
    x = rms_norm(x, params["ln_final"], cfg.rms_eps).astype(jnp.float32)

    valid = (jnp.arange(t, dtype=jnp.int32)[None, :] < prompt_lens[:, None])
    pooled = (x * valid[..., None]).sum(1) / jnp.maximum(
        prompt_lens[:, None].astype(jnp.float32), 1.0
    )
    return pooled / jnp.maximum(
        jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9
    )


def make_context_parallel_prefill(cfg: LlamaConfig, mesh: Mesh):
    """Long-context prefill with the sequence axis sharded over the mesh `sp`
    axis (ring attention — ops/ring_attention.py).

    Per-token ops (embed, norms, QKV/MLP matmuls, rope) shard trivially over
    the token axis under GSPMD; attention is the only op coupling tokens, and
    it runs as a shard_map ring so the full T×T score matrix never exists on
    one chip. Composes with tp over heads when tp divides num_kv_heads (the
    GQA group structure must split along kv-head boundaries); otherwise head
    compute replicates inside the ring — still correct, just not tp-scaled.

    Returns a jitted `fn(params, input_ids [B,T], prompt_lens [B]) ->
    (last_logits [B,V] fp32, k_all [L,B,T,K,D], v_all)`. The caller scatters
    k/v into its live slot cache (engine insert path) or keeps them
    seq-sharded for context-parallel decode. New TPU-first design — the
    reference has no long-context subsystem (SURVEY.md §5).
    """
    from llmlb_tpu.ops.ring_attention import ring_prefill_attention

    shard_rules_for(cfg, mesh.shape["tp"])  # tp-divisibility validation
    kv_shardable = cfg.num_kv_heads % mesh.shape["tp"] == 0
    head_axis = "tp" if kv_shardable else None
    seq_spec = NamedSharding(mesh, P("dp", "sp", None))

    @jax.jit
    def fn(params: Params, input_ids: jnp.ndarray, prompt_lens: jnp.ndarray):
        b, t = input_ids.shape
        inv_freq = rope_frequencies(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)
        positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None, :], (b, t))

        x = params["embed"][input_ids]  # [B, T, E]
        x = lax.with_sharding_constraint(x, seq_spec)
        stacked = {n: params[n]
                   for n in _with_scales(params, _layer_stacked_names(cfg))}

        def layer(carry_x, lp):
            carry_x, k, v = _attn_block(
                cfg, lp, carry_x, positions, inv_freq,
                lambda q, k, v: ring_prefill_attention(
                    q, k, v, prompt_lens, mesh,
                    head_axis=head_axis, kv_head_axis=head_axis,
                ),
            )
            carry_x = lax.with_sharding_constraint(carry_x, seq_spec)
            h = rms_norm(carry_x, lp["ln_mlp"], cfg.rms_eps)
            carry_x = carry_x + _mlp(lp, h)
            carry_x = lax.with_sharding_constraint(carry_x, seq_spec)
            return carry_x, (k, v)

        x, (k_all, v_all) = lax.scan(layer, x, stacked)

        last = jnp.maximum(prompt_lens - 1, 0)
        x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
        logits = _unembed(cfg, params, x_last)
        return logits, k_all.astype(cfg.dtype), v_all.astype(cfg.dtype)

    return fn


@partial(jax.jit, static_argnames=("cfg", "mesh", "window"),
         donate_argnames=("cache_k", "cache_v"))
def decode_step(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jnp.ndarray,  # [B] int32 — previous sampled token per slot
    seq_lens: jnp.ndarray,  # [B] int32 — tokens already in cache (new token's position)
    cache_k: jnp.ndarray,  # [L, B, S, K, D]
    cache_v: jnp.ndarray,
    mesh: Mesh | None = None,  # unused; shared family signature
    window: int | None = None,  # static context-window bucket (≥ max seq+1)
    lora_idx: jnp.ndarray | None = None,  # [B] int32 adapter pool rows
):
    """One decode step across all slots. Returns (logits [B, V] fp32, caches)."""
    return _decode_impl(params, cfg, input_ids, seq_lens, cache_k, cache_v,
                        window=window, lora_idx=lora_idx)
