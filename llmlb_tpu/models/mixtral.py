"""Mixtral-family sparse-MoE decoder (Mixtral-8x7B/8x22B, Qwen-MoE-class).

Same serving-shaped skeleton as models/llama.py (stacked layers + lax.scan,
static-shape prefill/decode over slot KV caches, GQA attention ops) with the
dense SwiGLU MLP swapped for top-k routed experts (ops/moe.py). Expert weights
carry an `experts` logical axis mapped to the mesh `ep` axis, so a
Mixtral-8x7B spans a multi-chip mesh as dp × ep × tp with GSPMD inserting the
dispatch/combine all-to-alls (BASELINE.json config #5 class).

The reference gateway does no inference and has no MoE (SURVEY.md §2.4); this
model family is new TPU-native design for the in-tree tpu:// engine.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from llmlb_tpu.models.llama import (
    LlamaConfig,
    _decode_impl,
    _decode_paged_impl,
    _prefill_extend_impl,
    _prefill_extend_paged_impl,
    _prefill_impl,
    _write_kv_fresh,
    kv_pool_values,
    make_write_kv_pages,
    make_write_kv_slots,
)
from llmlb_tpu.ops.moe import default_capacity, moe_dense_exact, moe_dispatch_combine
from llmlb_tpu.parallel.sharding import logical_to_sharding

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25

    @classmethod
    def from_hf_config(cls, hf: dict, dtype=jnp.bfloat16) -> "MixtralConfig":
        base = LlamaConfig.from_hf_config(hf, dtype)
        fields = {f.name: getattr(base, f.name) for f in dataclasses.fields(base)}
        return cls(
            **fields,
            num_experts=hf.get("num_local_experts", hf.get("num_experts", 8)),
            experts_per_token=hf.get("num_experts_per_tok", 2),
        )


def init_params(cfg: MixtralConfig, key: jax.Array) -> Params:
    """Random init for tests/benches; serving loads HF checkpoints."""
    d = cfg.head_dim_
    h, k_, e = cfg.num_heads, cfg.num_kv_heads, cfg.hidden_size
    f, l_, x_ = cfg.intermediate_size, cfg.num_layers, cfg.num_experts
    keys = iter(jax.random.split(key, 16))

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * fan_in**-0.5).astype(
            cfg.dtype
        )

    params: Params = {
        "embed": w(next(keys), (cfg.vocab_size, e), e),
        "wq": w(next(keys), (l_, e, h * d), e),
        "wk": w(next(keys), (l_, e, k_ * d), e),
        "wv": w(next(keys), (l_, e, k_ * d), e),
        "wo": w(next(keys), (l_, h * d, e), h * d),
        "router": w(next(keys), (l_, e, x_), e),
        "we_gate": w(next(keys), (l_, x_, e, f), e),
        "we_up": w(next(keys), (l_, x_, e, f), e),
        "we_down": w(next(keys), (l_, x_, f, e), f),
        "ln_attn": jnp.ones((l_, e), cfg.dtype),
        "ln_mlp": jnp.ones((l_, e), cfg.dtype),
        "ln_final": jnp.ones((e,), cfg.dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w(next(keys), (e, cfg.vocab_size), e)
    return params


def param_logical_axes(cfg: MixtralConfig) -> dict[str, tuple]:
    axes = {
        "embed": ("vocab", "embed"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "router": ("layers", "embed", None),  # router replicated: tiny
        "we_gate": ("layers", "experts", "embed", "ffn"),
        "we_up": ("layers", "experts", "embed", "ffn"),
        "we_down": ("layers", "experts", "ffn", "embed"),
        "ln_attn": ("layers", "embed"),
        "ln_mlp": ("layers", "embed"),
        "ln_final": ("embed",),
    }
    if not cfg.tie_word_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    # Int8 per-output-channel scales (llmlb_tpu/quant): the weight's axes
    # with the input (contraction) axis dropped. Extra entries for absent
    # leaves are never consulted.
    for name in ("wq", "wk", "wv", "wo"):
        axes[name + "_scale"] = (axes[name][0], axes[name][2])
    for name in ("we_gate", "we_up", "we_down"):
        w_axes = axes[name]
        axes[name + "_scale"] = (w_axes[0], w_axes[1], w_axes[3])
    # LoRA adapter pools (llmlb_tpu/lora): attention projections only — MoE
    # engines serve attention-target adapters; expert-FFN deltas are out of
    # scope (the routed dispatch would need per-expert per-adapter factors).
    for name in ("wq", "wk", "wv", "wo"):
        w_axes = axes[name]
        axes[name + "_lora_a"] = (w_axes[0], None, w_axes[1], None)
        axes[name + "_lora_b"] = (w_axes[0], None, None, w_axes[2])
    return axes


# Same rules as the dense family (ShardingRules already maps experts -> "ep").
from llmlb_tpu.models.llama import shard_rules_for  # noqa: E402,F401


def param_shardings(cfg: MixtralConfig, mesh: Mesh, rules=None):
    rules = rules or shard_rules_for(cfg, mesh.shape["tp"])
    return {
        name: logical_to_sharding(mesh, rules, *axes)
        for name, axes in param_logical_axes(cfg).items()
    }


# KV cache layouts (dense slots + paged pool) identical to llama's — reuse.
from llmlb_tpu.models.llama import (  # noqa: E402,F401
    init_kv_cache,
    init_kv_pages,
    kv_cache_shardings,
    kv_pages_shardings,
)


_STACKED = ["wq", "wk", "wv", "wo", "router", "we_gate", "we_up", "we_down",
            "ln_attn", "ln_mlp"]


def _moe_mlp(cfg: MixtralConfig, lp: Params, x: jnp.ndarray, mesh: Mesh | None,
             *, exact: bool, token_valid: jnp.ndarray | None = None):
    """x: [B, T, E] -> [B, T, E] through routed experts.

    Two regimes, chosen statically by the caller:
    - `exact=True` (decode, small prefills): exact dense-combine MoE — every
      expert runs on every token. Decode is HBM-bound on expert weights either
      way, and exactness keeps decode logits independent of which other
      requests share the batch (no capacity drops, no cross-request
      nondeterminism).
    - `exact=False` (large prefills): GShard capacity dispatch — routed FLOPs
      with capacity_factor headroom; over-capacity tokens are dropped
      (standard MoE serving trade-off, tunable via cfg.capacity_factor).
      `token_valid` keeps padding out of the capacity contest.
    """
    b, t, m = x.shape
    s = b * t
    flat = x.reshape(s, m)
    logits = flat @ lp["router"]
    # int8 expert weights carry per-output-channel scales (llmlb_tpu/quant);
    # absent on bf16 pytrees, in which case the original einsums run.
    scales = {
        f"w_{k}_scale": lp.get(f"we_{k}_scale")
        for k in ("gate", "up", "down")
    }
    if exact:
        out = moe_dense_exact(
            flat, logits, lp["we_gate"], lp["we_up"], lp["we_down"],
            num_selected=cfg.experts_per_token, mesh=mesh, **scales,
        )
    else:
        cap = default_capacity(
            s, cfg.num_experts, cfg.experts_per_token, cfg.capacity_factor
        )
        out = moe_dispatch_combine(
            flat, logits, lp["we_gate"], lp["we_up"], lp["we_down"],
            num_selected=cfg.experts_per_token, capacity=cap, mesh=mesh,
            token_valid=None if token_valid is None else token_valid.reshape(s),
            **scales,
        )
    return out.reshape(b, t, m)


def _moe_mlp_fn(cfg: MixtralConfig, mesh: Mesh | None, exact: bool):
    """Adapter matching llama's `mlp_fn(lp, h, token_valid, lora_idx)`
    contract. `lora_idx` is accepted and ignored: MoE engines serve
    attention-target adapters only (the expert FFNs carry no LoRA pools,
    so there is nothing for the index to select)."""

    def fn(lp, h, token_valid, lora_idx=None):
        return _moe_mlp(
            cfg, lp, h, mesh, exact=exact,
            token_valid=None if exact else token_valid,
        )

    return fn


@partial(jax.jit, static_argnames=("cfg", "mesh"),
         donate_argnames=("cache_k", "cache_v"))
def prefill(params, cfg: MixtralConfig, input_ids, prompt_lens, cache_k, cache_v,
            mesh: Mesh | None = None, lora_idx=None):
    """Prefill B prompts into fresh KV slots. Same contract as llama.prefill."""
    b, t = input_ids.shape
    return _prefill_impl(
        params, cfg, input_ids, prompt_lens, cache_k, cache_v, _write_kv_fresh,
        stacked_names=_STACKED,
        mlp_fn=_moe_mlp_fn(cfg, mesh, exact=b * t <= 4 * cfg.num_experts),
        lora_idx=lora_idx,
    )


@partial(jax.jit, static_argnames=("cfg", "mesh"),
         donate_argnames=("cache_k", "cache_v"))
def prefill_into_slots(params, cfg: MixtralConfig, input_ids, prompt_lens,
                       slot_ids, cache_k, cache_v, mesh: Mesh | None = None,
                       lora_idx=None):
    """Continuous-batching insert path. Same contract as llama.prefill_into_slots."""
    b, t = input_ids.shape
    return _prefill_impl(
        params, cfg, input_ids, prompt_lens, cache_k, cache_v,
        make_write_kv_slots(slot_ids),
        stacked_names=_STACKED,
        mlp_fn=_moe_mlp_fn(cfg, mesh, exact=b * t <= 4 * cfg.num_experts),
        lora_idx=lora_idx,
    )


@partial(jax.jit, static_argnames=("cfg", "mesh"),
         donate_argnames=("cache_k", "cache_v"))
def prefill_extend_slots(params, cfg: MixtralConfig, input_ids, chunk_lens,
                         start_pos, slot_ids, cache_k, cache_v,
                         mesh: Mesh | None = None, lora_idx=None):
    """Chunked-prefill append path. Same contract as llama.prefill_extend_slots."""
    b, t = input_ids.shape
    return _prefill_extend_impl(
        params, cfg, input_ids, chunk_lens, start_pos, slot_ids,
        cache_k, cache_v,
        stacked_names=_STACKED,
        mlp_fn=_moe_mlp_fn(cfg, mesh, exact=b * t <= 4 * cfg.num_experts),
        lora_idx=lora_idx,
    )


@partial(jax.jit, static_argnames=("cfg", "mesh", "window"),
         donate_argnames=("cache_k", "cache_v"))
def decode_step(params, cfg: MixtralConfig, input_ids, seq_lens, cache_k, cache_v,
                mesh: Mesh | None = None, window: int | None = None,
                lora_idx=None):
    """One decode step across all slots. Same contract as llama.decode_step.

    Decode is ALWAYS exact MoE: capacity drops here would make a request's
    tokens depend on which other slots share the batch."""
    return _decode_impl(
        params, cfg, input_ids, seq_lens, cache_k, cache_v,
        stacked_names=_STACKED, mlp_fn=_moe_mlp_fn(cfg, mesh, exact=True),
        window=window, lora_idx=lora_idx,
    )


@partial(jax.jit, static_argnames=("cfg", "mesh"),
         donate_argnames=("cache_k", "cache_v"))
def prefill_into_pages(params, cfg: MixtralConfig, input_ids, prompt_lens,
                       block_tables, cache_k, cache_v,
                       mesh: Mesh | None = None, lora_idx=None):
    """Paged insert path. Same contract as llama.prefill_into_pages —
    including its HANDOFF CONTRACT (docs/disaggregation.md): final-row
    logits aligned to batch rows and position-exact KV, so split-mode
    staging and cross-process replay hold for MoE engines too (the router
    is position-independent; expert choice rides the token, not the
    slot, so a handed-off stream routes identically on the adopter)."""
    b, t = input_ids.shape
    return _prefill_impl(
        params, cfg, input_ids, prompt_lens, cache_k, cache_v,
        make_write_kv_pages(block_tables, kv_pool_values(cache_k).shape[2]),
        stacked_names=_STACKED,
        mlp_fn=_moe_mlp_fn(cfg, mesh, exact=b * t <= 4 * cfg.num_experts),
        lora_idx=lora_idx,
    )


@partial(jax.jit, static_argnames=("cfg", "mesh"),
         donate_argnames=("cache_k", "cache_v"))
def prefill_extend_pages(params, cfg: MixtralConfig, input_ids, chunk_lens,
                         start_pos, block_tables, cache_k, cache_v,
                         mesh: Mesh | None = None, lora_idx=None):
    """Paged chunked-prefill append. Same contract as llama.prefill_extend_pages."""
    b, t = input_ids.shape
    return _prefill_extend_paged_impl(
        params, cfg, input_ids, chunk_lens, start_pos, block_tables,
        cache_k, cache_v,
        stacked_names=_STACKED,
        mlp_fn=_moe_mlp_fn(cfg, mesh, exact=b * t <= 4 * cfg.num_experts),
        lora_idx=lora_idx,
    )


@partial(jax.jit, static_argnames=("cfg", "mesh", "window"),
         donate_argnames=("cache_k", "cache_v"))
def verify_step(params, cfg: MixtralConfig, input_ids, chunk_lens, start_pos,
                slot_ids, cache_k, cache_v, mesh: Mesh | None = None,
                window: int | None = None, lora_idx=None):
    """Speculative verification over the dense slot cache. Same contract as
    llama.verify_step; exact MoE like decode — capacity drops would make a
    draft's acceptance depend on which other slots share the batch."""
    return _prefill_extend_impl(
        params, cfg, input_ids, chunk_lens, start_pos, slot_ids,
        cache_k, cache_v, stacked_names=_STACKED,
        mlp_fn=_moe_mlp_fn(cfg, mesh, exact=True),
        all_logits=True, window=window, lora_idx=lora_idx,
    )


@partial(jax.jit, static_argnames=("cfg", "mesh", "window"),
         donate_argnames=("cache_k", "cache_v"))
def verify_step_paged(params, cfg: MixtralConfig, input_ids, chunk_lens,
                      start_pos, block_tables, cache_k, cache_v,
                      mesh: Mesh | None = None, window: int | None = None,
                      lora_idx=None):
    """Paged speculative verification. Same contract as
    llama.verify_step_paged; exact MoE for the same batch-independence
    reason as decode_step."""
    return _prefill_extend_paged_impl(
        params, cfg, input_ids, chunk_lens, start_pos, block_tables,
        cache_k, cache_v, stacked_names=_STACKED,
        mlp_fn=_moe_mlp_fn(cfg, mesh, exact=True),
        all_logits=True, window=window, lora_idx=lora_idx,
    )


@partial(jax.jit, static_argnames=("cfg", "mesh", "window"),
         donate_argnames=("cache_k", "cache_v"))
def decode_step_paged(params, cfg: MixtralConfig, input_ids, seq_lens,
                      cache_k, cache_v, block_tables,
                      mesh: Mesh | None = None, window: int | None = None,
                      lora_idx=None):
    """One paged decode step. Same contract as llama.decode_step_paged;
    exact MoE for the same batch-independence reason as decode_step."""
    return _decode_paged_impl(
        params, cfg, input_ids, seq_lens, cache_k, cache_v, block_tables,
        stacked_names=_STACKED, mlp_fn=_moe_mlp_fn(cfg, mesh, exact=True),
        window=window, lora_idx=lora_idx,
    )
