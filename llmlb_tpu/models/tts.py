"""Text-to-speech: non-autoregressive byte→mel transformer + Griffin-Lim.

Backs /v1/audio/speech on the tpu:// engine. The reference ships only a
PyTorch TTS proof-of-concept run out-of-process (poc/vibevoice-pytorch/run.py,
SURVEY.md §2.3) and proxies speech requests to whatever endpoint advertises
the capability (api/audio.rs:377); this is the in-tree TPU-native equivalent:

- FastSpeech-style parallel synthesis: byte embedding → pre-LN transformer
  encoder → fixed-ratio length regulator → decoder stack → linear mel head.
  Everything static-shape and jitted; one forward per utterance (no
  autoregressive loop — synthesis latency is one MXU pass).
- Griffin-Lim vocoder in JAX: mel → linear magnitude via the mel filterbank
  pseudo-inverse, then `n_iter` rounds of ISTFT/STFT phase refinement under
  `lax.scan`. No external audio dependencies.
- Voice conditioning: a learned per-voice embedding table added to the
  encoder input ("alloy", "echo", ... map to rows; unknown voices fall back
  to row 0).

Weights are framework-native (our pytree in a safetensors file) — there is no
canonical public HF arch for this compact design; save/load round-trips via
save_checkpoint/load_checkpoint below.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from llmlb_tpu.models.whisper import (
    HOP_LENGTH,
    N_FFT,
    SAMPLE_RATE,
    _layer_norm,
    _mha,
    _sinusoids,
    mel_filterbank,
)

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TtsConfig:
    vocab_size: int = 256  # bytes
    d_model: int = 256
    encoder_layers: int = 4
    decoder_layers: int = 4
    num_heads: int = 4
    n_mels: int = 80
    upsample: int = 8  # mel frames per input byte (fixed-ratio length regulator)
    max_text_len: int = 512
    num_voices: int = 8
    dtype: Any = jnp.float32


VOICES = ("alloy", "echo", "fable", "onyx", "nova", "shimmer")


def voice_id(name: str) -> int:
    try:
        return 1 + VOICES.index(name.lower())
    except ValueError:
        return 0


def init_params(cfg: TtsConfig, key: jax.Array) -> Params:
    d = cfg.d_model
    ks = iter(jax.random.split(key, 24))

    def w(shape, fan_in):
        return (jax.random.normal(next(ks), shape, jnp.float32)
                * fan_in**-0.5).astype(cfg.dtype)

    def attn_block(layers):
        return {
            "wq": w((layers, d, d), d), "bq": jnp.zeros((layers, d), cfg.dtype),
            "wk": w((layers, d, d), d),
            "wv": w((layers, d, d), d), "bv": jnp.zeros((layers, d), cfg.dtype),
            "wo": w((layers, d, d), d), "bo": jnp.zeros((layers, d), cfg.dtype),
        }

    def mlp_block(layers):
        return {
            "w1": w((layers, d, 4 * d), d),
            "b1": jnp.zeros((layers, 4 * d), cfg.dtype),
            "w2": w((layers, 4 * d, d), 4 * d),
            "b2": jnp.zeros((layers, d), cfg.dtype),
        }

    def ln(layers):
        return (jnp.ones((layers, d), cfg.dtype), jnp.zeros((layers, d), cfg.dtype))

    el, dl = cfg.encoder_layers, cfg.decoder_layers
    max_frames = cfg.max_text_len * cfg.upsample
    return {
        "byte_embed": w((cfg.vocab_size, d), d),
        "voice_embed": w((cfg.num_voices, d), d),
        "enc_pos": jnp.asarray(_sinusoids(cfg.max_text_len, d), cfg.dtype),
        "dec_pos": jnp.asarray(_sinusoids(max_frames, d), cfg.dtype),
        "enc_attn": attn_block(el), "enc_mlp": mlp_block(el),
        "enc_ln1": ln(el), "enc_ln2": ln(el),
        "dec_attn": attn_block(dl), "dec_mlp": mlp_block(dl),
        "dec_ln1": ln(dl), "dec_ln2": ln(dl),
        "lnf": (jnp.ones((d,), cfg.dtype), jnp.zeros((d,), cfg.dtype)),
        "mel_head_w": w((d, cfg.n_mels), d),
        "mel_head_b": jnp.zeros((cfg.n_mels,), cfg.dtype),
    }


def _transformer(cfg: TtsConfig, x, attn, mlp, ln1, ln2, n_layers, mask=None):
    def layer(carry, i):
        at = jax.tree.map(lambda a: a[i], attn)
        ml = jax.tree.map(lambda a: a[i], mlp)
        l1 = jax.tree.map(lambda a: a[i], ln1)
        l2 = jax.tree.map(lambda a: a[i], ln2)
        h = _layer_norm(carry, l1)
        carry = carry + _mha(at, h, h, cfg.num_heads, mask=mask)
        h = _layer_norm(carry, l2)
        carry = carry + (jax.nn.gelu(h @ ml["w1"] + ml["b1"], approximate=False)
                         @ ml["w2"] + ml["b2"])
        return carry, None

    x, _ = lax.scan(layer, x, jnp.arange(n_layers))
    return x


@partial(jax.jit, static_argnames=("cfg",))
def synthesize_mel(params: Params, cfg: TtsConfig,
                   byte_ids: jnp.ndarray,  # [B, T] int32, right-padded
                   text_lens: jnp.ndarray,  # [B] int32
                   voice_ids: jnp.ndarray,  # [B] int32
                   ) -> jnp.ndarray:
    """[B, T*upsample, n_mels] mel frames (frames past text_lens*upsample are
    synthesized from padding and should be trimmed by the caller)."""
    b, t = byte_ids.shape
    x = params["byte_embed"][byte_ids] + params["enc_pos"][None, :t]
    x = x + params["voice_embed"][voice_ids][:, None, :]
    # mask attention to valid text positions
    valid = jnp.arange(t)[None, :] < text_lens[:, None]  # [B, T]
    mask = valid[:, None, None, :]  # [B, 1, 1, T]
    x = _transformer(cfg, x, params["enc_attn"], params["enc_mlp"],
                     params["enc_ln1"], params["enc_ln2"],
                     cfg.encoder_layers, mask=mask)
    # fixed-ratio length regulator: repeat each byte state `upsample` times
    frames = jnp.repeat(x, cfg.upsample, axis=1)
    frames = frames + params["dec_pos"][None, : frames.shape[1]]
    fvalid = jnp.repeat(valid, cfg.upsample, axis=1)
    fmask = fvalid[:, None, None, :]
    frames = _transformer(cfg, frames, params["dec_attn"], params["dec_mlp"],
                          params["dec_ln1"], params["dec_ln2"],
                          cfg.decoder_layers, mask=fmask)
    frames = _layer_norm(frames, params["lnf"])
    return frames @ params["mel_head_w"] + params["mel_head_b"]


# ---------------------------------------------------------------------------
# Griffin-Lim vocoder
# ---------------------------------------------------------------------------

def _stft(audio: jnp.ndarray) -> jnp.ndarray:
    window = jnp.asarray(np.hanning(N_FFT + 1)[:-1].astype(np.float32))
    n_frames = 1 + (audio.shape[0] - N_FFT) // HOP_LENGTH
    idx = (jnp.arange(n_frames)[:, None] * HOP_LENGTH
           + jnp.arange(N_FFT)[None, :])
    return jnp.fft.rfft(audio[idx] * window[None, :], axis=-1)


def _istft(spec: jnp.ndarray, n_samples: int) -> jnp.ndarray:
    window = jnp.asarray(np.hanning(N_FFT + 1)[:-1].astype(np.float32))
    frames = jnp.fft.irfft(spec, n=N_FFT, axis=-1) * window[None, :]
    n_frames = spec.shape[0]
    audio = jnp.zeros((n_samples,), jnp.float32)
    norm = jnp.zeros((n_samples,), jnp.float32)
    starts = jnp.arange(n_frames) * HOP_LENGTH
    idx = starts[:, None] + jnp.arange(N_FFT)[None, :]
    audio = audio.at[idx.reshape(-1)].add(frames.reshape(-1))
    norm = norm.at[idx.reshape(-1)].add((window**2)[None, :].repeat(
        n_frames, 0).reshape(-1))
    return audio / jnp.maximum(norm, 1e-8)


@partial(jax.jit, static_argnames=("n_iter",))
def griffin_lim(mel: jnp.ndarray, n_iter: int = 24,
                key: jax.Array | None = None) -> jnp.ndarray:
    """[frames, n_mels] log-mel-ish magnitudes -> [samples] float32 audio."""
    # mel -> linear magnitude via filterbank pseudo-inverse ([bins, n_mels])
    pinv = jnp.asarray(np.linalg.pinv(mel_filterbank(mel.shape[1])))
    mag = jnp.maximum(jnp.exp(mel) @ pinv.T, 0.0)  # [frames, bins]
    n_samples = (mag.shape[0] - 1) * HOP_LENGTH + N_FFT
    if key is None:
        key = jax.random.PRNGKey(0)
    phase = jax.random.uniform(key, mag.shape, jnp.float32, 0, 2 * np.pi)
    spec = mag * jnp.exp(1j * phase)

    def step(spec, _):
        audio = _istft(spec, n_samples)
        re = _stft(audio)
        re = re[: mag.shape[0]]
        spec = mag * jnp.exp(1j * jnp.angle(re))
        return spec, None

    spec, _ = lax.scan(step, spec, None, length=n_iter)
    audio = _istft(spec, n_samples)
    peak = jnp.max(jnp.abs(audio))
    return audio / jnp.maximum(peak, 1e-6) * 0.95


# ---------------------------------------------------------------------------
# Checkpoint round-trip (framework-native safetensors of the flat pytree)
# ---------------------------------------------------------------------------

def save_checkpoint(path: str, cfg: TtsConfig, params: Params) -> None:
    import json
    import os

    from safetensors.numpy import save_file

    flat = {}

    def add(prefix, leaf):
        if isinstance(leaf, dict):
            for k, v in leaf.items():
                add(f"{prefix}.{k}" if prefix else k, v)
        elif isinstance(leaf, tuple):
            for i, v in enumerate(leaf):
                add(f"{prefix}.{i}", v)
        else:
            flat[prefix] = np.asarray(leaf)

    add("", params)
    os.makedirs(path, exist_ok=True)
    save_file(flat, os.path.join(path, "model.safetensors"))
    meta = {k: v for k, v in dataclasses.asdict(cfg).items() if k != "dtype"}
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump({"model_type": "llmlb_tpu_tts", **meta}, f)


def load_checkpoint(path: str) -> tuple[TtsConfig, Params]:
    import json
    import os

    from safetensors.numpy import load_file

    with open(os.path.join(path, "config.json")) as f:
        meta = json.load(f)
    meta.pop("model_type", None)
    cfg = TtsConfig(**meta)
    flat = load_file(os.path.join(path, "model.safetensors"))
    params: Params = {}
    for key, value in flat.items():
        parts = key.split(".")
        node = params
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(value)

    def fix(node):
        if isinstance(node, dict):
            if set(node) == {"0", "1"}:
                return (fix(node["0"]), fix(node["1"]))
            return {k: fix(v) for k, v in node.items()}
        return node

    return cfg, fix(params)
