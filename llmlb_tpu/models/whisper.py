"""Whisper-family ASR (encoder-decoder transformer) — functional JAX.

Backs /v1/audio/transcriptions on the tpu:// engine. The reference only
*proxies* transcription requests to external runtimes (api/audio.rs:199-370
multipart re-proxy, capability selection :160-183); the model itself is new
TPU-native design:

- Log-mel frontend as jittable JAX ops (framed STFT via conv-style gather +
  rFFT, slaney mel filterbank precomputed in numpy) — the whole
  audio→text path stays on device.
- Encoder: two gelu convs (stride 1, 2) + fixed sinusoidal positions +
  pre-LN transformer stack, scanned over stacked layer params (compile once
  for any depth, same trick as models/llama.py).
- Decoder: learned positions, causal self-attention over a static-capacity
  KV cache, cross-attention against precomputed encoder K/V — serving-shaped
  `decode_step` with fully static shapes.
- Greedy transcription loop on host, one jitted step per token (token count
  per utterance is small; batching across requests happens at the service
  layer).

HF checkpoint layout (openai/whisper-*) maps via convert_hf_tensors below.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Params = dict[str, Any]

SAMPLE_RATE = 16000
N_FFT = 400
HOP_LENGTH = 160


@dataclasses.dataclass(frozen=True)
class WhisperConfig:
    vocab_size: int = 51865
    n_mels: int = 80
    d_model: int = 384  # whisper-tiny
    encoder_layers: int = 4
    decoder_layers: int = 4
    num_heads: int = 6
    n_audio_ctx: int = 1500  # 30 s of audio after conv stride 2
    n_text_ctx: int = 448
    # special tokens (multilingual vocab defaults)
    sot_token: int = 50258
    eot_token: int = 50257
    transcribe_token: int = 50359
    no_timestamps_token: int = 50363
    english_token: int = 50259
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @classmethod
    def from_hf_config(cls, hf: dict, dtype=jnp.float32) -> "WhisperConfig":
        vocab_size = hf["vocab_size"]
        # Derive special-token ids from the checkpoint config instead of
        # assuming the multilingual layout: .en checkpoints use
        # eot=50256, sot=50257 and have no language/transcribe tokens.
        eot = hf.get("eos_token_id", 50257)
        sot = hf.get("decoder_start_token_id", 50258)
        multilingual = vocab_size >= 51865
        if multilingual:
            # large-v3 (vocab 51866) adds <|yue|> at 50358, shifting the task
            # block up by one; derive the offset from the vocab size.
            shift = vocab_size - 51865
            transcribe = 50359 + shift
            no_timestamps = 50363 + shift
            english = 50259
            # Honour forced_decoder_ids when present ([(1, lang), (2, task)]).
            for pos, tok in (hf.get("forced_decoder_ids") or []):
                if pos == 1 and tok is not None:
                    english = tok
                elif pos == 2 and tok is not None:
                    transcribe = tok
        else:
            # English-only: no language/task tokens exist; mark them -1 so
            # greedy_transcribe_tokens skips them when building the prompt.
            transcribe = -1
            no_timestamps = 50362 if vocab_size > 50362 else -1
            english = -1
        return cls(
            vocab_size=vocab_size,
            n_mels=hf.get("num_mel_bins", 80),
            d_model=hf["d_model"],
            encoder_layers=hf["encoder_layers"],
            decoder_layers=hf["decoder_layers"],
            num_heads=hf["encoder_attention_heads"],
            n_audio_ctx=hf.get("max_source_positions", 1500),
            n_text_ctx=hf.get("max_target_positions", 448),
            sot_token=sot,
            eot_token=eot,
            transcribe_token=transcribe,
            no_timestamps_token=no_timestamps,
            english_token=english,
            dtype=dtype,
        )


# ---------------------------------------------------------------------------
# Log-mel frontend
# ---------------------------------------------------------------------------

def mel_filterbank(n_mels: int = 80, n_fft: int = N_FFT,
                   sample_rate: int = SAMPLE_RATE) -> np.ndarray:
    """Slaney-normalized triangular mel filters [n_mels, n_fft//2 + 1]
    (matches librosa.filters.mel defaults, which whisper's frontend uses)."""

    def hz_to_mel(f):
        f = np.asarray(f, np.float64)
        mel = 3.0 * f / 200.0
        log_region = f >= 1000.0
        mel = np.where(
            log_region,
            15.0 + np.log(np.maximum(f, 1e-9) / 1000.0) / (np.log(6.4) / 27.0),
            mel,
        )
        return mel

    def mel_to_hz(m):
        m = np.asarray(m, np.float64)
        f = 200.0 * m / 3.0
        log_region = m >= 15.0
        f = np.where(log_region, 1000.0 * np.exp((np.log(6.4) / 27.0) * (m - 15.0)), f)
        return f

    fft_freqs = np.linspace(0, sample_rate / 2, n_fft // 2 + 1)
    mel_pts = mel_to_hz(np.linspace(hz_to_mel(0.0), hz_to_mel(sample_rate / 2.0),
                                    n_mels + 2))
    fb = np.zeros((n_mels, n_fft // 2 + 1))
    for i in range(n_mels):
        lower, center, upper = mel_pts[i], mel_pts[i + 1], mel_pts[i + 2]
        up = (fft_freqs - lower) / max(center - lower, 1e-9)
        down = (upper - fft_freqs) / max(upper - center, 1e-9)
        fb[i] = np.maximum(0.0, np.minimum(up, down))
        # slaney: normalize each filter to unit area
        fb[i] *= 2.0 / (upper - lower)
    return fb.astype(np.float32)


def log_mel_spectrogram(audio: jnp.ndarray, n_mels: int = 80) -> jnp.ndarray:
    """[T_samples] float32 in [-1, 1] -> [n_frames, n_mels] log-mel, whisper
    conventions (reflect-pad, hann, log10, clamp to max-8, /4 + 1 scaling)."""
    window = jnp.asarray(np.hanning(N_FFT + 1)[:-1].astype(np.float32))
    pad = N_FFT // 2
    audio = jnp.pad(audio, (pad, pad), mode="reflect")
    n_frames = 1 + (audio.shape[0] - N_FFT) // HOP_LENGTH
    idx = (jnp.arange(n_frames)[:, None] * HOP_LENGTH
           + jnp.arange(N_FFT)[None, :])
    frames = audio[idx] * window[None, :]
    spec = jnp.fft.rfft(frames, axis=-1)
    power = jnp.abs(spec) ** 2  # [n_frames, n_fft//2+1]
    # whisper drops the last frame (it uses frames[:-1])
    power = power[:-1]
    fb = jnp.asarray(mel_filterbank(n_mels))
    mel = power @ fb.T
    log_spec = jnp.log10(jnp.maximum(mel, 1e-10))
    log_spec = jnp.maximum(log_spec, log_spec.max() - 8.0)
    return (log_spec + 4.0) / 4.0


# ---------------------------------------------------------------------------
# Params
# ---------------------------------------------------------------------------

def _sinusoids(length: int, channels: int) -> np.ndarray:
    """Whisper's fixed sinusoidal embedding (sin | cos concatenation)."""
    log_timescale = np.log(10000.0) / (channels // 2 - 1)
    inv = np.exp(-log_timescale * np.arange(channels // 2))
    scaled = np.arange(length)[:, None] * inv[None, :]
    return np.concatenate([np.sin(scaled), np.cos(scaled)], axis=1).astype(
        np.float32
    )


def init_params(cfg: WhisperConfig, key: jax.Array) -> Params:
    """Random init for tests; serving loads HF checkpoints."""
    d, h = cfg.d_model, cfg.num_heads
    ks = iter(jax.random.split(key, 32))

    def w(shape, fan_in):
        return (jax.random.normal(next(ks), shape, jnp.float32)
                * fan_in**-0.5).astype(cfg.dtype)

    def attn_block(layers, cross=False):
        blk = {
            "wq": w((layers, d, d), d), "bq": jnp.zeros((layers, d), cfg.dtype),
            "wk": w((layers, d, d), d),
            "wv": w((layers, d, d), d), "bv": jnp.zeros((layers, d), cfg.dtype),
            "wo": w((layers, d, d), d), "bo": jnp.zeros((layers, d), cfg.dtype),
        }
        return blk

    def mlp_block(layers):
        return {
            "w1": w((layers, d, 4 * d), d),
            "b1": jnp.zeros((layers, 4 * d), cfg.dtype),
            "w2": w((layers, 4 * d, d), 4 * d),
            "b2": jnp.zeros((layers, d), cfg.dtype),
        }

    def ln(layers=None, suffix=""):
        shape = (layers, d) if layers else (d,)
        return jnp.ones(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype)

    el, dl = cfg.encoder_layers, cfg.decoder_layers
    enc_ln1_g, enc_ln1_b = ln(el)
    enc_ln2_g, enc_ln2_b = ln(el)
    dec_ln1_g, dec_ln1_b = ln(dl)
    dec_lnx_g, dec_lnx_b = ln(dl)
    dec_ln2_g, dec_ln2_b = ln(dl)
    enc_lnf_g, enc_lnf_b = ln()
    dec_lnf_g, dec_lnf_b = ln()

    params: Params = {
        # encoder conv frontend: [width, in, out] layout for lax.conv
        "conv1_w": w((3, cfg.n_mels, d), 3 * cfg.n_mels),
        "conv1_b": jnp.zeros((d,), cfg.dtype),
        "conv2_w": w((3, d, d), 3 * d),
        "conv2_b": jnp.zeros((d,), cfg.dtype),
        "enc_pos": jnp.asarray(_sinusoids(cfg.n_audio_ctx, d), cfg.dtype),
        "enc_attn": attn_block(el),
        "enc_mlp": mlp_block(el),
        "enc_ln1": (enc_ln1_g, enc_ln1_b),
        "enc_ln2": (enc_ln2_g, enc_ln2_b),
        "enc_lnf": (enc_lnf_g, enc_lnf_b),
        # decoder
        "tok_embed": w((cfg.vocab_size, d), d),
        "dec_pos": w((cfg.n_text_ctx, d), d),
        "dec_attn": attn_block(dl),
        "dec_cross": attn_block(dl),
        "dec_mlp": mlp_block(dl),
        "dec_ln1": (dec_ln1_g, dec_ln1_b),
        "dec_lnx": (dec_lnx_g, dec_lnx_b),
        "dec_ln2": (dec_ln2_g, dec_ln2_b),
        "dec_lnf": (dec_lnf_g, dec_lnf_b),
    }
    return params


# ---------------------------------------------------------------------------
# Transformer pieces
# ---------------------------------------------------------------------------

def _layer_norm(x, gb, eps=1e-5):
    g, b = gb
    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * g + b


def _heads(x, n):  # [B, T, D] -> [B, T, H, Dh]
    b, t, d = x.shape
    return x.reshape(b, t, n, d // n)


def _mha(lp, x, kv, n_heads, mask=None):
    """Attention with whisper's conventions (k has no bias, q scaled)."""
    d = x.shape[-1]
    q = _heads(x @ lp["wq"] + lp["bq"], n_heads)
    k = _heads(kv @ lp["wk"], n_heads)
    v = _heads(kv @ lp["wv"] + lp["bv"], n_heads)
    scale = (d // n_heads) ** -0.25
    scores = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k * scale,
                        preferred_element_type=jnp.float32)
    if mask is not None:
        scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    return out.reshape(x.shape[0], x.shape[1], d) @ lp["wo"] + lp["bo"]


def _mlp(lp, x):
    return (jax.nn.gelu(x @ lp["w1"] + lp["b1"], approximate=False)
            @ lp["w2"] + lp["b2"])


def _stack_layer(tree, i):
    return jax.tree.map(lambda a: a[i], tree)


# ---------------------------------------------------------------------------
# Encoder
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("cfg",))
def encode_audio(params: Params, cfg: WhisperConfig,
                 mel: jnp.ndarray) -> jnp.ndarray:
    """[B, n_frames, n_mels] -> [B, n_audio_ctx', D] encoder states.
    n_frames must be even (conv2 stride 2)."""
    x = mel.astype(cfg.dtype)
    dn = ("NWC", "WIO", "NWC")
    x = jax.nn.gelu(
        lax.conv_general_dilated(x, params["conv1_w"], (1,), "SAME",
                                 dimension_numbers=dn) + params["conv1_b"],
        approximate=False,
    )
    x = jax.nn.gelu(
        lax.conv_general_dilated(x, params["conv2_w"], (2,), "SAME",
                                 dimension_numbers=dn) + params["conv2_b"],
        approximate=False,
    )
    t = x.shape[1]
    x = x + params["enc_pos"][None, :t]

    def layer(carry, i):
        attn = _stack_layer(params["enc_attn"], i)
        mlp = _stack_layer(params["enc_mlp"], i)
        ln1 = jax.tree.map(lambda a: a[i], params["enc_ln1"])
        ln2 = jax.tree.map(lambda a: a[i], params["enc_ln2"])
        h = _layer_norm(carry, ln1)
        carry = carry + _mha(attn, h, h, cfg.num_heads)
        h = _layer_norm(carry, ln2)
        carry = carry + _mlp(mlp, h)
        return carry, None

    x, _ = lax.scan(layer, x, jnp.arange(cfg.encoder_layers))
    return _layer_norm(x, params["enc_lnf"])


# ---------------------------------------------------------------------------
# Decoder
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("cfg",))
def decoder_logits(params: Params, cfg: WhisperConfig,
                   tokens: jnp.ndarray,  # [B, T]
                   enc_states: jnp.ndarray) -> jnp.ndarray:
    """Full-sequence (teacher-forced) decoder: [B, T, vocab] fp32 logits.
    Used for prompt processing and as the reference for the cached step."""
    b, t = tokens.shape
    x = params["tok_embed"][tokens] + params["dec_pos"][None, :t]
    causal = jnp.tril(jnp.ones((t, t), bool))[None, None]

    def layer(carry, i):
        attn = _stack_layer(params["dec_attn"], i)
        cross = _stack_layer(params["dec_cross"], i)
        mlp = _stack_layer(params["dec_mlp"], i)
        ln1 = jax.tree.map(lambda a: a[i], params["dec_ln1"])
        lnx = jax.tree.map(lambda a: a[i], params["dec_lnx"])
        ln2 = jax.tree.map(lambda a: a[i], params["dec_ln2"])
        h = _layer_norm(carry, ln1)
        carry = carry + _mha(attn, h, h, cfg.num_heads, mask=causal)
        h = _layer_norm(carry, lnx)
        carry = carry + _mha(cross, h, enc_states, cfg.num_heads)
        h = _layer_norm(carry, ln2)
        carry = carry + _mlp(mlp, h)
        return carry, None

    x, _ = lax.scan(layer, x, jnp.arange(cfg.decoder_layers))
    x = _layer_norm(x, params["dec_lnf"])
    return jnp.einsum("btd,vd->btv", x, params["tok_embed"],
                      preferred_element_type=jnp.float32)


def greedy_transcribe_tokens(params: Params, cfg: WhisperConfig,
                             mel: jnp.ndarray, max_tokens: int = 128,
                             language_token: int | None = None) -> list[int]:
    """Greedy decode one utterance. Host loop over the teacher-forced decoder
    (utterances are short; the jit cache sees pow2-bucketed lengths)."""
    enc = encode_audio(params, cfg, mel[None])
    # English-only checkpoints have no language/task tokens (marked -1 by
    # from_hf_config): prompt is just <|startoftranscript|>[<|notimestamps|>].
    lang = cfg.english_token if language_token is None else language_token
    tokens = [cfg.sot_token]
    for tok in (lang, cfg.transcribe_token, cfg.no_timestamps_token):
        if tok is not None and tok >= 0:
            tokens.append(tok)
    out: list[int] = []
    for _ in range(max_tokens):
        t = len(tokens)
        bucket = 8
        while bucket < t:
            bucket *= 2
        bucket = min(bucket, cfg.n_text_ctx)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :t] = tokens
        logits = decoder_logits(params, cfg, jnp.asarray(padded), enc)
        next_tok = int(np.asarray(logits[0, t - 1]).argmax())
        if next_tok == cfg.eot_token:
            break
        tokens.append(next_tok)
        out.append(next_tok)
        if len(tokens) >= cfg.n_text_ctx:
            break
    return out


# ---------------------------------------------------------------------------
# HF checkpoint mapping (openai/whisper-* via transformers WhisperForConditionalGeneration)
# ---------------------------------------------------------------------------

def convert_hf_tensors(cfg: WhisperConfig, get) -> Params:
    """Map transformers whisper tensor names onto our stacked pytree."""

    def stack(fmt, transpose=False):
        ws = []
        for i in range(len_range):
            w = get(fmt.format(i=i))
            ws.append(w.T if transpose else w)
        return np.stack(ws)

    def attn(prefix, layers):
        nonlocal len_range
        len_range = layers
        return {
            "wq": stack(prefix + ".q_proj.weight", True),
            "bq": stack(prefix + ".q_proj.bias"),
            "wk": stack(prefix + ".k_proj.weight", True),
            "wv": stack(prefix + ".v_proj.weight", True),
            "bv": stack(prefix + ".v_proj.bias"),
            "wo": stack(prefix + ".out_proj.weight", True),
            "bo": stack(prefix + ".out_proj.bias"),
        }

    def mlp(prefix, layers):
        nonlocal len_range
        len_range = layers
        return {
            "w1": stack(prefix + ".fc1.weight", True),
            "b1": stack(prefix + ".fc1.bias"),
            "w2": stack(prefix + ".fc2.weight", True),
            "b2": stack(prefix + ".fc2.bias"),
        }

    def ln_pair(prefix, layers=None):
        nonlocal len_range
        if layers:
            len_range = layers
            return (stack(prefix + ".weight"), stack(prefix + ".bias"))
        return (get(prefix + ".weight"), get(prefix + ".bias"))

    len_range = cfg.encoder_layers
    el, dl = cfg.encoder_layers, cfg.decoder_layers
    e = "model.encoder.layers.{i}"
    d = "model.decoder.layers.{i}"
    return {
        # HF conv weight is [out, in, width] -> ours [width, in, out]
        "conv1_w": np.transpose(get("model.encoder.conv1.weight"), (2, 1, 0)),
        "conv1_b": get("model.encoder.conv1.bias"),
        "conv2_w": np.transpose(get("model.encoder.conv2.weight"), (2, 1, 0)),
        "conv2_b": get("model.encoder.conv2.bias"),
        "enc_pos": get("model.encoder.embed_positions.weight"),
        "enc_attn": attn(e + ".self_attn", el),
        "enc_mlp": mlp(e, el),
        "enc_ln1": ln_pair(e + ".self_attn_layer_norm", el),
        "enc_ln2": ln_pair(e + ".final_layer_norm", el),
        "enc_lnf": ln_pair("model.encoder.layer_norm"),
        "tok_embed": get("model.decoder.embed_tokens.weight"),
        "dec_pos": get("model.decoder.embed_positions.weight"),
        "dec_attn": attn(d + ".self_attn", dl),
        "dec_cross": attn(d + ".encoder_attn", dl),
        "dec_mlp": mlp(d, dl),
        "dec_ln1": ln_pair(d + ".self_attn_layer_norm", dl),
        "dec_lnx": ln_pair(d + ".encoder_attn_layer_norm", dl),
        "dec_ln2": ln_pair(d + ".final_layer_norm", dl),
        "dec_lnf": ln_pair("model.decoder.layer_norm"),
    }
