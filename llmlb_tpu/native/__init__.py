"""ctypes bindings to the C++ native components (native/libllmlb_native.so).

The library is built with `make -C native` (done automatically on first use
when a toolchain is present). Every consumer has a pure-Python fallback, so
the framework runs without the native build — but weight loading and SSE
accounting use the native paths when available.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

log = logging.getLogger("llmlb_tpu.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libllmlb_native.so")

_lib: ctypes.CDLL | None = None
_lib_lock = threading.Lock()
_build_attempted = False


def _configure(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.st_open.restype = c.c_void_p
    lib.st_open.argtypes = [c.c_char_p]
    lib.st_error.restype = c.c_char_p
    lib.st_error.argtypes = [c.c_void_p]
    lib.st_num_tensors.restype = c.c_int64
    lib.st_num_tensors.argtypes = [c.c_void_p]
    lib.st_tensor_name.restype = c.c_char_p
    lib.st_tensor_name.argtypes = [c.c_void_p, c.c_int64]
    lib.st_tensor_dtype.restype = c.c_char_p
    lib.st_tensor_dtype.argtypes = [c.c_void_p, c.c_int64]
    lib.st_tensor_ndim.restype = c.c_int64
    lib.st_tensor_ndim.argtypes = [c.c_void_p, c.c_int64]
    lib.st_tensor_shape.restype = None
    lib.st_tensor_shape.argtypes = [c.c_void_p, c.c_int64, c.POINTER(c.c_int64)]
    lib.st_tensor_data.restype = c.c_void_p
    lib.st_tensor_data.argtypes = [c.c_void_p, c.c_int64, c.POINTER(c.c_int64)]
    lib.st_close.restype = None
    lib.st_close.argtypes = [c.c_void_p]

    lib.sha256_hex.restype = None
    lib.sha256_hex.argtypes = [c.c_char_p, c.c_int64, c.c_char_p]
    lib.chain_hash_hex.restype = None
    lib.chain_hash_hex.argtypes = [
        c.c_char_p, c.POINTER(c.c_char_p), c.POINTER(c.c_int64), c.c_int64,
        c.c_char_p,
    ]

    # Router core (scheduler hot path) — optional: older .so builds lack it,
    # and LoadManager falls back to pure Python when these are absent.
    if hasattr(lib, "rc_new"):
        lib.rc_new.restype = c.c_void_p
        lib.rc_new.argtypes = [c.c_double]
        lib.rc_free.restype = None
        lib.rc_free.argtypes = [c.c_void_p]
        lib.rc_update_tps.restype = None
        lib.rc_update_tps.argtypes = [
            c.c_void_p, c.c_char_p, c.c_char_p, c.c_char_p,
            c.c_int64, c.c_double, c.c_double,
        ]
        lib.rc_seed_tps.restype = None
        lib.rc_seed_tps.argtypes = [
            c.c_void_p, c.c_char_p, c.c_char_p, c.c_char_p,
            c.c_double, c.c_int64, c.c_double,
        ]
        lib.rc_get_tps.restype = c.c_double
        lib.rc_get_tps.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p, c.c_char_p]
        lib.rc_clear_endpoint.restype = None
        lib.rc_clear_endpoint.argtypes = [c.c_void_p, c.c_char_p]
        lib.rc_tracked_keys.restype = c.c_int64
        lib.rc_tracked_keys.argtypes = [c.c_void_p]
        lib.rc_begin.restype = None
        lib.rc_begin.argtypes = [c.c_void_p, c.c_char_p]
        lib.rc_release.restype = None
        lib.rc_release.argtypes = [c.c_void_p, c.c_char_p]
        lib.rc_active.restype = c.c_int64
        lib.rc_active.argtypes = [c.c_void_p, c.c_char_p]
        lib.rc_total_active.restype = c.c_int64
        lib.rc_total_active.argtypes = [c.c_void_p]
        lib.rc_total_requests.restype = c.c_int64
        lib.rc_total_requests.argtypes = [c.c_void_p]
        lib.rc_select.restype = c.c_int64
        lib.rc_select.argtypes = [
            c.c_void_p, c.c_char_p, c.POINTER(c.c_char_p),
            c.POINTER(c.c_double), c.c_int64, c.c_int64, c.c_char_p, c.c_int,
        ]
        lib.rc_snapshot.restype = c.c_int64
        lib.rc_snapshot.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    if hasattr(lib, "rc_tps_info"):
        lib.rc_tps_info.restype = c.c_int32
        lib.rc_tps_info.argtypes = [
            c.c_void_p, c.c_char_p, c.c_char_p, c.c_char_p,
            c.POINTER(c.c_double), c.POINTER(c.c_int64),
            c.POINTER(c.c_double),
        ]
    # Consistent-hash owner + constant-time compare (proxy hot path) —
    # optional like the router core: stale .so builds lack them and the
    # Python twins stay behaviorally identical.
    if hasattr(lib, "hrw_select"):
        lib.hrw_select.restype = c.c_int64
        lib.hrw_select.argtypes = [
            c.c_char_p, c.POINTER(c.c_char_p), c.c_int64,
        ]
    if hasattr(lib, "ct_equal"):
        lib.ct_equal.restype = c.c_int32
        lib.ct_equal.argtypes = [c.c_char_p, c.c_int64, c.c_char_p, c.c_int64]

    lib.sse_new.restype = c.c_void_p
    lib.sse_feed.restype = None
    lib.sse_feed.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.sse_frames.restype = c.c_int64
    lib.sse_frames.argtypes = [c.c_void_p]
    lib.sse_usage.restype = c.c_int32
    lib.sse_usage.argtypes = [
        c.c_void_p, c.POINTER(c.c_int64), c.POINTER(c.c_int64)
    ]
    lib.sse_free.restype = None
    lib.sse_free.argtypes = [c.c_void_p]


def ensure_native_built() -> bool:
    """Build the library if missing. BLOCKING (runs make): call this from
    process startup (server mains, test setup), never from a request path."""
    global _build_attempted
    with _lib_lock:
        if _build_attempted:
            return os.path.exists(_LIB_PATH)
        _build_attempted = True
        try:
            # Always invoke make: its dependency tracking rebuilds the .so when
            # the C++ sources changed (a stale library would otherwise be used
            # silently) and is a near-no-op when fresh.
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                check=True, capture_output=True, timeout=120,
            )
        except Exception as e:
            log.info("native build unavailable (%s); using Python fallbacks", e)
            return os.path.exists(_LIB_PATH)
    return os.path.exists(_LIB_PATH)


def load_native() -> ctypes.CDLL | None:
    """Load the already-built native library; None if unavailable. Does NOT
    build — ensure_native_built() does that at process startup."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            _configure(lib)
            _lib = lib
        except OSError as e:
            log.warning("failed to load native library: %s", e)
            return None
        return _lib


# ---------------------------------------------------------------- safetensors

_ST_DTYPES = {
    "F64": "float64", "F32": "float32", "F16": "float16", "BF16": "bfloat16",
    "I64": "int64", "I32": "int32", "I16": "int16", "I8": "int8",
    "U8": "uint8", "BOOL": "bool",
}


class NativeSafetensors:
    """Zero-copy reader over one .safetensors file via the C++ mmap reader."""

    def __init__(self, path: str):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = lib.st_open(path.encode())
        err = lib.st_error(self._handle)
        if err:
            message = err.decode()
            lib.st_close(self._handle)
            self._handle = None
            raise ValueError(f"safetensors open failed: {message}")
        self._index: dict[str, int] = {}
        for i in range(lib.st_num_tensors(self._handle)):
            self._index[lib.st_tensor_name(self._handle, i).decode()] = i

    def keys(self):
        return list(self._index)

    def get_tensor(self, name: str):
        """Owned array (safe after close). The mmap view is copied exactly
        once here; async device transfers (jax.device_put retains the numpy
        array, not this reader) must never alias the mapping, which is
        unmapped when the reader is dropped."""
        import numpy as np

        return np.array(self._view(name))

    def _view(self, name: str):
        import ml_dtypes  # ships with jax; provides numpy bfloat16
        import numpy as np

        i = self._index[name]
        lib = self._lib
        dtype_tag = lib.st_tensor_dtype(self._handle, i).decode()
        ndim = lib.st_tensor_ndim(self._handle, i)
        shape = (ctypes.c_int64 * max(ndim, 1))()
        lib.st_tensor_shape(self._handle, i, shape)
        nbytes = ctypes.c_int64()
        ptr = lib.st_tensor_data(self._handle, i, ctypes.byref(nbytes))
        buf = (ctypes.c_char * nbytes.value).from_address(ptr)
        dtype_name = _ST_DTYPES.get(dtype_tag)
        if dtype_name is None:
            raise ValueError(f"unsupported safetensors dtype {dtype_tag}")
        np_dtype = (
            ml_dtypes.bfloat16 if dtype_name == "bfloat16"
            else np.dtype(dtype_name)
        )
        arr = np.frombuffer(buf, dtype=np_dtype)
        return arr.reshape(tuple(shape[d] for d in range(ndim)))

    def close(self):
        # getattr: __init__ may raise before _handle is assigned (native lib
        # unavailable) and __del__ still runs on the half-constructed object.
        handle = getattr(self, "_handle", None)
        if handle is not None:
            self._lib.st_close(handle)
            self._handle = None

    def __del__(self):
        self.close()


# ----------------------------------------------------------------- hash chain


def native_chain_hash(prev_hash_hex: str, entries: list[bytes]) -> str | None:
    lib = load_native()
    if lib is None:
        return None
    n = len(entries)
    arr = (ctypes.c_char_p * n)(*entries)
    lens = (ctypes.c_int64 * n)(*[len(e) for e in entries])
    out = ctypes.create_string_buffer(65)
    lib.chain_hash_hex(prev_hash_hex.encode(), arr, lens, n, out)
    return out.value.decode()


# ------------------------------------------------------------------ SSE scan


class NativeSseScanner:
    def __init__(self):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = lib.sse_new()

    def feed(self, chunk: bytes) -> None:
        self._lib.sse_feed(self._handle, chunk, len(chunk))

    @property
    def frames(self) -> int:
        return self._lib.sse_frames(self._handle)

    def usage(self) -> tuple[int, int] | None:
        pt = ctypes.c_int64()
        ct = ctypes.c_int64()
        if self._lib.sse_usage(self._handle, ctypes.byref(pt), ctypes.byref(ct)):
            return int(pt.value), int(ct.value)
        return None

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.sse_free(self._handle)
            self._handle = None


# ------------------------------------------------- hot-path micro primitives


def native_hrw_available() -> bool:
    lib = load_native()
    return lib is not None and hasattr(lib, "hrw_select")


def native_hrw_select(key: str, endpoint_ids: list[str]) -> int:
    """Index of the consistent-hash (rendezvous) owner of `key` among
    `endpoint_ids`; -1 for an empty list. Bit-identical to
    balancer.hrw_owner — tested side by side."""
    lib = load_native()
    n = len(endpoint_ids)
    if lib is None or not hasattr(lib, "hrw_select") or n == 0:
        return -1
    arr = (ctypes.c_char_p * n)(*[e.encode() for e in endpoint_ids])
    return lib.hrw_select(key.encode(), arr, n)


def native_ct_equal(a: bytes, b: bytes) -> bool | None:
    """Constant-time byte equality in compiled code; None when the native
    library (or symbol) is unavailable — callers fall back to
    hmac.compare_digest."""
    lib = load_native()
    if lib is None or not hasattr(lib, "ct_equal"):
        return None
    return bool(lib.ct_equal(a, len(a), b, len(b)))


# ---------------------------------------------------------------- router core


class NativeRouterCore:
    """C++ scheduler state: TPS-EMA map + active counts + round-robin
    selection (native/router_core.cpp). Raises RuntimeError when the library
    (or this symbol, in a stale build) is unavailable — LoadManager keeps the
    pure-Python implementation as the fallback."""

    def __init__(self, alpha: float):
        lib = load_native()
        if lib is None or not hasattr(lib, "rc_new"):
            raise RuntimeError("native router core unavailable")
        self._lib = lib
        self._handle = lib.rc_new(alpha)

    def update_tps(self, eid: str, model: str, kind: str,
                   tokens: int, duration_s: float, now: float) -> None:
        self._lib.rc_update_tps(
            self._handle, eid.encode(), model.encode(), kind.encode(),
            tokens, duration_s, now,
        )

    def seed_tps(self, eid: str, model: str, kind: str,
                 ema: float, samples: int, now: float) -> None:
        self._lib.rc_seed_tps(
            self._handle, eid.encode(), model.encode(), kind.encode(),
            ema, samples, now,
        )

    def get_tps(self, eid: str, model: str, kind: str) -> float | None:
        v = self._lib.rc_get_tps(
            self._handle, eid.encode(), model.encode(), kind.encode()
        )
        return None if v < 0 else v

    def tps_info(self, eid: str, model: str,
                 kind: str) -> tuple[float, int, float] | None:
        """(ema, samples, last_update) or None when unmeasured — feeds the
        cross-worker TPS gossip (publish + last-writer-wins compare)."""
        if not hasattr(self._lib, "rc_tps_info"):
            return None  # stale .so: gossip publish just skips this key
        ema = ctypes.c_double()
        samples = ctypes.c_int64()
        last = ctypes.c_double()
        got = self._lib.rc_tps_info(
            self._handle, eid.encode(), model.encode(), kind.encode(),
            ctypes.byref(ema), ctypes.byref(samples), ctypes.byref(last),
        )
        if not got:
            return None
        return float(ema.value), int(samples.value), float(last.value)

    def clear_endpoint(self, eid: str) -> None:
        self._lib.rc_clear_endpoint(self._handle, eid.encode())

    def tracked_keys(self) -> int:
        return self._lib.rc_tracked_keys(self._handle)

    def begin(self, eid: str) -> None:
        self._lib.rc_begin(self._handle, eid.encode())

    def release(self, eid: str) -> None:
        self._lib.rc_release(self._handle, eid.encode())

    def active(self, eid: str) -> int:
        return self._lib.rc_active(self._handle, eid.encode())

    def total_active(self) -> int:
        return self._lib.rc_total_active(self._handle)

    def total_requests(self) -> int:
        return self._lib.rc_total_requests(self._handle)

    def select(self, model: str, kind: str, eids: list[str],
               penalties: list[float], cap: int, admit: bool) -> int:
        n = len(eids)
        arr = (ctypes.c_char_p * n)(*[e.encode() for e in eids])
        pens = (ctypes.c_double * n)(*penalties)
        return self._lib.rc_select(
            self._handle, model.encode(), arr, pens, n, cap,
            kind.encode(), 1 if admit else 0,
        )

    def snapshot(self) -> dict[str, dict]:
        # Size-then-fill with a growth retry: the map can gain keys between
        # the two calls (another thread's update_tps), in which case the fill
        # call reports a larger size and we re-read — never parse a
        # truncated buffer.
        needed = self._lib.rc_snapshot(self._handle, None, 0)
        while True:
            if needed <= 0:
                return {}
            cap = needed + 4096  # slack for keys added between calls
            buf = ctypes.create_string_buffer(cap)
            needed = self._lib.rc_snapshot(self._handle, buf, cap)
            if needed <= cap:
                break
        out: dict[str, dict] = {}
        for line in buf.raw[:needed].decode().splitlines():
            eid, model, kind, ema, samples, last_update = line.split("\t")
            out[f"{eid}:{model}:{kind}"] = {
                "ema_tps": round(float(ema), 3),
                "samples": int(samples),
                "last_update": float(last_update),
            }
        return out

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.rc_free(self._handle)
            self._handle = None
