from llmlb_tpu.ops.norms import rms_norm
from llmlb_tpu.ops.rope import apply_rope, rope_frequencies
from llmlb_tpu.ops.attention import (
    gqa_attention_prefill,
    gqa_attention_decode,
    paged_attention_decode,
    paged_attention_extend,
)
from llmlb_tpu.ops.sampling import sample_tokens

__all__ = [
    "rms_norm",
    "apply_rope",
    "rope_frequencies",
    "gqa_attention_prefill",
    "gqa_attention_decode",
    "paged_attention_decode",
    "paged_attention_extend",
    "sample_tokens",
]
