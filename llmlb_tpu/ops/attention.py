"""Grouped-query attention for serving: batched prefill + single-token decode.

Design notes (TPU-first):
- Static shapes everywhere: prefill runs at bucketed sequence lengths, decode at
  T=1 over a fixed-capacity per-slot KV cache. Ragged reality is expressed with
  masks, not dynamic shapes, so XLA tiles everything onto the MXU.
- Softmax in float32; QK^T and PV in bf16 inputs with fp32 accumulation
  (`preferred_element_type`) — the MXU accumulates in fp32 natively.
- GQA is expressed by folding the group dimension into einsum so no materialized
  `repeat_kv` copy hits HBM.

The reference gateway never touches attention (it proxies; SURVEY.md §5
"long-context: absent") — this op family is new TPU-native design. A Pallas ragged
paged attention kernel (PAPERS.md) replaces the dense decode path in a later phase;
this XLA version is the correctness baseline it is checked against.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

_NEG_INF = -1e30  # large finite value: -inf breaks softmax rows that are fully masked


def _pallas_enabled() -> bool:
    """Route to the Pallas kernels (ops/pallas_attention.py)?

    `LLMLB_TPU_ATTENTION=pallas|xla` forces a path; `auto` (default) picks
    Pallas on an unpartitioned TPU. A pallas_call is opaque to XLA sharding
    propagation, so multi-device meshes keep the einsum path unless the caller
    wraps the step in shard_map and forces `pallas`.
    """
    mode = os.environ.get("LLMLB_TPU_ATTENTION", "auto")
    if mode == "pallas":
        return True
    if mode == "xla":
        return False
    return jax.default_backend() == "tpu" and jax.device_count() == 1


def _split_gqa(q: jnp.ndarray, num_kv_heads: int) -> jnp.ndarray:
    """[B, T, H, D] -> [B, T, K, G, D] where H = K * G."""
    b, t, h, d = q.shape
    return q.reshape(b, t, num_kv_heads, h // num_kv_heads, d)


def gqa_attention_prefill(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,  # [B, T, K, D]
    v: jnp.ndarray,  # [B, T, K, D]
    prompt_lens: jnp.ndarray,  # [B] int32 — tokens beyond this are padding
) -> jnp.ndarray:
    """Causal self-attention over a freshly-prefilled prompt. Returns [B, T, H, D]."""
    if _pallas_enabled():
        from llmlb_tpu.ops.pallas_attention import flash_prefill

        return flash_prefill(q, k, v, prompt_lens)
    b, t, h, d = q.shape
    k_heads = k.shape[2]
    qg = _split_gqa(q, k_heads)
    scale = d**-0.5

    # [B, K, G, Tq, Tk] fp32 scores
    scores = jnp.einsum(
        "btkgd,bskd->bkgts", qg, k, preferred_element_type=jnp.float32
    ) * scale

    pos = jnp.arange(t, dtype=jnp.int32)
    causal = pos[:, None] >= pos[None, :]  # [Tq, Tk]
    valid = pos[None, :] < prompt_lens[:, None, None]  # broadcast to [B, 1, Tk]
    mask = causal[None, :, :] & valid  # [B, Tq, Tk]
    scores = jnp.where(mask[:, None, None, :, :], scores, _NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgts,bskd->btkgd", probs.astype(q.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, t, h, d).astype(q.dtype)


def gqa_attention_extend(
    q: jnp.ndarray,  # [B, T, H, D] — chunk of queries
    k_cache: jnp.ndarray,  # [B, S, K, D] — slot cache incl. this chunk's keys
    v_cache: jnp.ndarray,  # [B, S, K, D]
    q_positions: jnp.ndarray,  # [B, T] int32 — global position of each query
    chunk_lens: jnp.ndarray | None = None,  # [B] int32 — enables Pallas route
) -> jnp.ndarray:
    """Chunked-prefill attention: a chunk of T queries attends causally against
    the full slot cache (earlier chunks + this chunk). Query i at global
    position p may see cache positions <= p. Returns [B, T, H, D].

    Generalizes decode (T=1); backs the engine's chunked long-prompt prefill
    path (no reference counterpart — SURVEY.md §5 long-context is greenfield).
    On a single TPU the Pallas flash kernel serves this; it assumes the
    engine's contiguous chunk positions (q_positions[b] = start + iota), which
    is what both callers construct.
    """
    if chunk_lens is not None and _pallas_enabled():
        from llmlb_tpu.ops.pallas_attention import flash_extend

        return flash_extend(
            q, k_cache, v_cache, q_positions[:, 0], chunk_lens
        )
    b, t, h, d = q.shape
    k_heads = k_cache.shape[2]
    qg = _split_gqa(q, k_heads)  # [B, T, K, G, D]
    scale = d**-0.5

    scores = jnp.einsum(
        "btkgd,bskd->bkgts", qg, k_cache, preferred_element_type=jnp.float32
    ) * scale  # [B, K, G, T, S]

    s = k_cache.shape[1]
    cap_pos = jnp.arange(s, dtype=jnp.int32)
    mask = cap_pos[None, None, :] <= q_positions[:, :, None]  # [B, T, S]
    scores = jnp.where(mask[:, None, None, :, :], scores, _NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgts,bskd->btkgd", probs.astype(q.dtype), v_cache,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, t, h, d).astype(q.dtype)


def gather_kv_pages(pages, tables: jnp.ndarray,
                    dtype=jnp.bfloat16) -> jnp.ndarray:
    """Materialize contiguous per-row KV from the page pool: [P, PS, K, D]
    gathered by block tables [B, N] -> [B, N*PS, K, D]. This is the XLA
    fallback path (CPU tests / partitioned meshes) — on an unpartitioned TPU
    the Pallas paged kernels index the pool through the block table instead
    and never build this copy.

    An int8 pool arrives as a {"q": int8 values, "s": f32 scales [P, PS, K]}
    pair (llmlb_tpu/quant): both gather through the same table and the cells
    dequantize to `dtype` here — the attention callers pass their compute
    dtype so this route matches the Pallas quant kernels' numerics exactly
    (f32 dequant -> q.dtype operands). HBM moved the int8 bytes + scales."""
    if isinstance(pages, dict):
        b, n = tables.shape
        _, ps, k, d = pages["q"].shape
        vals = pages["q"][tables].reshape(b, n * ps, k, d)
        scales = pages["s"][tables].reshape(b, n * ps, k)
        return (vals.astype(jnp.float32)
                * scales[..., None]).astype(dtype)
    b, n = tables.shape
    _, ps, k, d = pages.shape
    return pages[tables].reshape(b, n * ps, k, d)


def _pool_shape(pages):
    return (pages["q"] if isinstance(pages, dict) else pages).shape


def paged_attention_decode(
    q: jnp.ndarray,  # [B, 1, H, D]
    k_pages,  # [P, PS, K, D] pool, or quantized {"q","s"} pair
    v_pages,  # [P, PS, K, D]
    block_tables: jnp.ndarray,  # [B, PPN] int32
    kv_lens: jnp.ndarray,  # [B] int32 — valid logical length per row
    window: int | None = None,  # static: read only the first `window` cells
) -> jnp.ndarray:
    """One-token decode attention against the PAGED KV pool. Same contract
    as gqa_attention_decode — `window` (STATIC) bounds the logical sweep,
    rounded up to whole pages; rows with kv_lens beyond the swept pages
    produce garbage the caller must discard (parked/freed slot rows)."""
    ps = _pool_shape(k_pages)[1]
    ppn = block_tables.shape[1]
    pages = ppn if window is None else max(1, min(ppn, -(-window // ps)))
    if _pallas_enabled():
        if isinstance(k_pages, dict):
            from llmlb_tpu.ops.pallas_attention import paged_flash_decode_quant

            return paged_flash_decode_quant(
                q[:, 0], k_pages["q"], k_pages["s"], v_pages["q"],
                v_pages["s"], block_tables, kv_lens, pages=pages,
            )[:, None]
        from llmlb_tpu.ops.pallas_attention import paged_flash_decode

        return paged_flash_decode(
            q[:, 0], k_pages, v_pages, block_tables, kv_lens, pages=pages
        )[:, None]
    tables = block_tables[:, :pages] if pages < ppn else block_tables
    k_cache = gather_kv_pages(k_pages, tables, dtype=q.dtype)
    v_cache = gather_kv_pages(v_pages, tables, dtype=q.dtype)
    return gqa_attention_decode(q, k_cache, v_cache, kv_lens)


def paged_attention_extend(
    q: jnp.ndarray,  # [B, T, H, D] — chunk of queries
    k_pages,  # [P, PS, K, D] pool, or quantized {"q","s"} pair
    v_pages,  # [P, PS, K, D]
    block_tables: jnp.ndarray,  # [B, PPN] int32
    q_positions: jnp.ndarray,  # [B, T] int32 — global position of each query
    chunk_lens: jnp.ndarray,  # [B] int32 — valid queries in the chunk
) -> jnp.ndarray:
    """Chunked-prefill attention against the PAGED KV pool: the chunk's
    queries attend causally over row b's pages (earlier chunks + this
    chunk). Paged counterpart of gqa_attention_extend; assumes the engine's
    contiguous chunk positions (q_positions[b] = start + iota)."""
    if _pallas_enabled():
        if isinstance(k_pages, dict):
            from llmlb_tpu.ops.pallas_attention import paged_flash_extend_quant

            return paged_flash_extend_quant(
                q, k_pages["q"], k_pages["s"], v_pages["q"], v_pages["s"],
                block_tables, q_positions[:, 0], chunk_lens,
            )
        from llmlb_tpu.ops.pallas_attention import paged_flash_extend

        return paged_flash_extend(
            q, k_pages, v_pages, block_tables, q_positions[:, 0], chunk_lens
        )
    k_cache = gather_kv_pages(k_pages, block_tables, dtype=q.dtype)
    v_cache = gather_kv_pages(v_pages, block_tables, dtype=q.dtype)
    # chunk_lens=None pins gqa_attention_extend to the XLA einsum path (the
    # caches are already materialized dense here).
    return gqa_attention_extend(q, k_cache, v_cache, q_positions, None)


def gqa_attention_decode(
    q: jnp.ndarray,  # [B, 1, H, D]
    k_cache: jnp.ndarray,  # [B, S, K, D] — slot-capacity cache incl. current token
    v_cache: jnp.ndarray,  # [B, S, K, D]
    kv_lens: jnp.ndarray,  # [B] int32 — valid cache length per slot (incl. current)
    window: int | None = None,  # static: read only the first `window` cells
) -> jnp.ndarray:
    """One-token decode attention against the slot cache. Returns [B, 1, H, D].

    `window` (STATIC) bounds how much of the capacity axis is read: the
    scheduler picks the smallest bucket covering every active sequence, so
    attention HBM traffic scales with the context actually in use instead of
    the full slot capacity (reading 2048 cells for 300-token contexts wasted
    ~85% of decode's cache bandwidth). Rows with kv_lens <= window are
    exact; rows with kv_lens > window (parked chunked-prefill / freed slots,
    whose device counters sit at capacity) produce garbage the caller must
    discard — the engine's emission loop skips exactly those rows."""
    s = k_cache.shape[1]
    if window is not None and window < s:
        if _pallas_enabled():
            from llmlb_tpu.ops.pallas_attention import flash_decode

            # the kernel bounds its grid instead of slicing (no copy)
            return flash_decode(
                q[:, 0], k_cache, v_cache, kv_lens, window=window
            )[:, None]
        k_cache = jax.lax.slice_in_dim(k_cache, 0, window, axis=1)
        v_cache = jax.lax.slice_in_dim(v_cache, 0, window, axis=1)
        s = window
    elif _pallas_enabled():
        from llmlb_tpu.ops.pallas_attention import flash_decode

        return flash_decode(q[:, 0], k_cache, v_cache, kv_lens)[:, None]
    b, t, h, d = q.shape
    k_heads = k_cache.shape[2]
    qg = _split_gqa(q, k_heads)  # [B, 1, K, G, D]
    scale = d**-0.5

    scores = jnp.einsum(
        "btkgd,bskd->bkgts", qg, k_cache, preferred_element_type=jnp.float32
    ) * scale  # [B, K, G, 1, S]

    valid = jnp.arange(s, dtype=jnp.int32)[None, :] < kv_lens[:, None]  # [B, S]
    scores = jnp.where(valid[:, None, None, None, :], scores, _NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgts,bskd->btkgd", probs.astype(q.dtype), v_cache,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, t, h, d).astype(q.dtype)
