"""Device-resident grammar tables: constraint masking without host round-trips.

The legacy structured-output path (docs/structured-outputs.md) keeps the
grammar on the host: after every sampled token the scheduler advances a
per-slot DFA cursor in Python, looks up the next state's float32 [V] bias
row, and scatters it into a device mask buffer before the next dispatch.
That host walk is why constrained slots fell out of burst decode (PR 5) and
why speculative drafts needed a host pre-walk (PR 7) — the mask for step
t+1 does not exist until the host has seen token t.

This module moves the grammar itself onto the device. Each compiled schema's
``TokenConstraint.transition_table()`` (int32 ``[states, V]`` → next state,
-1 disallowed) is appended into ONE concatenated device array shared by all
resident schemas, with per-schema row offsets. A slot's grammar cursor is
then just an int32 (absolute row index), and both the mask and the cursor
advance become O(1) gathers inside the fused decode/verify program:

    bias[b, v]  = 0.0 where table[state[b], v] >= 0 else MASK_NEG
    state'[b]   = table[state[b], token[b]]        (clamped to state[b]
                                                    when the entry is -1)

Row 0 of the table is the FREE row: all zeros, meaning "every token allowed,
next state 0". Unconstrained slots carry cursor 0, get an all-zero bias
(``logits + 0.0`` is bit-preserving), and self-loop — so one fused program
serves mixed constrained/free batches with no branching.

Memory: states × V × 4 bytes per schema (int32), uploaded once per schema —
vs the host-mirror approach's per-step [slots, V] float32 scatter. The
budget knob ``LLMLB_GRAMMAR_TABLE_MB`` caps total table bytes; registration
past the budget returns None and the scheduler falls back to the legacy
host-mask path for that schema (correctness is never budget-gated).
"""

from __future__ import annotations

import logging
import os
import threading

import jax.numpy as jnp
import numpy as np

from llmlb_tpu.structured.constraint import MASK_NEG, TokenConstraint

log = logging.getLogger("llmlb.ops.grammar")

# Total device-table budget across all resident schemas. 64 MiB holds e.g.
# 1024 DFA states over a 16k vocab (1024 x 16384 x 4B = 64 MiB) — far past
# any schema the structured-output compiler emits today.
_DEFAULT_BUDGET_MB = 64


def _env_budget_bytes() -> int:
    raw = os.environ.get("LLMLB_GRAMMAR_TABLE_MB", "")
    try:
        mb = float(raw) if raw else float(_DEFAULT_BUDGET_MB)
    except ValueError:
        mb = float(_DEFAULT_BUDGET_MB)
    return max(1, int(mb * (1 << 20)))


class GrammarTables:
    """Concatenated next-state tables for every schema the engine has seen.

    Grow-only by design: schemas are already LRU-capped upstream in
    ConstraintCompiler (32 entries), so the working set is small; freeing
    rows would invalidate live slot cursors mid-request. ``register`` is
    idempotent per TokenConstraint instance and returns the ABSOLUTE row
    index of that schema's DFA start-of-table (add the local DFA state to
    get a cursor). A strong reference to each registered constraint is held
    so a recycled ``id()`` can never alias two schemas to one offset.

    Thread-safety: register() runs on the step loop and insert paths under
    the scheduler's own locks; the internal lock only guards the host-side
    table growth vs. ``device()`` reads from scrape threads.
    """

    def __init__(self, vocab_size: int, *, budget_bytes: int | None = None):
        self.vocab_size = int(vocab_size)
        self.budget_bytes = (int(budget_bytes) if budget_bytes is not None
                             else _env_budget_bytes())
        self._lock = threading.Lock()
        # row 0 = the free row (see module docstring)
        self._host = np.zeros((1, self.vocab_size), dtype=np.int32)
        self._offsets: dict[int, int] = {}  # id(tc) -> absolute row offset
        self._owners: list[TokenConstraint] = []  # keep ids stable
        self._device: jnp.ndarray | None = None
        self.schemas_registered = 0
        self.schemas_rejected = 0

    # ------------------------------------------------------------ registration

    def register(self, tc: TokenConstraint) -> int | None:
        """Absolute row offset for `tc`'s DFA state 0, or None when adding
        the schema would exceed the table budget."""
        with self._lock:
            off = self._offsets.get(id(tc))
            if off is not None:
                return off
            table = tc.transition_table()
            if table.shape[1] != self.vocab_size:
                raise ValueError(
                    f"vocab mismatch: table {table.shape[1]} vs "
                    f"grammar tables {self.vocab_size}"
                )
            new_bytes = (self._host.shape[0] + table.shape[0]) \
                * self.vocab_size * 4
            if new_bytes > self.budget_bytes:
                self.schemas_rejected += 1
                return None
            off = self._host.shape[0]
            # next-state entries become absolute rows into the concatenated
            # table; -1 (disallowed) stays -1
            shifted = np.where(table >= 0, table + off, table)
            self._host = np.concatenate([self._host, shifted], axis=0)
            self._offsets[id(tc)] = off
            self._owners.append(tc)
            self._device = None  # re-upload on next device() call
            self.schemas_registered += 1
            return off

    # ----------------------------------------------------------------- reading

    def device(self) -> jnp.ndarray:
        """Device mirror of the concatenated table. Re-uploaded only after a
        new schema registered (per schema, not per step)."""
        with self._lock:
            if self._device is None:
                self._device = jnp.asarray(self._host)
            return self._device

    @property
    def rows(self) -> int:
        with self._lock:
            return self._host.shape[0]

    @property
    def nbytes(self) -> int:
        with self._lock:
            return int(self._host.nbytes)

    def info(self) -> dict:
        with self._lock:
            return {
                "rows": int(self._host.shape[0]),
                "bytes": int(self._host.nbytes),
                "budget_bytes": self.budget_bytes,
                "schemas": self.schemas_registered,
                "rejected": self.schemas_rejected,
            }


# ------------------------------------------------------------- jittable ops


def grammar_bias(table: jnp.ndarray, states: jnp.ndarray) -> jnp.ndarray:
    """Additive float32 [B, V] sampling bias for the given cursors: 0 where
    the token keeps the match alive, MASK_NEG where it kills it. Row 0
    cursors (free slots) yield all zeros — bit-preserving under addition."""
    rows = table[states]  # [B, V] int32 gather
    return jnp.where(rows >= 0, jnp.float32(0.0), MASK_NEG)


def grammar_advance(table: jnp.ndarray, states: jnp.ndarray,
                    tokens: jnp.ndarray) -> jnp.ndarray:
    """Next cursors after sampling `tokens` [B] from `states` [B]. A -1
    entry (token disallowed — only reachable for positions the mask never
    sampled, e.g. rejected speculative draft columns) clamps to the current
    state so lockstep cursor math stays in-table."""
    nxt = table[states, tokens]
    return jnp.where(nxt >= 0, nxt, states)


__all__ = ["GrammarTables", "grammar_advance", "grammar_bias"]
