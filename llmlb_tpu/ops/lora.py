"""Batched grouped LoRA matmul (bgmv): per-row adapter deltas in one dispatch.

Multi-LoRA serving (docs/lora.md) keeps every resident adapter's A/B factors
stacked in device pools `a [N, IN, R]` / `b [N, R, OUT]` per projection, and
each batch row carries an adapter index. The delta for row i is

    delta_i = (x_i @ a[idx_i]) @ b[idx_i]        # rank-R bottleneck

added to the BASE projection's output — so a mixed-adapter batch (including
adapter-free rows, which point at the all-zero identity row 0) decodes in ONE
dispatch instead of one sub-batch per adapter. This is the punica/vLLM "bgmv"
shape (PAPERS.md: S-LoRA lineage), built here in two flavors:

- `lora_delta_xla`: gather-by-index + two einsums. Runs anywhere (CPU tests,
  partitioned meshes — a pallas_call is opaque to GSPMD sharding propagation,
  same caveat as ops/attention.py).
- `lora_delta_pallas`: a Pallas TPU kernel. The adapter indices arrive via
  scalar prefetch (PrefetchScalarGridSpec), and the per-row A/B blocks are
  DMA'd straight from their pool rows by the block index_map — the gathered
  [B, IN, R] copy the XLA path materializes never exists. Grid is (B,);
  blocks take the full trailing dims, satisfying the Mosaic tiling rule the
  attention kernels rely on (block dims equal to array dims are always
  legal), so any (IN, R, OUT) works — ranks are far below one lane tile.

Numerics: fp32 accumulation through both thin matmuls
(`preferred_element_type`), delta returned in fp32; the caller adds it to the
base output and casts. Adapter-free rows read the all-zero row 0, so their
delta is exactly 0.0 and `base + 0.0` is bit-identical to the no-LoRA path.

`LLMLB_TPU_LORA=pallas|xla|auto` forces a path (auto: Pallas on a
single-device TPU, the ops/attention.py convention).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp


def _pallas_enabled() -> bool:
    mode = os.environ.get("LLMLB_TPU_LORA", "auto")
    if mode == "pallas":
        return True
    if mode == "xla":
        return False
    return jax.default_backend() == "tpu" and jax.device_count() == 1


def lora_delta_xla(
    x: jnp.ndarray,  # [B, T, IN]
    a: jnp.ndarray,  # [N, IN, R]
    b: jnp.ndarray,  # [N, R, OUT]
    idx: jnp.ndarray,  # [B] int32 — adapter pool row per batch row (0 = none)
) -> jnp.ndarray:
    """Per-row LoRA delta via take-along gather + two thin einsums.

    Returns [B, T, OUT] fp32. The gather materializes each row's factors
    ([B, IN, R] / [B, R, OUT]) — fine for XLA which fuses it into the
    contraction reads; the Pallas kernel avoids it outright.
    """
    a_sel = jnp.take(a, idx, axis=0)  # [B, IN, R]
    b_sel = jnp.take(b, idx, axis=0)  # [B, R, OUT]
    u = jnp.einsum("bti,bir->btr", x, a_sel,
                   preferred_element_type=jnp.float32)
    return jnp.einsum("btr,bro->bto", u, b_sel,
                      preferred_element_type=jnp.float32)


def _bgmv_kernel(idx_ref, x_ref, a_ref, b_ref, o_ref):
    """One batch row: shrink (x @ A) then expand (u @ B), fp32 accumulate.
    A/B blocks were already DMA'd from pool row idx_ref[bi] by the
    index_maps — the kernel body never touches the index itself."""
    u = jax.lax.dot_general(
        x_ref[0], a_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [T, R]
    o_ref[0] = jax.lax.dot_general(
        u, b_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def lora_delta_pallas(
    x: jnp.ndarray,  # [B, T, IN]
    a: jnp.ndarray,  # [N, IN, R]
    b: jnp.ndarray,  # [N, R, OUT]
    idx: jnp.ndarray,  # [B] int32
    interpret: bool = False,
) -> jnp.ndarray:
    """bgmv Pallas kernel: gather A/B by adapter index through the block
    index_map (scalar-prefetched indices steer the DMA), two thin matmuls
    per row. Returns [B, T, OUT] fp32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bsz, t, in_dim = x.shape
    _, _, r = a.shape
    out_dim = b.shape[2]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bsz,),
        in_specs=[
            pl.BlockSpec((1, t, in_dim), lambda bi, idx: (bi, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, in_dim, r), lambda bi, idx: (idx[bi], 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, r, out_dim), lambda bi, idx: (idx[bi], 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, t, out_dim), lambda bi, idx: (bi, 0, 0),
                               memory_space=pltpu.VMEM),
    )
    return pl.pallas_call(
        _bgmv_kernel,
        out_shape=jax.ShapeDtypeStruct((bsz, t, out_dim), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(idx.astype(jnp.int32), x, a, b)


def lora_delta(
    x: jnp.ndarray,  # [B, T, IN]
    a: jnp.ndarray,  # [N, IN, R]
    b: jnp.ndarray,  # [N, R, OUT]
    idx: jnp.ndarray,  # [B] int32
) -> jnp.ndarray:
    """Dispatcher: Pallas bgmv on an unpartitioned TPU, XLA gather path
    elsewhere (LLMLB_TPU_LORA forces either). Returns [B, T, OUT] fp32."""
    if _pallas_enabled():
        return lora_delta_pallas(x, a, b, idx)
    return lora_delta_xla(x, a, b, idx)
