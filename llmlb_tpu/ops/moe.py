"""Mixture-of-experts layer: top-k routing with capacity-bounded dispatch.

TPU-first design — the GShard/Mesh-TensorFlow einsum formulation rather than
gather/scatter token shuffling:

- Static shapes: every tensor's shape depends only on (tokens, experts,
  capacity), never on routing decisions. Raggedness is expressed by dropping
  tokens over capacity (standard capacity-factor semantics), so the whole layer
  jits once and tiles onto the MXU.
- Expert parallelism rides GSPMD: expert-major tensors are sharding-constrained
  to the mesh `ep` axis and XLA inserts the dispatch/combine all-to-alls. No
  hand-written collectives — the idiomatic TPU way (scaling-book recipe).
- dispatch/combine are one-hot einsums (bf16 matmuls on the MXU), which beats
  dynamic scatter on TPU for the expert counts this framework targets (8-64).

The reference has no MoE anywhere (it is a gateway; SURVEY.md §2.4 "no EP");
this op exists for the BASELINE.json config #5 class (Mixtral-8x7B across
multi-slice v5e) as new TPU-native design.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _expert_mm(eq: str, a: jnp.ndarray, w: jnp.ndarray,
               scale: jnp.ndarray | None) -> jnp.ndarray:
    """Per-expert einsum with optional int8 dequant (llmlb_tpu/quant): the
    int8 -> compute-dtype convert fuses into the operand read, and the
    per-output-channel scale [E, out] applies to the f32 OUTPUT — exact,
    since the scale is constant along the contraction. Unquantized weights
    run the original einsum untouched. Returns f32 (caller casts)."""
    if scale is None:
        return jnp.einsum(eq, a, w, preferred_element_type=jnp.float32)
    y = jnp.einsum(eq, a, w.astype(a.dtype),
                   preferred_element_type=jnp.float32)
    return y * scale[:, None, :]


def top_k_routing(
    router_logits: jnp.ndarray,  # [S, E] fp32
    num_selected: int,
):
    """Top-k gate: returns (weights [S, k] fp32 normalized, indices [S, k])."""
    gate_vals, gate_idx = lax.top_k(router_logits, num_selected)
    # Mixtral normalizes softmax over the selected k (not over all experts).
    weights = jax.nn.softmax(gate_vals, axis=-1)
    return weights, gate_idx


def moe_dispatch_combine(
    x: jnp.ndarray,  # [S, M] tokens (S = B*T)
    router_logits: jnp.ndarray,  # [S, E]
    w_gate: jnp.ndarray,  # [E, M, F] per-expert gate proj (silu branch)
    w_up: jnp.ndarray,  # [E, M, F]
    w_down: jnp.ndarray,  # [E, F, M]
    *,
    num_selected: int,
    capacity: int,
    mesh: Mesh | None = None,
    ep_axis: str = "ep",
    token_valid: jnp.ndarray | None = None,  # [S] bool — False = padding
    w_gate_scale: jnp.ndarray | None = None,  # [E, F] int8 dequant scales
    w_up_scale: jnp.ndarray | None = None,  # [E, F]
    w_down_scale: jnp.ndarray | None = None,  # [E, M]
) -> jnp.ndarray:
    """SwiGLU expert MLPs with top-k dispatch. Returns [S, M].

    Tokens beyond an expert's `capacity` are dropped (contribute zero), per
    standard capacity-factor semantics; callers size capacity as
    ceil(S * k / E) * capacity_factor. Pass `token_valid` for padded batches:
    padding tokens would otherwise route like real tokens and burn expert
    capacity (a mostly-padded bucket could evict every real token).
    """
    s, m = x.shape
    e = w_gate.shape[0]
    weights, gate_idx = top_k_routing(router_logits.astype(jnp.float32), num_selected)

    # Position of each (token, choice) in its expert's buffer: running count of
    # prior assignments to the same expert, priority by (choice rank, token id).
    # one_hot: [S, k, E]
    one_hot = jax.nn.one_hot(gate_idx, e, dtype=jnp.int32)
    if token_valid is not None:
        one_hot = one_hot * token_valid.astype(jnp.int32)[:, None, None]
    # flatten choices k-major so choice-0 assignments beat choice-1 on capacity
    flat = one_hot.transpose(1, 0, 2).reshape(num_selected * s, e)  # [kS, E]
    pos_flat = jnp.cumsum(flat, axis=0) - flat  # position within expert
    pos = pos_flat.reshape(num_selected, s, e).transpose(1, 0, 2)  # [S, k, E]
    in_cap = (pos < capacity) & (one_hot == 1)

    # dispatch mask [S, E, C]: token s -> slot pos in expert e (for kept pairs)
    slot_oh = jax.nn.one_hot(
        jnp.where(in_cap, pos, capacity), capacity, dtype=x.dtype
    )  # [S, k, E, C] — overflow rows one_hot to nothing (index == C)
    dispatch = slot_oh.sum(axis=1)  # [S, E, C]
    combine = (slot_oh * weights[:, :, None, None].astype(x.dtype)).sum(axis=1)

    expert_in = jnp.einsum(
        "sec,sm->ecm", dispatch, x, preferred_element_type=jnp.float32
    ).astype(x.dtype)  # [E, C, M]
    if mesh is not None and ep_axis in mesh.axis_names:
        expert_in = lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P(ep_axis, None, None))
        )

    # Per-expert SwiGLU, batched over the (ep-sharded) expert dim.
    h = jax.nn.silu(
        _expert_mm("ecm,emf->ecf", expert_in, w_gate,
                   w_gate_scale).astype(x.dtype)
    ) * _expert_mm("ecm,emf->ecf", expert_in, w_up,
                   w_up_scale).astype(x.dtype)
    expert_out = _expert_mm(
        "ecf,efm->ecm", h, w_down, w_down_scale
    ).astype(x.dtype)
    if mesh is not None and ep_axis in mesh.axis_names:
        expert_out = lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P(ep_axis, None, None))
        )

    out = jnp.einsum(
        "sec,ecm->sm", combine, expert_out, preferred_element_type=jnp.float32
    )
    return out.astype(x.dtype)


def moe_dense_exact(
    x: jnp.ndarray,  # [S, M]
    router_logits: jnp.ndarray,  # [S, E]
    w_gate: jnp.ndarray,  # [E, M, F]
    w_up: jnp.ndarray,  # [E, M, F]
    w_down: jnp.ndarray,  # [E, F, M]
    *,
    num_selected: int,
    mesh: Mesh | None = None,
    ep_axis: str = "ep",
    w_gate_scale: jnp.ndarray | None = None,  # [E, F] int8 dequant scales
    w_up_scale: jnp.ndarray | None = None,  # [E, F]
    w_down_scale: jnp.ndarray | None = None,  # [E, M]
) -> jnp.ndarray:
    """Exact top-k MoE: every expert runs on every token, combine masks the
    rest. E/k × the routed FLOPs — the right trade for *decode*, where S is a
    small decode batch and the step is bound by streaming expert weights from
    HBM (which dense and routed both do), not by MXU FLOPs. No tokens are ever
    dropped, so decode logits are exactly consistent with an unbounded-capacity
    prefill. Expert dim still shards over `ep`.
    """
    weights, gate_idx = top_k_routing(router_logits.astype(jnp.float32), num_selected)
    e = w_gate.shape[0]
    # [S, E] combine weights (zero for unselected experts)
    combine = (jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)
               * weights[..., None]).sum(axis=1)

    h = jax.nn.silu(
        _expert_mm("sm,emf->esf", x, w_gate, w_gate_scale).astype(x.dtype)
    ) * _expert_mm("sm,emf->esf", x, w_up, w_up_scale).astype(x.dtype)
    expert_out = _expert_mm(
        "esf,efm->esm", h, w_down, w_down_scale
    )  # [E, S, M] fp32
    if mesh is not None and ep_axis in mesh.axis_names:
        expert_out = lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P(ep_axis, None, None))
        )
    out = jnp.einsum("se,esm->sm", combine, expert_out)
    return out.astype(x.dtype)


def default_capacity(tokens: int, num_experts: int, num_selected: int,
                     capacity_factor: float = 1.25) -> int:
    """GShard-style capacity: factor × even-split load, floor 4, MXU-friendly
    multiple of 4."""
    cap = int(tokens * num_selected / num_experts * capacity_factor)
    return max(4, (cap + 3) // 4 * 4)
