"""Normalization ops. Computed in float32, cast back — bf16 accumulate drifts."""

from __future__ import annotations

import jax.lax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """RMSNorm: x * w / rms(x). Keeps the VPU in fp32 for the reduction."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)
