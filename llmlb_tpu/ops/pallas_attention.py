"""Pallas TPU attention kernels: ragged flash-decode + causal flash-prefill.

These are the hot ops of the serving engine (SURVEY.md §7 phase 4: "ragged paged
attention Pallas kernel"). The XLA einsum paths in ops/attention.py are the
correctness baselines; these kernels replace them on TPU:

- `flash_decode`: one-token GQA attention against the slot KV cache. Grid is
  (batch, kv_block) with the kv-block axis innermost, so Pallas's grid pipeline
  double-buffers the next KV block's DMA behind the current block's compute.
  Online softmax (m/l/acc) lives in VMEM scratch across the kv-block sweep.
  Raggedness: per-slot `kv_lens` arrive via scalar prefetch (SMEM) and blocks
  past the valid length skip their FLOPs entirely (`pl.when`) — decode cost
  scales with the *actual* context, not the slot capacity.
- `flash_prefill`: causal self-attention over bucketed prompts. Grid is
  (batch, q_block, kv_block); fully-future KV blocks (k_start > q_end) skip
  compute, giving the ~2x causal FLOP saving dense XLA attention leaves on the
  table. The GQA group dim is folded into the q-row dim so the MXU sees
  [BLK_Q*G, D] x [D, BLK_K] matmuls instead of G tiny ones.

Mosaic tiling: blocks always take the FULL trailing (heads, head_dim) dims —
the lowering requires the last two block dims be (8,128)-aligned *or* equal to
the array dims, and "equal" holds for any head count this way. KV heads are
iterated with a static (unrolled) loop inside the kernel.

Numerics match the XLA baselines: fp32 scores/softmax/accumulation
(`preferred_element_type`), finite -1e30 masking (fully-masked rows stay NaN-free).

Multi-device note: a `pallas_call` is opaque to XLA's sharding propagation, so
the dispatcher in ops/attention.py only routes here when the computation is not
partitioned over devices (single-chip serving, or inside `shard_map`).

The reference has no counterpart (it proxies inference — SURVEY.md L0); design
follows the public ragged-paged-attention pattern (PAPERS.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30  # finite: keeps fully-masked softmax rows NaN-free


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _online_update(m_ref, l_ref, acc_ref, idx, scores, v):
    """One online-softmax accumulation step into scratch rows `idx`."""
    m_prev = m_ref[idx]
    m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
    correction = jnp.exp(m_prev - m_new)
    p = jnp.exp(scores - m_new)  # f32
    l_ref[idx] = l_ref[idx] * correction + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_ref[idx] = acc_ref[idx] * correction + pv
    m_ref[idx] = m_new


# ---------------------------------------------------------------------------
# Decode: q [B, H, D] vs slot cache [B, S, K, D], ragged kv_lens [B]
# ---------------------------------------------------------------------------


def _decode_kernel(
    # scalar prefetch
    kv_lens_ref,  # [B] int32 (SMEM)
    # inputs
    q_ref,  # [1, K, G, D]
    k_ref,  # [1, BLK, K, D]
    v_ref,  # [1, BLK, K, D]
    # output
    o_ref,  # [1, K, G, D]
    # scratch
    m_ref,  # [K, G, 1] f32
    l_ref,  # [K, G, 1] f32
    acc_ref,  # [K, G, D] f32
    *,
    block_k: int,
    num_kv: int,
    scale: float,
):
    b = pl.program_id(0)
    s = pl.program_id(1)
    num_blocks = pl.num_programs(1)
    kv_len = kv_lens_ref[b]

    @pl.when(s == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(s * block_k < kv_len)
    def _compute():
        col = s * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), dimension=1
        )
        valid = col < kv_len  # [1, BLK]
        for h in range(num_kv):  # static unroll over KV heads
            q = q_ref[0, h]  # [G, D]
            k = k_ref[0, :, h, :]  # [BLK, D]
            v = v_ref[0, :, h, :]
            scores = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [G, BLK]
            scores = jnp.where(valid, scores, _NEG_INF)
            _online_update(m_ref, l_ref, acc_ref, h, scores, v)

    @pl.when(s == num_blocks - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_k", "interpret", "window")
)
def flash_decode(
    q: jnp.ndarray,  # [B, H, D]
    k_cache: jnp.ndarray,  # [B, S, K, D]
    v_cache: jnp.ndarray,  # [B, S, K, D]
    kv_lens: jnp.ndarray,  # [B] int32 — valid cache length per slot
    *,
    block_k: int = 128,
    interpret: bool | None = None,
    window: int | None = None,  # static: sweep only the first `window` cells
) -> jnp.ndarray:
    """Ragged one-token GQA decode attention. Returns [B, H, D] in q.dtype.

    `window` bounds the kv-block sweep (grid), NOT the input shapes — the
    kernel simply never DMAs cache blocks past it, so short contexts in a
    large-capacity cache cost only the traffic they actually need and no
    slice copy is materialized. Contract: rows with kv_lens <= window are
    exact; rows with kv_lens > window produce GARBAGE (their mask believes
    unswept cells are valid) and the caller must discard them — the engine
    does this for parked/freed slot rows, whose device counters sit at
    capacity while the scheduler picks the window from active rows only."""
    if interpret is None:
        interpret = _interpret_default()
    b, h, d = q.shape
    s = k_cache.shape[1]
    num_kv = k_cache.shape[2]
    g = h // num_kv
    blk = min(block_k, s)
    sweep = s if window is None else max(blk, min(window, s))
    num_blocks = pl.cdiv(sweep, blk)
    qg = q.reshape(b, num_kv, g, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, num_blocks),
        in_specs=[
            pl.BlockSpec(
                (1, num_kv, g, d), lambda bi, si, lens: (bi, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, blk, num_kv, d), lambda bi, si, lens: (bi, si, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, blk, num_kv, d), lambda bi, si, lens: (bi, si, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, num_kv, g, d), lambda bi, si, lens: (bi, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((num_kv, g, 1), jnp.float32),
            pltpu.VMEM((num_kv, g, 1), jnp.float32),
            pltpu.VMEM((num_kv, g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, block_k=blk, num_kv=num_kv, scale=d**-0.5
        ),
        out_shape=jax.ShapeDtypeStruct((b, num_kv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(kv_lens.astype(jnp.int32), qg, k_cache, v_cache)
    return out.reshape(b, h, d)


# ---------------------------------------------------------------------------
# Paged decode: q [B, H, D] vs page pool [P, PS, K, D], block tables [B, PPN]
# ---------------------------------------------------------------------------


def _paged_decode_kernel(block_tables_ref, kv_lens_ref, *refs, **kw):
    """Same online-softmax sweep as _decode_kernel; the block-table ref is
    consumed by the BlockSpec index_map (it picks which POOL page each grid
    step DMAs), so the body only needs the ragged lengths."""
    del block_tables_ref
    _decode_kernel(kv_lens_ref, *refs, **kw)


@functools.partial(jax.jit, static_argnames=("pages", "interpret"))
def paged_flash_decode(
    q: jnp.ndarray,  # [B, H, D]
    k_pages: jnp.ndarray,  # [P, PS, K, D] — global page pool
    v_pages: jnp.ndarray,  # [P, PS, K, D]
    block_tables: jnp.ndarray,  # [B, PPN] int32 — logical page i of row b
    kv_lens: jnp.ndarray,  # [B] int32 — valid logical length per row
    *,
    pages: int | None = None,  # static: sweep only the first `pages` pages
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Ragged PAGED one-token GQA decode attention. Returns [B, H, D].

    The grid is (batch, logical_page) and the KV BlockSpec index_map gathers
    each step's page THROUGH the prefetched block table
    (`block_tables[b, i]` picks the pool row to DMA) — attention reads the
    scattered pool directly, no contiguous per-row copy is ever
    materialized. `pages` plays the role of flash_decode's `window`: the
    sweep stops after that many logical pages and rows whose kv_lens extend
    beyond produce garbage the caller must discard (parked/freed slot rows).
    """
    if interpret is None:
        interpret = _interpret_default()
    b, h, d = q.shape
    ps = k_pages.shape[1]
    num_kv = k_pages.shape[2]
    g = h // num_kv
    ppn = block_tables.shape[1]
    sweep = ppn if pages is None else max(1, min(pages, ppn))
    qg = q.reshape(b, num_kv, g, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, sweep),
        in_specs=[
            pl.BlockSpec(
                (1, num_kv, g, d),
                lambda bi, si, tables, lens: (bi, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ps, num_kv, d),
                lambda bi, si, tables, lens: (tables[bi, si], 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ps, num_kv, d),
                lambda bi, si, tables, lens: (tables[bi, si], 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, num_kv, g, d),
            lambda bi, si, tables, lens: (bi, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((num_kv, g, 1), jnp.float32),
            pltpu.VMEM((num_kv, g, 1), jnp.float32),
            pltpu.VMEM((num_kv, g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_decode_kernel, block_k=ps, num_kv=num_kv, scale=d**-0.5
        ),
        out_shape=jax.ShapeDtypeStruct((b, num_kv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(block_tables.astype(jnp.int32), kv_lens.astype(jnp.int32),
      qg, k_pages, v_pages)
    return out.reshape(b, h, d)


# ---------------------------------------------------------------------------
# Quantized paged decode: int8 page pool + per-vector f32 scales. The scale
# arrays [P, PS, K] ride the SAME block-table prefetch as the values (their
# BlockSpec index_map picks the identical pool page per grid step), and each
# KV vector dequantizes in VMEM right before its dot — HBM moved int8 bytes.
# ---------------------------------------------------------------------------


def _paged_decode_quant_kernel(
    block_tables_ref,  # consumed by the index maps
    kv_lens_ref,  # [B] int32 (SMEM)
    q_ref,  # [1, K, G, D]
    k_ref,  # [1, PS, K, D] int8
    ks_ref,  # [1, PS, K] f32
    v_ref,  # [1, PS, K, D] int8
    vs_ref,  # [1, PS, K] f32
    o_ref,  # [1, K, G, D]
    m_ref, l_ref, acc_ref,
    *,
    block_k: int,
    num_kv: int,
    scale: float,
):
    del block_tables_ref
    b = pl.program_id(0)
    s = pl.program_id(1)
    num_blocks = pl.num_programs(1)
    kv_len = kv_lens_ref[b]

    @pl.when(s == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(s * block_k < kv_len)
    def _compute():
        col = s * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), dimension=1
        )
        valid = col < kv_len  # [1, BLK]
        for h in range(num_kv):  # static unroll over KV heads
            q = q_ref[0, h]  # [G, D]
            k = (k_ref[0, :, h, :].astype(jnp.float32)
                 * ks_ref[0, :, h][:, None]).astype(q.dtype)  # [BLK, D]
            v = (v_ref[0, :, h, :].astype(jnp.float32)
                 * vs_ref[0, :, h][:, None]).astype(q.dtype)
            scores = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [G, BLK]
            scores = jnp.where(valid, scores, _NEG_INF)
            _online_update(m_ref, l_ref, acc_ref, h, scores, v)

    @pl.when(s == num_blocks - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("pages", "interpret"))
def paged_flash_decode_quant(
    q: jnp.ndarray,  # [B, H, D]
    k_pages: jnp.ndarray,  # [P, PS, K, D] int8
    k_scales: jnp.ndarray,  # [P, PS, K] f32 — per written K vector
    v_pages: jnp.ndarray,  # [P, PS, K, D] int8
    v_scales: jnp.ndarray,  # [P, PS, K] f32
    block_tables: jnp.ndarray,  # [B, PPN] int32
    kv_lens: jnp.ndarray,  # [B] int32
    *,
    pages: int | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Int8 variant of paged_flash_decode: dequant-on-read inside the
    kernel. Same grid/garbage contract; numerics match the XLA dequant
    fallback (f32 dequant -> q.dtype operands -> f32 accumulation)."""
    if interpret is None:
        interpret = _interpret_default()
    b, h, d = q.shape
    ps = k_pages.shape[1]
    num_kv = k_pages.shape[2]
    g = h // num_kv
    ppn = block_tables.shape[1]
    sweep = ppn if pages is None else max(1, min(pages, ppn))
    qg = q.reshape(b, num_kv, g, d)

    def page_map(bi, si, tables, lens):
        return (tables[bi, si], 0, 0, 0)

    def scale_map(bi, si, tables, lens):
        return (tables[bi, si], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, sweep),
        in_specs=[
            pl.BlockSpec(
                (1, num_kv, g, d),
                lambda bi, si, tables, lens: (bi, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, ps, num_kv, d), page_map,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ps, num_kv), scale_map,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ps, num_kv, d), page_map,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ps, num_kv), scale_map,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, num_kv, g, d),
            lambda bi, si, tables, lens: (bi, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((num_kv, g, 1), jnp.float32),
            pltpu.VMEM((num_kv, g, 1), jnp.float32),
            pltpu.VMEM((num_kv, g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_decode_quant_kernel, block_k=ps, num_kv=num_kv,
            scale=d**-0.5,
        ),
        out_shape=jax.ShapeDtypeStruct((b, num_kv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(block_tables.astype(jnp.int32), kv_lens.astype(jnp.int32),
      qg, k_pages, k_scales, v_pages, v_scales)
    return out.reshape(b, h, d)


# ---------------------------------------------------------------------------
# Prefill: causal q [B, T, H, D] vs fresh k/v [B, T, K, D], ragged prompt_lens
# ---------------------------------------------------------------------------


def _prefill_kernel(
    # scalar prefetch
    prompt_lens_ref,  # [B] int32 (SMEM)
    # inputs
    q_ref,  # [1, BLK_Q, K, G, D]
    k_ref,  # [1, BLK_K, K, D]
    v_ref,  # [1, BLK_K, K, D]
    # output
    o_ref,  # [1, BLK_Q, K, G, D]
    # scratch
    m_ref,  # [K, BLK_Q * G, 1] f32
    l_ref,  # [K, BLK_Q * G, 1] f32
    acc_ref,  # [K, BLK_Q * G, D] f32
    *,
    block_q: int,
    block_k: int,
    num_kv: int,
    groups: int,
    scale: float,
):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k_blocks = pl.num_programs(2)
    prompt_len = prompt_lens_ref[b]

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    rows = block_q * groups
    # causal skip: the whole KV block is in the future of the whole Q block
    not_all_future = k_start <= q_start + block_q - 1
    # ragged skip: the whole KV block is beyond the prompt
    in_prompt = k_start < prompt_len

    @pl.when(jnp.logical_and(not_all_future, in_prompt))
    def _compute():
        row = jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), dimension=0)
        col = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_k), dimension=1
        )
        q_pos = q_start + row // groups
        mask = (col <= q_pos) & (col < prompt_len)
        for h in range(num_kv):  # static unroll over KV heads
            q = q_ref[0, :, h].reshape(rows, -1)  # [BLK_Q*G, D]; t slow, g fast
            k = k_ref[0, :, h, :]  # [BLK_K, D]
            v = v_ref[0, :, h, :]
            scores = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [BLK_Q*G, BLK_K]
            scores = jnp.where(mask, scores, _NEG_INF)
            _online_update(m_ref, l_ref, acc_ref, h, scores, v)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = (acc_ref[:] / l_safe).astype(o_ref.dtype)  # [K, BLK_Q*G, D]
        o_ref[0] = out.reshape(num_kv, block_q, groups, -1).transpose(1, 0, 2, 3)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "interpret")
)
def flash_prefill(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,  # [B, T, K, D]
    v: jnp.ndarray,  # [B, T, K, D]
    prompt_lens: jnp.ndarray,  # [B] int32
    *,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Causal ragged GQA prefill attention. Returns [B, T, H, D] in q.dtype."""
    if interpret is None:
        interpret = _interpret_default()
    b, t, h, d = q.shape
    num_kv = k.shape[2]
    g = h // num_kv
    blk_q = min(block_q, t)
    blk_k = min(block_k, t)
    grid = (b, pl.cdiv(t, blk_q), pl.cdiv(t, blk_k))
    qg = q.reshape(b, t, num_kv, g, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, blk_q, num_kv, g, d),
                lambda bi, qi, si, lens: (bi, qi, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, blk_k, num_kv, d), lambda bi, qi, si, lens: (bi, si, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, blk_k, num_kv, d), lambda bi, qi, si, lens: (bi, si, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, blk_q, num_kv, g, d),
            lambda bi, qi, si, lens: (bi, qi, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((num_kv, blk_q * g, 1), jnp.float32),
            pltpu.VMEM((num_kv, blk_q * g, 1), jnp.float32),
            pltpu.VMEM((num_kv, blk_q * g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _prefill_kernel,
            block_q=blk_q,
            block_k=blk_k,
            num_kv=num_kv,
            groups=g,
            scale=d**-0.5,
        ),
        out_shape=jax.ShapeDtypeStruct((b, t, num_kv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(prompt_lens.astype(jnp.int32), qg, k, v)
    return out.reshape(b, t, h, d)


# ---------------------------------------------------------------------------
# Extend (chunked prefill): q chunk [B, T, H, D] vs slot cache [B, S, K, D],
# chunk starts at global position start_pos[b] (contiguous positions).
# ---------------------------------------------------------------------------


def _extend_kernel(
    # scalar prefetch
    start_pos_ref,  # [B] int32 (SMEM) — global position of the chunk's 1st query
    chunk_lens_ref,  # [B] int32 (SMEM) — valid queries in the chunk
    # inputs
    q_ref,  # [1, BLK_Q, K, G, D]
    k_ref,  # [1, BLK_K, K, D]  (cache block)
    v_ref,  # [1, BLK_K, K, D]
    # output
    o_ref,  # [1, BLK_Q, K, G, D]
    # scratch
    m_ref,  # [K, BLK_Q * G, 1] f32
    l_ref,  # [K, BLK_Q * G, 1] f32
    acc_ref,  # [K, BLK_Q * G, D] f32
    *,
    block_q: int,
    block_k: int,
    num_kv: int,
    groups: int,
    scale: float,
):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k_blocks = pl.num_programs(2)
    start = start_pos_ref[b]

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    rows = block_q * groups
    # Skip KV blocks entirely in the future of every query in this Q block
    # (query global positions are start + q_start .. start + q_start+BLK_Q-1),
    # so extend cost scales with the context actually filled, not capacity;
    # also skip Q blocks made entirely of padding rows (beyond chunk_lens) —
    # their zero-initialized output is ignored by the caller.
    useful = jnp.logical_and(
        k_start <= start + q_start + block_q - 1,
        q_start < chunk_lens_ref[b],
    )

    @pl.when(useful)
    def _compute():
        row = jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), dimension=0)
        col = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_k), dimension=1
        )
        q_pos = start + q_start + row // groups  # global position per query
        mask = col <= q_pos
        for h in range(num_kv):  # static unroll over KV heads
            q = q_ref[0, :, h].reshape(rows, -1)  # [BLK_Q*G, D]
            k = k_ref[0, :, h, :]  # [BLK_K, D]
            v = v_ref[0, :, h, :]
            scores = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            scores = jnp.where(mask, scores, _NEG_INF)
            _online_update(m_ref, l_ref, acc_ref, h, scores, v)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        o_ref[0] = out.reshape(num_kv, block_q, groups, -1).transpose(1, 0, 2, 3)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "interpret")
)
def flash_extend(
    q: jnp.ndarray,  # [B, T, H, D] — chunk of queries
    k_cache: jnp.ndarray,  # [B, S, K, D] — slot rows incl. this chunk's keys
    v_cache: jnp.ndarray,  # [B, S, K, D]
    start_pos: jnp.ndarray,  # [B] int32 — global position of the first query
    chunk_lens: jnp.ndarray,  # [B] int32 — valid queries (rest are padding)
    *,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Chunked-prefill attention: T contiguous queries starting at global
    position start_pos[b] attend causally over the slot cache (earlier chunks
    + this chunk). Pallas counterpart of ops.attention.gqa_attention_extend
    for the engine's long-prompt path. Returns [B, T, H, D] in q.dtype."""
    if interpret is None:
        interpret = _interpret_default()
    b, t, h, d = q.shape
    s = k_cache.shape[1]
    num_kv = k_cache.shape[2]
    g = h // num_kv
    blk_q = min(block_q, t)
    blk_k = min(block_k, s)
    grid = (b, pl.cdiv(t, blk_q), pl.cdiv(s, blk_k))
    qg = q.reshape(b, t, num_kv, g, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, blk_q, num_kv, g, d),
                lambda bi, qi, si, starts, lens: (bi, qi, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, blk_k, num_kv, d),
                lambda bi, qi, si, starts, lens: (bi, si, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, blk_k, num_kv, d),
                lambda bi, qi, si, starts, lens: (bi, si, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, blk_q, num_kv, g, d),
            lambda bi, qi, si, starts, lens: (bi, qi, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((num_kv, blk_q * g, 1), jnp.float32),
            pltpu.VMEM((num_kv, blk_q * g, 1), jnp.float32),
            pltpu.VMEM((num_kv, blk_q * g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _extend_kernel,
            block_q=blk_q,
            block_k=blk_k,
            num_kv=num_kv,
            groups=g,
            scale=d**-0.5,
        ),
        out_shape=jax.ShapeDtypeStruct((b, t, num_kv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(start_pos.astype(jnp.int32), chunk_lens.astype(jnp.int32),
      qg, k_cache, v_cache)
    return out.reshape(b, t, h, d)


# ---------------------------------------------------------------------------
# Paged extend (chunked prefill): q chunk [B, T, H, D] vs page pool
# [P, PS, K, D] through block tables [B, PPN]; chunk starts at start_pos[b].
# ---------------------------------------------------------------------------


def _paged_extend_kernel(block_tables_ref, start_pos_ref, chunk_lens_ref,
                         *refs, **kw):
    """Same masked sweep as _extend_kernel; logical KV position of grid step
    `ki` is ki * page_size because the index_map walks the block table in
    logical order — the body never needs the table itself."""
    del block_tables_ref
    _extend_kernel(start_pos_ref, chunk_lens_ref, *refs, **kw)


@functools.partial(jax.jit, static_argnames=("block_q", "interpret"))
def paged_flash_extend(
    q: jnp.ndarray,  # [B, T, H, D] — chunk of queries
    k_pages: jnp.ndarray,  # [P, PS, K, D] — global page pool
    v_pages: jnp.ndarray,  # [P, PS, K, D]
    block_tables: jnp.ndarray,  # [B, PPN] int32
    start_pos: jnp.ndarray,  # [B] int32 — global position of the first query
    chunk_lens: jnp.ndarray,  # [B] int32 — valid queries (rest are padding)
    *,
    block_q: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Paged chunked-prefill attention: T contiguous queries starting at
    global position start_pos[b] attend causally over row b's pages (earlier
    chunks + this chunk), gathered through the prefetched block table by the
    KV BlockSpec index_map. KV blocks entirely in the future of the chunk
    skip their FLOPs (`pl.when` in _extend_kernel), so cost scales with the
    context actually filled, not pool capacity. Returns [B, T, H, D]."""
    if interpret is None:
        interpret = _interpret_default()
    b, t, h, d = q.shape
    ps = k_pages.shape[1]
    num_kv = k_pages.shape[2]
    g = h // num_kv
    ppn = block_tables.shape[1]
    blk_q = min(block_q, t)
    grid = (b, pl.cdiv(t, blk_q), ppn)
    qg = q.reshape(b, t, num_kv, g, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, blk_q, num_kv, g, d),
                lambda bi, qi, si, tables, starts, lens: (bi, qi, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ps, num_kv, d),
                lambda bi, qi, si, tables, starts, lens:
                    (tables[bi, si], 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ps, num_kv, d),
                lambda bi, qi, si, tables, starts, lens:
                    (tables[bi, si], 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, blk_q, num_kv, g, d),
            lambda bi, qi, si, tables, starts, lens: (bi, qi, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((num_kv, blk_q * g, 1), jnp.float32),
            pltpu.VMEM((num_kv, blk_q * g, 1), jnp.float32),
            pltpu.VMEM((num_kv, blk_q * g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_extend_kernel,
            block_q=blk_q,
            block_k=ps,
            num_kv=num_kv,
            groups=g,
            scale=d**-0.5,
        ),
        out_shape=jax.ShapeDtypeStruct((b, t, num_kv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(block_tables.astype(jnp.int32), start_pos.astype(jnp.int32),
      chunk_lens.astype(jnp.int32), qg, k_pages, v_pages)
    return out.reshape(b, t, h, d)


# ---------------------------------------------------------------------------
# Quantized paged extend: int8 pool + per-vector scales, dequant-on-read —
# the verify/chunked-prefill counterpart of paged_flash_decode_quant.
# ---------------------------------------------------------------------------


def _paged_extend_quant_kernel(
    block_tables_ref,  # consumed by the index maps
    start_pos_ref,  # [B] int32 (SMEM)
    chunk_lens_ref,  # [B] int32 (SMEM)
    q_ref,  # [1, BLK_Q, K, G, D]
    k_ref,  # [1, PS, K, D] int8
    ks_ref,  # [1, PS, K] f32
    v_ref,  # [1, PS, K, D] int8
    vs_ref,  # [1, PS, K] f32
    o_ref,  # [1, BLK_Q, K, G, D]
    m_ref, l_ref, acc_ref,
    *,
    block_q: int,
    block_k: int,
    num_kv: int,
    groups: int,
    scale: float,
):
    del block_tables_ref
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k_blocks = pl.num_programs(2)
    start = start_pos_ref[b]

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    rows = block_q * groups
    useful = jnp.logical_and(
        k_start <= start + q_start + block_q - 1,
        q_start < chunk_lens_ref[b],
    )

    @pl.when(useful)
    def _compute():
        row = jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), dimension=0)
        col = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_k), dimension=1
        )
        q_pos = start + q_start + row // groups
        mask = col <= q_pos
        for h in range(num_kv):  # static unroll over KV heads
            q = q_ref[0, :, h].reshape(rows, -1)  # [BLK_Q*G, D]
            k = (k_ref[0, :, h, :].astype(jnp.float32)
                 * ks_ref[0, :, h][:, None]).astype(q.dtype)  # [BLK_K, D]
            v = (v_ref[0, :, h, :].astype(jnp.float32)
                 * vs_ref[0, :, h][:, None]).astype(q.dtype)
            scores = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            scores = jnp.where(mask, scores, _NEG_INF)
            _online_update(m_ref, l_ref, acc_ref, h, scores, v)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        o_ref[0] = out.reshape(num_kv, block_q, groups, -1).transpose(1, 0, 2, 3)


@functools.partial(jax.jit, static_argnames=("block_q", "interpret"))
def paged_flash_extend_quant(
    q: jnp.ndarray,  # [B, T, H, D] — chunk of queries
    k_pages: jnp.ndarray,  # [P, PS, K, D] int8
    k_scales: jnp.ndarray,  # [P, PS, K] f32
    v_pages: jnp.ndarray,  # [P, PS, K, D] int8
    v_scales: jnp.ndarray,  # [P, PS, K] f32
    block_tables: jnp.ndarray,  # [B, PPN] int32
    start_pos: jnp.ndarray,  # [B] int32
    chunk_lens: jnp.ndarray,  # [B] int32
    *,
    block_q: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Int8 variant of paged_flash_extend: scales gather through the same
    prefetched block table and each page's vectors dequantize in VMEM.
    Same causal/ragged skip logic and garbage contract."""
    if interpret is None:
        interpret = _interpret_default()
    b, t, h, d = q.shape
    ps = k_pages.shape[1]
    num_kv = k_pages.shape[2]
    g = h // num_kv
    ppn = block_tables.shape[1]
    blk_q = min(block_q, t)
    grid = (b, pl.cdiv(t, blk_q), ppn)
    qg = q.reshape(b, t, num_kv, g, d)

    def page_map(bi, qi, si, tables, starts, lens):
        return (tables[bi, si], 0, 0, 0)

    def scale_map(bi, qi, si, tables, starts, lens):
        return (tables[bi, si], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, blk_q, num_kv, g, d),
                lambda bi, qi, si, tables, starts, lens: (bi, qi, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, ps, num_kv, d), page_map,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ps, num_kv), scale_map,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ps, num_kv, d), page_map,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ps, num_kv), scale_map,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, blk_q, num_kv, g, d),
            lambda bi, qi, si, tables, starts, lens: (bi, qi, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((num_kv, blk_q * g, 1), jnp.float32),
            pltpu.VMEM((num_kv, blk_q * g, 1), jnp.float32),
            pltpu.VMEM((num_kv, blk_q * g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_extend_quant_kernel,
            block_q=blk_q,
            block_k=ps,
            num_kv=num_kv,
            groups=g,
            scale=d**-0.5,
        ),
        out_shape=jax.ShapeDtypeStruct((b, t, num_kv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(block_tables.astype(jnp.int32), start_pos.astype(jnp.int32),
      chunk_lens.astype(jnp.int32), qg, k_pages, k_scales, v_pages, v_scales)
    return out.reshape(b, t, h, d)
