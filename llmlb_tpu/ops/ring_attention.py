"""Ring attention: sequence-parallel causal prefill over an `sp` mesh axis.

Long-context prefill is the one place where a single chip's HBM cannot hold the
working set (activations + KV for 128k+ tokens). The TPU-native answer is
sequence parallelism: shard the token axis over `sp` devices and rotate KV
blocks around the ring with `lax.ppermute` while each device keeps its query
chunk resident. Attention statistics are merged with the online-softmax
recurrence (running max / running sum), so the result is bit-comparable to
dense softmax attention up to float associativity.

Communication pattern (per layer): sp-1 ppermute hops of the local KV block
([B, T/sp, K, D] each) — nearest-neighbour ICI traffic that overlaps with the
per-block QK^T/PV matmuls on the MXU. This is the standard ring-attention
schedule (Liu et al., see PAPERS.md); causality means on average half the
blocks are fully masked for a given query chunk. We still traverse the full
ring (static schedule — XLA requires it) but skip the FLOPs for fully-masked
blocks via `lax.cond`-free masking, which XLA folds into the einsum when the
block contributes nothing.

The reference gateway has no sequence parallelism of any kind (SURVEY.md §2.4,
§5 "long-context: absent") — this subsystem is new TPU-first design required by
the north star (BASELINE.json long-context configs).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

_NEG_INF = -1e30  # finite: fully-masked rows must still produce softmax-able sums


def _ring_attention_local(
    q: jnp.ndarray,  # [B, C, H, D] local query chunk (C = T / sp)
    k: jnp.ndarray,  # [B, C, K, D] local key chunk
    v: jnp.ndarray,  # [B, C, K, D] local value chunk
    prompt_lens: jnp.ndarray,  # [B] int32, replicated — global valid lengths
    *,
    axis_name: str,
    axis_size: int,
) -> jnp.ndarray:
    """Per-device ring attention body (runs inside shard_map)."""
    b, c, h, d = q.shape
    kh = k.shape[2]
    g = h // kh
    scale = d**-0.5

    rank = lax.axis_index(axis_name)
    q_pos = rank * c + jnp.arange(c, dtype=jnp.int32)  # [C] global query positions
    qg = q.reshape(b, c, kh, g, d)

    # Online-softmax state, all fp32: running max m, running sum l, accum o.
    m = jnp.full((b, kh, g, c), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, kh, g, c), jnp.float32)
    o = jnp.zeros((b, c, kh, g, d), jnp.float32)

    # Ring schedule: at step s each device holds the KV block originally owned
    # by rank (rank - s) mod sp. The loop is a static Python unroll — sp is a
    # small static mesh dim, and a static perm lets XLA pipeline ppermute with
    # the matmuls of the next step.
    fwd_perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    for s in range(axis_size):
        src = (rank - s) % axis_size
        k_pos = src * c + jnp.arange(c, dtype=jnp.int32)  # [C] global key positions

        scores = jnp.einsum(
            "bckgd,bskd->bkgcs", qg, k, preferred_element_type=jnp.float32
        ) * scale  # [B, K, G, C, Ck]

        causal = q_pos[:, None] >= k_pos[None, :]  # [C, Ck]
        valid = k_pos[None, :] < prompt_lens[:, None]  # [B, Ck]
        mask = causal[None, :, :] & valid[:, None, :]  # [B, C, Ck]
        scores = jnp.where(mask[:, None, None, :, :], scores, _NEG_INF)

        m_new = jnp.maximum(m, scores.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])  # [B, K, G, C, Ck]
        l = l * corr + p.sum(axis=-1)
        pv = jnp.einsum(
            "bkgcs,bskd->bckgd", p.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
        o = o * jnp.moveaxis(corr, -1, 1)[..., None] + pv
        m = m_new

        if s != axis_size - 1:  # last block needs no forwarding
            k = lax.ppermute(k, axis_name, fwd_perm)
            v = lax.ppermute(v, axis_name, fwd_perm)

    # Normalize; guard fully-masked rows (padding queries) against 0/0.
    l_safe = jnp.maximum(jnp.moveaxis(l, -1, 1)[..., None], 1e-30)
    out = o / l_safe
    return out.reshape(b, c, h, d).astype(q.dtype)


def ring_prefill_attention(
    q: jnp.ndarray,  # [B, T, H, D] — T divisible by mesh sp
    k: jnp.ndarray,  # [B, T, K, D]
    v: jnp.ndarray,  # [B, T, K, D]
    prompt_lens: jnp.ndarray,  # [B] int32
    mesh: Mesh,
    *,
    seq_axis: str = "sp",
    batch_axis: str | None = "dp",
    head_axis: str | None = "tp",
    kv_head_axis: str | None = "unset",
) -> jnp.ndarray:
    """Causal GQA prefill attention, sequence-sharded over `seq_axis`.

    Drop-in equal to ops.attention.gqa_attention_prefill (same [B, T, H, D] in/
    out), but the sequence axis lives sharded across the ring — the full T×T
    score matrix never materializes on any one chip. Composes with batch
    sharding over `batch_axis` and head (tensor-parallel) sharding over
    `head_axis`: ppermute only rotates within each (dp, tp) fiber.
    """
    sp = mesh.shape[seq_axis]
    if q.shape[1] % sp:
        raise ValueError(f"seq len {q.shape[1]} not divisible by sp={sp}")
    if kv_head_axis == "unset":  # kv heads replicate when tp exceeds their count
        kv_head_axis = head_axis
    q_spec = P(batch_axis, seq_axis, head_axis, None)
    kv_spec = P(batch_axis, seq_axis, kv_head_axis, None)
    fn = shard_map(
        partial(_ring_attention_local, axis_name=seq_axis, axis_size=sp),
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, P(batch_axis)),
        out_specs=q_spec,
        check_rep=False,
    )
    return fn(q, k, v, prompt_lens)
