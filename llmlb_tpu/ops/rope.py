"""Rotary position embeddings (interleaved-half convention, HF-compatible).

Supports plain RoPE (Llama-2/Qwen/Mistral) and Llama-3 frequency scaling.
Frequencies are computed from integer positions at trace time — no precomputed
table in HBM, XLA fuses the sin/cos into the surrounding elementwise graph.
"""

from __future__ import annotations

import dataclasses
import math

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Llama-3-style rope scaling (factor-based NTK with wavelength thresholds)."""

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position: int = 8192


def rope_frequencies(
    head_dim: int,
    theta: float = 10000.0,
    scaling: RopeScaling | None = None,
) -> jnp.ndarray:
    """Per-pair inverse frequencies, shape [head_dim // 2], float32."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta**exponents)
    if scaling is not None:
        low_wl = scaling.original_max_position / scaling.low_freq_factor
        high_wl = scaling.original_max_position / scaling.high_freq_factor
        wavelen = 2.0 * math.pi / inv_freq
        smooth = (scaling.original_max_position / wavelen - scaling.low_freq_factor) / (
            scaling.high_freq_factor - scaling.low_freq_factor
        )
        smooth = jnp.clip(smooth, 0.0, 1.0)
        scaled = inv_freq / scaling.factor
        blended = (1.0 - smooth) * scaled + smooth * inv_freq
        inv_freq = jnp.where(
            wavelen > low_wl, scaled, jnp.where(wavelen < high_wl, inv_freq, blended)
        )
    return inv_freq


def apply_rope(
    x: jnp.ndarray,  # [B, T, H, D]
    positions: jnp.ndarray,  # [B, T] int32
    inv_freq: jnp.ndarray,  # [D // 2]
) -> jnp.ndarray:
    """Rotate q or k by position. Split-half (rotate_half) layout, as HF Llama."""
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B, T, D/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [B, T, 1, D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    rotated = jnp.concatenate((x1 * cos - x2 * sin, x2 * cos + x1 * sin), axis=-1)
    return rotated.astype(x.dtype)
