"""Token sampling on-device: greedy / temperature / top-k / top-p in one jittable op.

All sampling parameters are traced arrays (per-request, shape [B]) so one compiled
decode step serves every request mix — no recompile when a user changes
temperature. Top-p runs inside a static top-K=64 prefilter: a full 128k-vocab sort
per step would thrash HBM bandwidth for no quality gain (p-mass beyond the top 64
logits is negligible at serving temperatures).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

TOPK_PREFILTER = 64


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] float32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B] float32; 0 => greedy
    top_p: jnp.ndarray,  # [B] float32 in (0, 1]
    top_k: jnp.ndarray,  # [B] int32; 0 => disabled. NOTE: the candidate pool is
    # always capped at TOPK_PREFILTER=64, so top_k values above 64 (and "disabled")
    # clamp to 64 — an intentional serving trade-off, see module docstring.
) -> jnp.ndarray:
    """Returns sampled token ids [B] int32."""
    b, v = logits.shape
    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    k = min(TOPK_PREFILTER, v)
    top_logits, top_ids = jax.lax.top_k(logits, k)  # [B, k] sorted desc

    # top-k restriction (within the prefilter window)
    ranks = jnp.arange(k, dtype=jnp.int32)[None, :]
    eff_top_k = jnp.where(top_k <= 0, k, jnp.minimum(top_k, k))[:, None]
    top_logits = jnp.where(ranks < eff_top_k, top_logits, -jnp.inf)

    # temperature
    safe_temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = top_logits / safe_temp

    # top-p (nucleus) over the sorted window
    probs = jax.nn.softmax(scaled, axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    # keep tokens whose cumulative mass *before* them is < top_p (always keep rank 0)
    keep = (cumulative - probs) < top_p[:, None]
    scaled = jnp.where(keep, scaled, -jnp.inf)

    sampled_idx = jax.random.categorical(key, scaled, axis=-1)  # [B] in [0, k)
    sampled_ids = jnp.take_along_axis(top_ids, sampled_idx[:, None], axis=-1)[:, 0]

    return jnp.where(temperature <= 0.0, greedy_ids, sampled_ids.astype(jnp.int32))
