"""Token sampling on-device: greedy / temperature / top-k / top-p in one jittable op.

All sampling parameters are traced arrays (per-request, shape [B]) so one compiled
decode step serves every request mix — no recompile when a user changes
temperature. Top-p runs inside a static top-K=64 prefilter: a full 128k-vocab sort
per step would thrash HBM bandwidth for no quality gain (p-mass beyond the top 64
logits is negligible at serving temperatures).

Two per-request extensions ride the same traced-input discipline (no recompile
per request mix):

- `mask_bias` [B, V]: additive grammar-constraint bias (0 allowed / -1e30
  blocked, llmlb_tpu/structured). Applied to the FULL logits BEFORE the top-k
  prefilter and before the greedy argmax — an allowed set living entirely
  outside the unconstrained top-64 must still be sampleable, so masking after
  the prefilter would leave all-blocked rows.
- `seeds`/`steps` [B]: per-request deterministic sampling. Rows with
  seed >= 0 draw from fold_in(PRNGKey(seed), step) instead of the shared
  batch key, so a seeded request reproduces its token sequence regardless of
  which other requests share the batch. Rows with seed < 0 are bit-identical
  to the shared-key path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

TOPK_PREFILTER = 64


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] float32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B] float32; 0 => greedy
    top_p: jnp.ndarray,  # [B] float32 in (0, 1]
    top_k: jnp.ndarray,  # [B] int32; 0 => disabled. NOTE: the candidate pool is
    # always capped at TOPK_PREFILTER=64, so top_k values above 64 (and "disabled")
    # clamp to 64 — an intentional serving trade-off, see module docstring.
    mask_bias: jnp.ndarray | None = None,  # [B, V] float32 additive, or None
    seeds: jnp.ndarray | None = None,  # [B] int32; < 0 => shared batch key
    steps: jnp.ndarray | None = None,  # [B] int32 position for the seed fold
) -> jnp.ndarray:
    """Returns sampled token ids [B] int32."""
    if mask_bias is not None:
        # BEFORE argmax and BEFORE the prefilter: greedy and stochastic paths
        # both see only allowed tokens.
        logits = logits + mask_bias
    b, v = logits.shape
    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    k = min(TOPK_PREFILTER, v)
    top_logits, top_ids = jax.lax.top_k(logits, k)  # [B, k] sorted desc

    # top-k restriction (within the prefilter window)
    ranks = jnp.arange(k, dtype=jnp.int32)[None, :]
    eff_top_k = jnp.where(top_k <= 0, k, jnp.minimum(top_k, k))[:, None]
    top_logits = jnp.where(ranks < eff_top_k, top_logits, -jnp.inf)

    # temperature
    safe_temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = top_logits / safe_temp

    # top-p (nucleus) over the sorted window
    probs = jax.nn.softmax(scaled, axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    # keep tokens whose cumulative mass *before* them is < top_p (always keep rank 0)
    keep = (cumulative - probs) < top_p[:, None]
    scaled = jnp.where(keep, scaled, -jnp.inf)

    sampled_idx = jax.random.categorical(key, scaled, axis=-1)  # [B] in [0, k)
    if seeds is not None:
        step_idx = (steps if steps is not None
                    else jnp.zeros_like(seeds)).astype(jnp.uint32)
        def _row_key(seed, step):
            return jax.random.fold_in(
                jax.random.PRNGKey(jnp.maximum(seed, 0)), step
            )
        row_keys = jax.vmap(_row_key)(seeds, step_idx)
        seeded_idx = jax.vmap(jax.random.categorical)(row_keys, scaled)
        sampled_idx = jnp.where(seeds >= 0, seeded_idx, sampled_idx)
    sampled_ids = jnp.take_along_axis(top_ids, sampled_idx[:, None], axis=-1)[:, 0]

    return jnp.where(temperature <= 0.0, greedy_ids, sampled_ids.astype(jnp.int32))
