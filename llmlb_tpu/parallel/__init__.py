from llmlb_tpu.parallel.mesh import MeshConfig, build_mesh
from llmlb_tpu.parallel.sharding import ShardingRules, logical_to_sharding

__all__ = ["MeshConfig", "build_mesh", "ShardingRules", "logical_to_sharding"]
