"""Multi-host serving: jax.distributed bring-up + DCN-aware hybrid meshes.

The reference's distributed story is HTTP between gateway and runtimes
(SURVEY.md §2.4: "no NCCL/MPI/Gloo anywhere") — multi-host model execution is
TPU-native new design. The shape of it:

- Each host (TPU slice worker) runs one engine process; `init_from_env()`
  brings up `jax.distributed` so all processes see one global device set.
- `build_hybrid_mesh()` lays DCN-crossing axes (dp replicas, ep experts)
  OUTSIDE the ICI axes (sp, tp), so latency-critical collectives (tp
  all-reduce every layer, sp ring ppermute) ride ICI and only
  high-arithmetic-intensity or per-request work crosses DCN — the
  BASELINE.json config #5 (Mixtral multi-slice) layout.
- On real multi-slice TPU, device "slices" drive the DCN grouping; in the
  CPU simulation used by tests and the driver dry-run, process boundaries
  stand in for slices (`process_is_granule`).

Spawned 2-host CPU simulation: `python -m llmlb_tpu.parallel.distributed
--selftest` (used by __graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

import logging
import os

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from llmlb_tpu.parallel.mesh import MeshConfig

log = logging.getLogger("llmlb_tpu.parallel.distributed")


def init_from_env() -> bool:
    """Initialize jax.distributed from LLMLB_* env (returns True if it ran).

    LLMLB_COORDINATOR=host:port, LLMLB_NUM_HOSTS, LLMLB_HOST_ID configure the
    cluster explicitly; on Cloud TPU pods, calling with no variables set but
    LLMLB_DISTRIBUTED=1 lets JAX autodetect from the TPU metadata. Must run
    before the first backend use."""
    coordinator = os.environ.get("LLMLB_COORDINATOR")
    num_hosts = int(os.environ.get("LLMLB_NUM_HOSTS", "0") or 0)
    if coordinator and num_hosts > 1:
        host_id = int(os.environ.get("LLMLB_HOST_ID", "0"))
        log.info("jax.distributed: %s host %d/%d",
                 coordinator, host_id, num_hosts)
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_hosts,
            process_id=host_id,
        )
        return True
    if os.environ.get("LLMLB_DISTRIBUTED") == "1":
        log.info("jax.distributed: TPU-pod autodetect")
        jax.distributed.initialize()
        return True
    return False


def build_hybrid_mesh(
    ici: MeshConfig,
    *,
    dcn_dp: int = 1,
    dcn_ep: int = 1,
    devices=None,
) -> Mesh:
    """(dp, sp, ep, tp) mesh whose dp/ep axes may span slices over DCN.

    `ici` sizes the within-slice axes (dp, sp, ep, tp — resolved against the
    per-slice device count); `dcn_dp`/`dcn_ep` multiply dp/ep across slices.
    sp and tp never cross DCN: a per-layer all-reduce (tp) or per-block
    ppermute (sp) over DCN would serialize every step on millisecond RTTs,
    while dp (independent requests) and ep (one a2a per MoE layer, large
    messages) tolerate it.
    """
    devices = list(devices if devices is not None else jax.devices())
    n_slices = dcn_dp * dcn_ep
    per_slice = len(devices) // n_slices
    ici = ici.resolve(per_slice)
    # CPU simulation has no slice topology (devices either lack slice_index
    # or all report the same slice): fall back to process boundaries as the
    # DCN granule.
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    granule = (None in slice_ids) or len(slice_ids) < n_slices
    dev_array = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(ici.dp, ici.sp, ici.ep, ici.tp),
        dcn_mesh_shape=(dcn_dp, 1, dcn_ep, 1),
        devices=devices,
        process_is_granule=granule,
    )
    return Mesh(dev_array, axis_names=("dp", "sp", "ep", "tp"))


# ---------------------------------------------------------------------------
# 2-host CPU self-test (spawned by __graft_entry__.dryrun_multichip)
# ---------------------------------------------------------------------------


def _selftest_worker(process_id: int, num_hosts: int, port: int,
                     devices_per_host: int) -> None:
    """One simulated host: join the cluster, build a hybrid mesh with dp
    across DCN, and run the Mixtral-tiny sharded serving step (BASELINE
    config #5's multi-slice MoE layout at CI size)."""
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=num_hosts,
        process_id=process_id,
    )
    assert jax.device_count() == num_hosts * devices_per_host
    import jax.numpy as jnp

    from llmlb_tpu.engine.presets import get_preset
    from llmlb_tpu.models import mixtral
    from llmlb_tpu.parallel.mesh import default_tp

    cfg = get_preset("debug-moe-tiny")
    # replicas across hosts (DCN), experts + tp inside each host (ICI);
    # gcd keeps ep dividing both the per-host device count and the expert
    # count for any host size
    import math

    per_host = devices_per_host
    ep = math.gcd(per_host, cfg.num_experts)
    tp = default_tp(per_host // ep, cfg.num_heads, cfg.num_kv_heads)
    mesh = build_hybrid_mesh(
        MeshConfig(dp=per_host // (ep * tp), ep=ep, tp=tp),
        dcn_dp=num_hosts,
    )

    params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
    sh = mixtral.param_shardings(cfg, mesh)
    params = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
    dp_total = mesh.shape["dp"]
    batch = 2 * dp_total
    ck, cv = mixtral.init_kv_cache(cfg, batch, 16)
    ck_sh, cv_sh = mixtral.kv_cache_shardings(cfg, mesh)
    ck, cv = jax.device_put(ck, ck_sh), jax.device_put(cv, cv_sh)
    ids = jax.random.randint(jax.random.PRNGKey(1), (batch, 8), 0,
                             cfg.vocab_size)
    lens = jnp.full((batch,), 8, jnp.int32)

    logits, ck, cv = mixtral.prefill(params, cfg, ids, lens, ck, cv, mesh)
    tok = jnp.argmax(logits, -1).astype(jnp.int32)
    logits, ck, cv = mixtral.decode_step(params, cfg, tok, lens, ck, cv, mesh)
    # logits span non-addressable devices; reduce to a (replicated) scalar
    # before fetching — each process may only read its local shards
    finite = bool(jax.jit(lambda x: jnp.isfinite(x).all())(logits))
    assert finite, "non-finite logits on hybrid mesh"
    if process_id == 0:
        print(
            f"multihost selftest OK: {num_hosts} hosts x {devices_per_host} "
            f"devices, mesh dp={dp_total} (dcn x ici) ep={mesh.shape['ep']} "
            f"tp={mesh.shape['tp']}, MoE prefill+decode finite",
            flush=True,
        )


def selftest_requests(cfg):
    """The canonical request set for engine lockstep equivalence checks —
    shared by _engine_worker and the single-host baseline in tests so the
    comparison stays structural, not copy-paste."""
    from llmlb_tpu.engine.scheduler import Request, SamplingParams

    rng = np.random.default_rng(11)
    return [
        Request(
            prompt_ids=list(rng.integers(1, cfg.vocab_size, size=(12,))),
            sampling=SamplingParams(temperature=0.0, max_tokens=6),
        )
        for _ in range(2)
    ]


def collect_tokens(reqs, timeout: float = 240.0) -> list[list[int]]:
    outs = []
    for r in reqs:
        toks = []
        while True:
            kind, val = r.events.get(timeout=timeout)
            if kind == "token":
                toks.append(int(val))
            elif kind == "done":
                break
            else:
                raise AssertionError(f"engine error: {val}")
        outs.append(toks)
    return outs


def _engine_worker(process_id: int, num_hosts: int, port: int,
                   devices_per_host: int) -> None:
    """Lockstep serving across hosts: every process builds the same
    EngineCore over the global device mesh; the leader submits requests and
    the tick-plan broadcast (engine/multihost.py) keeps followers
    dispatching the identical collective programs. Prints the greedy tokens
    so the parent can compare with a single-host run."""
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=num_hosts,
        process_id=process_id,
    )
    from llmlb_tpu.engine.presets import get_preset
    from llmlb_tpu.engine.scheduler import EngineCore

    cfg = get_preset("debug-tiny")
    core = EngineCore(cfg, num_slots=2, slot_capacity=64,
                      prefill_buckets=(16,), seed=0)
    assert (core.coordinator is not None) and (
        core.coordinator.is_leader == (process_id == 0)
    )
    core.start()
    if process_id == 0:
        try:
            reqs = selftest_requests(cfg)
            for r in reqs:
                core.submit(r)
            outs = collect_tokens(reqs)
            print(f"ENGINE_TOKENS {outs!r}", flush=True)
        finally:
            core.stop()  # broadcasts shutdown; followers exit their loops
    else:
        # Follower: the step thread runs the lockstep loop until the leader
        # broadcasts stop — park until then (stopping locally would desync
        # the cluster and strand the leader in its next exchange).
        core._thread.join()
        core.stop()
        print("follower exited cleanly", flush=True)


def run_multihost_selftest(num_hosts: int = 2, devices_per_host: int = 4,
                           timeout_s: float = 300.0,
                           mode: str = "--worker") -> None:
    """Spawn `num_hosts` CPU processes that form a jax.distributed cluster
    and execute a DCN-aware sharded step: mode "--worker" runs the hybrid-
    mesh MoE step, "--engine-worker" runs the full lockstep EngineCore and
    returns the leader's greedy tokens. Raises on any failure."""
    import socket
    import subprocess
    import sys

    def fresh_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    import time as _time

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices_per_host}"
    env.pop("PYTHONSTARTUP", None)

    def spawn_round() -> list:
        port = fresh_port()
        return [
            subprocess.Popen(
                [sys.executable, "-m", "llmlb_tpu.parallel.distributed",
                 mode, str(pid), str(num_hosts), str(port),
                 str(devices_per_host)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
            for pid in range(num_hosts)
        ]

    deadline = _time.monotonic() + timeout_s  # shared: the whole cluster
    # The bind-then-close port probe is racy (another process can claim the
    # port before the coordinator binds it) — retry with a fresh port when
    # the failure is the coordinator bind, not the code under test.
    for attempt in range(3):
        procs = spawn_round()
        outs = []
        for p in procs:
            try:
                out, err = p.communicate(
                    timeout=max(1.0, deadline - _time.monotonic())
                )
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise RuntimeError("multihost selftest timed out")
            outs.append((p.returncode, out, err))
        failures = [(rc, err) for rc, _, err in outs if rc != 0]
        bind_race = any(
            "address already in use" in err.lower()
            or "failed to bind" in err.lower()
            for _, err in failures
        )
        if failures and bind_race and attempt < 2:
            log.warning("coordinator port race; retrying with a fresh port")
            continue
        break
    for rc, out, err in outs:
        if rc != 0:
            raise RuntimeError(
                f"multihost worker failed (rc={rc}):\n{err[-2000:]}"
            )
    if mode == "--engine-worker":
        import ast

        for _, out, _ in outs:
            for line in out.splitlines():
                if line.startswith("ENGINE_TOKENS "):
                    return ast.literal_eval(line[len("ENGINE_TOKENS "):])
        raise RuntimeError(f"no ENGINE_TOKENS line in worker output: {outs}")
    assert any("multihost selftest OK" in out for _, out, _ in outs), outs


if __name__ == "__main__":
    import sys

    if "--worker" in sys.argv or "--engine-worker" in sys.argv:
        mode = "--worker" if "--worker" in sys.argv else "--engine-worker"
        i = sys.argv.index(mode)
        # workers are spawned with JAX_PLATFORMS=cpu in env; assert it beat
        # the axon sitecustomize before any backend exists
        jax.config.update("jax_platforms", "cpu")
        worker = _selftest_worker if mode == "--worker" else _engine_worker
        worker(
            int(sys.argv[i + 1]), int(sys.argv[i + 2]),
            int(sys.argv[i + 3]), int(sys.argv[i + 4]),
        )
    elif "--selftest" in sys.argv:
        run_multihost_selftest()
        print("OK")
