"""Device mesh construction for the TPU engine.

The engine scales via a named `jax.sharding.Mesh` with axes:

    dp — data parallel (replica batches; gradient-free serving means pure request DP)
    sp — sequence/context parallel (ring attention over sequence chunks for
         long-context prefill; KV blocks rotate between sp neighbours via
         `ppermute` — see ops/ring_attention.py)
    ep — expert parallel (MoE expert dim sharded across devices; GSPMD inserts
         the dispatch/combine all-to-alls — see ops/moe.py)
    tp — tensor parallel (Megatron-style sharding of attention heads / MLP widths,
         rides ICI within a slice; innermost axis so tp collectives are between
         ICI nearest-neighbours)

The reference gateway has no intra-model parallelism at all (SURVEY.md §2.4) — its
only parallelism is request-level routing across endpoints. Model parallelism is a
new, first-class component of the TPU build (BASELINE.json north star).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes of the mesh axes. -1 means "use all remaining devices"."""

    dp: int = 1
    tp: int = -1
    sp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        dp, tp, sp, ep = self.dp, self.tp, self.sp, self.ep
        unknown = [a for a in (dp, tp, sp, ep) if a == -1]
        if len(unknown) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if tp == -1:
            tp = n_devices // (dp * sp * ep)
        if dp == -1:
            dp = n_devices // (tp * sp * ep)
        if sp == -1:
            sp = n_devices // (dp * tp * ep)
        if ep == -1:
            ep = n_devices // (dp * tp * sp)
        if dp * tp * sp * ep != n_devices:
            raise ValueError(
                f"mesh dp={dp} sp={sp} ep={ep} tp={tp} does not cover "
                f"{n_devices} devices"
            )
        return MeshConfig(dp=dp, tp=tp, sp=sp, ep=ep)


def build_mesh(config: MeshConfig | None = None, devices=None) -> Mesh:
    """Build a (dp, sp, ep, tp) mesh over the given devices (default: all).

    Device order matters on TPU: `jax.devices()` enumerates in ICI-topology order,
    so adjacent tp ranks are ICI neighbours and tp collectives (the latency-critical
    ones in tensor-parallel decode) stay on-chip-interconnect rather than DCN. sp
    sits outside ep/tp so each ring-attention ppermute hop crosses as few ICI
    links as possible for the given inner-parallelism degree.
    """
    devices = list(devices if devices is not None else jax.devices())
    config = (config or MeshConfig()).resolve(len(devices))
    dev_array = np.asarray(devices).reshape(
        config.dp, config.sp, config.ep, config.tp
    )
    return Mesh(dev_array, axis_names=("dp", "sp", "ep", "tp"))


def default_tp(n_devices: int, num_heads: int, num_kv_heads: int) -> int:
    """Largest valid power-of-two tp degree for a model. kv heads may be
    replicated (tp a multiple of kv_heads) when tp exceeds the kv head count."""
    tp = 1
    while True:
        cand = tp * 2
        if cand > n_devices or n_devices % cand or num_heads % cand:
            break
        if num_kv_heads % cand and cand % num_kv_heads:
            break
        tp = cand
    return tp


def is_power_of_two(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def validate_tp(num_heads: int, num_kv_heads: int, tp: int) -> None:
    if num_heads % tp != 0:
        raise ValueError(f"num_heads={num_heads} not divisible by tp={tp}")
    if num_kv_heads % tp != 0 and tp % num_kv_heads != 0:
        raise ValueError(
            f"num_kv_heads={num_kv_heads} incompatible with tp={tp}: "
            "need kv_heads % tp == 0 (sharded) or tp % kv_heads == 0 (replicated)"
        )
