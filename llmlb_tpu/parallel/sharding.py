"""Logical-axis → mesh-axis sharding rules.

Params and activations are annotated with *logical* axis names; `ShardingRules`
maps them onto mesh axes. This keeps model code mesh-agnostic: the same forward
runs on 1 chip (all rules → None) or a v5e-8 (tp rules active) without edits.
The reference has no model parallelism at all (SURVEY.md §2.4); this is new,
TPU-first design per the BASELINE.json north star.
"""

from __future__ import annotations

import dataclasses

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """Maps logical axis names to mesh axis names (or None = replicated)."""

    batch: str | None = "dp"
    # attention heads / MLP hidden width — the Megatron tp axis
    heads: str | None = "tp"
    kv_heads: str | None = "tp"
    ffn: str | None = "tp"
    vocab: str | None = "tp"
    # MoE expert dim (ops/moe.py); GSPMD inserts dispatch/combine all-to-alls
    experts: str | None = "ep"
    # residual-stream model dim: replicated (activations all-reduced after tp matmuls)
    embed: str | None = None
    head_dim: str | None = None
    seq: str | None = None
    layers: str | None = None

    def spec(self, *logical_axes: str | None) -> P:
        return P(*(getattr(self, ax) if ax is not None else None for ax in logical_axes))


def logical_to_sharding(
    mesh: Mesh, rules: ShardingRules, *logical_axes: str | None
) -> NamedSharding:
    return NamedSharding(mesh, rules.spec(*logical_axes))


def constrain(x: jax.Array, mesh: Mesh, rules: ShardingRules, *logical_axes):
    """with_sharding_constraint by logical axes; no-op outside a mesh context."""
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, rules.spec(*logical_axes))
    )
