"""Int8 quantization for weights and KV pages (docs/quantization.md).

Two independent knobs, combined as `--quantize weights|kv|all|off`
(`LLMLB_QUANTIZE`, default off):

- **weights**: per-output-channel symmetric int8 for the big projection
  matrices (attention q/k/v/o, MLP gate/up/down, MoE expert weights),
  stored as `{int8 values, f32 scales}` param pairs. Matmuls dequantize on
  the fly — the int8 -> bf16 convert fuses into the einsum's operand read,
  so HBM traffic is the int8 bytes, and the per-channel scale applies to
  the matmul OUTPUT (scale depends only on the output channel, so
  `x @ W_q * s == x @ (W_q * s)` exactly in fp32 accumulation).
- **kv**: int8 KV cache pages. The paged pool becomes
  `{int8 values [L,P,PS,K,D], f32 scales [L,P,PS,K]}` — one symmetric
  absmax scale per written K/V vector (per token, per head), quantized on
  write by every prefill/decode/verify path and dequantized on read by the
  attention kernels (scales ride the same block-table gather). Page ids,
  refcounts, block tables, prefix-cache sharing, and spec-decode rollback
  are untouched: scales are just a second array indexed by the same pages.

Everything here is shape-polymorphic and works on numpy arrays (host-side
streaming checkpoint quantization in engine/weights.py) and jax arrays
(in-jit KV write paths) alike. With the knob off nothing in the serving
path changes — bf16 output is bit-identical (tier-1 guarded).
"""

from llmlb_tpu.quant.core import (
    KV_SCALE_DTYPE,
    WEIGHT_QUANT_NAMES,
    QuantConfig,
    dequantize_channelwise,
    dequantize_kv,
    kv_cell_bytes,
    parse_quant_mode,
    quantize_channelwise,
    quantize_kv,
    quantize_params,
)

__all__ = [
    "KV_SCALE_DTYPE",
    "WEIGHT_QUANT_NAMES",
    "QuantConfig",
    "dequantize_channelwise",
    "dequantize_kv",
    "kv_cell_bytes",
    "parse_quant_mode",
    "quantize_channelwise",
    "quantize_kv",
    "quantize_params",
]
