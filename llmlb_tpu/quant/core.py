"""Symmetric int8 quantization primitives (weights + KV vectors).

Scheme (docs/quantization.md): absmax symmetric over a reduction group —
`scale = max(|x|) / 127`, `q = round(x / scale)` clipped to [-127, 127].
No zero point: transformer weights and KV activations are near-zero-mean,
symmetric quantization keeps dequant a single fused multiply, and the MXU
accumulates the int8->bf16 operands in fp32 either way.

Groups:
- weights: per OUTPUT channel (reduce over the input axis, axis=-2 of the
  [..., in, out] matmul layout) — one f32 scale per output column. Error is
  bounded by scale/2 = absmax/254 per element, and the scale commutes with
  the contraction so it applies to the matmul output.
- KV: per written vector (reduce over the head_dim axis, axis=-1) — one
  f32 scale per (token, kv-head). Finer than per-page scaling on purpose:
  decode appends one token at a time, and a coarser group would need
  re-scaling already-written int8 cells when a later token's amplitude
  grows past the group's absmax.

Implementations are numpy/jax polymorphic: the array module is inferred
from the input so host-side checkpoint streaming (numpy, engine/weights.py)
and in-jit KV writes (jax) share one code path.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

# Param names whose matmul weights quantize (both model families; names
# absent from a family's pytree are simply skipped). Embeddings, norms,
# lm_head, router, and biases stay bf16: they are small, and the embed /
# lm_head tables feed gathers and the fp32 unembed where int8 error is
# least welcome.
WEIGHT_QUANT_NAMES = (
    "wq", "wk", "wv", "wo",          # attention projections
    "wg", "wu", "wd",                # dense SwiGLU MLP
    "we_gate", "we_up", "we_down",   # MoE expert FFNs
)

SCALE_SUFFIX = "_scale"
KV_SCALE_DTYPE = np.float32
_QMAX = 127.0
_EPS = 1e-8  # all-zero groups quantize to zeros with a harmless tiny scale


@dataclasses.dataclass(frozen=True)
class QuantConfig:
    """Resolved quantization knobs for one engine."""

    weights: bool = False
    kv: bool = False

    @property
    def mode(self) -> str:
        if self.weights and self.kv:
            return "all"
        if self.weights:
            return "weights"
        if self.kv:
            return "kv"
        return "off"

    @property
    def enabled(self) -> bool:
        return self.weights or self.kv


def parse_quant_mode(mode: str | None = None) -> QuantConfig:
    """Resolve `--quantize` / LLMLB_QUANTIZE into a QuantConfig.

    Accepts off|weights|kv|all (case-insensitive; "0"/"false"/"none" alias
    off). Raises ValueError for anything else — a typo'd mode must not
    silently serve bf16 while the operator believes HBM halved."""
    if mode is None:
        mode = os.environ.get("LLMLB_QUANTIZE", "off")
    key = str(mode).strip().lower()
    if key in ("off", "0", "false", "none", ""):
        return QuantConfig()
    if key == "weights":
        return QuantConfig(weights=True)
    if key == "kv":
        return QuantConfig(kv=True)
    if key == "all":
        return QuantConfig(weights=True, kv=True)
    raise ValueError(
        f"quantize mode must be off|weights|kv|all, got {mode!r}"
    )


def _xp(x):
    """numpy for numpy inputs, jax.numpy for everything else."""
    if isinstance(x, np.ndarray):
        return np
    import jax.numpy as jnp

    return jnp


# --------------------------------------------------------------- weights


def quantize_channelwise(w, axis: int = -2):
    """Per-output-channel symmetric int8: reduce |w| over `axis` (the input
    axis of the [..., in, out] matmul layout). Returns (int8 values with
    w's shape, f32 scales with `axis` removed)."""
    xp = _xp(w)
    wf = w.astype(np.float32)
    amax = xp.max(xp.abs(wf), axis=axis)
    scale = xp.maximum(amax, _EPS) / _QMAX
    q = xp.clip(
        xp.round(wf / xp.expand_dims(scale, axis)), -_QMAX, _QMAX
    ).astype(np.int8)
    return q, scale.astype(np.float32)


def dequantize_channelwise(q, scale, dtype=None, axis: int = -2):
    """Inverse of quantize_channelwise (tests / reference math — the
    serving matmuls never materialize this; they scale the output)."""
    xp = _xp(q)
    out = q.astype(np.float32) * xp.expand_dims(scale, axis)
    return out.astype(dtype) if dtype is not None else out


def quantize_params(params: dict, names=WEIGHT_QUANT_NAMES) -> dict:
    """Quantize the projection weights of a param pytree in place of their
    bf16 leaves, adding `<name>_scale` companions. Idempotent: leaves that
    already carry a scale (or are already int8) pass through untouched.
    Works on numpy and jax pytrees (dict shape preserved)."""
    out: dict = {}
    for name, v in params.items():
        out[name] = v
    for name in names:
        v = out.get(name)
        if v is None or f"{name}{SCALE_SUFFIX}" in out:
            continue
        if np.dtype(v.dtype) == np.int8:
            continue
        q, scale = quantize_channelwise(v)
        out[name] = q
        out[f"{name}{SCALE_SUFFIX}"] = scale
    return out


# -------------------------------------------------------------------- KV


def quantize_kv(kv):
    """Quantize K or V vectors on write: absmax over the trailing head_dim
    axis. kv [..., D] -> (int8 [..., D], f32 [...])."""
    xp = _xp(kv)
    kvf = kv.astype(np.float32)
    amax = xp.max(xp.abs(kvf), axis=-1)
    scale = xp.maximum(amax, _EPS) / _QMAX
    q = xp.clip(
        xp.round(kvf / scale[..., None]), -_QMAX, _QMAX
    ).astype(np.int8)
    return q, scale.astype(np.float32)


def dequantize_kv(q, scale, dtype):
    """Dequantize gathered KV cells on read: values [..., D] * scales
    [..., 1] -> `dtype` (the attention op's compute dtype)."""
    return (q.astype(np.float32) * scale[..., None]).astype(dtype)


def kv_cell_bytes(head_dim: int, quantized: bool,
                  itemsize: int = 2) -> float:
    """HBM bytes per cached (token, head) cell: D values plus, when
    quantized, one f32 scale amortized over the vector. The honest figure
    the bytes-per-token / bytes-per-page accounting uses."""
    if quantized:
        return head_dim * 1 + np.dtype(KV_SCALE_DTYPE).itemsize
    return head_dim * itemsize
