"""Draft-free speculative decoding: n-gram/prompt-lookup drafting.

The decode loop's biggest structural cost is one device round trip per
emitted token per sequence. Speculative decoding breaks that coupling:
a cheap *drafter* proposes the next K tokens, the target model scores all
K+1 positions in ONE batched extend-style step over the paged KV cache
(the Ragged Paged Attention shape from PR 3), and the scheduler accepts
the longest prefix of drafts that match the model's own sampled tokens.
Every verify step emits between 1 and K+1 tokens — and because the model
samples every emitted token itself, the output distribution is exactly
the non-speculative one (see docs/speculative.md for the argument).

This package is the *drafting* side: `PromptLookupDrafter` is a per-slot
suffix-match n-gram index over the request's prompt + generated tokens —
no second model, no extra HBM, microseconds per proposal. It shines
precisely on the workloads the engine already optimizes for: shared-prefix
chat (answers quote the prompt) and structured output (JSON keys repeat).

Scheduler wiring (verify dispatch, acceptance walk, KV-page rollback,
constraint lookahead) lives in engine/scheduler.py; the K+1 model step in
models/llama.py `verify_step{,_paged}`.
"""

from llmlb_tpu.spec.drafter import PromptLookupDrafter, SpecConfig

__all__ = ["PromptLookupDrafter", "SpecConfig"]
