"""Prompt-lookup drafting: a suffix-match n-gram index over one request.

The proposal model of classic prompt-lookup decoding: if the last n tokens
of the sequence also occurred earlier (in the prompt or in already-generated
text), the tokens that followed that earlier occurrence are a strong guess
for what comes next. Deterministic, free of a second model, and strongest
exactly where this engine's traffic is predictable — shared-prefix chat
(answers quote the prompt) and JSON-mode output (keys and punctuation
repeat).

Index shape: for each n in [min_ngram, max_ngram] a dict mapping the n-gram
tuple to the position *after* its most recent occurrence. Updates are O(1)
per appended token (one dict write per n); proposals are O(max_ngram) dict
lookups plus a list slice. Indexing is deliberately one token *behind* the
live tail: when token t is appended, the n-grams ending at the PREVIOUS
position are indexed, so a lookup of the current tail can never match
itself — it finds the most recent strictly-earlier occurrence.

Determinism matters beyond reproducibility: in multihost lockstep every
host drafts from the same mirrored token history, so identical proposals
(and therefore identical verify dispatches) fall out for free.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Engine-level speculative-decoding defaults (per-request `speculative`
    knobs override `enabled`/`max_draft_tokens` within these bounds)."""

    enabled: bool = False
    max_draft_tokens: int = 4
    max_ngram: int = 3
    min_ngram: int = 1

    def __post_init__(self):
        if self.max_draft_tokens < 1:
            raise ValueError("max_draft_tokens must be >= 1")
        if not 1 <= self.min_ngram <= self.max_ngram:
            raise ValueError(
                "need 1 <= min_ngram <= max_ngram, got "
                f"[{self.min_ngram}, {self.max_ngram}]"
            )


class PromptLookupDrafter:
    """Per-request n-gram index over prompt + generated tokens.

    Owned by the scheduler step loop (one per speculating slot); not
    thread-safe by design. `append` is called for every emitted token,
    `propose` once per decode step that considers speculating.
    """

    __slots__ = ("max_ngram", "min_ngram", "tokens", "_index")

    def __init__(self, prompt_ids, *, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self.tokens: list[int] = []
        # n -> {ngram tuple -> position AFTER its latest occurrence}; only
        # n-grams ending strictly before the current tail are present.
        self._index: dict[int, dict[tuple, int]] = {
            n: {} for n in range(min_ngram, max_ngram + 1)
        }
        for t in prompt_ids:
            self.append(int(t))

    def __len__(self) -> int:
        return len(self.tokens)

    def append(self, token: int) -> None:
        """Extend the sequence by one token, indexing the n-grams that end
        at the previous tail (they now have a known follower: `token`)."""
        tokens = self.tokens
        prev_len = len(tokens)
        tokens.append(token)
        for n in range(self.min_ngram, self.max_ngram + 1):
            if prev_len >= n:
                self._index[n][tuple(tokens[prev_len - n:prev_len])] = prev_len

    def propose(self, k: int) -> list[int]:
        """Up to `k` draft tokens continuing the current tail, from the most
        recent earlier occurrence of the longest matching tail n-gram.
        Empty list when nothing matches (the step falls back to plain
        decode — proposing from no evidence would just burn verify FLOPs)."""
        if k <= 0:
            return []
        tokens = self.tokens
        length = len(tokens)
        for n in range(min(self.max_ngram, length), self.min_ngram - 1, -1):
            follow = self._index[n].get(tuple(tokens[length - n:]))
            if follow is not None:
                return tokens[follow:follow + k]
        return []
