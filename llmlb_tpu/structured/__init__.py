"""Structured outputs: grammar-constrained decoding for the tpu:// engine.

Pipeline (each stage its own module):

    JSON Schema ──json_schema.py──▶ regex ──regex_dfa.py──▶ char DFA
        ──constraint.py──▶ per-state token masks over the vocabulary
        ──engine──▶ [B, V] additive logit bias, applied BEFORE the top-k
                    sampling prefilter (ops/sampling.py)

`openai_api.inspect_request` is the single notion of a valid structured
request, shared by the gateway (early 400s) and the engine (actual
constraint construction). See docs/structured-outputs.md.
"""

from llmlb_tpu.structured.constraint import (
    MASK_NEG,
    ConstraintCompiler,
    ConstraintState,
    TokenConstraint,
    spec_hash,
    spec_regex,
)
from llmlb_tpu.structured.json_schema import (
    UnsupportedSchemaError,
    any_object_regex,
    schema_to_regex,
)
from llmlb_tpu.structured.openai_api import (
    StructuredRequest,
    inspect_request,
    parse_seed,
)
from llmlb_tpu.structured.regex_dfa import (
    CharDfa,
    RegexSyntaxError,
    compile_regex,
)

__all__ = [
    "MASK_NEG",
    "CharDfa",
    "ConstraintCompiler",
    "ConstraintState",
    "RegexSyntaxError",
    "StructuredRequest",
    "TokenConstraint",
    "UnsupportedSchemaError",
    "any_object_regex",
    "compile_regex",
    "inspect_request",
    "parse_seed",
    "schema_to_regex",
    "spec_hash",
    "spec_regex",
]
