"""Token-level constraints: char-DFA × tokenizer vocabulary → mask tables.

The back of the pipeline. A `TokenConstraint` is a compiled artifact bound to
one (grammar, tokenizer) pair: per-DFA-state boolean rows over the vocabulary
(`allowed[s, v]` — sampling token v from state s keeps the match alive), built
by walking every token's decoded text through the character DFA via a trie so
shared token prefixes are walked once. EOS is allowed exactly in accepting
states, which is how terminal acceptance becomes `finish_reason="stop"`: once
the grammar is complete and nothing else may follow, the mask leaves only EOS
and the engine's normal EOS path fires.

`ConstraintState` is the per-request cursor the scheduler advances on each
sampled token (re-walking the token's text — a handful of dict lookups — so
no [S, V] next-state table is stored). `ConstraintCompiler` caches compiled
artifacts LRU per schema hash; repeat schemas skip both regex→DFA and the
vocabulary scan, which is the expensive part (O(states × trie nodes) — see
docs/structured-outputs.md for sizing).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import OrderedDict

import numpy as np

from llmlb_tpu.structured.json_schema import (
    UnsupportedSchemaError,
    any_object_regex,
    schema_to_regex,
)
from llmlb_tpu.structured.regex_dfa import CharDfa, compile_regex

# Additive logit bias for disallowed tokens. Large negative finite instead of
# -inf: adding -inf to an already -inf logit (top-k padding) would be fine,
# but finite keeps softmax/top-p free of inf-inf NaN edge cases everywhere.
MASK_NEG = np.float32(-1e30)


def spec_regex(spec: dict) -> str:
    """Constraint spec (the wire form riding SamplingParams) → regex.

    Specs:  {"type": "json_object"}
            {"type": "json_schema", "schema": {...}}
            {"type": "regex", "pattern": "..."}
            {"type": "tool_call", "name": "...", "schema": {...}}  (arguments
            object of a forced function call — constrained like json_schema;
            `name` is metadata for response shaping, not the grammar)
    """
    if not isinstance(spec, dict):
        raise ValueError("constraint spec must be an object")
    kind = spec.get("type")
    if kind == "json_object":
        return any_object_regex()
    if kind in ("json_schema", "tool_call"):
        schema = spec.get("schema")
        if schema is None:
            raise ValueError(f"constraint spec {kind!r} requires 'schema'")
        return schema_to_regex(schema)
    if kind == "regex":
        pattern = spec.get("pattern")
        if not isinstance(pattern, str) or not pattern:
            raise ValueError("constraint spec 'regex' requires 'pattern'")
        return pattern
    raise ValueError(f"unknown constraint spec type {kind!r}")


def spec_hash(spec: dict) -> str:
    return hashlib.sha256(
        json.dumps(spec, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()


def _token_trie(token_texts: list[str | None]) -> dict:
    """Trie over token texts; node = {char: child} plus "ids" at nodes where
    one or more tokens end. Tokens decoding to nothing are excluded — they
    would advance the grammar zero characters and let the model stall the
    constraint forever."""
    root: dict = {}
    for tid, text in enumerate(token_texts):
        if not text:
            continue
        node = root
        for ch in text:
            node = node.setdefault(ch, {})
        node.setdefault("ids", []).append(tid)
    return root


class TokenConstraint:
    """One grammar compiled against one vocabulary."""

    def __init__(self, dfa: CharDfa, token_texts: list[str | None],
                 eos_id: int):
        self.dfa = dfa
        self.eos_id = eos_id
        vocab = len(token_texts)
        states = dfa.num_states
        self.allowed = np.zeros((states, vocab), dtype=bool)
        trie = _token_trie(token_texts)
        # DFS per start state; the trie shares prefix walks across tokens.
        for s0 in range(states):
            stack = [(trie, s0)]
            row = self.allowed[s0]
            while stack:
                node, st = stack.pop()
                for ch, child in node.items():
                    if ch == "ids":
                        row[child] = True
                        continue
                    nxt = dfa.step(st, ch)
                    if nxt is not None:
                        stack.append((child, nxt))
            if dfa.is_accepting(s0):
                row[eos_id] = True
        self._texts = token_texts
        # Precomputed -inf-style bias rows are built lazily per state and
        # memoized: most requests only ever visit a fraction of the states.
        self._bias_rows: dict[int, np.ndarray] = {}
        self._bias_lock = threading.Lock()
        # Dense next-state table for the device grammar path (ops/grammar).
        # Built on first request and cached: fused decode registers each
        # compiled schema once, not per step.
        self._transition: np.ndarray | None = None

    def transition_table(self) -> np.ndarray:
        """Dense int32 ``[states, V]`` next-state table: ``table[s, v]`` is
        the DFA state after sampling token v from state s, or -1 when v is
        disallowed. Invariant: ``table[s, v] >= 0  <=>  allowed[s, v]``, so
        a bias derived on-device from this table (0 where >= 0, MASK_NEG
        where -1) is bit-identical to ``bias_row``. The EOS column maps an
        accepting state to itself (EOS ends the request; the self-loop keeps
        lockstep device cursors valid past it). Dead-end rows mirror the
        bias_row fail-open: everything -1 except EOS self-looping."""
        with self._bias_lock:
            table = self._transition
            if table is not None:
                return table
        states, vocab = self.allowed.shape
        table = np.full((states, vocab), -1, dtype=np.int32)
        trie = _token_trie(self._texts)
        for s0 in range(states):
            stack = [(trie, s0)]
            row = table[s0]
            while stack:
                node, st = stack.pop()
                for ch, child in node.items():
                    if ch == "ids":
                        row[child] = st
                        continue
                    nxt = self.dfa.step(st, ch)
                    if nxt is not None:
                        stack.append((child, nxt))
            if self.dfa.is_accepting(s0):
                row[self.eos_id] = s0
            if not self.allowed[s0].any():
                # dead-end fail-open: only EOS survives, self-looping
                row[:] = -1
                row[self.eos_id] = s0
        with self._bias_lock:
            if self._transition is None:
                self._transition = table
            return self._transition

    @property
    def num_states(self) -> int:
        return self.allowed.shape[0]

    @property
    def nbytes(self) -> int:
        return int(self.allowed.nbytes)

    def bias_row(self, state: int) -> np.ndarray:
        """Additive float32 [V] row: 0 where allowed, MASK_NEG where not."""
        with self._bias_lock:
            row = self._bias_rows.get(state)
            if row is None:
                if not self.allowed[state].any():
                    # Dead-end state (vocabulary gap): fail open to EOS so
                    # the request terminates instead of sampling an
                    # arbitrary all-blocked token. One shared fallback for
                    # the live cursor AND speculative lookahead masks —
                    # callers that need to count the violation check
                    # allowed[state].any() themselves (ConstraintState).
                    row = np.full((self.allowed.shape[1],), MASK_NEG,
                                  dtype=np.float32)
                    row[self.eos_id] = np.float32(0.0)
                else:
                    row = np.where(self.allowed[state], np.float32(0.0),
                                   MASK_NEG)
                self._bias_rows[state] = row
        return row

    def advance(self, state: int, token_id: int) -> int | None:
        """Next DFA state after sampling `token_id`, None if it kills the
        match (cannot happen when the mask was applied, but callers treat
        None as a violation rather than trusting that)."""
        text = self._texts[token_id] if 0 <= token_id < len(self._texts) else None
        if not text:
            return None
        return self.dfa.walk(state, text)


class ConstraintState:
    """Per-request cursor over a TokenConstraint. Not thread-safe; owned by
    the scheduler step loop."""

    __slots__ = ("tc", "state", "violated")

    def __init__(self, tc: TokenConstraint):
        self.tc = tc
        self.state: int = tc.dfa.start
        self.violated = False

    @property
    def is_accepting(self) -> bool:
        return self.tc.dfa.is_accepting(self.state)

    def bias_row(self) -> np.ndarray:
        if not self.tc.allowed[self.state].any():
            # No token can advance the grammar from here (vocabulary gap —
            # e.g. a tokenizer with no token for a required character).
            # tc.bias_row fails open to EOS so the slot frees; the live
            # cursor additionally marks the violation for accounting.
            self.violated = True
        return self.tc.bias_row(self.state)

    def advance(self, token_id: int) -> bool:
        """Advance on a sampled token. False (and `violated`) if the token
        was not actually allowed — the state is left unchanged."""
        if token_id == self.tc.eos_id:
            if not self.is_accepting:
                self.violated = True
                return False
            return True
        nxt = self.tc.advance(self.state, token_id)
        if nxt is None:
            self.violated = True
            return False
        self.state = nxt
        return True


class ConstraintCompiler:
    """schema/spec → TokenConstraint, LRU-cached per (spec hash, tokenizer).

    One compiler is bound to one tokenizer (vocab texts are snapshotted at
    construction); the cache key is the spec hash alone. `metrics` is any
    object with the EngineMetrics structured hooks (duck-typed; None is
    fine), fed compile timings and cache hit/miss/eviction events.
    """

    def __init__(self, tokenizer, vocab_size: int, *, max_entries: int = 32,
                 metrics=None):
        self.eos_id = int(tokenizer.eos_id)
        self.vocab_size = int(vocab_size)
        self.metrics = metrics
        self.max_entries = max(1, int(max_entries))
        self._cache: OrderedDict[str, TokenConstraint] = OrderedDict()
        self._lock = threading.Lock()
        self.compile_cache_hits = 0
        self.compile_cache_misses = 0
        self.evictions = 0
        self._token_texts: list[str | None] | None = None
        self._tokenizer = tokenizer

    def _texts(self) -> list[str | None]:
        if self._token_texts is None:
            texts: list[str | None] = []
            for i in range(self.vocab_size):
                if i == self.eos_id:
                    texts.append(None)
                    continue
                try:
                    text = self._tokenizer.decode([i])
                except Exception:
                    text = ""
                texts.append(text or None)
            self._token_texts = texts
        return self._token_texts

    def compile_spec(self, spec: dict) -> TokenConstraint:
        key = spec_hash(spec)
        with self._lock:
            tc = self._cache.get(key)
            if tc is not None:
                self._cache.move_to_end(key)
                self.compile_cache_hits += 1
                if self.metrics is not None:
                    self.metrics.record_mask_cache_hit()
                return tc
        # Compile outside the lock: a slow first compile must not block
        # cache hits for other schemas. A racing duplicate compile of the
        # same spec is wasted work, not a correctness problem.
        started = time.monotonic()
        regex = spec_regex(spec)  # raises for malformed/unsupported specs
        dfa = compile_regex(regex)
        tc = TokenConstraint(dfa, self._texts(), self.eos_id)
        elapsed = time.monotonic() - started
        with self._lock:
            won = key not in self._cache
            if won:
                self.compile_cache_misses += 1
                self._cache[key] = tc
                while len(self._cache) > self.max_entries:
                    self._cache.popitem(last=False)
                    self.evictions += 1
                    if self.metrics is not None:
                        self.metrics.record_mask_cache_eviction()
            else:
                # lost a duplicate-compile race: the winner already counted
                # the miss — counting another would diverge the /metrics
                # counters from this cache's own hit-rate figures
                self.compile_cache_hits += 1
            tc = self._cache[key]
        if self.metrics is not None:
            if won:
                self.metrics.record_mask_cache_miss()
                self.metrics.record_schema_compile(elapsed)
            else:
                self.metrics.record_mask_cache_hit()
        return tc

    def info(self) -> dict:
        """JSON block for /api/system, /api/health, and /metrics gauges."""
        with self._lock:
            entries = len(self._cache)
            nbytes = sum(tc.nbytes for tc in self._cache.values())
            hits, misses = self.compile_cache_hits, self.compile_cache_misses
        total = hits + misses
        return {
            "enabled": True,
            "mask_cache_entries": entries,
            "mask_cache_max_entries": self.max_entries,
            "mask_cache_bytes": nbytes,
            "compile_cache_hits": hits,
            "compile_cache_misses": misses,
            "compile_cache_hit_rate": round(hits / total, 4) if total else None,
            "evictions": self.evictions,
            "vocab_size": self.vocab_size,
        }


__all__ = [
    "MASK_NEG",
    "ConstraintCompiler",
    "ConstraintState",
    "TokenConstraint",
    "UnsupportedSchemaError",
    "spec_hash",
    "spec_regex",
]
