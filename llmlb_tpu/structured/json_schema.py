"""JSON Schema → regular grammar, the front of the constraint pipeline.

Compiles the practical subset of JSON Schema that agentic clients actually
send (typed objects, enums, consts, bounded arrays, anyOf/oneOf, non-recursive
$ref) into a regex over the compact JSON serialization — no whitespace between
tokens, object keys in schema declaration order. The output is regular by
construction: anything that would need a stack (recursive $ref, unbounded
nesting in free-form mode) or that a regex cannot enforce (numeric ranges,
uniqueItems) raises UnsupportedSchemaError naming the feature, so the gateway
can 400 with a message instead of proxying a constraint the engine would
silently mis-enforce.

The regex dialect is the one `regex_dfa.py` accepts; everything emitted here
compiles there. Guarantee: any string matching the emitted regex parses as
JSON and validates against the schema (the bench asserts this end to end).
"""

from __future__ import annotations

import json

# JSON primitive grammars (compact form). Strings allow any non-control,
# non-quote, non-backslash character plus the standard escapes.
STRING_CHAR = (
    r'(?:[^"\\\x00-\x1f]|\\["\\/bfnrt]|\\u[0-9a-fA-F]{4})'
)
STRING = f'"{STRING_CHAR}*"'
INTEGER = r"-?(?:0|[1-9][0-9]*)"
NUMBER = r"-?(?:0|[1-9][0-9]*)(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?"
BOOLEAN = r"(?:true|false)"
NULL = r"null"

# Free-form JSON ("json_object" mode, or a schema with no type) is not
# regular; it is approximated by expanding values to this nesting depth.
DEFAULT_ANY_DEPTH = 3
# Optional properties multiply alternatives (every optional subset in
# declaration order must be a branch); 2^6 = 64 branches is the ceiling.
MAX_OPTIONAL_PROPERTIES = 6
MAX_STRING_LENGTH = 256  # aligns with regex_dfa.MAX_BOUNDED_REPEAT
MAX_ARRAY_ITEMS = 256  # same compile bound as strings
# Hard ceiling on the compiled grammar, checked at EVERY node: nested $refs
# with optional-property branches multiply (a sub-KB hostile schema can
# otherwise expand to gigabytes on the gateway event loop — classic
# billion-laughs), and the ceiling also bounds the engine's NFA/DFA size.
MAX_REGEX_LEN = 65536

# Keywords whose semantics a DFA cannot honor. Ignoring them would emit
# schema-INVALID output while claiming a guarantee, so they hard-fail.
_UNSUPPORTED_KEYWORDS = (
    "$dynamicRef", "$dynamicAnchor", "$recursiveRef", "patternProperties",
    "allOf", "not", "if", "then", "else", "unevaluatedProperties",
    "unevaluatedItems", "dependentSchemas", "dependentRequired",
    "propertyNames", "contains", "uniqueItems", "multipleOf",
    "minimum", "maximum", "exclusiveMinimum", "exclusiveMaximum",
    "minProperties", "maxProperties", "prefixItems",
)

_ESCAPE_CHARS = set("\\^$.|?*+()[]{}")


class UnsupportedSchemaError(ValueError):
    """Schema uses a feature outside the compilable subset. `feature` names
    it; the message (which reaches 400 bodies) always contains the name."""

    def __init__(self, feature: str, detail: str = ""):
        self.feature = feature
        msg = f"unsupported JSON-Schema feature: {feature}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


def _lit(text: str) -> str:
    """Regex that matches `text` literally (our dialect's escaping)."""
    out = []
    for ch in text:
        if ch in _ESCAPE_CHARS:
            out.append("\\" + ch)
        elif ord(ch) < 0x20:
            out.append(f"\\u{ord(ch):04x}")
        else:
            out.append(ch)
    return "".join(out)


def _json_literal(value) -> str:
    """Regex matching exactly the compact JSON serialization of `value`."""
    return _lit(json.dumps(value, separators=(",", ":"), ensure_ascii=False))


def _any_value(depth: int) -> str:
    scalars = [STRING, NUMBER, BOOLEAN, NULL]
    if depth <= 0:
        return "(?:" + "|".join(scalars) + ")"
    inner = _any_value(depth - 1)
    obj = f'\\{{(?:"{STRING_CHAR}*":{inner}(?:,"{STRING_CHAR}*":{inner})*)?\\}}'
    arr = f"\\[(?:{inner}(?:,{inner})*)?\\]"
    return "(?:" + "|".join(scalars + [obj, arr]) + ")"


def any_object_regex(depth: int = DEFAULT_ANY_DEPTH) -> str:
    """`response_format: json_object` — any JSON object, nesting bounded."""
    inner = _any_value(depth - 1)
    return f'\\{{(?:"{STRING_CHAR}*":{inner}(?:,"{STRING_CHAR}*":{inner})*)?\\}}'


# Codepoints a JSON string may not contain RAW (they need \-escaping):
# controls, the quote, the backslash. A user `pattern` whose language can
# include one of these would let the grammar force output that no longer
# parses as JSON — the subsystem's core guarantee.
_JSON_UNSAFE = ((0x00, 0x1F), (0x22, 0x22), (0x5C, 0x5C))


def _check_pattern(pattern) -> None:
    """Validate a `pattern` keyword at SCHEMA compile time: it must be
    syntactically inside the supported regex dialect (so the engine's DFA
    compile cannot fail later, after a stream is already committed), and its
    alphabet must stay clear of characters that need JSON escaping."""
    from llmlb_tpu.structured.regex_dfa import RegexSyntaxError, compile_regex

    if not isinstance(pattern, str) or not pattern:
        raise UnsupportedSchemaError("pattern", "must be a non-empty string")
    try:
        dfa = compile_regex(pattern)
    except RegexSyntaxError as e:
        raise UnsupportedSchemaError("pattern", str(e)) from None
    bounds = dfa.boundaries
    for trans in dfa.trans:
        for seg in trans:
            lo = bounds[seg]
            hi = (bounds[seg + 1] - 1) if seg + 1 < len(bounds) else 0x10FFFF
            for ulo, uhi in _JSON_UNSAFE:
                if lo <= uhi and ulo <= hi:
                    raise UnsupportedSchemaError(
                        "pattern",
                        "may match a character that needs JSON string "
                        f"escaping (U+{max(lo, ulo):04X}); restrict the "
                        "pattern's character classes",
                    )


def _resolve_ref(ref: str, root: dict) -> dict:
    if not isinstance(ref, str) or not ref.startswith("#/"):
        raise UnsupportedSchemaError("$ref", f"only '#/...' refs, got {ref!r}")
    node: object = root
    for part in ref[2:].split("/"):
        part = part.replace("~1", "/").replace("~0", "~")
        if not isinstance(node, dict) or part not in node:
            raise UnsupportedSchemaError("$ref", f"unresolvable {ref!r}")
        node = node[part]
    if not isinstance(node, (dict, bool)):
        raise UnsupportedSchemaError("$ref", f"{ref!r} is not a schema")
    return node  # type: ignore[return-value]


def _string_regex(schema: dict) -> str:
    if "pattern" in schema:
        for kw in ("minLength", "maxLength"):
            if kw in schema:
                raise UnsupportedSchemaError(
                    "pattern", f"cannot combine with {kw}"
                )
        _check_pattern(schema["pattern"])
        # JSON Schema `pattern` is unanchored; constrained decoding treats it
        # as a full match of the string body (docs/structured-outputs.md).
        return f'"(?:{schema["pattern"]})"'
    lo = schema.get("minLength", 0)
    hi = schema.get("maxLength")
    if not isinstance(lo, int) or lo < 0:
        raise UnsupportedSchemaError("minLength", "must be a non-negative int")
    if hi is not None and (not isinstance(hi, int) or hi < lo):
        raise UnsupportedSchemaError("maxLength", "must be an int >= minLength")
    if max(lo, hi or 0) > MAX_STRING_LENGTH:
        raise UnsupportedSchemaError(
            "maxLength", f"bounds over {MAX_STRING_LENGTH} are not compilable"
        )
    if lo == 0 and hi is None:
        return STRING
    if hi is None:
        return f'"{STRING_CHAR}{{{lo},}}"'
    return f'"{STRING_CHAR}{{{lo},{hi}}}"'


def _array_regex(schema: dict, root: dict, depth: int,
                 active: frozenset) -> str:
    item = schema.get("items", True)
    inner = _compile(item, root, depth - 1, active)
    lo = schema.get("minItems", 0)
    hi = schema.get("maxItems")
    if not isinstance(lo, int) or lo < 0:
        raise UnsupportedSchemaError("minItems", "must be a non-negative int")
    if hi is not None and (not isinstance(hi, int) or hi < lo):
        raise UnsupportedSchemaError("maxItems", "must be an int >= minItems")
    if max(lo, hi or 0) > MAX_ARRAY_ITEMS:
        # mirror MAX_STRING_LENGTH: bounds past the repeat cap must fail HERE
        # (the gateway's validation pass), not at the engine's DFA compile
        # after a stream is already committed
        raise UnsupportedSchemaError(
            "maxItems", f"bounds over {MAX_ARRAY_ITEMS} are not compilable"
        )
    if hi is not None and hi == 0:
        return r"\[\]"
    if lo == 0:
        tail = f"(?:,{inner})*" if hi is None else f"(?:,{inner}){{0,{hi - 1}}}"
        return f"\\[(?:{inner}{tail})?\\]"
    tail = (f"(?:,{inner}){{{lo - 1},}}" if hi is None
            else f"(?:,{inner}){{{lo - 1},{hi - 1}}}")
    return f"\\[{inner}{tail}\\]"


def _object_regex(schema: dict, root: dict, depth: int,
                  active: frozenset) -> str:
    props = schema.get("properties")
    addl = schema.get("additionalProperties")
    if props is None:
        if isinstance(addl, dict):
            # map-shaped object: any keys, values per the addl schema
            inner = _compile(addl, root, depth - 1, active)
            return (f'\\{{(?:"{STRING_CHAR}*":{inner}'
                    f'(?:,"{STRING_CHAR}*":{inner})*)?\\}}')
        if addl in (None, True):
            # open object with no declared shape: free-form, depth-bounded
            return any_object_regex(max(1, depth))
        return r"\{\}"  # additionalProperties: false and no properties
    if not isinstance(props, dict):
        raise UnsupportedSchemaError("properties", "must be an object")
    if addl not in (None, False):
        raise UnsupportedSchemaError(
            "additionalProperties",
            "only false (closed objects) is supported with properties",
        )
    required = schema.get("required", [])
    if not isinstance(required, list):
        raise UnsupportedSchemaError("required", "must be an array")
    unknown = [k for k in required if k not in props]
    if unknown:
        raise UnsupportedSchemaError(
            "required", f"names undeclared properties {unknown!r}"
        )
    names = list(props)  # declaration order is emission order
    optional = [k for k in names if k not in set(required)]
    if len(optional) > MAX_OPTIONAL_PROPERTIES:
        raise UnsupportedSchemaError(
            "optional properties",
            f"{len(optional)} optional properties need "
            f"2^{len(optional)} branches; at most "
            f"{MAX_OPTIONAL_PROPERTIES} are supported",
        )
    members = {
        k: f'"{_lit(k)}":{_compile(v, root, depth - 1, active)}'
        for k, v in props.items()
    }
    # One branch per optional subset, keys always in declaration order.
    branches = []
    for bits in range(1 << len(optional)):
        chosen = {optional[i] for i in range(len(optional)) if bits >> i & 1}
        keys = [k for k in names if k in set(required) or k in chosen]
        branches.append(
            "\\{" + ",".join(members[k] for k in keys) + "\\}"
            if keys else r"\{\}"
        )
    seen: set[str] = set()
    unique = [b for b in branches if not (b in seen or seen.add(b))]
    return unique[0] if len(unique) == 1 else "(?:" + "|".join(unique) + ")"


def _compile(schema, root: dict, depth: int, active: frozenset) -> str:
    """Size-checked wrapper: every node's emitted regex is bounded, and since
    parents only concatenate/alternate checked children plus O(1) glue, the
    per-node check bounds the whole grammar — multiplicative expansion
    (repeated $refs under optional-property branches) fails fast instead of
    materializing gigabytes on the caller's thread."""
    out = _compile_node(schema, root, depth, active)
    if len(out) > MAX_REGEX_LEN:
        raise UnsupportedSchemaError(
            "schema complexity",
            f"compiled grammar exceeds {MAX_REGEX_LEN} characters; simplify "
            f"nested/optional/$ref structure",
        )
    return out


def _compile_node(schema, root: dict, depth: int, active: frozenset) -> str:
    if schema is True or schema == {}:
        return _any_value(max(0, depth))
    if schema is False:
        raise UnsupportedSchemaError("false schema", "matches nothing")
    if not isinstance(schema, dict):
        raise UnsupportedSchemaError("schema", f"must be an object, got "
                                               f"{type(schema).__name__}")
    for kw in _UNSUPPORTED_KEYWORDS:
        if kw in schema:
            raise UnsupportedSchemaError(kw)
    if depth < 0:
        raise UnsupportedSchemaError(
            "nesting depth", "schema nests deeper than the compilable bound"
        )

    if "$ref" in schema:
        ref = schema["$ref"]
        if ref in active:
            raise UnsupportedSchemaError("recursive $ref", str(ref))
        return _compile(_resolve_ref(ref, root), root, depth,
                        active | {ref})

    if "const" in schema:
        return _json_literal(schema["const"])
    if "enum" in schema:
        values = schema["enum"]
        if not isinstance(values, list) or not values:
            raise UnsupportedSchemaError("enum", "must be a non-empty array")
        return "(?:" + "|".join(_json_literal(v) for v in values) + ")"
    for combinator in ("anyOf", "oneOf"):
        if combinator in schema:
            subs = schema[combinator]
            if not isinstance(subs, list) or not subs:
                raise UnsupportedSchemaError(
                    combinator, "must be a non-empty array"
                )
            return "(?:" + "|".join(
                _compile(s, root, depth, active) for s in subs
            ) + ")"

    stype = schema.get("type")
    if isinstance(stype, list):
        if not stype:
            raise UnsupportedSchemaError("type", "empty type array")
        return "(?:" + "|".join(
            _compile({**schema, "type": t}, root, depth, active)
            for t in stype
        ) + ")"
    if stype is None:
        return _any_value(max(0, depth))
    if stype == "string":
        return _string_regex(schema)
    if stype == "integer":
        return INTEGER
    if stype == "number":
        return NUMBER
    if stype == "boolean":
        return BOOLEAN
    if stype == "null":
        return NULL
    if stype == "array":
        return _array_regex(schema, root, depth, active)
    if stype == "object":
        return _object_regex(schema, root, depth, active)
    raise UnsupportedSchemaError("type", f"unknown type {stype!r}")


def schema_to_regex(schema, *, depth: int = 8) -> str:
    """Compile a JSON Schema into an equivalent full-match regex.

    `depth` bounds nesting of free-form subtrees (schemas without a type);
    explicitly-typed nesting is naturally bounded by the schema itself but
    still counts against it, so pathological 100-level schemas fail instead
    of exploding the DFA.
    """
    root = schema if isinstance(schema, dict) else {}
    return _compile(schema, root, depth, frozenset())
