"""OpenAI-dialect structured-output request parsing, shared by both layers.

The gateway calls `inspect_request` to validate `response_format` /
`tool_choice` up front (malformed shapes and unsupported JSON-Schema features
become a 400 with the feature named, instead of being proxied blind); the
tpu:// engine calls it again to build the actual constraint spec it hands the
scheduler. Both layers therefore agree on exactly one notion of "valid".

Anthropic `/v1/messages` bodies are converted to OpenAI chat shape before
reaching this module (gateway/api_anthropic.anthropic_request_to_openai), so
forced `tool_choice: {type: "tool"}` arrives here as a forced function call.
"""

from __future__ import annotations

import dataclasses

from llmlb_tpu.structured.json_schema import schema_to_regex
from llmlb_tpu.structured.constraint import spec_regex


@dataclasses.dataclass(frozen=True)
class StructuredRequest:
    """What a request asked for, normalized.

    kind: "json_object" | "json_schema" | "tool_call"
    spec: the wire-safe constraint spec for SamplingParams.constraint
    tool_name: set for kind == "tool_call" (response shaping needs it)
    """

    kind: str
    spec: dict
    tool_name: str | None = None


def _tool_by_name(tools, name: str) -> dict | None:
    for tool in tools or []:
        if not isinstance(tool, dict):
            continue
        fn = tool.get("function") or {}
        if isinstance(fn, dict) and fn.get("name") == name:
            return fn
    return None


def _forced_tool(body: dict) -> dict | None:
    """The function dict of a forced tool call, None when tool choice is
    auto/none/absent. Raises ValueError for malformed shapes."""
    choice = body.get("tool_choice")
    if choice is None or choice in ("auto", "none"):
        return None
    tools = body.get("tools")
    if choice == "required":
        if not isinstance(tools, list) or not tools:
            raise ValueError("tool_choice 'required' needs a 'tools' array")
        if len(tools) != 1:
            # Cannot constrain "one of several tools" to a single arguments
            # grammar; pass through unconstrained rather than guessing.
            return None
        fn = (tools[0] or {}).get("function")
        if not isinstance(fn, dict) or not fn.get("name"):
            raise ValueError("tools[0].function.name is required")
        return fn
    if isinstance(choice, dict):
        if choice.get("type") != "function":
            raise ValueError(
                f"unsupported tool_choice type {choice.get('type')!r}"
            )
        name = (choice.get("function") or {}).get("name")
        if not isinstance(name, str) or not name:
            raise ValueError("tool_choice.function.name is required")
        fn = _tool_by_name(tools, name)
        if fn is None:
            raise ValueError(f"tool_choice names unknown function {name!r}")
        return fn
    raise ValueError("tool_choice must be 'auto', 'none', 'required', "
                     "or a {type: 'function'} object")


def inspect_request(body: dict) -> StructuredRequest | None:
    """Parse + validate the structured-output fields of an OpenAI chat body.

    Returns None for unconstrained requests. Raises ValueError (including
    UnsupportedSchemaError, whose message names the offending feature) for
    malformed or uncompilable requests — the caller maps that to a 400.
    """
    rf = body.get("response_format")
    forced = _forced_tool(body)

    structured: StructuredRequest | None = None
    if rf is not None:
        if not isinstance(rf, dict):
            raise ValueError("response_format must be an object")
        rtype = rf.get("type")
        if rtype in (None, "text"):
            structured = None
        elif rtype == "json_object":
            structured = StructuredRequest(
                kind="json_object", spec={"type": "json_object"}
            )
        elif rtype == "json_schema":
            js = rf.get("json_schema")
            if not isinstance(js, dict):
                raise ValueError(
                    "response_format.json_schema must be an object"
                )
            schema = js.get("schema")
            if not isinstance(schema, (dict, bool)):
                raise ValueError(
                    "response_format.json_schema.schema must be an object"
                )
            schema_to_regex(schema)  # raises UnsupportedSchemaError early
            structured = StructuredRequest(
                kind="json_schema",
                spec={"type": "json_schema", "schema": schema},
            )
        else:
            raise ValueError(
                f"unsupported response_format type {rtype!r} (expected "
                f"'text', 'json_object', or 'json_schema')"
            )

    if forced is not None:
        if structured is not None:
            raise ValueError(
                "response_format and a forced tool_choice cannot be combined"
            )
        schema = forced.get("parameters")
        if schema is None:
            schema = {"type": "object"}  # parameterless tool: any object
        if not isinstance(schema, (dict, bool)):
            raise ValueError("tool function parameters must be an object")
        spec = {"type": "tool_call", "name": forced["name"], "schema": schema}
        spec_regex(spec)  # raises UnsupportedSchemaError early
        return StructuredRequest(
            kind="tool_call", spec=spec, tool_name=forced["name"]
        )
    return structured


def parse_seed(body: dict) -> int | None:
    """OpenAI `seed`: plumbed to the engine's per-request PRNG fold."""
    seed = body.get("seed")
    if seed is None:
        return None
    if isinstance(seed, bool) or not isinstance(seed, int):
        raise ValueError("'seed' must be an integer")
    # fold into uint32 space; OpenAI allows arbitrary ints
    return seed & 0x7FFFFFFF
