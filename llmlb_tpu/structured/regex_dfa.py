"""Regex → character-level DFA, the middle stage of the constraint pipeline.

Grammar-constrained decoding needs a machine it can ask two questions of,
hundreds of thousands of times during table construction and once per sampled
token at serving time: "from state S, does character C keep the match alive,
and where does it land?" A backtracking engine (Python's `re`) cannot answer
per-state questions, so this module implements the classic pipeline directly:

    pattern text → AST → Thompson NFA → subset-construction DFA
                 → dead-state pruning (every surviving state can still accept)

The supported syntax is the subset the JSON-Schema compiler emits plus what
user `pattern` keywords commonly need: literals, `.`, escapes (`\\d \\w \\s
\\n \\r \\t \\f \\xHH \\uHHHH` and escaped metacharacters), character classes
with ranges and negation, grouping (`(...)` / `(?:...)`), alternation, and the
quantifiers `* + ? {m} {m,} {m,n}` (bounded repeats are expanded, so `n` is
capped — see MAX_BOUNDED_REPEAT). Transitions are stored per disjoint
codepoint segment, not per character, so classes like `[^"\\\\]` cost one
entry rather than a million.
"""

from __future__ import annotations

import dataclasses
from bisect import bisect_right

MAX_CODEPOINT = 0x10FFFF
# {m,n} expands to n concatenated copies; a huge bound would explode the NFA.
# 256 covers every repeat the schema compiler emits (maxLength is capped to
# the same figure) while keeping worst-case construction well under a second.
MAX_BOUNDED_REPEAT = 256


class RegexSyntaxError(ValueError):
    """The pattern uses syntax outside the supported subset."""


# ------------------------------------------------------------------ char sets


def _normalize(ranges: list[tuple[int, int]]) -> tuple[tuple[int, int], ...]:
    out: list[tuple[int, int]] = []
    for lo, hi in sorted(r for r in ranges if r[0] <= r[1]):
        if out and lo <= out[-1][1] + 1:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return tuple(out)


def _negate(ranges: tuple[tuple[int, int], ...]) -> tuple[tuple[int, int], ...]:
    out = []
    prev = 0
    for lo, hi in ranges:
        if lo > prev:
            out.append((prev, lo - 1))
        prev = hi + 1
    if prev <= MAX_CODEPOINT:
        out.append((prev, MAX_CODEPOINT))
    return tuple(out)


_DIGIT = ((48, 57),)
_WORD = _normalize([(48, 57), (65, 90), (95, 95), (97, 122)])
_SPACE = _normalize([(9, 13), (32, 32)])
_ANY = ((0, MAX_CODEPOINT),)


# ------------------------------------------------------------------------ AST


@dataclasses.dataclass(frozen=True)
class _Chars:
    ranges: tuple[tuple[int, int], ...]


@dataclasses.dataclass(frozen=True)
class _Concat:
    parts: tuple


@dataclasses.dataclass(frozen=True)
class _Alt:
    options: tuple


@dataclasses.dataclass(frozen=True)
class _Repeat:
    node: object
    min: int
    max: int | None  # None = unbounded


class _Parser:
    def __init__(self, pattern: str):
        self.src = pattern
        self.pos = 0

    def error(self, msg: str) -> RegexSyntaxError:
        return RegexSyntaxError(
            f"{msg} at position {self.pos} in pattern {self.src!r}"
        )

    def peek(self) -> str | None:
        return self.src[self.pos] if self.pos < len(self.src) else None

    def take(self) -> str:
        ch = self.peek()
        if ch is None:
            raise self.error("unexpected end of pattern")
        self.pos += 1
        return ch

    def parse(self):
        node = self.alt()
        if self.pos != len(self.src):
            raise self.error(f"unexpected {self.src[self.pos]!r}")
        return node

    def alt(self):
        options = [self.concat()]
        while self.peek() == "|":
            self.take()
            options.append(self.concat())
        return options[0] if len(options) == 1 else _Alt(tuple(options))

    def concat(self):
        parts = []
        while self.peek() not in (None, "|", ")"):
            parts.append(self.repeat())
        if len(parts) == 1:
            return parts[0]
        return _Concat(tuple(parts))

    def repeat(self):
        node = self.atom()
        while True:
            ch = self.peek()
            if ch == "*":
                self.take()
                node = _Repeat(node, 0, None)
            elif ch == "+":
                self.take()
                node = _Repeat(node, 1, None)
            elif ch == "?":
                self.take()
                node = _Repeat(node, 0, 1)
            elif ch == "{":
                node = self.braces(node)
            else:
                return node

    def braces(self, node):
        start = self.pos
        self.take()  # "{"
        body = ""
        while self.peek() not in (None, "}"):
            body += self.take()
        if self.peek() != "}":
            raise self.error("unterminated {...} quantifier")
        self.take()
        try:
            if "," not in body:
                lo = hi = int(body)
            else:
                lo_s, hi_s = body.split(",", 1)
                lo = int(lo_s)
                hi = int(hi_s) if hi_s.strip() else None
        except ValueError:
            self.pos = start
            raise self.error(f"malformed quantifier {{{body}}}") from None
        if lo < 0 or (hi is not None and hi < lo):
            self.pos = start
            raise self.error(f"invalid quantifier bounds {{{body}}}")
        if max(lo, hi or 0) > MAX_BOUNDED_REPEAT:
            self.pos = start
            raise self.error(
                f"quantifier bound over {MAX_BOUNDED_REPEAT} in {{{body}}}"
            )
        return _Repeat(node, lo, hi)

    def atom(self):
        ch = self.take()
        if ch == "(":
            if self.peek() == "?":
                self.take()
                if self.peek() != ":":
                    raise self.error("only (?:...) groups are supported")
                self.take()
            node = self.alt()
            if self.peek() != ")":
                raise self.error("unterminated group")
            self.take()
            return node
        if ch == "[":
            return self.char_class()
        if ch == ".":
            return _Chars(_ANY)
        if ch == "\\":
            return _Chars(self.escape(in_class=False))
        if ch in "*+?{":
            raise self.error(f"quantifier {ch!r} with nothing to repeat")
        if ch in ")]":
            raise self.error(f"unmatched {ch!r}")
        if ch in "^$":
            raise self.error(
                f"anchor {ch!r} is not supported (patterns are full-match)"
            )
        return _Chars(((ord(ch), ord(ch)),))

    def escape(self, in_class: bool) -> tuple[tuple[int, int], ...]:
        ch = self.take()
        if ch == "d":
            return _DIGIT
        if ch == "D":
            return _negate(_DIGIT)
        if ch == "w":
            return _WORD
        if ch == "W":
            return _negate(_WORD)
        if ch == "s":
            return _SPACE
        if ch == "S":
            return _negate(_SPACE)
        simple = {"n": 10, "r": 13, "t": 9, "f": 12, "v": 11, "0": 0,
                  "a": 7, "b": 8 if in_class else None, "e": 27}
        if ch in simple and simple[ch] is not None:
            cp = simple[ch]
            return ((cp, cp),)
        if ch in ("x", "u"):
            width = 2 if ch == "x" else 4
            digits = self.src[self.pos : self.pos + width]
            if len(digits) != width:
                raise self.error(f"truncated \\{ch} escape")
            try:
                cp = int(digits, 16)
            except ValueError:
                raise self.error(f"malformed \\{ch} escape") from None
            self.pos += width
            return ((cp, cp),)
        if ch.isalnum():
            raise self.error(f"unsupported escape \\{ch}")
        return ((ord(ch), ord(ch)),)  # escaped metacharacter, literal

    def char_class(self):
        negated = False
        if self.peek() == "^":
            self.take()
            negated = True
        ranges: list[tuple[int, int]] = []
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                raise self.error("unterminated character class")
            if ch == "]" and not first:
                self.take()
                break
            first = False
            if ch == "\\":
                self.take()
                sub = self.escape(in_class=True)
                if len(sub) > 1 or sub[0][0] != sub[0][1]:
                    ranges.extend(sub)  # \d-style class escape; no ranges off it
                    continue
                lo = sub[0][0]
            else:
                lo = ord(self.take())
            if self.peek() == "-" and self.src[self.pos + 1 : self.pos + 2] not in ("", "]"):
                self.take()
                if self.peek() == "\\":
                    self.take()
                    sub = self.escape(in_class=True)
                    if len(sub) != 1 or sub[0][0] != sub[0][1]:
                        raise self.error("class escape cannot end a range")
                    hi = sub[0][0]
                else:
                    hi = ord(self.take())
                if hi < lo:
                    raise self.error("reversed character-class range")
                ranges.append((lo, hi))
            else:
                ranges.append((lo, lo))
        norm = _normalize(ranges)
        return _Chars(_negate(norm) if negated else norm)


# ------------------------------------------------------------------------ NFA


class _Nfa:
    def __init__(self):
        self.eps: list[list[int]] = []
        self.edges: list[list[tuple[tuple[tuple[int, int], ...], int]]] = []

    def state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1


def _build_nfa(node, nfa: _Nfa) -> tuple[int, int]:
    """Thompson construction: returns (start, accept) fragment states."""
    if isinstance(node, _Chars):
        s, a = nfa.state(), nfa.state()
        nfa.edges[s].append((node.ranges, a))
        return s, a
    if isinstance(node, _Concat):
        if not node.parts:
            s = nfa.state()
            return s, s
        start, acc = _build_nfa(node.parts[0], nfa)
        for part in node.parts[1:]:
            s2, a2 = _build_nfa(part, nfa)
            nfa.eps[acc].append(s2)
            acc = a2
        return start, acc
    if isinstance(node, _Alt):
        s, a = nfa.state(), nfa.state()
        for opt in node.options:
            os_, oa = _build_nfa(opt, nfa)
            nfa.eps[s].append(os_)
            nfa.eps[oa].append(a)
        return s, a
    if isinstance(node, _Repeat):
        if node.max is None:
            # min copies then a Kleene loop
            s = nfa.state()
            cur = s
            for _ in range(node.min):
                fs, fa = _build_nfa(node.node, nfa)
                nfa.eps[cur].append(fs)
                cur = fa
            loop_s, loop_a = _build_nfa(node.node, nfa)
            acc = nfa.state()
            nfa.eps[cur].append(loop_s)
            nfa.eps[cur].append(acc)
            nfa.eps[loop_a].append(loop_s)
            nfa.eps[loop_a].append(acc)
            return s, acc
        # bounded: min mandatory copies + (max - min) optional ones
        s = nfa.state()
        acc = nfa.state()
        cur = s
        for _ in range(node.min):
            fs, fa = _build_nfa(node.node, nfa)
            nfa.eps[cur].append(fs)
            cur = fa
        for _ in range(node.max - node.min):
            nfa.eps[cur].append(acc)  # may stop here
            fs, fa = _build_nfa(node.node, nfa)
            nfa.eps[cur].append(fs)
            cur = fa
        nfa.eps[cur].append(acc)
        return s, acc
    raise AssertionError(f"unknown AST node {node!r}")


# ------------------------------------------------------------------------ DFA


class CharDfa:
    """Deterministic automaton over disjoint codepoint segments.

    `boundaries` are segment start codepoints (sorted); a character maps to
    segment `bisect_right(boundaries, cp) - 1`. `trans[state]` maps segment
    index → next state; missing entries are the dead state. Every state in
    the machine can still reach an accepting state (dead states are pruned),
    so "has a transition" is exactly "the match can still complete".
    """

    def __init__(self, boundaries: list[int], trans: list[dict[int, int]],
                 accepting: frozenset[int], start: int):
        self.boundaries = boundaries
        self.trans = trans
        self.accepting = accepting
        self.start = start

    @property
    def num_states(self) -> int:
        return len(self.trans)

    def segment_of(self, cp: int) -> int:
        return bisect_right(self.boundaries, cp) - 1

    def step(self, state: int, ch: str) -> int | None:
        return self.trans[state].get(self.segment_of(ord(ch)))

    def walk(self, state: int, text: str) -> int | None:
        """Advance through every char of `text`; None once the match dies."""
        for ch in text:
            state = self.trans[state].get(self.segment_of(ord(ch)))
            if state is None:
                return None
        return state

    def is_accepting(self, state: int) -> bool:
        return state in self.accepting

    def live_segments(self, state: int):
        return self.trans[state].keys()


def compile_regex(pattern: str) -> CharDfa:
    """Full pipeline: parse, NFA, subset-construct, prune dead states."""
    ast = _Parser(pattern).parse()
    nfa = _Nfa()
    start, accept = _build_nfa(ast, nfa)

    # Disjoint alphabet segments from every range boundary in the NFA.
    points = {0}
    for edges in nfa.edges:
        for ranges, _ in edges:
            for lo, hi in ranges:
                points.add(lo)
                if hi + 1 <= MAX_CODEPOINT:
                    points.add(hi + 1)
    boundaries = sorted(points)
    nseg = len(boundaries)

    def seg_range(seg: int) -> tuple[int, int]:
        lo = boundaries[seg]
        hi = (boundaries[seg + 1] - 1) if seg + 1 < nseg else MAX_CODEPOINT
        return lo, hi

    def eps_closure(states: frozenset[int]) -> frozenset[int]:
        stack = list(states)
        seen = set(states)
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    start_set = eps_closure(frozenset({start}))
    index: dict[frozenset[int], int] = {start_set: 0}
    order = [start_set]
    trans: list[dict[int, int]] = [{}]
    work = [start_set]
    while work:
        cur = work.pop()
        ci = index[cur]
        # segment → set of NFA targets
        by_seg: dict[int, set[int]] = {}
        for s in cur:
            for ranges, tgt in nfa.edges[s]:
                for lo, hi in ranges:
                    seg = bisect_right(boundaries, lo) - 1
                    while seg < nseg:
                        slo, shi = seg_range(seg)
                        if slo > hi:
                            break
                        by_seg.setdefault(seg, set()).add(tgt)
                        seg += 1
        for seg, tgts in by_seg.items():
            nxt = eps_closure(frozenset(tgts))
            ni = index.get(nxt)
            if ni is None:
                ni = index[nxt] = len(order)
                order.append(nxt)
                trans.append({})
                work.append(nxt)
            trans[ci][seg] = ni

    accepting = {i for i, st in enumerate(order) if accept in st}

    # Prune states that cannot reach acceptance (a transition into one is a
    # guaranteed dead match — masking must treat it as disallowed).
    reverse: dict[int, set[int]] = {}
    for i, t in enumerate(trans):
        for nxt in t.values():
            reverse.setdefault(nxt, set()).add(i)
    live = set(accepting)
    stack = list(accepting)
    while stack:
        s = stack.pop()
        for p in reverse.get(s, ()):
            if p not in live:
                live.add(p)
                stack.append(p)
    if 0 not in live:
        raise RegexSyntaxError(f"pattern matches nothing: {pattern!r}")
    remap = {old: new for new, old in enumerate(sorted(live))}
    pruned = [
        {seg: remap[n] for seg, n in trans[old].items() if n in live}
        for old in sorted(live)
    ]
    return CharDfa(
        boundaries=boundaries,
        trans=pruned,
        accepting=frozenset(remap[s] for s in accepting),
        start=remap[0],
    )
