"""Model-ingestion probe: validate a checkpoint before serving it.

TPU equivalent of the reference's native ingestion POCs (SURVEY.md §2.3 #2-3:
the safetensors reader that checks shard integrity and known-bad tensors, and
the ONNX session probe that proves a checkpoint loads into a runtime). Here
the probe:

  1. walks every safetensors shard with the C++ mmap reader (falling back to
     pure-Python parsing), checking header integrity, dtype support, NaN/Inf
     contamination, and per-shard tensor counts;
  2. cross-checks tensor names/shapes against the architecture config
     (config.json) the serving engine would build;
  3. optionally lowers the model's prefill step to StableHLO — proof the
     checkpoint's architecture actually compiles for the target — and emits
     a machine-readable metadata report.

Usage: python -m llmlb_tpu.tools.ingest_probe CHECKPOINT_DIR [--stablehlo OUT]
Exit code 0 = servable; 1 = validation findings; 2 = unreadable.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os

import numpy as np


@dataclasses.dataclass
class ProbeReport:
    checkpoint: str
    shards: list[dict] = dataclasses.field(default_factory=list)
    tensor_count: int = 0
    total_bytes: int = 0
    dtypes: dict = dataclasses.field(default_factory=dict)
    findings: list[str] = dataclasses.field(default_factory=list)
    config: dict | None = None
    stablehlo_bytes: int | None = None

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        return {**dataclasses.asdict(self), "ok": self.ok}


_SUPPORTED_DTYPES = {"F32", "F16", "BF16", "I32", "I64", "U8", "I8"}


def _iter_shard_tensors(path: str):
    """Yield (name, dtype_str, shape, np_array_or_None) per tensor. Uses the
    native mmap reader when built; otherwise parses the safetensors header
    in Python (header-only: no data validation on the fallback path). The
    fallback only engages when the native reader failed before yielding
    anything — a mid-iteration native failure must propagate rather than
    restart the walk and double-count tensors already yielded."""
    yielded = False
    try:
        from llmlb_tpu.native import NativeSafetensors

        st = NativeSafetensors(path)
        try:
            for name in st.keys():
                arr = st.get_tensor(name)
                yielded = True
                yield name, str(arr.dtype), tuple(arr.shape), arr
        finally:
            st.close()
        return
    except Exception:
        if yielded:
            raise
    # pure-python header walk
    import struct

    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen))
    for name, meta in header.items():
        if name == "__metadata__":
            continue
        yield name, meta.get("dtype", "?"), tuple(meta.get("shape", ())), None


def probe_checkpoint(model_dir: str, *, sample_values: bool = True,
                     stablehlo_out: str | None = None) -> ProbeReport:
    report = ProbeReport(checkpoint=os.path.abspath(model_dir))
    shards = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not shards:
        report.findings.append("no .safetensors shards found")
        return report

    seen: dict[str, tuple] = {}
    for path in shards:
        shard_info = {"file": os.path.basename(path),
                      "bytes": os.path.getsize(path), "tensors": 0}
        try:
            for name, dtype, shape, arr in _iter_shard_tensors(path):
                shard_info["tensors"] += 1
                report.tensor_count += 1
                report.dtypes[dtype] = report.dtypes.get(dtype, 0) + 1
                if name in seen:
                    report.findings.append(
                        f"duplicate tensor {name!r} (also in {seen[name][0]})"
                    )
                seen[name] = (os.path.basename(path), shape)
                if arr is None:  # header-only path: safetensors dtype string
                    bad_dtype = dtype.upper() not in _SUPPORTED_DTYPES
                else:
                    # native path: numpy dtype string. bfloat16 comes from
                    # ml_dtypes, for which np.issubdtype(.., np.number) is
                    # False — but it is the dominant LLM checkpoint dtype.
                    try:
                        bad_dtype = not (
                            str(dtype) == "bfloat16"
                            or np.issubdtype(np.dtype(dtype), np.number)
                        )
                    except TypeError:
                        bad_dtype = True
                if bad_dtype:
                    report.findings.append(
                        f"{name}: unsupported dtype {dtype}"
                    )
                if arr is not None and sample_values and arr.size:
                    flat = arr.reshape(-1)
                    # bounded sample: checking multi-GB tensors fully would
                    # defeat the point of an mmap probe. bfloat16 counts as
                    # floating even though np.issubdtype says otherwise.
                    is_float = (str(arr.dtype) == "bfloat16"
                                or np.issubdtype(arr.dtype, np.floating))
                    sample = np.asarray(
                        flat[:: max(1, flat.size // 4096)][:8192],
                        np.float32,
                    ) if is_float else None
                    if sample is not None and not np.isfinite(sample).all():
                        report.findings.append(
                            f"{name}: non-finite values (NaN/Inf) in shard "
                            f"{os.path.basename(path)}"
                        )
        except Exception as e:
            report.findings.append(
                f"{os.path.basename(path)}: unreadable ({e})"
            )
        report.total_bytes += shard_info["bytes"]
        report.shards.append(shard_info)

    # index coverage: every tensor the index names must exist
    index_path = os.path.join(model_dir, "model.safetensors.index.json")
    if os.path.isfile(index_path):
        try:
            with open(index_path) as f:
                weight_map = json.load(f).get("weight_map", {})
            missing = [t for t in weight_map if t not in seen]
            if missing:
                report.findings.append(
                    f"{len(missing)} tensors in the index are missing from "
                    f"shards (first: {missing[0]})"
                )
        except (OSError, ValueError) as e:
            report.findings.append(f"unreadable shard index: {e}")

    # architecture cross-check + optional StableHLO lowering
    config_path = os.path.join(model_dir, "config.json")
    if os.path.isfile(config_path):
        try:
            from llmlb_tpu.engine.weights import load_config

            cfg = load_config(model_dir)
            report.config = {
                "num_layers": cfg.num_layers,
                "hidden_size": cfg.hidden_size,
                "num_heads": cfg.num_heads,
                "num_kv_heads": cfg.num_kv_heads,
                "vocab_size": cfg.vocab_size,
                "max_position_embeddings": cfg.max_position_embeddings,
            }
            expected = cfg.num_layers
            found_layers = len({
                name.split(".")[2] for name in seen
                if name.startswith("model.layers.")
            })
            if found_layers and found_layers != expected:
                report.findings.append(
                    f"config says {expected} layers but shards carry "
                    f"{found_layers}"
                )
            if stablehlo_out is not None:
                report.stablehlo_bytes = _emit_stablehlo(cfg, stablehlo_out)
        except Exception as e:
            report.findings.append(f"config/arch check failed: {e}")
    else:
        report.findings.append("no config.json (cannot cross-check arch)")
    return report


def _emit_stablehlo(cfg, out_path: str) -> int:
    """Lower the prefill step to StableHLO text — proof the architecture
    compiles for the serving path (the ONNX-probe equivalent)."""
    import jax
    import jax.numpy as jnp

    from llmlb_tpu.models import family_for

    family = family_for(cfg)
    params = family.init_params(cfg, jax.random.PRNGKey(0))
    ck, cv = family.init_kv_cache(cfg, 1, 32)
    ids = jnp.zeros((1, 16), jnp.int32)
    lens = jnp.full((1,), 16, jnp.int32)

    lowered = jax.jit(
        lambda p, i, n, k, v: family.prefill(p, cfg, i, n, k, v)[0]
    ).lower(params, ids, lens, ck, cv)
    text = lowered.as_text()
    with open(out_path, "w") as f:
        f.write(text)
    return len(text)


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m llmlb_tpu.tools.ingest_probe",
        description="Validate a checkpoint before serving it.",
    )
    parser.add_argument("checkpoint_dir")
    parser.add_argument(
        "--stablehlo", metavar="OUT",
        help="also lower the prefill step to StableHLO text at OUT",
    )
    args = parser.parse_args(argv)
    if not os.path.isdir(args.checkpoint_dir):
        print(json.dumps({"error": f"not a directory: {args.checkpoint_dir}"}))
        return 2
    report = probe_checkpoint(args.checkpoint_dir, stablehlo_out=args.stablehlo)
    print(json.dumps(report.to_json(), indent=2))
    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
