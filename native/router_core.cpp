// Router core: the gateway's hot-path scheduler state in C++.
//
// Native-parity component: the reference keeps its TPS-EMA scheduler in
// compiled code (Rust balancer/mod.rs — EMA types.rs:98-121, selection
// :1922-1985, leases/active counts :2273-2427). Here the same state machine
// — per-(endpoint, model, api_kind) EMA map, per-endpoint active counts,
// per-model round-robin counters, and the scoring/tie-break selection — is a
// C++ library driven from LoadManager via ctypes, with the pure-Python
// implementation as the always-available fallback. Selection semantics are
// bit-identical to balancer.py _select_locked (tested side by side):
//   score = +inf when unmeasured else ema * telemetry_penalty
//   top = argmax(score); ties -> max penalty; remaining ties -> round-robin.
//
// All calls lock one mutex; the gateway's request rate (micro-ops per
// request) is far below contention range, and a single lock keeps the
// cross-language state machine easy to reason about.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <limits>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct TpsState {
  double ema = 0.0;
  int64_t samples = 0;
  double last_update = 0.0;
};

struct RouterCore {
  std::mutex mu;
  double alpha;
  std::unordered_map<std::string, TpsState> tps;  // eid \x1f model \x1f kind
  std::unordered_map<std::string, int64_t> active;     // endpoint id
  std::unordered_map<std::string, int64_t> rr;         // model
  int64_t total_requests = 0;

  explicit RouterCore(double a) : alpha(a) {}
};

std::string key3(const char* eid, const char* model, const char* kind) {
  std::string k(eid);
  k.push_back('\x1f');
  k += model;
  k.push_back('\x1f');
  k += kind;
  return k;
}

}  // namespace

// Implemented in hash_chain.cpp (same shared object): hex SHA-256 of a byte
// buffer. Reused for the rendezvous-hash owner below so the native and
// Python (hashlib) sides agree bit for bit.
extern "C" void sha256_hex(const char* data, int64_t len, char* out_hex);

extern "C" {

void* rc_new(double alpha) { return new RouterCore(alpha); }

void rc_free(void* h) { delete static_cast<RouterCore*>(h); }

void rc_update_tps(void* h, const char* eid, const char* model,
                   const char* kind, int64_t tokens, double duration_s,
                   double now) {
  if (duration_s <= 0 || tokens <= 0) return;
  auto* rc = static_cast<RouterCore*>(h);
  std::lock_guard<std::mutex> g(rc->mu);
  TpsState& st = rc->tps[key3(eid, model, kind)];
  const double tps = static_cast<double>(tokens) / duration_s;
  if (st.samples == 0) {
    st.ema = tps;
  } else {
    st.ema = rc->alpha * tps + (1.0 - rc->alpha) * st.ema;
  }
  st.samples += 1;
  st.last_update = now;
}

void rc_seed_tps(void* h, const char* eid, const char* model, const char* kind,
                 double ema, int64_t samples, double now) {
  auto* rc = static_cast<RouterCore*>(h);
  std::lock_guard<std::mutex> g(rc->mu);
  rc->tps[key3(eid, model, kind)] = TpsState{ema, samples, now};
}

// Returns the EMA, or -1.0 when the key is unmeasured (absent or 0 samples).
double rc_get_tps(void* h, const char* eid, const char* model,
                  const char* kind) {
  auto* rc = static_cast<RouterCore*>(h);
  std::lock_guard<std::mutex> g(rc->mu);
  auto it = rc->tps.find(key3(eid, model, kind));
  if (it == rc->tps.end() || it->second.samples == 0) return -1.0;
  return it->second.ema;
}

void rc_clear_endpoint(void* h, const char* eid) {
  auto* rc = static_cast<RouterCore*>(h);
  std::lock_guard<std::mutex> g(rc->mu);
  const std::string prefix = std::string(eid) + '\x1f';
  for (auto it = rc->tps.begin(); it != rc->tps.end();) {
    if (it->first.compare(0, prefix.size(), prefix) == 0) {
      it = rc->tps.erase(it);
    } else {
      ++it;
    }
  }
}

int64_t rc_tracked_keys(void* h) {
  auto* rc = static_cast<RouterCore*>(h);
  std::lock_guard<std::mutex> g(rc->mu);
  return static_cast<int64_t>(rc->tps.size());
}

// begin_request: unconditional lease acquire (+1 active, +1 total).
void rc_begin(void* h, const char* eid) {
  auto* rc = static_cast<RouterCore*>(h);
  std::lock_guard<std::mutex> g(rc->mu);
  rc->active[eid] += 1;
  rc->total_requests += 1;
}

void rc_release(void* h, const char* eid) {
  auto* rc = static_cast<RouterCore*>(h);
  std::lock_guard<std::mutex> g(rc->mu);
  int64_t& a = rc->active[eid];
  if (a > 0) a -= 1;
}

int64_t rc_active(void* h, const char* eid) {
  auto* rc = static_cast<RouterCore*>(h);
  std::lock_guard<std::mutex> g(rc->mu);
  auto it = rc->active.find(eid);
  return it == rc->active.end() ? 0 : it->second;
}

int64_t rc_total_active(void* h) {
  auto* rc = static_cast<RouterCore*>(h);
  std::lock_guard<std::mutex> g(rc->mu);
  int64_t total = 0;
  for (const auto& kv : rc->active) total += kv.second;
  return total;
}

int64_t rc_total_requests(void* h) {
  auto* rc = static_cast<RouterCore*>(h);
  std::lock_guard<std::mutex> g(rc->mu);
  return rc->total_requests;
}

// Selection over n candidates (parallel arrays of endpoint ids and
// telemetry penalties). Candidates at/over the active cap are excluded.
// Returns the index of the chosen candidate, or -1 when none qualify.
// When admit != 0 the chosen endpoint's lease is acquired atomically under
// the same lock (try_admit semantics — no select-then-begin race).
int64_t rc_select(void* h, const char* model, const char** eids,
                  const double* penalties, int64_t n, int64_t cap,
                  const char* kind, int admit) {
  auto* rc = static_cast<RouterCore*>(h);
  std::lock_guard<std::mutex> g(rc->mu);

  const double inf = std::numeric_limits<double>::infinity();
  std::vector<int64_t> idx;
  std::vector<double> score;
  idx.reserve(n);
  score.reserve(n);
  for (int64_t i = 0; i < n; ++i) {
    auto ait = rc->active.find(eids[i]);
    const int64_t a = ait == rc->active.end() ? 0 : ait->second;
    if (a >= cap) continue;
    auto tit = rc->tps.find(key3(eids[i], model, kind));
    const bool unmeasured = tit == rc->tps.end() || tit->second.samples == 0;
    idx.push_back(i);
    score.push_back(unmeasured ? inf : tit->second.ema * penalties[i]);
  }
  if (idx.empty()) return -1;

  const double best = *std::max_element(score.begin(), score.end());
  std::vector<int64_t> top;
  for (size_t j = 0; j < idx.size(); ++j) {
    if (score[j] == best) top.push_back(idx[j]);
  }
  if (top.size() > 1) {
    double best_pen = -inf;
    for (int64_t i : top) best_pen = std::max(best_pen, penalties[i]);
    std::vector<int64_t> filtered;
    for (int64_t i : top) {
      if (penalties[i] == best_pen) filtered.push_back(i);
    }
    top.swap(filtered);
  }
  int64_t& counter = rc->rr[model];
  const int64_t chosen = top[counter % static_cast<int64_t>(top.size())];
  counter += 1;
  if (admit) {
    rc->active[eids[chosen]] += 1;
    rc->total_requests += 1;
  }
  return chosen;
}

// (ema, samples, last_update) for one tracked key. Returns 1 when the key is
// measured, 0 otherwise. Feeds TPS gossip: the publisher ships the exact
// local state and the receiver compares last_update for last-writer-wins.
int32_t rc_tps_info(void* h, const char* eid, const char* model,
                    const char* kind, double* ema, int64_t* samples,
                    double* last_update) {
  auto* rc = static_cast<RouterCore*>(h);
  std::lock_guard<std::mutex> g(rc->mu);
  auto it = rc->tps.find(key3(eid, model, kind));
  if (it == rc->tps.end() || it->second.samples == 0) return 0;
  *ema = it->second.ema;
  *samples = it->second.samples;
  *last_update = it->second.last_update;
  return 1;
}

// Rendezvous (highest-random-weight) consistent-hash owner of `key` over n
// candidate ids: argmax over the first 8 bytes (big-endian) of
// sha256("key|id"), ties toward the lexicographically smallest id — the
// exact rule of balancer.hrw_owner, so every worker (and both languages)
// maps a prefix to the same endpoint with zero coordination. Returns the
// winning index, or -1 for an empty candidate list.
int64_t hrw_select(const char* key, const char** ids, int64_t n) {
  if (n <= 0) return -1;
  int64_t best = -1;
  uint64_t best_w = 0;
  std::string buf;
  char hex[65];
  for (int64_t i = 0; i < n; ++i) {
    buf.assign(key);
    buf.push_back('|');
    buf += ids[i];
    sha256_hex(buf.data(), static_cast<int64_t>(buf.size()), hex);
    uint64_t w = 0;
    for (int j = 0; j < 16; ++j) {
      const char c = hex[j];
      w = (w << 4) |
          static_cast<uint64_t>(c <= '9' ? c - '0' : c - 'a' + 10);
    }
    if (best < 0 || w > best_w ||
        (w == best_w && std::strcmp(ids[i], ids[best]) < 0)) {
      best = i;
      best_w = w;
    }
  }
  return best;
}

// Constant-time byte comparison for the auth hot path (API keys, JWT
// signatures): scans both buffers fully regardless of where they differ,
// so the comparison leaks length only (Python twin: hmac.compare_digest).
int32_t ct_equal(const uint8_t* a, int64_t alen, const uint8_t* b,
                 int64_t blen) {
  uint64_t acc = static_cast<uint64_t>(alen ^ blen);
  const int64_t n = alen < blen ? alen : blen;
  for (int64_t i = 0; i < n; ++i) acc |= static_cast<uint64_t>(a[i] ^ b[i]);
  // fold in trailing bytes of the longer buffer so timing does not depend
  // on the shorter prefix matching
  for (int64_t i = n; i < alen; ++i) acc |= static_cast<uint64_t>(a[i]) | 1;
  for (int64_t i = n; i < blen; ++i) acc |= static_cast<uint64_t>(b[i]) | 1;
  return acc == 0 ? 1 : 0;
}

// Snapshot of the TPS map as tab/newline-separated text:
//   eid \t model \t kind \t ema \t samples \t last_update \n
// Returns the number of bytes required; writes up to `cap` bytes into `buf`
// (call once with cap=0 to size, then again with a buffer).
int64_t rc_snapshot(void* h, char* buf, int64_t cap) {
  auto* rc = static_cast<RouterCore*>(h);
  std::lock_guard<std::mutex> g(rc->mu);
  std::string out;
  out.reserve(rc->tps.size() * 64);
  char line[256];
  for (const auto& kv : rc->tps) {
    std::string k = kv.first;
    std::replace(k.begin(), k.end(), '\x1f', '\t');
    std::snprintf(line, sizeof(line), "\t%.17g\t%lld\t%.17g\n", kv.second.ema,
                  static_cast<long long>(kv.second.samples),
                  kv.second.last_update);
    out += k;
    out += line;
  }
  const int64_t needed = static_cast<int64_t>(out.size());
  if (buf != nullptr && cap > 0) {
    std::memcpy(buf, out.data(), std::min<int64_t>(needed, cap));
  }
  return needed;
}

}  // extern "C"
