// safetensors_reader: mmap-based zero-copy safetensors file reader.
//
// TPU-native equivalent of the reference's poc/nemotron-safetensors-cpp probe
// (SURVEY.md §2.3 item 2), built as a reusable shared library feeding the
// engine's weight ingestion: the Python side gets (name, dtype, shape, data
// pointer) per tensor and wraps the mapped region in numpy arrays without
// copying, so multi-GB checkpoints stream host->HBM without a host-side copy.
//
// File format (public spec, huggingface/safetensors): 8-byte little-endian
// header length N, then N bytes of JSON: {"name": {"dtype": "F32",
// "shape": [..], "data_offsets": [begin, end]}, ...} with optional
// "__metadata__", then the tensor byte buffer.

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

struct TensorInfo {
  std::string name;
  std::string dtype;
  std::vector<int64_t> shape;
  uint64_t begin = 0;
  uint64_t end = 0;
};

struct File {
  int fd = -1;
  uint8_t *map = nullptr;
  size_t size = 0;
  size_t data_start = 0;
  std::vector<TensorInfo> tensors;
  std::string error;
};

// --- minimal JSON parser for the safetensors header subset -----------------

struct Parser {
  const char *p;
  const char *end;

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }
  bool expect(char c) {
    skip_ws();
    if (p < end && *p == c) {
      ++p;
      return true;
    }
    return false;
  }
  bool parse_string(std::string &out) {
    skip_ws();
    if (p >= end || *p != '"')
      return false;
    ++p;
    out.clear();
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        ++p;
        switch (*p) {
        case 'n': out += '\n'; break;
        case 't': out += '\t'; break;
        case 'r': out += '\r'; break;
        case '\\': out += '\\'; break;
        case '"': out += '"'; break;
        case '/': out += '/'; break;
        default: out += *p; break; // \uXXXX not needed for tensor names
        }
      } else {
        out += *p;
      }
      ++p;
    }
    if (p >= end)
      return false;
    ++p; // closing quote
    return true;
  }
  bool parse_uint(uint64_t &out) {
    skip_ws();
    if (p >= end || *p < '0' || *p > '9')
      return false;
    out = 0;
    while (p < end && *p >= '0' && *p <= '9') {
      out = out * 10 + uint64_t(*p - '0');
      ++p;
    }
    return true;
  }
  // skip any JSON value (for __metadata__ contents)
  bool skip_value() {
    skip_ws();
    if (p >= end)
      return false;
    if (*p == '"') {
      std::string s;
      return parse_string(s);
    }
    if (*p == '{' || *p == '[') {
      char open = *p, close = (*p == '{') ? '}' : ']';
      int depth = 0;
      bool in_str = false;
      while (p < end) {
        char c = *p;
        if (in_str) {
          if (c == '\\')
            ++p;
          else if (c == '"')
            in_str = false;
        } else if (c == '"') {
          in_str = true;
        } else if (c == open) {
          ++depth;
        } else if (c == close) {
          --depth;
          if (depth == 0) {
            ++p;
            return true;
          }
        }
        ++p;
      }
      return false;
    }
    // number / literal
    while (p < end && *p != ',' && *p != '}' && *p != ']')
      ++p;
    return true;
  }
};

bool parse_header(File *f, const char *json, size_t len) {
  Parser ps{json, json + len};
  if (!ps.expect('{'))
    return false;
  ps.skip_ws();
  if (ps.p < ps.end && *ps.p == '}')
    return true; // empty header
  while (true) {
    std::string key;
    if (!ps.parse_string(key))
      return false;
    if (!ps.expect(':'))
      return false;
    if (key == "__metadata__") {
      if (!ps.skip_value())
        return false;
    } else {
      TensorInfo t;
      t.name = key;
      if (!ps.expect('{'))
        return false;
      while (true) {
        std::string field;
        if (!ps.parse_string(field))
          return false;
        if (!ps.expect(':'))
          return false;
        if (field == "dtype") {
          if (!ps.parse_string(t.dtype))
            return false;
        } else if (field == "shape") {
          if (!ps.expect('['))
            return false;
          ps.skip_ws();
          if (ps.p < ps.end && *ps.p == ']') {
            ++ps.p;
          } else {
            while (true) {
              uint64_t d;
              if (!ps.parse_uint(d))
                return false;
              t.shape.push_back(int64_t(d));
              if (ps.expect(']'))
                break;
              if (!ps.expect(','))
                return false;
            }
          }
        } else if (field == "data_offsets") {
          if (!ps.expect('['))
            return false;
          if (!ps.parse_uint(t.begin))
            return false;
          if (!ps.expect(','))
            return false;
          if (!ps.parse_uint(t.end))
            return false;
          if (!ps.expect(']'))
            return false;
        } else {
          if (!ps.skip_value())
            return false;
        }
        if (ps.expect('}'))
          break;
        if (!ps.expect(','))
          return false;
      }
      f->tensors.push_back(std::move(t));
    }
    if (ps.expect('}'))
      return true;
    if (!ps.expect(','))
      return false;
  }
}

} // namespace

extern "C" {

void *st_open(const char *path) {
  File *f = new File();
  f->fd = open(path, O_RDONLY);
  if (f->fd < 0) {
    f->error = "cannot open file";
    return f;
  }
  struct stat st;
  if (fstat(f->fd, &st) != 0 || st.st_size < 8) {
    f->error = "stat failed or file too small";
    return f;
  }
  f->size = size_t(st.st_size);
  f->map = static_cast<uint8_t *>(
      mmap(nullptr, f->size, PROT_READ, MAP_PRIVATE, f->fd, 0));
  if (f->map == MAP_FAILED) {
    f->map = nullptr;
    f->error = "mmap failed";
    return f;
  }
  uint64_t header_len = 0;
  std::memcpy(&header_len, f->map, 8); // little-endian hosts only (x86/arm)
  if (header_len > f->size - 8) {     // written to avoid uint64 wraparound
    f->error = "header length exceeds file size";
    return f;
  }
  f->data_start = 8 + size_t(header_len);
  if (!parse_header(f, reinterpret_cast<const char *>(f->map + 8),
                    size_t(header_len))) {
    f->tensors.clear();
    f->error = "header JSON parse failed";
    return f;
  }
  // validate offsets against the data region
  size_t data_len = f->size - f->data_start;
  for (const auto &t : f->tensors) {
    if (t.end < t.begin || t.end > data_len) {
      f->tensors.clear();
      f->error = "tensor data_offsets out of range: " + t.name;
      return f;
    }
  }
  return f;
}

const char *st_error(void *handle) {
  File *f = static_cast<File *>(handle);
  return f->error.empty() ? nullptr : f->error.c_str();
}

int64_t st_num_tensors(void *handle) {
  return int64_t(static_cast<File *>(handle)->tensors.size());
}

const char *st_tensor_name(void *handle, int64_t i) {
  return static_cast<File *>(handle)->tensors[size_t(i)].name.c_str();
}

const char *st_tensor_dtype(void *handle, int64_t i) {
  return static_cast<File *>(handle)->tensors[size_t(i)].dtype.c_str();
}

int64_t st_tensor_ndim(void *handle, int64_t i) {
  return int64_t(static_cast<File *>(handle)->tensors[size_t(i)].shape.size());
}

void st_tensor_shape(void *handle, int64_t i, int64_t *out) {
  const auto &shape = static_cast<File *>(handle)->tensors[size_t(i)].shape;
  for (size_t d = 0; d < shape.size(); ++d)
    out[d] = shape[d];
}

// Returns the pointer into the mapping; nbytes via out param.
const uint8_t *st_tensor_data(void *handle, int64_t i, int64_t *nbytes) {
  File *f = static_cast<File *>(handle);
  const TensorInfo &t = f->tensors[size_t(i)];
  *nbytes = int64_t(t.end - t.begin);
  return f->map + f->data_start + t.begin;
}

void st_close(void *handle) {
  File *f = static_cast<File *>(handle);
  if (f->map)
    munmap(f->map, f->size);
  if (f->fd >= 0)
    close(f->fd);
  delete f;
}

} // extern "C"
