// sse_scan: streaming SSE frame scanner for the proxy hot path.
//
// Native twin of gateway/token_accounting.py's line splitter (the reference's
// per-chunk SSE parse loop is Rust, api/proxy.rs:120-270). Feed raw bytes as
// they pass through; the scanner splits `data:` lines, counts frames, and
// extracts the last `"usage": {...}` object's prompt/completion token values
// with a targeted scan (no general JSON parse on the hot path). Content-text
// accumulation for the estimation fallback stays in Python — it only runs
// when an upstream omitted usage, off the hot path.

#include <cstdint>
#include <cstring>
#include <string>

namespace {

struct Scanner {
  std::string buffer;
  int64_t frames = 0;
  int64_t prompt_tokens = -1;
  int64_t completion_tokens = -1;

  static bool find_int_after(const std::string &s, const char *key,
                             size_t from, int64_t *out) {
    size_t k = s.find(key, from);
    if (k == std::string::npos)
      return false;
    size_t p = s.find(':', k + std::strlen(key));
    if (p == std::string::npos)
      return false;
    ++p;
    while (p < s.size() && (s[p] == ' ' || s[p] == '\t'))
      ++p;
    if (p >= s.size() || s[p] < '0' || s[p] > '9')
      return false;
    int64_t v = 0;
    while (p < s.size() && s[p] >= '0' && s[p] <= '9') {
      v = v * 10 + (s[p] - '0');
      ++p;
    }
    *out = v;
    return true;
  }

  void feed_line(const std::string &line) {
    size_t start = 0;
    while (start < line.size() &&
           (line[start] == ' ' || line[start] == '\r'))
      ++start;
    if (line.compare(start, 5, "data:") != 0)
      return;
    size_t ds = start + 5;
    while (ds < line.size() && line[ds] == ' ')
      ++ds;
    if (ds >= line.size())
      return;
    if (line.compare(ds, 6, "[DONE]") == 0)
      return;
    ++frames;
    size_t u = line.find("\"usage\"", ds);
    if (u == std::string::npos)
      return;
    int64_t pt, ct;
    bool got = false;
    if (find_int_after(line, "\"prompt_tokens\"", u, &pt)) {
      got = true;
    } else if (find_int_after(line, "\"input_tokens\"", u, &pt)) {
      got = true;
    } else {
      pt = -1;
    }
    if (find_int_after(line, "\"completion_tokens\"", u, &ct)) {
      got = true;
    } else if (find_int_after(line, "\"output_tokens\"", u, &ct)) {
      got = true;
    } else {
      ct = -1;
    }
    // only accept a usage object that reported something non-zero, matching
    // the Python accumulator's "usage != (0, 0)" rule
    if (got && (pt > 0 || ct > 0)) {
      prompt_tokens = pt < 0 ? 0 : pt;
      completion_tokens = ct < 0 ? 0 : ct;
    }
  }

  void feed(const uint8_t *data, size_t len) {
    buffer.append(reinterpret_cast<const char *>(data), len);
    size_t pos = 0;
    while (true) {
      size_t nl = buffer.find('\n', pos);
      if (nl == std::string::npos)
        break;
      feed_line(buffer.substr(pos, nl - pos));
      pos = nl + 1;
    }
    buffer.erase(0, pos);
  }
};

} // namespace

extern "C" {

void *sse_new() { return new Scanner(); }

void sse_feed(void *handle, const uint8_t *data, int64_t len) {
  static_cast<Scanner *>(handle)->feed(data, size_t(len));
}

int64_t sse_frames(void *handle) {
  return static_cast<Scanner *>(handle)->frames;
}

// Returns 1 if a usage object was captured; fills prompt/completion tokens.
int32_t sse_usage(void *handle, int64_t *prompt, int64_t *completion) {
  Scanner *s = static_cast<Scanner *>(handle);
  if (s->prompt_tokens < 0 && s->completion_tokens < 0)
    return 0;
  *prompt = s->prompt_tokens < 0 ? 0 : s->prompt_tokens;
  *completion = s->completion_tokens < 0 ? 0 : s->completion_tokens;
  return 1;
}

void sse_free(void *handle) { delete static_cast<Scanner *>(handle); }

} // extern "C"
