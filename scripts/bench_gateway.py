"""Gateway overhead bench: req/s + latency through the full proxy path.

The reference's one committed benchmark is wrk against its Rust router with a
local upstream — 170,600 req/s, p50 0.249 ms (BASELINE.md). This measures the
same thing for this gateway: an in-process mock OpenAI upstream, the real app
(auth, audit, gate, TPS accounting all active), and N concurrent non-streaming
/v1/chat/completions callers. Run:

    python scripts/bench_gateway.py [--seconds 10] [--concurrency 50]

Prints one JSON line. Python/aiohttp will not reach a Rust router's ceiling;
the number is tracked honestly in bench_runs/MEASUREMENTS.md and bounds how
much gateway CPU one TPU engine's request rate can consume.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time


async def run_bench(seconds: float, concurrency: int) -> dict:
    from tests.support import GatewayHarness, MockOpenAIEndpoint

    gw = await GatewayHarness.create()
    upstream = await MockOpenAIEndpoint(model="bench-model").start()
    try:
        gw.register_mock(upstream.url, ["bench-model"])
        headers = dict(await gw.inference_headers())
        payload = {
            "model": "bench-model",
            "messages": [{"role": "user", "content": "ping"}],
            "stream": False,
        }

        # warmup
        for _ in range(20):
            resp = await gw.client.post(
                "/v1/chat/completions", json=payload, headers=headers
            )
            assert resp.status == 200, await resp.text()
            await resp.read()

        latencies: list[float] = []
        done = 0
        errors = 0
        deadline = time.perf_counter() + seconds

        async def worker() -> None:
            nonlocal done, errors
            while time.perf_counter() < deadline:
                t0 = time.perf_counter()
                try:
                    resp = await gw.client.post(
                        "/v1/chat/completions", json=payload, headers=headers
                    )
                    await resp.read()
                    if resp.status == 200:
                        done += 1
                        latencies.append(time.perf_counter() - t0)
                    else:
                        errors += 1
                except Exception:
                    errors += 1

        t0 = time.perf_counter()
        await asyncio.gather(*(worker() for _ in range(concurrency)))
        elapsed = time.perf_counter() - t0

        latencies.sort()

        def pct(p: float) -> float:
            if not latencies:
                return 0.0
            return latencies[min(len(latencies) - 1, int(len(latencies) * p))]

        return {
            "metric": "gateway_proxy_requests_per_sec",
            "value": round(done / elapsed, 1),
            "unit": "req/s",
            "vs_baseline": round(done / elapsed / 170600.51, 5),
            "requests": done,
            "errors": errors,
            "seconds": round(elapsed, 2),
            "concurrency": concurrency,
            "p50_ms": round(1000 * pct(0.50), 2),
            "p90_ms": round(1000 * pct(0.90), 2),
            "p99_ms": round(1000 * pct(0.99), 2),
            "native_router": gw.state.load_manager.stats().get(
                "native_router", False
            ),
        }
    finally:
        await upstream.stop()
        await gw.close()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--seconds", type=float, default=10.0)
    parser.add_argument("--concurrency", type=int, default=50)
    args = parser.parse_args()
    result = asyncio.run(run_bench(args.seconds, args.concurrency))
    print(json.dumps(result))


if __name__ == "__main__":
    sys.path.insert(0, ".")
    main()
