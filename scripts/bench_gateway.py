"""Gateway overhead bench: req/s + latency through the full proxy path.

The reference's one committed benchmark is wrk against its Rust router with a
local upstream — 170,600 req/s, p50 0.249 ms (BASELINE.md). This measures the
same thing for this gateway: an in-process mock OpenAI upstream, the real app
(auth, audit, gate, TPS accounting all active), and N concurrent non-streaming
/v1/chat/completions callers. Run:

    python scripts/bench_gateway.py [--seconds 10] [--concurrency 50]

Prints one JSON line. Python/aiohttp will not reach a Rust router's ceiling;
the number is tracked honestly in bench_runs/MEASUREMENTS.md and bounds how
much gateway CPU one TPU engine's request rate can consume.

The gateway's own /metrics is scraped before and after the timed window and
the TTFT/E2E/queue-wait percentile deltas are printed under "prometheus", so
bench output and the Prometheus view agree on one source of truth.

Multi-worker modes (docs/deployment.md):

    python scripts/bench_gateway.py --workload throughput [--workers 4]

spawns REAL gateway processes (`serve --workers N`, SO_REUSEPORT) in front
of stub-engine processes and drives closed-loop load from separate client
processes, recording the 1..N scaling curve with p50/p99 at matched load
AND per-request gateway CPU from /proc (the core-count-independent figure
— see the docstring on run_throughput_bench for why wall-clock scaling on
a 2-core CI box measures the container, not the gateway).

    python scripts/bench_gateway.py --workload chaos --workers 4

runs the chaos drill across N shared-nothing worker states wired by the
real gossip bus: >=99% client success while an endpoint flaps, plus the
directly measured cross-worker breaker-propagation latency.

A second mode measures the prefix KV cache end to end with a REAL in-process
tpu:// engine (CPU backend) behind the gateway:

    python scripts/bench_gateway.py --workload shared-prefix [--requests 24]

Every request shares one long system prompt with a varying user tail — the
production chat shape. The bench classifies each request hit/miss from the
engine's own prefix counters and reports the hit rate, prefill tokens served
from cache, and mean TTFT split by hit vs miss, alongside the engine
/metrics exposition names so Prometheus shows the same story.

Disaggregated prefill/decode (docs/disaggregation.md):

    python scripts/bench_gateway.py --workload disagg

serves the slo-mix ITL scenario (background decoders + concurrent
420-token prompts) three ways — no protection, PR 10's chunk budget, and
PR 11's `--role split` — and reports background ITL, long-prompt TTFT,
the per-loop prefill-dispatch ledger (the zero-prefill-on-decode-loop
invariant), and handoff counts.

KV page shipping + host-RAM offload (docs/kv-cache.md):

    python scripts/bench_gateway.py --workload kv-ship

runs a 384-token-context preempt/resume and an evicted-prefix warm
return, each twice on identical traffic — replay (recompute) vs ship
(host-tier restore) — and reports resume gap, return TTFT, the
prefill-dispatch ledger (zero dispatches per shipped resume), and
cross-mode token identity.

Fused decode dispatch (docs/fused-decode.md):

    python scripts/bench_gateway.py --workload fused

drives mixed traffic (plain + LoRA + JSON-constrained, speculation and
int8 KV on) through the full gateway twice — LLMLB_FUSED_DECODE on vs
off — and reports per-step device dispatch counts from the scheduler's
ledger (fused holds exactly 1), decode tok/s both modes, and cross-mode
token identity.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import re
import sys
import time


def _pin_platform() -> None:
    """On CPU-only hosts jax's TPU backend init hangs ~30 s per retry inside
    make_c_api_client (BENCH_r05 tail); decide from host evidence BEFORE the
    first device touch. Shares bench.py's detection so both harnesses agree."""
    sys.path.insert(0, ".")
    from bench import force_cpu_platform, tpu_possibly_present

    if not tpu_possibly_present():
        force_cpu_platform("no TPU evidence on this host; "
                           "set LLMLB_BENCH_FORCE_TPU_PROBE=1 to override")

_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)\{(.*)\}\s+(-?[0-9.eE+]+)$"
)
_LE_RE = re.compile(r'le="([^"]+)"')

GATEWAY_HISTOGRAMS = (
    "llmlb_gateway_ttft_seconds",
    "llmlb_gateway_e2e_seconds",
    "llmlb_gateway_queue_wait_seconds",
)


def parse_gateway_histograms(text: str) -> dict:
    """Cumulative bucket counts per histogram family, summed across label
    sets (models/endpoints): {family: {le: count}}."""
    out: dict[str, dict[str, float]] = {name: {} for name in GATEWAY_HISTOGRAMS}
    for line in text.splitlines():
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labels, value = m.group(1), m.group(2), float(m.group(3))
        for family in GATEWAY_HISTOGRAMS:
            if name == family + "_bucket":
                le = _LE_RE.search(labels)
                if le:
                    buckets = out[family]
                    buckets[le.group(1)] = buckets.get(le.group(1), 0.0) + value
    return out


def delta_percentile(before: dict, after: dict, pct: float) -> float | None:
    """Percentile of the requests observed BETWEEN two scrapes, linearly
    interpolated within the landing bucket — the same estimate Prometheus'
    histogram_quantile makes over a rate() window."""
    edges = sorted((k for k in after if k != "+Inf"), key=float)
    deltas = []
    for le in edges + ["+Inf"]:
        deltas.append(after.get(le, 0.0) - before.get(le, 0.0))
    total = deltas[-1]
    if total <= 0:
        return None
    target = total * pct / 100.0
    lower = 0.0
    prev_cum = 0.0
    for le, cum in zip(edges, deltas[:-1]):
        count = cum - prev_cum
        if count > 0 and cum >= target:
            frac = (target - prev_cum) / count
            return lower + frac * (float(le) - lower)
        prev_cum = cum
        lower = float(le)
    return float(edges[-1]) if edges else None


async def scrape_metrics(gw) -> dict:
    """One GET /metrics, parsed into per-family cumulative buckets."""
    resp = await gw.client.get("/metrics")
    assert resp.status == 200, await resp.text()
    return parse_gateway_histograms(await resp.text())


async def run_bench(seconds: float, concurrency: int) -> dict:
    from tests.support import GatewayHarness, MockOpenAIEndpoint

    gw = await GatewayHarness.create()
    upstream = await MockOpenAIEndpoint(model="bench-model").start()
    try:
        gw.register_mock(upstream.url, ["bench-model"])
        headers = dict(await gw.inference_headers())
        payload = {
            "model": "bench-model",
            "messages": [{"role": "user", "content": "ping"}],
            "stream": False,
        }

        # warmup
        for _ in range(20):
            resp = await gw.client.post(
                "/v1/chat/completions", json=payload, headers=headers
            )
            assert resp.status == 200, await resp.text()
            await resp.read()

        # Scrape-before: the percentile deltas below cover exactly the timed
        # window, so bench output and Prometheus agree on one source of truth.
        before = await scrape_metrics(gw)

        latencies: list[float] = []
        done = 0
        errors = 0
        deadline = time.perf_counter() + seconds

        async def worker() -> None:
            nonlocal done, errors
            while time.perf_counter() < deadline:
                t0 = time.perf_counter()
                try:
                    resp = await gw.client.post(
                        "/v1/chat/completions", json=payload, headers=headers
                    )
                    await resp.read()
                    if resp.status == 200:
                        done += 1
                        latencies.append(time.perf_counter() - t0)
                    else:
                        errors += 1
                except Exception:
                    errors += 1

        t0 = time.perf_counter()
        await asyncio.gather(*(worker() for _ in range(concurrency)))
        elapsed = time.perf_counter() - t0

        after = await scrape_metrics(gw)
        prom = {}
        for family, short in (("llmlb_gateway_ttft_seconds", "ttft"),
                              ("llmlb_gateway_e2e_seconds", "e2e"),
                              ("llmlb_gateway_queue_wait_seconds",
                               "queue_wait")):
            for p in (50, 99):
                v = delta_percentile(before[family], after[family], p)
                prom[f"{short}_p{p}_ms"] = (round(v * 1000, 3)
                                            if v is not None else None)

        # SLO goodput: the same attainment counters Prometheus scrapes
        # (llmlb_gateway_slo_*), summarized as the bench's goodput line
        resp = await gw.client.get("/metrics")
        exposition = await resp.text()

        def slo_sum(name: str) -> float:
            total = 0.0
            for line in exposition.splitlines():
                if line.startswith(name + "{") or line.startswith(name + " "):
                    total += float(line.rsplit(" ", 1)[1])
            return total

        eligible = slo_sum("llmlb_gateway_slo_eligible_total")
        met = slo_sum("llmlb_gateway_slo_met_total")
        slo_cfg = gw.state.metrics.slo
        goodput = {
            "slo_eligible": int(eligible),
            "slo_met": int(met),
            "ratio": round(met / eligible, 4) if eligible else None,
            "ttft_miss": int(slo_sum("llmlb_gateway_slo_ttft_miss_total")),
            "itl_miss": int(slo_sum("llmlb_gateway_slo_itl_miss_total")),
            "ttft_target_ms": (round(slo_cfg.ttft_target_s * 1000, 1)
                               if slo_cfg else None),
            "itl_target_ms": (round(slo_cfg.itl_target_s * 1000, 1)
                              if slo_cfg else None),
        }
        print(
            f"[bench] goodput: {goodput['slo_met']}/{goodput['slo_eligible']}"
            f" requests met SLO (ratio {goodput['ratio']}, TTFT target "
            f"{goodput['ttft_target_ms']}ms, ITL target "
            f"{goodput['itl_target_ms']}ms)",
            file=sys.stderr,
        )

        latencies.sort()

        def pct(p: float) -> float:
            if not latencies:
                return 0.0
            return latencies[min(len(latencies) - 1, int(len(latencies) * p))]

        return {
            "metric": "gateway_proxy_requests_per_sec",
            "value": round(done / elapsed, 1),
            "unit": "req/s",
            "vs_baseline": round(done / elapsed / 170600.51, 5),
            "requests": done,
            "errors": errors,
            "seconds": round(elapsed, 2),
            "concurrency": concurrency,
            "p50_ms": round(1000 * pct(0.50), 2),
            "p90_ms": round(1000 * pct(0.90), 2),
            "p99_ms": round(1000 * pct(0.99), 2),
            "goodput": goodput,
            "prometheus": prom,
            "native_router": gw.state.load_manager.stats().get(
                "native_router", False
            ),
        }
    finally:
        await upstream.stop()
        await gw.close()


async def run_prefix_bench(requests: int) -> dict:
    """Shared-prefix workload against a real tpu:// engine (CPU backend)
    proxied through the full gateway: repeated system prompt, varying tails.
    Sequential on purpose — each request is classified hit/miss from the
    engine's prefix counters, so TTFT can be split by cache outcome."""
    import aiohttp
    from aiohttp.test_utils import TestServer

    from llmlb_tpu.engine.server import create_engine_app
    from llmlb_tpu.engine.service import Engine
    from tests.support import GatewayHarness

    engine = Engine.from_preset(
        "debug-tiny", num_slots=4, slot_capacity=256,
        prefill_buckets=(16, 32, 64),
    )
    eng_server = TestServer(create_engine_app(engine, owns_engine=False))
    await eng_server.start_server()
    gw = await GatewayHarness.create()
    try:
        gw.register_mock(
            f"http://127.0.0.1:{eng_server.port}", [engine.model_id]
        )
        headers = dict(await gw.inference_headers())
        # ~130 byte-tokens of shared head, well past the 16-token min prefix
        system = ("You are the TPU serving assistant. Answer briefly and "
                  "cite the runbook section when relevant. ") * 2
        metrics = engine.core.metrics

        ttft_hit: list[float] = []
        ttft_miss: list[float] = []
        for i in range(requests):
            payload = {
                "model": engine.model_id,
                "messages": [
                    {"role": "system", "content": system},
                    {"role": "user", "content": f"Question {i}: status of "
                                                f"pool {i % 7}?"},
                ],
                "max_tokens": 8, "temperature": 0.0, "stream": True,
            }
            hits_before = metrics.prefix_hits_total
            t0 = time.perf_counter()
            ttft = None
            resp = await gw.client.post("/v1/chat/completions", json=payload,
                                        headers=headers)
            assert resp.status == 200, await resp.text()
            async for raw in resp.content:
                line = raw.decode(errors="replace").strip()
                if not line.startswith("data: ") or line == "data: [DONE]":
                    continue
                chunk = json.loads(line[len("data: "):])
                if ttft is None and any(
                    c.get("delta", {}).get("content")
                    for c in chunk.get("choices", [])
                ):
                    ttft = time.perf_counter() - t0
            await resp.release()
            if ttft is None:
                continue
            if metrics.prefix_hits_total > hits_before:
                ttft_hit.append(ttft)
            else:
                ttft_miss.append(ttft)

        # cross-check the Prometheus exposition carries the same counters
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://127.0.0.1:{eng_server.port}/metrics"
            ) as r:
                exposition = await r.text()
        assert "llmlb_engine_prefix_cache_hits_total" in exposition

        hits = metrics.prefix_hits_total
        misses = metrics.prefix_misses_total
        cached = metrics.prefix_cached_tokens_total
        # actual shared token head between any two requests of this
        # workload, aligned down to the engine's prefix quantum — the
        # denominator for "what fraction of shareable tokens came from cache"
        ids = [engine.encode_chat([
            {"role": "system", "content": system},
            {"role": "user", "content": f"Question {i}: status of "
                                        f"pool {i % 7}?"},
        ]) for i in (0, 1)]
        lcp = 0
        while (lcp < min(len(ids[0]), len(ids[1]))
               and ids[0][lcp] == ids[1][lcp]):
            lcp += 1
        align = engine.core.prefix_align or 1
        shared_est = max(1, (requests - 1) * ((lcp // align) * align))

        def mean(xs):
            return round(sum(xs) / len(xs) * 1000, 2) if xs else None

        return {
            "metric": "prefix_cache_shared_prefix_workload",
            "requests": requests,
            "prefix_hits": hits,
            "prefix_misses": misses,
            "hit_rate": round(hits / max(1, hits + misses), 3),
            "prefill_tokens_saved": cached,
            "shared_tokens_hit_fraction": round(cached / shared_est, 3),
            "ttft_hit_mean_ms": mean(ttft_hit),
            "ttft_miss_mean_ms": mean(ttft_miss),
            "engine_prefix_cache": engine.core.prefix_cache_info(),
        }
    finally:
        await gw.close()
        await eng_server.close()
        engine.shutdown()


async def run_mixed_length_bench(requests_n: int) -> dict:
    """Paged-vs-dense occupancy at EQUAL HBM budget: one pool worth of KV
    serves a mixed short/long workload under both layouts. Dense reserves
    slot_capacity rows per slot, capping concurrency at its slot count;
    paged holds pages per token actually cached, so the same bytes admit
    many more short requests at once. Reports peak concurrent sequences per
    layout and confirms the page-pool gauges are visible in /metrics."""
    import random

    import aiohttp
    from aiohttp.test_utils import TestServer

    from llmlb_tpu.engine.scheduler import SamplingParams
    from llmlb_tpu.engine.server import create_engine_app
    from llmlb_tpu.engine.service import Engine

    capacity, page = 256, 16
    dense_slots = 4
    results: dict = {}
    for layout in ("dense", "paged"):
        kwargs = dict(
            num_slots=dense_slots, slot_capacity=capacity,
            prefill_buckets=(16, 32, 64), kv_layout=layout,
            kv_page_size=page,
        )
        if layout == "paged":
            # same pool bytes as the dense cache (+1 trash page); the extra
            # slots are bookkeeping only — HBM does not grow with them
            kwargs["kv_pages"] = dense_slots * (capacity // page) + 1
            kwargs["num_slots"] = dense_slots * 4
        engine = Engine.from_preset("debug-tiny", **kwargs)
        eng_server = TestServer(create_engine_app(engine, owns_engine=False))
        await eng_server.start_server()
        try:
            r = random.Random(0)
            prompts = []
            for i in range(requests_n):
                # 1-in-4 long prompts; the rest are short chats that would
                # each strand a full slot row under the dense layout
                n = 200 if i % 4 == 0 else 12
                prompts.append([r.randrange(1, 500) for _ in range(n)])

            peak = 0
            done = False

            async def sample() -> None:
                nonlocal peak
                while not done:
                    peak = max(peak, engine.core.stats().active_slots)
                    await asyncio.sleep(0.002)

            sampler = asyncio.create_task(sample())
            t0 = time.perf_counter()
            outs = await asyncio.gather(*(
                engine.complete(p, SamplingParams(temperature=0.0,
                                                  max_tokens=8))
                for p in prompts
            ))
            elapsed = time.perf_counter() - t0
            done = True
            await sampler

            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://127.0.0.1:{eng_server.port}/metrics"
                ) as resp:
                    exposition = await resp.text()
            info = engine.core.kv_cache_info()
            results[layout] = {
                "num_slots": engine.core.num_slots,
                "kv_hbm_bytes": info["hbm_bytes"],
                "peak_concurrent_sequences": peak,
                "seconds": round(elapsed, 2),
                "finished": sum(
                    1 for o in outs if o.finish_reason in ("stop", "length")
                ),
                "page_gauges_in_metrics": (
                    "llmlb_engine_kv_pages_total" in exposition
                    if layout == "paged" else None
                ),
                "kv_cache": info,
            }
        finally:
            await eng_server.close()
            engine.shutdown()
    dense_b = results["dense"]["kv_hbm_bytes"]
    paged_b = results["paged"]["kv_hbm_bytes"]
    return {
        "metric": "paged_vs_dense_mixed_length_occupancy",
        "requests": requests_n,
        # paged may carry the one reserved trash page of extra HBM
        "equal_hbm_budget": abs(paged_b - dense_b) <= dense_b // dense_slots,
        "peak_concurrency_gain": round(
            results["paged"]["peak_concurrent_sequences"]
            / max(1, results["dense"]["peak_concurrent_sequences"]), 2
        ),
        "dense": results["dense"],
        "paged": results["paged"],
    }


async def run_quantized_bench(requests_n: int) -> dict:
    """Int8-KV occupancy and throughput at EQUAL HBM budget
    (docs/quantization.md). Three engines, identical except the
    `--quantize` knob: bf16 baseline, int8 KV pages, int8 weights+KV.
    The quantized pools get as many pages as the bf16 pool's BYTES buy
    (bytes_per_page is ~(D+4)/2D of bf16, so ~1.9x the pages), and a
    saturating swarm of identical short chats measures peak concurrent
    sequences per budget — the paged-attention analogue of the
    mixed-length dense-vs-paged bench. Also reports decode tok/s and a
    greedy output-divergence sample (int8 vs bf16 token streams on the
    same prompts)."""
    import dataclasses as dc
    import random

    import jax.numpy as jnp

    from llmlb_tpu.engine.presets import get_preset
    from llmlb_tpu.engine.scheduler import SamplingParams, kv_page_bytes
    from llmlb_tpu.engine.service import Engine
    from llmlb_tpu.engine.tokenizer import ByteTokenizer
    from llmlb_tpu.engine.scheduler import EngineCore

    # head_dim 64 at bf16 — the serving-shaped cell: int8 page bytes are
    # (64+4)/(64·2) = 53% of bf16, so one HBM budget holds ~1.88x pages
    cfg = dc.replace(
        get_preset("debug-tiny"), hidden_size=256, num_heads=4,
        num_kv_heads=2, intermediate_size=512, dtype=jnp.bfloat16,
    )
    capacity, page = 64, 16
    bf16_pages = 33  # 32 usable + trash page: the HBM budget
    budget_bytes = bf16_pages * kv_page_bytes(cfg, page, quantized=False)
    int8_pages = budget_bytes // kv_page_bytes(cfg, page, quantized=True)
    # 28-token prompts reserve BOTH of a request's pages at admission
    # (prompt+gen stays inside 2 pages), so peak concurrency is bounded by
    # the pool, not by decode-growth cuts — the quantity under test
    prompt_len, gen = 28, 3

    r = random.Random(0)
    prompts = [[r.randrange(1, cfg.vocab_size)
                for _ in range(prompt_len)] for _ in range(requests_n)]
    divergence_prompts = prompts[:4]

    results: dict = {}
    baseline_tokens: list[list[int]] | None = None
    for mode in ("bf16", "int8-kv", "int8-all"):
        quantize = {"bf16": "off", "int8-kv": "kv", "int8-all": "all"}[mode]
        pages = bf16_pages if mode == "bf16" else int(int8_pages)
        core = EngineCore(
            cfg, num_slots=32, slot_capacity=capacity,
            prefill_buckets=(16,), seed=0, kv_page_size=page,
            kv_pages=pages, quantize=quantize, prefix_cache=False,
        )
        core.start()
        engine = Engine("quant-bench", core, ByteTokenizer(cfg.vocab_size))
        try:
            peak = 0
            done = False

            async def sample() -> None:
                nonlocal peak
                while not done:
                    peak = max(peak, core.stats().active_slots)
                    await asyncio.sleep(0.002)

            sampler = asyncio.create_task(sample())
            t0 = time.perf_counter()
            outs = await asyncio.gather(*(
                engine.complete(p, SamplingParams(temperature=0.0,
                                                  max_tokens=gen))
                for p in prompts
            ))
            elapsed = time.perf_counter() - t0
            done = True
            await sampler

            # greedy divergence sample vs the bf16 streams
            sample_tokens = []
            for p in divergence_prompts:
                req_toks = []
                async for delta in engine.stream(
                    p, SamplingParams(temperature=0.0, max_tokens=8)
                ):
                    req_toks.append(delta.text)
                sample_tokens.append("".join(req_toks))
            if baseline_tokens is None:
                baseline_tokens = sample_tokens
                diverged = 0.0
            else:
                diverged = sum(
                    1 for a, b in zip(baseline_tokens, sample_tokens)
                    if a != b
                ) / len(sample_tokens)

            completion_tokens = sum(o.completion_tokens for o in outs)
            info = core.kv_cache_info()
            results[mode] = {
                "quantize": quantize,
                "kv_dtype": info["kv_dtype"],
                "pages_total": info["pages_total"],
                "bytes_per_page": info["bytes_per_page"],
                "kv_hbm_bytes": info["hbm_bytes"],
                "peak_concurrent_sequences": peak,
                "decode_tokens_per_sec": round(
                    completion_tokens / elapsed, 1
                ),
                "seconds": round(elapsed, 2),
                "finished": sum(
                    1 for o in outs
                    if o.finish_reason in ("stop", "length")
                ),
                "output_divergence_sample": round(diverged, 3),
                "param_bytes": core.quant_info()["param_bytes"],
            }
        finally:
            engine.shutdown()

    bf16_b = results["bf16"]["kv_hbm_bytes"]
    kv_b = results["int8-kv"]["kv_hbm_bytes"]
    return {
        "metric": "quantized_equal_hbm_budget",
        "requests": requests_n,
        "hbm_budget_bytes": budget_bytes,
        # pools match the budget within one page's rounding
        "equal_hbm_budget": abs(kv_b - bf16_b) <= results["int8-kv"][
            "bytes_per_page"
        ],
        "peak_concurrency_gain_int8_kv": round(
            results["int8-kv"]["peak_concurrent_sequences"]
            / max(1, results["bf16"]["peak_concurrent_sequences"]), 2
        ),
        "bytes_per_page_ratio": round(
            results["int8-kv"]["bytes_per_page"]
            / results["bf16"]["bytes_per_page"], 3
        ),
        "bf16": results["bf16"],
        "int8_kv": results["int8-kv"],
        "int8_all": results["int8-all"],
    }


async def run_structured_bench(requests: int) -> dict:
    """Structured-outputs workload: mixed schema-constrained + free-form
    traffic through the full gateway against a real tpu:// engine (CPU
    backend). Asserts 100% schema-valid JSON on every constrained response
    and reports the TTFT/TPS overhead of constrained decoding vs the
    free-form baseline, plus compile-cache effectiveness (second and later
    requests with the same schema must skip DFA construction)."""
    import jsonschema
    from aiohttp.test_utils import TestServer

    from llmlb_tpu.engine.server import create_engine_app
    from llmlb_tpu.engine.service import Engine
    from tests.support import GatewayHarness

    schema = {
        "type": "object",
        "properties": {
            "city": {"enum": ["sf", "nyc", "tokyo"]},
            "celsius": {"type": "boolean"},
            "temp": {"type": "integer"},
        },
        "required": ["city", "celsius", "temp"],
    }
    engine = Engine.from_preset(
        "debug-tiny", model_id="bench-structured", num_slots=4,
        slot_capacity=256, prefill_buckets=(16, 32, 64),
    )
    eng_server = TestServer(create_engine_app(engine, owns_engine=False))
    await eng_server.start_server()
    gw = await GatewayHarness.create()
    try:
        from llmlb_tpu.gateway.types import Capability

        gw.register_mock(
            f"http://127.0.0.1:{eng_server.port}", [engine.model_id],
            capabilities=[Capability.CHAT_COMPLETION,
                          Capability.STRUCTURED_OUTPUTS],
        )
        headers = dict(await gw.inference_headers())

        async def one(i: int, constrained: bool) -> dict:
            payload = {
                "model": engine.model_id,
                "messages": [{"role": "user",
                              "content": f"weather report {i}"}],
                "max_tokens": 96, "temperature": 1.0, "stream": True,
            }
            if constrained:
                payload["response_format"] = {
                    "type": "json_schema",
                    "json_schema": {"name": "weather", "schema": schema},
                }
            t0 = time.perf_counter()
            ttft = None
            text = ""
            finish = None
            tokens = 0
            resp = await gw.client.post("/v1/chat/completions", json=payload,
                                        headers=headers)
            assert resp.status == 200, await resp.text()
            async for raw in resp.content:
                line = raw.decode(errors="replace").strip()
                if not line.startswith("data: ") or line == "data: [DONE]":
                    continue
                chunk = json.loads(line[len("data: "):])
                for c in chunk.get("choices", []):
                    delta = c.get("delta", {})
                    if delta.get("content"):
                        if ttft is None:
                            ttft = time.perf_counter() - t0
                        text += delta["content"]
                    if c.get("finish_reason"):
                        finish = c["finish_reason"]
                usage = chunk.get("usage")
                if usage:
                    tokens = usage.get("completion_tokens", 0)
            await resp.release()
            return {"ttft": ttft, "e2e": time.perf_counter() - t0,
                    "text": text, "finish": finish, "tokens": tokens}

        # XLA-warm the engine with free-form traffic first, so the cold
        # constrained request below isolates the SCHEMA compile cost rather
        # than the first-ever prefill/decode compile.
        for _ in range(2):
            await one(0, False)

        # cold first constrained request pays the schema compile; capture it
        # separately so the cache-effectiveness claim is measurable
        cold = await one(0, True)
        jsonschema.validate(json.loads(cold["text"]), schema)
        metrics = engine.core.metrics

        results = {"constrained": [], "free": []}
        valid = 1
        for i in range(1, requests):
            constrained = i % 2 == 0
            r = await one(i, constrained)
            if constrained:
                obj = json.loads(r["text"])  # must parse...
                jsonschema.validate(obj, schema)  # ...and validate
                assert r["finish"] == "stop", r["finish"]
                valid += 1
                results["constrained"].append(r)
            else:
                results["free"].append(r)

        def mean_ms(rows, key):
            vals = [r[key] for r in rows if r[key] is not None]
            return round(sum(vals) / len(vals) * 1000, 2) if vals else None

        def tps(rows):
            toks = sum(r["tokens"] for r in rows)
            secs = sum(r["e2e"] for r in rows)
            return round(toks / secs, 1) if secs else None

        info = engine.core.structured_info()
        compile_p50 = metrics.schema_compile.percentile(50) or 0.0
        warm_ttft = mean_ms(results["constrained"], "ttft")
        free_ttft = mean_ms(results["free"], "ttft")
        constrained_n = len(results["constrained"]) + 1
        return {
            "metric": "structured_outputs_mixed_workload",
            "requests": requests,
            "constrained_requests": constrained_n,
            "schema_valid": valid,
            "schema_valid_fraction": round(valid / constrained_n, 3),
            "ttft_constrained_cold_ms": round(cold["ttft"] * 1000, 2)
            if cold["ttft"] else None,
            "ttft_constrained_warm_mean_ms": warm_ttft,
            "ttft_free_mean_ms": free_ttft,
            "ttft_constraint_overhead_ms": (
                round(warm_ttft - free_ttft, 2)
                if warm_ttft is not None and free_ttft is not None else None
            ),
            "tps_constrained": tps(results["constrained"]),
            "tps_free": tps(results["free"]),
            "schema_compile_p50_ms": round(compile_p50 * 1000, 2),
            # cache effectiveness: >0 hits means repeat schemas skipped DFA
            # construction; warm added TTFT must undercut one compile
            "compile_cache_hits": info["compile_cache_hits"],
            "compile_cache_misses": info["compile_cache_misses"],
            "warm_overhead_under_compile_time": (
                warm_ttft is not None and free_ttft is not None
                and (warm_ttft - free_ttft) < max(compile_p50 * 1000, 1e-9)
            ) if compile_p50 else None,
            "mask_cache_bytes": info["mask_cache_bytes"],
            "constraint_violations": metrics.constraint_violations_total,
            "masked_decode_steps": metrics.masked_decode_steps_total,
            "engine_structured": info,
        }
    finally:
        await gw.close()
        await eng_server.close()
        engine.shutdown()


async def run_spec_bench(requests: int) -> dict:
    """Speculative-decoding workload: predictable continuations (shared-
    prefix chat + JSON-mode structured output) through the full gateway
    against a real tpu:// engine (CPU backend), run twice — speculation on
    and off — on otherwise identical engines. Reports drafted/accepted
    tokens, acceptance rate, and decode tok/s for both modes; the JSON-mode
    half must stay 100% schema-valid under speculation."""
    import jsonschema
    from aiohttp.test_utils import TestServer

    from llmlb_tpu.engine.server import create_engine_app
    from llmlb_tpu.engine.service import Engine
    from llmlb_tpu.gateway.types import Capability
    from tests.support import GatewayHarness

    # An array of identical items: grammar + greedy decode make the
    # continuation maximally predictable — the structured shape speculation
    # exists to accelerate (acceptance approaches 1).
    schema = {"type": "array", "items": {"enum": ["aa"]},
              "minItems": 20, "maxItems": 20}
    system = ("You are the TPU serving assistant. Answer briefly and "
              "cite the runbook section when relevant. ") * 2

    async def run_mode(spec: bool) -> dict:
        engine = Engine.from_preset(
            "debug-tiny", model_id="bench-spec", num_slots=4,
            slot_capacity=512, prefill_buckets=(16, 32, 64),
            spec_decode=spec, spec_max_draft=6,
        )
        eng_server = TestServer(create_engine_app(engine, owns_engine=False))
        await eng_server.start_server()
        gw = await GatewayHarness.create()
        try:
            gw.register_mock(
                f"http://127.0.0.1:{eng_server.port}", [engine.model_id],
                capabilities=[Capability.CHAT_COMPLETION,
                              Capability.STRUCTURED_OUTPUTS],
            )
            headers = dict(await gw.inference_headers())

            async def one(i: int, constrained: bool) -> dict:
                payload = {
                    "model": engine.model_id,
                    "messages": [
                        {"role": "system", "content": system},
                        {"role": "user",
                         "content": f"question {i}: 1 2 3 4 5 6 7 8"},
                    ],
                    "max_tokens": 140, "temperature": 0.0, "stream": True,
                }
                if constrained:
                    payload["response_format"] = {
                        "type": "json_schema",
                        "json_schema": {"name": "items", "schema": schema},
                    }
                t0 = time.perf_counter()
                ttft = None
                text = ""
                tokens = 0
                resp = await gw.client.post("/v1/chat/completions",
                                            json=payload, headers=headers)
                assert resp.status == 200, await resp.text()
                async for raw in resp.content:
                    line = raw.decode(errors="replace").strip()
                    if not line.startswith("data: ") or line == "data: [DONE]":
                        continue
                    chunk = json.loads(line[len("data: "):])
                    for c in chunk.get("choices", []):
                        if c.get("delta", {}).get("content"):
                            if ttft is None:
                                ttft = time.perf_counter() - t0
                            text += c["delta"]["content"]
                    usage = chunk.get("usage")
                    if usage:
                        tokens = usage.get("completion_tokens", 0)
                await resp.release()
                e2e = time.perf_counter() - t0
                if constrained:
                    jsonschema.validate(json.loads(text), schema)
                return {"tokens": tokens,
                        "decode_s": max(1e-9, e2e - (ttft or 0.0))}

            # XLA warmup outside the timed window (incl. one of each shape)
            await one(0, False)
            await one(0, True)

            t0 = time.perf_counter()
            rows = await asyncio.gather(*(
                one(i, i % 2 == 0) for i in range(requests)
            ))
            wall = time.perf_counter() - t0
            m = engine.core.metrics
            tokens = sum(r["tokens"] for r in rows)
            decode_s = sum(r["decode_s"] for r in rows)
            drafted = m.spec_draft_tokens_total
            return {
                "spec_decode": spec,
                "requests": requests,
                "completion_tokens": tokens,
                "wall_s": round(wall, 2),
                "tok_per_s_wall": round(tokens / wall, 1),
                # per-request decode time excludes each request's TTFT
                # (prefill), summed across the concurrent batch
                "decode_tok_per_s": round(tokens / decode_s, 1),
                "verify_steps": m.spec_verify_steps_total,
                "drafted_tokens": drafted,
                "accepted_tokens": m.spec_accepted_tokens_total,
                "emitted_tokens": m.spec_emitted_tokens_total,
                "acceptance_rate": (
                    round(m.spec_accepted_tokens_total / drafted, 3)
                    if drafted else None
                ),
                "constraint_violations": m.constraint_violations_total,
                "engine_spec": engine.core.spec_info(),
            }
        finally:
            await gw.close()
            await eng_server.close()
            engine.shutdown()

    off = await run_mode(False)
    on = await run_mode(True)
    assert off["verify_steps"] == 0  # speculation off: path never dispatches
    return {
        "metric": "spec_decode_workload",
        "requests": requests,
        "speedup_wall": round(on["tok_per_s_wall"] / off["tok_per_s_wall"], 2),
        "speedup_decode": round(
            on["decode_tok_per_s"] / off["decode_tok_per_s"], 2
        ),
        "acceptance_rate": on["acceptance_rate"],
        "spec_on": on,
        "spec_off": off,
    }


async def run_lora_bench(requests: int) -> dict:
    """Multi-LoRA workload (docs/lora.md): a mixed-adapter request stream
    (3 adapters + adapter-free traffic) through the FULL gateway against a
    real tpu:// engine (CPU backend), two ways:

    - batched: all requests concurrent — the bgmv path decodes the mixed
      batch together, every adapter stays resident (pool of 4);
    - naive: the one-adapter-at-a-time swapping baseline — the engine's
      pool holds ONE adapter and requests run strictly in arrival order,
      so every adapter switch in the interleaved stream evicts and
      reloads (what serving N tenants looks like on a server that must
      swap the single active adapter instead of batching them).

    Reports decode tok/s, wall-clock, per-request latency, adapter cache
    hit rate (1 - loads/adapter_requests), and asserts the two modes'
    greedy outputs are token-identical (batching must not change any
    tenant's stream).

    CPU-host honesty (the BENCH_r09 throughput stance): on a CPU backend
    decode compute scales ~linearly with batch width, so batching buys no
    wall-clock here and the committed transferable evidence is structural —
    device dispatches per served token (batched runs ~6x fewer programs;
    on TPU, where a wider decode step costs ~the same HBM sweep, that IS
    the speedup) and the adapter cache hit rate (the naive server reloads
    an adapter on nearly every switch)."""
    import tempfile

    from aiohttp.test_utils import TestServer

    from llmlb_tpu.engine.presets import get_preset
    from llmlb_tpu.engine.server import create_engine_app
    from llmlb_tpu.engine.service import Engine
    from llmlb_tpu.gateway.types import Capability, EndpointType
    from llmlb_tpu.lora import save_adapter
    from tests.support import GatewayHarness

    adapters = ("acme", "globex", "initech")
    lora_dir = tempfile.mkdtemp(prefix="bench-lora-")
    cfg = get_preset("debug-tiny")
    for name in adapters:
        save_adapter(lora_dir, name, cfg, rank=8)

    # request plan: round-robin across 3 adapters + adapter-free rows
    plan = [(adapters[i % 4] if i % 4 < 3 else None)
            for i in range(requests)]
    gen_tokens = 24

    async def run_mode(label: str, max_adapters: int,
                       serialize: bool) -> dict:
        engine = Engine.from_preset(
            "debug-tiny", model_id="bench-lora", num_slots=8,
            slot_capacity=128, prefill_buckets=(16, 32), seed=0,
            lora_dir=lora_dir, lora_max_adapters=max_adapters,
        )
        eng_server = TestServer(create_engine_app(engine,
                                                  owns_engine=False))
        await eng_server.start_server()
        gw = await GatewayHarness.create()
        try:
            gw.register_mock(
                f"http://127.0.0.1:{eng_server.port}", [engine.model_id],
                endpoint_type=EndpointType.TPU,
                capabilities=[Capability.CHAT_COMPLETION, Capability.LORA],
            )
            headers = dict(await gw.inference_headers())

            async def one(i: int, adapter: str | None) -> dict:
                payload = {
                    "model": engine.model_id,
                    # ONE prompt for every tenant: output differences are
                    # then purely the adapters' doing (distinctness check)
                    "messages": [{"role": "user",
                                  "content": "ticket escalation report"}],
                    "max_tokens": gen_tokens, "temperature": 0.0,
                }
                if adapter is not None:
                    payload["lora"] = adapter
                t_req = time.perf_counter()
                resp = await gw.client.post("/v1/chat/completions",
                                            json=payload, headers=headers)
                assert resp.status == 200, await resp.text()
                body = await resp.json()
                return {
                    "adapter": adapter,
                    "text": body["choices"][0]["message"]["content"],
                    "tokens": body["usage"]["completion_tokens"],
                    "e2e_s": time.perf_counter() - t_req,
                }

            core = engine.core
            peak = 0
            done = False

            async def sample() -> None:
                nonlocal peak
                while not done:
                    peak = max(peak, core.stats().active_slots)
                    await asyncio.sleep(0.002)

            sampler = asyncio.create_task(sample())
            steps0 = core.metrics.decode_step.n
            t0 = time.perf_counter()
            if serialize:
                # one adapter at a time, ARRIVAL order: every adapter
                # switch in the interleaved stream swaps the pool's single
                # slot (evict + disk->device reload) before decoding
                outs = [await one(i, a) for i, a in enumerate(plan)]
            else:
                outs = list(await asyncio.gather(*(
                    one(i, a) for i, a in enumerate(plan)
                )))
            elapsed = time.perf_counter() - t0
            done = True
            await sampler

            adapter_requests = sum(1 for a in plan if a is not None)
            loads = core.metrics.lora_loads_total
            completion = sum(o["tokens"] for o in outs)
            lat = sorted(o["e2e_s"] for o in outs)
            return {
                "request_latency_mean_s": round(
                    sum(lat) / len(lat), 3
                ),
                "request_latency_p99_s": round(
                    lat[min(len(lat) - 1, int(0.99 * len(lat)))], 3
                ),
                "label": label,
                "requests": len(outs),
                "seconds": round(elapsed, 2),
                "decode_tokens_per_sec": round(completion / elapsed, 1),
                "decode_dispatches": core.metrics.decode_step.n - steps0,
                "peak_concurrent_sequences": peak,
                "adapter_requests": adapter_requests,
                "adapter_loads": loads,
                "adapter_evictions": core.metrics.lora_evictions_total,
                "adapter_cache_hit_rate": round(
                    1.0 - loads / max(1, adapter_requests), 3
                ),
                "gateway_lora_requests":
                    gw.state.metrics.summary()["lora_requests_total"],
                "outputs": {o["adapter"] or "": o["text"] for o in outs},
            }
        finally:
            await gw.close()
            await eng_server.close()
            engine.shutdown()

    batched = await run_mode("batched", max_adapters=4, serialize=False)
    naive = await run_mode("naive-swap", max_adapters=1, serialize=True)

    # tenant-stream integrity: batching must not change any output, and
    # the adapters must actually produce distinct streams on one prompt
    # (else everything above is vacuous)
    identical = batched["outputs"] == naive["outputs"]
    distinct = len(set(batched["outputs"].values())) == len(adapters) + 1
    for mode in (batched, naive):
        mode.pop("outputs")
    return {
        "metric": "lora_mixed_adapter_workload",
        "requests": requests,
        "adapters": len(adapters),
        "outputs_token_identical_across_modes": identical,
        "adapters_distinct": distinct,
        "wall_clock_speedup": round(
            naive["seconds"] / max(1e-9, batched["seconds"]), 2
        ),
        "decode_tps_ratio": round(
            batched["decode_tokens_per_sec"]
            / max(1e-9, naive["decode_tokens_per_sec"]), 2
        ),
        "batched": batched,
        "naive": naive,
        "dispatch_reduction": round(
            naive["decode_dispatches"]
            / max(1, batched["decode_dispatches"]), 2
        ),
        "cpu_host_caveat": (
            "wall-clock unjudgeable on a CPU backend: decode compute "
            "scales ~linearly with batch width, so batching cannot win "
            "here; the transferable figures are dispatch_reduction and "
            "adapter_cache_hit_rate (see docstring)"
        ),
        "passed": bool(
            identical and distinct
            and batched["adapter_cache_hit_rate"]
            > naive["adapter_cache_hit_rate"]
            and batched["decode_dispatches"] < naive["decode_dispatches"]
        ),
    }


async def run_fused_bench(requests: int) -> dict:
    """Fused-decode workload (docs/fused-decode.md): mixed traffic — plain
    chat, LoRA-adapter, JSON-schema-constrained, all with speculation and
    int8 KV on — through the FULL gateway against a real tpu:// engine
    (CPU backend), twice on identical engines: LLMLB_FUSED_DECODE on vs
    off. Reports decode tok/s and, the transferable figure, the per-step
    device dispatch count from the scheduler's ledger: fused must hold
    exactly 1.0 per decode/verify step while legacy runs 3-5, and greedy
    outputs must be token-identical across modes.

    CPU-host honesty (the BENCH_r09 stance): XLA:CPU fuses the whole step
    into host code either way, so dispatch overhead here is Python-sized
    and wall-clock gains are noise; the committed evidence is structural —
    dispatches per step and zero constrained single-step fallbacks. On
    TPU each dispatch is a host->device launch + its H2D/D2H syncs, and
    the per-step count IS the latency story."""
    import tempfile

    import jsonschema
    from aiohttp.test_utils import TestServer

    from llmlb_tpu.engine.presets import get_preset
    from llmlb_tpu.engine.server import create_engine_app
    from llmlb_tpu.engine.service import Engine
    from llmlb_tpu.gateway.types import Capability, EndpointType
    from llmlb_tpu.lora import save_adapter
    from tests.support import GatewayHarness

    lora_dir = tempfile.mkdtemp(prefix="bench-fused-")
    save_adapter(lora_dir, "acme", get_preset("debug-tiny"), rank=8)

    # array-of-identical-items schema: grammar + greedy decode make the
    # constrained continuation predictable, so speculation engages on the
    # constrained rows too (the 4-feature-on shape this PR fuses)
    schema = {"type": "array", "items": {"enum": ["aa"]},
              "minItems": 8, "maxItems": 8}
    system = "You are the TPU serving assistant. Answer briefly. " * 2
    # request plan by i % 3: plain chat, LoRA adapter, JSON-constrained
    kinds = [("plain", "lora", "json")[i % 3] for i in range(requests)]

    async def run_mode(fused: bool) -> dict:
        engine = Engine.from_preset(
            "debug-tiny", model_id="bench-fused", num_slots=4,
            slot_capacity=256, prefill_buckets=(16, 32, 64), seed=0,
            quantize="kv", lora_dir=lora_dir, spec_decode=True,
            spec_max_draft=4, fused_decode=fused,
        )
        eng_server = TestServer(create_engine_app(engine,
                                                  owns_engine=False))
        await eng_server.start_server()
        gw = await GatewayHarness.create()
        try:
            gw.register_mock(
                f"http://127.0.0.1:{eng_server.port}", [engine.model_id],
                endpoint_type=EndpointType.TPU,
                capabilities=[Capability.CHAT_COMPLETION,
                              Capability.STRUCTURED_OUTPUTS,
                              Capability.LORA],
            )
            headers = dict(await gw.inference_headers())

            async def one(i: int, kind: str) -> dict:
                payload = {
                    "model": engine.model_id,
                    "messages": [
                        {"role": "system", "content": system},
                        {"role": "user",
                         "content": f"question {i}: 1 2 3 4 5 6 7 8"},
                    ],
                    "max_tokens": 64, "temperature": 0.0,
                }
                if kind == "lora":
                    payload["lora"] = "acme"
                elif kind == "json":
                    payload["response_format"] = {
                        "type": "json_schema",
                        "json_schema": {"name": "items", "schema": schema},
                    }
                resp = await gw.client.post("/v1/chat/completions",
                                            json=payload, headers=headers)
                assert resp.status == 200, await resp.text()
                body = await resp.json()
                text = body["choices"][0]["message"]["content"]
                if kind == "json":
                    jsonschema.validate(json.loads(text), schema)
                return {"text": text,
                        "tokens": body["usage"]["completion_tokens"]}

            # XLA warmup outside the timed window, one of each shape
            for kind in ("plain", "lora", "json"):
                await one(-1, kind)

            t0 = time.perf_counter()
            outs = list(await asyncio.gather(*(
                one(i, k) for i, k in enumerate(kinds)
            )))
            elapsed = time.perf_counter() - t0

            m = engine.core.metrics
            records = engine.core.step_stats.snapshot(limit=512)["records"]
            decs = [r for r in records
                    if r["kind"] in ("decode", "verify")]
            per_step = [r["dispatches"] for r in decs] or [0]
            completion = sum(o["tokens"] for o in outs)
            return {
                "fused": fused,
                "requests": len(outs),
                "seconds": round(elapsed, 2),
                "completion_tokens": completion,
                "decode_tokens_per_sec": round(completion / elapsed, 1),
                "decode_steps_observed": len(decs),
                "dispatches_per_step_mean": round(
                    sum(per_step) / len(per_step), 2),
                "dispatches_per_step_max": max(per_step),
                "decode_dispatches_total": m.decode_dispatches_total,
                "fused_decode_steps_total": m.fused_decode_steps_total,
                "constrained_burst_fallbacks":
                    m.constrained_burst_fallback_total,
                "masked_decode_steps": m.masked_decode_steps_total,
                "spec_verify_steps": m.spec_verify_steps_total,
                "spec_acceptance_rate": (
                    round(m.spec_accepted_tokens_total
                          / m.spec_draft_tokens_total, 3)
                    if m.spec_draft_tokens_total else None
                ),
                "outputs": {i: o["text"] for i, o in enumerate(outs)},
            }
        finally:
            await gw.close()
            await eng_server.close()
            engine.shutdown()

    on = await run_mode(True)
    off = await run_mode(False)
    identical = on["outputs"] == off["outputs"]
    for mode in (on, off):
        mode.pop("outputs")
    return {
        "metric": "fused_decode_workload",
        "requests": requests,
        "outputs_token_identical_across_modes": identical,
        "dispatch_reduction_per_step": round(
            off["dispatches_per_step_mean"]
            / max(1e-9, on["dispatches_per_step_mean"]), 2
        ),
        "decode_tps_ratio": round(
            on["decode_tokens_per_sec"]
            / max(1e-9, off["decode_tokens_per_sec"]), 2
        ),
        "fused_on": on,
        "fused_off": off,
        "cpu_host_caveat": (
            "wall-clock unjudgeable on a CPU backend: XLA:CPU dispatch "
            "overhead is Python-sized, so collapsing dispatches cannot "
            "show up in tok/s here; the transferable figures are "
            "dispatches_per_step (fused holds exactly 1) and zero "
            "constrained_burst_fallbacks (see docstring)"
        ),
        "passed": bool(
            identical
            and on["dispatches_per_step_max"] == 1
            and on["constrained_burst_fallbacks"] == 0
            and on["masked_decode_steps"] > 0
            and on["spec_verify_steps"] > 0
            and off["dispatches_per_step_mean"] > 1.0
        ),
    }


async def _make_named_key(gw, name: str) -> str:
    """A second inference API key so the slo-mix workload has distinct
    tenants (rate-limit overrides key by API-key name)."""
    resp = await gw.client.post(
        "/api/api-keys",
        json={"name": name,
              "permissions": ["openai.inference", "openai.models.read"]},
        headers=await gw.admin_headers(),
    )
    assert resp.status == 201, await resp.text()
    return (await resp.json())["api_key"]


def _gap_stats(gaps: list[float]) -> dict:
    """p50/p99/max over inter-token gaps, plus the fraction of gaps that
    would blow a 250 ms ITL target — the per-gap view a mean hides."""
    if not gaps:
        return {"n": 0}
    s = sorted(gaps)
    return {
        "n": len(s),
        "p50_ms": round(s[len(s) // 2] * 1000, 1),
        "p99_ms": round(s[min(len(s) - 1, int(len(s) * 0.99))] * 1000, 1),
        "max_ms": round(s[-1] * 1000, 1),
        "frac_over_250ms": round(
            sum(1 for g in s if g > 0.25) / len(s), 4
        ),
    }


async def run_slo_mix_bench(requests: int) -> dict:
    """SLO-mix workload (docs/scheduling.md): the adversarial tenant mix
    overload protection exists for, through the full gateway against a real
    tpu:// engine (CPU backend). Three labeled sub-scenarios matching the
    acceptance bar:

    (a) itl_bound — background streams decode while a batch of long
        prompts (the CPU-scaled stand-in for a 128k arrival; debug-tiny
        caps positions at 512) prefills, with the chunk budget off vs on.
        Reports client-measured inter-token gap p99/max for the background
        decoders: off shows the prefill spike, on bounds it.
    (b) ratelimit — one greedy API key fires concurrent waves against a
        per-key token bucket while a background tenant trickles requests:
        the greedy key's excess 429s with honest Retry-After, the
        background tenant's goodput holds at 1.0.
    (c) preemption — a low-priority stream on a single-slot engine is
        parked by a high-priority arrival and resumes; its final text must
        be identical to an uninterrupted reference run.

    Goodput (PR 6's SLO machinery, by priority class) is the reported
    figure, not raw throughput. Wall-clock numbers are CPU-host bound and
    not TPU-transferable; the mechanisms (chunk interleaving, bucket math,
    park/resume identity) are.
    """
    from aiohttp.test_utils import TestServer

    from llmlb_tpu.engine.server import create_engine_app
    from llmlb_tpu.engine.service import Engine
    from llmlb_tpu.gateway.config import RateLimitConfig
    from llmlb_tpu.gateway.ratelimit import RateLimiter
    from tests.support import GatewayHarness

    LONG_CHARS = 420  # ByteTokenizer: ~1 token/char; slot capacity is 512
    CHUNK_BUDGET = 16
    # Prompts probed to decode long (no early EOS) under the seed-0 random
    # weights — greedy on a random tiny model stops whenever EOS wins the
    # argmax, so background decoders must be prompts that keep emitting.
    BG_PROMPTS = (
        "background chat 0", "background chat 3",
        "lorem ipsum dolor sit amet", "alpha bravo charlie delta",
    )

    async def stream_chat(gw, headers, content, *, priority, max_tokens,
                          marks: list | None = None) -> dict:
        """One streaming chat; records the arrival time of every content
        delta into `marks` (client-side ITL ground truth)."""
        payload = {
            "model": "bench-slo",
            "messages": [{"role": "user", "content": content}],
            "max_tokens": max_tokens, "temperature": 0.0, "stream": True,
            "priority": priority,
        }
        t0 = time.perf_counter()
        text, ttft = "", None
        resp = await gw.client.post("/v1/chat/completions", json=payload,
                                    headers=headers)
        assert resp.status == 200, await resp.text()
        async for raw in resp.content:
            line = raw.decode(errors="replace").strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            chunk = json.loads(line[len("data: "):])
            for c in chunk.get("choices", []):
                delta = c.get("delta", {}).get("content")
                if delta:
                    now = time.perf_counter()
                    if ttft is None:
                        ttft = now - t0
                    if marks is not None:
                        marks.append(now)
                    text += delta
        await resp.release()
        return {"text": text, "ttft_s": ttft}

    # ---------------------------------------------- (a) ITL bound on/off
    async def itl_mode(budget: int) -> dict:
        engine = Engine.from_preset(
            "debug-tiny", model_id="bench-slo", num_slots=8,
            slot_capacity=512, prefill_buckets=(16, 32, 64, 128, 256),
            kv_layout="paged", kv_page_size=16, seed=0,
            prefill_chunk_budget=budget, prefix_cache=False,
        )
        eng_server = TestServer(create_engine_app(engine, owns_engine=False))
        await eng_server.start_server()
        gw = await GatewayHarness.create()
        try:
            gw.register_mock(f"http://127.0.0.1:{eng_server.port}",
                             [engine.model_id])
            headers = await gw.inference_headers()
            # warm every compiled shape outside the measured window: one
            # background-shaped stream (its prefill bucket + decode) and
            # one long prompt (256-chunk path, or budget-sized chunks)
            await stream_chat(gw, headers, BG_PROMPTS[0], priority="high",
                              max_tokens=8)
            await stream_chat(gw, headers, "x" * LONG_CHARS, priority="low",
                              max_tokens=2)

            marks: list[list[float]] = [[] for _ in BG_PROMPTS]
            bg = [
                asyncio.create_task(stream_chat(
                    gw, headers, prompt, priority="high",
                    max_tokens=160, marks=marks[i],
                ))
                for i, prompt in enumerate(BG_PROMPTS)
            ]
            ready_by = time.monotonic() + 120.0
            while any(len(m) < 3 for m in marks):  # all decoding for real
                if time.monotonic() > ready_by:
                    raise RuntimeError(
                        "background streams never reached steady decode"
                    )
                await asyncio.sleep(0.005)
            prefills_before = engine.core.metrics.prefill_step.n
            t_long = time.perf_counter()
            longs = await asyncio.gather(*(
                stream_chat(gw, headers, "x" * LONG_CHARS, priority="low",
                            max_tokens=4)
                for _ in range(3)
            ))
            long_wall = time.perf_counter() - t_long
            await asyncio.gather(*bg)
            prefill_steps = engine.core.metrics.prefill_step.n - prefills_before
            gaps = [b - a for m in marks for a, b in zip(m, m[1:])]
            return {
                "prefill_chunk_budget": budget,
                "background_streams": len(bg),
                "long_prompts": len(longs),
                "long_prompt_tokens_each": LONG_CHARS,
                "long_wall_s": round(long_wall, 2),
                "prefill_dispatches_for_longs": prefill_steps,
                "background_itl": _gap_stats(gaps),
                "gateway_goodput_by_priority":
                    gw.state.metrics.summary()["goodput_by_priority"],
            }
        finally:
            await gw.close()
            await eng_server.close()
            engine.shutdown()

    itl_off = await itl_mode(0)
    itl_on = await itl_mode(CHUNK_BUDGET)

    # ------------------------------------------------- (b) rate limiting
    async def ratelimit_phase() -> dict:
        engine = Engine.from_preset(
            "debug-tiny", model_id="bench-slo", num_slots=8,
            slot_capacity=128, prefill_buckets=(16, 32, 64),
            prefix_cache=False, seed=0,
        )
        eng_server = TestServer(create_engine_app(engine, owns_engine=False))
        await eng_server.start_server()
        gw = await GatewayHarness.create()
        try:
            gw.register_mock(f"http://127.0.0.1:{eng_server.port}",
                             [engine.model_id])
            greedy_key = await _make_named_key(gw, "greedy")
            bg_key = await _make_named_key(gw, "background")
            rps, burst = 2.0, 2.0
            gw.state.ratelimit = RateLimiter(RateLimitConfig(
                overrides={"greedy": {"rps": rps, "burst": burst,
                                      "tpm": 0.0}},
            ))

            def body(prio):
                return {"model": "bench-slo",
                        "messages": [{"role": "user", "content": "ping"}],
                        "max_tokens": 8, "temperature": 0.0,
                        "priority": prio}

            async def greedy_wave(n):
                resps = await asyncio.gather(*(
                    gw.client.post("/v1/chat/completions", json=body("low"),
                                   headers={"Authorization":
                                            f"Bearer {greedy_key}"})
                    for _ in range(n)
                ))
                out = []
                for r in resps:
                    retry_after = r.headers.get("Retry-After")
                    await r.release()
                    out.append((r.status, retry_after))
                return out

            async def background_trickle(n):
                ok = 0
                for _ in range(n):
                    r = await gw.client.post(
                        "/v1/chat/completions", json=body("high"),
                        headers={"Authorization": f"Bearer {bg_key}"})
                    ok += int(r.status == 200)
                    await r.release()
                    await asyncio.sleep(0.25)
                return ok

            # warm the engine shapes before the timed window
            await background_trickle(1)

            waves = max(4, requests // 6)
            t0 = time.perf_counter()
            bg_task = asyncio.create_task(background_trickle(8))
            greedy_results = []
            for _ in range(waves):
                greedy_results += await greedy_wave(6)
                await asyncio.sleep(0.4)
            bg_ok = await bg_task
            elapsed = time.perf_counter() - t0

            granted = [r for r in greedy_results if r[0] == 200]
            refused = [r for r in greedy_results if r[0] == 429]
            fair_share = burst + rps * elapsed
            summary = gw.state.metrics.summary()
            return {
                "greedy_limits": {"rps": rps, "burst": burst},
                "elapsed_s": round(elapsed, 2),
                "greedy_fired": len(greedy_results),
                "greedy_granted": len(granted),
                "greedy_429": len(refused),
                "greedy_fair_share_cap": round(fair_share, 1),
                "greedy_within_share": len(granted) <= fair_share + 1,
                "all_429_carry_retry_after": all(
                    ra is not None and int(ra) >= 1 for _, ra in refused
                ),
                "background_requests": 9,
                "background_ok": bg_ok + 1,  # incl. the warmup request
                "gateway_ratelimit_rejections":
                    summary["ratelimit_rejections_total"],
                "gateway_goodput_by_priority":
                    summary["goodput_by_priority"],
            }
        finally:
            await gw.close()
            await eng_server.close()
            engine.shutdown()

    ratelimit = await ratelimit_phase()

    # --------------------------------------- (c) preemption + resume
    async def preemption_phase() -> dict:
        engine = Engine.from_preset(
            "debug-tiny", model_id="bench-slo",
            num_slots=1, slot_capacity=128, prefill_buckets=(16, 32),
            kv_layout="paged", kv_page_size=16, prefix_cache=False, seed=0,
        )
        eng_server = TestServer(create_engine_app(engine, owns_engine=False))
        await eng_server.start_server()
        gw = await GatewayHarness.create()
        try:
            gw.register_mock(f"http://127.0.0.1:{eng_server.port}",
                             [engine.model_id])
            headers = await gw.inference_headers()
            victim = "the quick brown fox jumps over"

            # uninterrupted reference (single slot, nothing else running)
            ref = await stream_chat(gw, headers, victim, priority="low",
                                    max_tokens=48)

            before = engine.core.metrics.preemptions_total
            marks: list[float] = []
            task = asyncio.create_task(stream_chat(
                gw, headers, victim, priority="low", max_tokens=48,
                marks=marks,
            ))
            ready_by = time.monotonic() + 120.0
            while len(marks) < 2:  # decoding, past first_pending
                if time.monotonic() > ready_by:
                    raise RuntimeError("victim stream never started decoding")
                await asyncio.sleep(0.005)
            hi = await stream_chat(gw, headers, "interloper",
                                   priority="high", max_tokens=6)
            got = await task
            m = engine.core.metrics
            return {
                "preemptions": m.preemptions_total - before,
                "resumes": m.preempt_resumes_total,
                "victim_tokens": len(got["text"]),
                "interloper_tokens": len(hi["text"]),
                "token_identical_resume": got["text"] == ref["text"],
                "engine_sched": engine.core.sched_info(),
            }
        finally:
            await gw.close()
            await eng_server.close()
            engine.shutdown()

    preempt = await preemption_phase()

    passed = (
        itl_on["background_itl"]["max_ms"]
        < itl_off["background_itl"]["max_ms"]
        and itl_on["prefill_dispatches_for_longs"]
        > itl_off["prefill_dispatches_for_longs"]
        and ratelimit["greedy_429"] > 0
        and ratelimit["greedy_within_share"]
        and ratelimit["all_429_carry_retry_after"]
        and ratelimit["background_ok"] == ratelimit["background_requests"]
        and preempt["preemptions"] >= 1
        and preempt["token_identical_resume"]
    )
    return {
        "metric": "slo_mix_workload",
        "passed": passed,
        "itl_bound": {"budget_off": itl_off, "budget_on": itl_on},
        "ratelimit": ratelimit,
        "preemption": preempt,
        "caveats": (
            "CPU host, debug-tiny model (512-position cap): the 'long' "
            "prompt is a 420-token stand-in for a 128k arrival and all "
            "wall-clock figures are CPU-bound; the mechanisms measured "
            "(chunk-budget interleaving, token-bucket shares, park/resume "
            "identity) transfer, the absolute latencies do not."
        ),
    }


async def run_disagg_bench(requests: int) -> dict:
    """Disaggregation workload (docs/disaggregation.md): the slo-mix ITL
    scenario — background streams decoding while 420-token prompts arrive —
    served three ways on the same traffic:

    (a) baseline  — `--role both`, chunk budget OFF (the prefill spike);
    (b) budget_on — `--role both`, chunk budget 16 (PR 10's overload
        protection: ITL bounded, prefill serialized against decode);
    (c) split     — `--role split` (PR 11): prefill pool + decode pool,
        page-id handoff, no budget.

    The claim under test: split holds background decode p99 ITL at or
    better than budget_on's (decode never waits behind more than one
    in-flight prefill dispatch) WITHOUT budget_on's prefill serialization
    penalty (long-prompt TTFT drops back toward the unbudgeted figure),
    and zero prefill dispatches execute on the decode pool's loop.
    Wall-clock numbers are CPU-host bound; the mechanism transfers.
    """
    from aiohttp.test_utils import TestServer

    from llmlb_tpu.engine.server import create_engine_app
    from llmlb_tpu.engine.service import Engine
    from tests.support import GatewayHarness

    LONG_CHARS = 420  # ByteTokenizer: ~1 token/char; slot capacity is 512
    CHUNK_BUDGET = 16
    BG_PROMPTS = (
        "background chat 0", "background chat 3",
        "lorem ipsum dolor sit amet", "alpha bravo charlie delta",
    )

    async def stream_chat(gw, headers, content, *, max_tokens,
                          marks: list | None = None) -> dict:
        payload = {
            "model": "bench-disagg",
            "messages": [{"role": "user", "content": content}],
            "max_tokens": max_tokens, "temperature": 0.0, "stream": True,
        }
        t0 = time.perf_counter()
        text, ttft = "", None
        resp = await gw.client.post("/v1/chat/completions", json=payload,
                                    headers=headers)
        assert resp.status == 200, await resp.text()
        async for raw in resp.content:
            line = raw.decode(errors="replace").strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            chunk = json.loads(line[len("data: "):])
            for c in chunk.get("choices", []):
                delta = c.get("delta", {}).get("content")
                if delta:
                    now = time.perf_counter()
                    if ttft is None:
                        ttft = now - t0
                    if marks is not None:
                        marks.append(now)
                    text += delta
        await resp.release()
        return {"text": text, "ttft_s": ttft}

    async def mode(label: str, *, role: str, budget: int) -> dict:
        extra = {"role": role}
        if role == "split":
            # 1 prefill slot + 7 decode slots: 4 background streams and 3
            # concurrent longs all fit the decode pool after adoption
            extra["disagg_prefill_slots"] = 1
        engine = Engine.from_preset(
            "debug-tiny", model_id="bench-disagg", num_slots=8,
            slot_capacity=512, prefill_buckets=(16, 32, 64, 128, 256),
            kv_layout="paged", kv_page_size=16, seed=0,
            prefill_chunk_budget=budget, prefix_cache=False, **extra,
        )
        eng_server = TestServer(create_engine_app(engine, owns_engine=False))
        await eng_server.start_server()
        gw = await GatewayHarness.create()
        try:
            gw.register_mock(f"http://127.0.0.1:{eng_server.port}",
                             [engine.model_id])
            headers = await gw.inference_headers()
            # warm the compiled shapes outside the measured window
            await stream_chat(gw, headers, BG_PROMPTS[0], max_tokens=8)
            await stream_chat(gw, headers, "x" * LONG_CHARS, max_tokens=2)

            marks: list[list[float]] = [[] for _ in BG_PROMPTS]
            bg = [
                asyncio.create_task(stream_chat(
                    gw, headers, prompt, max_tokens=160, marks=marks[i],
                ))
                for i, prompt in enumerate(BG_PROMPTS)
            ]
            ready_by = time.monotonic() + 120.0
            while any(len(m) < 3 for m in marks):
                if time.monotonic() > ready_by:
                    raise RuntimeError(
                        "background streams never reached steady decode"
                    )
                await asyncio.sleep(0.005)
            t_long = time.perf_counter()
            longs = await asyncio.gather(*(
                stream_chat(gw, headers, "x" * LONG_CHARS, max_tokens=4)
                for _ in range(3)
            ))
            t_long_end = time.perf_counter()
            long_wall = t_long_end - t_long
            await asyncio.gather(*bg)
            gaps = [b - a for m in marks for a, b in zip(m, m[1:])]
            # the acceptance figure: inter-token gaps WHILE the long
            # prompts were in flight — the contention window the split
            # exists to protect. Whole-stream gaps are reported too, but
            # they dilute the prefill spike with minutes of uncontended
            # decode (and CPU-host noise swamps the p99 there).
            during = [
                b - a for m in marks for a, b in zip(m, m[1:])
                if b >= t_long and a <= t_long_end
            ]
            ttfts = sorted(r["ttft_s"] for r in longs)
            out = {
                "mode": label,
                "role": role,
                "prefill_chunk_budget": budget,
                "background_streams": len(bg),
                "long_prompts": len(longs),
                "long_prompt_tokens_each": LONG_CHARS,
                "long_wall_s": round(long_wall, 2),
                "long_ttft_s": {
                    "min": round(ttfts[0], 3),
                    "mean": round(sum(ttfts) / len(ttfts), 3),
                    "max": round(ttfts[-1], 3),
                },
                "background_itl": _gap_stats(gaps),
                "background_itl_during_prefill": _gap_stats(during),
            }
            if role == "split":
                out["prefill_dispatch_by_loop"] = dict(
                    engine.core.prefill_dispatch_by_loop
                )
                out["handoffs"] = dict(engine.core.metrics.handoff_total)
            return out
        finally:
            await gw.close()
            await eng_server.close()
            engine.shutdown()

    baseline = await mode("baseline", role="both", budget=0)
    budget_on = await mode("budget_on", role="both", budget=CHUNK_BUDGET)
    split = await mode("split", role="split", budget=0)

    passed = (
        # ITL during the contention window: split at or better than the
        # budget-bounded figure (the ISSUE acceptance criterion)
        split["background_itl_during_prefill"]["p99_ms"]
        <= budget_on["background_itl_during_prefill"]["p99_ms"]
        # TTFT: split does not pay the chunk serialization penalty —
        # long prompts land closer to the unbudgeted baseline than to
        # budget_on's serialized figure
        and split["long_ttft_s"]["mean"] < budget_on["long_ttft_s"]["mean"]
        # isolation invariant: the decode pool ran ZERO prefill dispatches
        and split["prefill_dispatch_by_loop"]["decode"] == 0
        and split["handoffs"]["in_process"] >= 7  # 4 bg + 3 longs
    )
    return {
        "metric": "disagg_workload",
        "passed": passed,
        "baseline": baseline,
        "budget_on": budget_on,
        "split": split,
        "caveats": (
            "CPU host, debug-tiny model (512-position cap): the 'long' "
            "prompt is a 420-token stand-in for a 128k arrival and all "
            "wall-clock figures are CPU-bound. The split-mode mechanism "
            "(two step loops, page-id handoff, decode-first turnstile) "
            "transfers to TPU; the absolute ITL/TTFT figures do not. "
            "Single host: both loops share one device, so split removes "
            "scheduling contention, not compute contention."
        ),
    }


async def run_kv_ship_bench(requests: int) -> dict:
    """KV shipping workload (docs/kv-cache.md): move KV, don't recompute it,
    measured where the recompute bill actually lands — a long context.

    Two scenarios on the real engine core (CPU backend, debug-tiny,
    seed 0, greedy), each run twice on identical traffic:

    (a) **preempt-resume**: a 384-token-context stream is parked
        mid-decode by a priority-0 interloper, then resumed. Replay mode
        (LLMLB_KV_OFFLOAD_BYTES=0) re-prefills prompt+committed; ship
        mode restores the parked pages from the host tier. Measured: the
        resume gap (interloper finish -> victim's next token), prefill
        dispatches, token identity across modes.
    (b) **warm return**: prompt A's cached prefix is evicted D2H under
        page pressure (an intervening same-size prompt B on a small
        pool), then A returns. Tier off re-prefills all 384 tokens (two
        chunk dispatches at this bucket set); tier on restores the
        aligned head H2D and prefills ONE suffix chunk. Measured: return
        TTFT, prefill dispatches on the return, token identity.

    Pass requires bit-identical outputs between modes in both scenarios,
    zero resume prefill dispatches in ship mode, the warm return landing
    in one suffix dispatch, and the ship-mode resume gap beating replay.
    """
    import numpy as np

    from llmlb_tpu.engine.presets import get_preset
    from llmlb_tpu.engine.scheduler import EngineCore, Request, SamplingParams

    cfg = get_preset("debug-tiny")
    LONG = 384
    CORE_KW = dict(num_slots=1, slot_capacity=512,
                   prefill_buckets=(16, 32, 64, 128, 256), seed=0,
                   kv_layout="paged", kv_page_size=16)
    iters = max(4, requests // 6)

    def _req(prompt, max_tokens=4, priority=1):
        return Request(prompt_ids=list(prompt),
                       sampling=SamplingParams(temperature=0.0,
                                               max_tokens=max_tokens,
                                               priority=priority))

    def _collect(request, timeout=300):
        toks = []
        while True:
            kind, value = request.events.get(timeout=timeout)
            if kind == "token":
                toks.append(value)
            elif kind == "error":
                raise RuntimeError(f"engine error: {value}")
            else:
                return toks

    def _stats(xs: list[float]) -> dict:
        xs = sorted(xs)
        return {"mean_ms": round(1e3 * sum(xs) / len(xs), 2),
                "min_ms": round(1e3 * xs[0], 2),
                "max_ms": round(1e3 * xs[-1], 2)}

    def resume_scenario(ship: bool) -> dict:
        kw = dict(CORE_KW, prefix_cache=False)
        if ship:
            kw["kv_offload_bytes"] = 1 << 30
        core = EngineCore(cfg, **kw)
        core.start()
        try:
            rng = np.random.default_rng(17)
            prompt = list(rng.integers(1, cfg.vocab_size, size=(LONG,)))
            inter = [2] * 8
            # compile every shape outside the measured window — including
            # one full unmeasured park/resume so the restore scatter's jit
            # compile (ship mode) never lands inside a measured gap
            _collect(core.submit(_req(prompt, max_tokens=2, priority=2)))
            _collect(core.submit(_req(inter, max_tokens=4, priority=0)))
            warm = core.submit(_req(prompt, max_tokens=24, priority=2))
            seen = 0
            while seen < 3:
                kind, value = warm.events.get(timeout=300)
                assert kind == "token", (kind, value)
                seen += 1
            _collect(core.submit(_req(inter, max_tokens=4, priority=0)))
            _collect(warm)
            gaps, outs = [], []
            disp0 = sum(core.prefill_dispatch_by_loop.values())
            for _ in range(iters):
                victim = core.submit(_req(prompt, max_tokens=24,
                                          priority=2))
                toks = []
                while len(toks) < 3:  # decoding: the park is mid-stream
                    kind, value = victim.events.get(timeout=300)
                    assert kind == "token", (kind, value)
                    toks.append(value)
                _collect(core.submit(_req(inter, max_tokens=4, priority=0)))
                t0 = time.perf_counter()
                kind, value = victim.events.get(timeout=300)
                gaps.append(time.perf_counter() - t0)
                assert kind == "token", (kind, value)
                outs.append(toks + [value] + _collect(victim))
            disp = sum(core.prefill_dispatch_by_loop.values()) - disp0
            info = core.kv_transfer_info()
            return {
                "mode": "ship" if ship else "replay",
                "parks": iters,
                "resume_gap": _stats(gaps),
                "prefill_dispatches": disp,
                "restored": info["restored_total"],
                "restored_bytes": info["restored_bytes_total"],
                "outputs": outs,
            }
        finally:
            core.stop()

    def warm_return_scenario(ship: bool) -> dict:
        kw = dict(CORE_KW, num_slots=2, kv_pages=40)
        if ship:
            kw["kv_offload_bytes"] = 1 << 30
        core = EngineCore(cfg, **kw)
        core.start()
        try:
            rng = np.random.default_rng(23)
            A = list(rng.integers(1, cfg.vocab_size, size=(LONG,)))
            B = list(rng.integers(1, cfg.vocab_size, size=(LONG,)))
            out_a = _collect(core.submit(_req(A, max_tokens=8)))
            _collect(core.submit(_req(B, max_tokens=8)))  # evicts A's prefix
            # unmeasured warm return: compiles the restore scatter (ship
            # mode) so the measured figure is the steady-state cost
            _collect(core.submit(_req(A, max_tokens=8)))
            _collect(core.submit(_req(B, max_tokens=8)))  # evicts A again
            disp0 = sum(core.prefill_dispatch_by_loop.values())
            t0 = time.perf_counter()
            req = core.submit(_req(A, max_tokens=8))
            kind, first = req.events.get(timeout=300)
            ttft = time.perf_counter() - t0
            assert kind == "token", (kind, first)
            out_a2 = [first] + _collect(req)
            info = core.kv_transfer_info()
            return {
                "mode": "ship" if ship else "replay",
                "return_ttft_ms": round(1e3 * ttft, 2),
                "return_prefill_dispatches":
                    sum(core.prefill_dispatch_by_loop.values()) - disp0,
                "tier_hits": info["offload"].get("hits", 0),
                "tier_spills": info["offload"].get("spills", 0),
                "outputs_identical": out_a2 == out_a,
            }
        finally:
            core.stop()

    replay = resume_scenario(False)
    ship = resume_scenario(True)
    warm_off = warm_return_scenario(False)
    warm_on = warm_return_scenario(True)
    resume_identical = ship.pop("outputs") == replay.pop("outputs")
    passed = (
        resume_identical
        # ship resumes ran ZERO prefill dispatches: the ledger shows only
        # each iteration's own chunked prefill + the interloper's
        and ship["prefill_dispatches"] < replay["prefill_dispatches"]
        and ship["restored"] >= iters
        and ship["resume_gap"]["mean_ms"] < replay["resume_gap"]["mean_ms"]
        and warm_on["outputs_identical"] and warm_off["outputs_identical"]
        and warm_on["tier_hits"] >= 1
        and warm_on["return_prefill_dispatches"] == 1  # one suffix chunk
        and warm_off["return_prefill_dispatches"] >= 2  # full re-prefill
    )
    return {
        "metric": "kv_ship_workload",
        "passed": passed,
        "context_tokens": LONG,
        "resume_outputs_token_identical": resume_identical,
        "preempt_resume": {"replay": replay, "ship": ship},
        "warm_return": {"replay": warm_off, "ship": warm_on},
        "caveats": (
            "CPU host, debug-tiny model: absolute gap/TTFT figures are "
            "CPU-bound and the D2H/H2D 'copies' are host memcpys — on a "
            "TPU the restore costs a real PCIe/ICI transfer but the "
            "replay costs a real O(context) prefill, so the structural "
            "figures (zero resume prefill dispatches, one-suffix-chunk "
            "warm returns, bit-identical outputs) are the transferable "
            "evidence; the wall-clock ratio is not."
        ),
    }


def _run_stub_server(port: int) -> None:
    """Hidden mode: a minimal OpenAI-compatible stub engine in its own
    process, so gateway workers under test never share a Python runtime
    (or GIL) with their upstream."""
    from aiohttp import web

    async def models(request):
        return web.json_response(
            {"object": "list",
             "data": [{"id": "bench-model", "object": "model"}]}
        )

    payload = {
        "id": "chatcmpl-stub", "object": "chat.completion",
        "model": "bench-model",
        "choices": [{"index": 0,
                     "message": {"role": "assistant", "content": "pong"},
                     "finish_reason": "stop"}],
        "usage": {"prompt_tokens": 7, "completion_tokens": 2,
                  "total_tokens": 9},
    }
    body = json.dumps(payload).encode()

    async def chat(request):
        await request.read()
        return web.Response(body=body, content_type="application/json")

    app = web.Application()
    app.router.add_get("/v1/models", models)
    app.router.add_post("/v1/chat/completions", chat)
    web.run_app(app, host="127.0.0.1", port=port, access_log=None,
                print=None)


def _run_client_runner(spec_json: str) -> None:
    """Hidden mode: one closed-loop load-generator process. Reads a JSON
    spec {url, api_key, seconds, concurrency}, hammers
    /v1/chat/completions, prints one JSON line {requests, errors,
    latencies_sample} (reservoir-sampled so the pipe stays bounded)."""
    import random

    import aiohttp

    spec = json.loads(spec_json)

    async def run() -> dict:
        rng = random.Random(1234)
        payload = {
            "model": "bench-model",
            "messages": [{"role": "user", "content": "ping"}],
            "stream": False,
        }
        headers = {"Authorization": f"Bearer {spec['api_key']}"}
        done = 0
        errors = 0
        sample: list[float] = []  # reservoir, cap 4000
        seen = 0
        deadline = time.perf_counter() + spec["seconds"]
        connector = aiohttp.TCPConnector(limit=0)
        async with aiohttp.ClientSession(connector=connector) as session:

            async def worker() -> None:
                nonlocal done, errors, seen
                while time.perf_counter() < deadline:
                    t0 = time.perf_counter()
                    try:
                        async with session.post(
                            spec["url"] + "/v1/chat/completions",
                            json=payload, headers=headers,
                        ) as resp:
                            await resp.read()
                            if resp.status == 200:
                                done += 1
                                lat = time.perf_counter() - t0
                                seen += 1
                                if len(sample) < 4000:
                                    sample.append(lat)
                                else:
                                    j = rng.randrange(seen)
                                    if j < 4000:
                                        sample[j] = lat
                            else:
                                errors += 1
                    except Exception:
                        errors += 1

            await asyncio.gather(
                *(worker() for _ in range(spec["concurrency"]))
            )
        return {"requests": done, "errors": errors,
                "latencies_sample": sample}

    print(json.dumps(asyncio.run(run())))


def _http_json(method: str, url: str, body=None, headers=None,
               timeout: float = 5.0):
    import urllib.request

    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
        return resp.status, (json.loads(raw) if raw else None)


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _gateway_worker_pids(supervisor_pid: int) -> list[int]:
    """Direct children of the supervisor process (the forked workers)."""
    pids: list[int] = []
    try:
        for task in os.listdir(f"/proc/{supervisor_pid}/task"):
            path = f"/proc/{supervisor_pid}/task/{task}/children"
            try:
                with open(path) as f:
                    pids.extend(int(p) for p in f.read().split())
            except OSError:
                pass
    except OSError:
        pass
    return sorted(set(pids))


def _cpu_seconds(pids: list[int]) -> float:
    """Total utime+stime of the given pids, in seconds."""
    ticks = 0
    for pid in pids:
        try:
            with open(f"/proc/{pid}/stat") as f:
                fields = f.read().rsplit(")", 1)[1].split()
            ticks += int(fields[11]) + int(fields[12])  # utime, stime
        except (OSError, IndexError, ValueError):
            pass
    return ticks / os.sysconf("SC_CLK_TCK")


def run_throughput_bench(seconds: float, concurrency: int,
                         workers_list: list[int], clients: int) -> dict:
    """Closed-loop wrk-style load against REAL gateway processes
    (`serve --workers N`, SO_REUSEPORT) in front of stub engines, 1 vs N
    workers on the same host. Load generators and stubs are separate
    processes so neither shares a GIL with the gateway under test. Records
    the scaling curve with p50/p99 at matched offered load (same client
    pool for every N).

    Honesty: on a host with fewer cores than (workers + clients + stubs)
    the wall-clock curve measures the CONTAINER, not the gateway — Python
    workers scale with physical cores, and a 2-core CI box cannot show 4x
    anything. The bench therefore also records gateway CPU-time per
    request from /proc (core-count independent): flat CPU/request from 1
    to N workers means the multi-worker machinery (gossip, WAL sharing,
    SO_REUSEPORT) adds no per-request cost, i.e. near-linear scaling
    wherever cores exist. ``passed_3x_bar`` is only judged when the host
    has enough cores to make the wall-clock claim meaningful."""
    import shutil
    import signal as _signal
    import subprocess
    import tempfile

    tmp = tempfile.mkdtemp(prefix="llmlb-throughput-")
    procs: list = []
    results: dict[str, dict] = {}
    try:
        stub_ports = [_free_port(), _free_port()]
        for port in stub_ports:
            procs.append(subprocess.Popen(
                [sys.executable, __file__, "--stub-server", str(port)],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            ))
        for port in stub_ports:
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                try:
                    status, _ = _http_json(
                        "GET", f"http://127.0.0.1:{port}/v1/models"
                    )
                    if status == 200:
                        break
                except OSError:
                    time.sleep(0.1)
            else:
                raise RuntimeError(f"stub on :{port} never came up")

        for n in workers_list:
            gw_port = _free_port()
            data_dir = os.path.join(tmp, f"gw{n}")
            env = dict(os.environ)
            env.update({
                "LLMLB_DATA_DIR": data_dir,
                "LLMLB_LOG_DIR": os.path.join(data_dir, "logs"),
                "LLMLB_ADMIN_PASSWORD": "benchpass1",
                # hot-path knobs the deployment docs recommend for load:
                # cached API-key auth, no per-request access log line
                "LLMLB_AUTH_CACHE_TTL": "60",
                "LLMLB_MAX_ACTIVE_PER_ENDPOINT": "4096",
                "LLMLB_HEALTH_CHECK_INTERVAL": "1",
                "LLMLB_TRACE_TIMELINE_SAMPLE": "0",
                # batched history writes for EVERY point on the curve (it is
                # the multi-worker default; the 1-worker baseline must not
                # pay sync WAL commits the N-worker runs skip)
                "LLMLB_HISTORY_FLUSH_SECS": "0.5",
            })
            base = f"http://127.0.0.1:{gw_port}"
            gw_log_path = os.path.join(tmp, f"gw{n}.log")
            gw_log = open(gw_log_path, "wb")
            gw = subprocess.Popen(
                [sys.executable, "-m", "llmlb_tpu.gateway.server", "serve",
                 "--host", "127.0.0.1", "--port", str(gw_port),
                 "--workers", str(n)],
                env=env, stdout=subprocess.DEVNULL, stderr=gw_log,
            )
            procs.append(gw)
            try:
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    if gw.poll() is not None:
                        gw_log.flush()
                        with open(gw_log_path, errors="replace") as f:
                            tail = f.read()[-2000:]
                        raise RuntimeError(
                            f"gateway --workers {n} exited {gw.returncode}:"
                            f"\n{tail}"
                        )
                    try:
                        status, _ = _http_json("GET", f"{base}/health",
                                               timeout=1)
                        if status == 200:
                            break
                    except OSError:
                        time.sleep(0.2)
                else:
                    raise RuntimeError("gateway never answered /health")

                _, login = _http_json("POST", f"{base}/api/auth/login", {
                    "username": "admin", "password": "benchpass1",
                })
                admin = {"Authorization": f"Bearer {login['token']}"}
                _, key = _http_json("POST", f"{base}/api/api-keys", {
                    "name": "bench",
                    "permissions": ["openai.inference"],
                }, headers=admin)
                api_key = key["api_key"]
                for port in stub_ports:
                    _http_json("POST", f"{base}/api/endpoints", {
                        "base_url": f"http://127.0.0.1:{port}",
                        "name": f"stub-{port}",
                        "endpoint_type": "openai_compatible",
                    }, headers=admin)
                # model appears once the (primary worker's) health checker
                # probes + syncs; the registry change gossips to siblings
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    try:
                        status, _ = _http_json(
                            "POST", f"{base}/v1/chat/completions",
                            {"model": "bench-model",
                             "messages": [{"role": "user",
                                           "content": "warm"}]},
                            headers={"Authorization": f"Bearer {api_key}"},
                        )
                        if status == 200:
                            break
                    except OSError:
                        pass
                    time.sleep(0.3)
                else:
                    raise RuntimeError("bench-model never became routable")

                worker_pids = _gateway_worker_pids(gw.pid) or [gw.pid]
                cpu_before = _cpu_seconds(worker_pids)
                spec = {"url": base, "api_key": api_key, "seconds": seconds,
                        "concurrency": max(1, concurrency // clients)}
                t0 = time.perf_counter()
                runners = [subprocess.Popen(
                    [sys.executable, __file__, "--client-runner",
                     json.dumps(spec)],
                    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                ) for _ in range(clients)]
                rows = []
                for r in runners:
                    out, _ = r.communicate(timeout=seconds + 60)
                    rows.append(json.loads(out))
                elapsed = time.perf_counter() - t0
                gw_cpu_s = _cpu_seconds(worker_pids) - cpu_before

                requests_total = sum(r["requests"] for r in rows)
                errors = sum(r["errors"] for r in rows)
                lats = sorted(
                    x for r in rows for x in r["latencies_sample"]
                )

                def pct(p: float) -> float | None:
                    if not lats:
                        return None
                    return lats[min(len(lats) - 1, int(len(lats) * p))]

                # per-worker spread from the merged, worker-labeled /metrics
                per_worker: dict[str, float] = {}
                try:
                    import re as _re
                    import urllib.request as _ur

                    with _ur.urlopen(f"{base}/metrics", timeout=3) as resp:
                        for line in resp.read().decode().splitlines():
                            m = _re.match(
                                r'llmlb_gateway_requests_total\{.*'
                                r'route="/v1/chat/completions".*\} (\S+)',
                                line,
                            )
                            if m:
                                w = _re.search(r'worker="(\d+)"', line)
                                wk = w.group(1) if w else "0"
                                per_worker[wk] = (
                                    per_worker.get(wk, 0.0) + float(m.group(1))
                                )
                except OSError:
                    pass

                results[str(n)] = {
                    "workers": n,
                    "req_per_sec": round(requests_total / elapsed, 1),
                    "requests": requests_total,
                    "errors": errors,
                    "seconds": round(elapsed, 2),
                    "concurrency": clients * spec["concurrency"],
                    "client_processes": clients,
                    "p50_ms": (round(pct(0.50) * 1000, 2)
                               if lats else None),
                    "p90_ms": (round(pct(0.90) * 1000, 2)
                               if lats else None),
                    "p99_ms": (round(pct(0.99) * 1000, 2)
                               if lats else None),
                    "per_worker_requests": per_worker,
                    "gateway_cpu_seconds": round(gw_cpu_s, 2),
                    "gateway_cpu_ms_per_request": (
                        round(gw_cpu_s * 1000 / requests_total, 3)
                        if requests_total else None
                    ),
                    # capacity one dedicated core would sustain at this
                    # worker count's per-request cost — the figure that
                    # transfers to a host with enough cores
                    "implied_req_per_sec_per_gateway_core": (
                        round(1000.0 * requests_total / (gw_cpu_s * 1000), 1)
                        if gw_cpu_s > 0 and requests_total else None
                    ),
                }
                print(f"[bench] workers={n}: "
                      f"{results[str(n)]['req_per_sec']} req/s "
                      f"p50={results[str(n)]['p50_ms']}ms "
                      f"p99={results[str(n)]['p99_ms']}ms "
                      f"cpu/req={results[str(n)]['gateway_cpu_ms_per_request']}ms "
                      f"spread={per_worker}", file=sys.stderr)
            finally:
                if gw.poll() is None:
                    gw.send_signal(_signal.SIGTERM)
                    try:
                        gw.wait(timeout=15)
                    except subprocess.TimeoutExpired:
                        gw.kill()

        base_rps = results[str(workers_list[0])]["req_per_sec"]
        curve = {
            k: round(v["req_per_sec"] / base_rps, 2)
            for k, v in results.items()
        }
        host_cpus = os.cpu_count() or 1
        # a meaningful N-worker wall-clock claim needs cores for N workers
        # plus the load generators and stubs feeding them
        cores_needed = max(workers_list) + 2
        out = {
            "metric": "gateway_multiworker_throughput",
            "unit": "req/s",
            "workload": "closed-loop non-streaming chat vs stub engines",
            "host_cpus": host_cpus,
            "scaling_vs_1_worker": curve,
            "runs": results,
        }
        base_cpu = results[str(workers_list[0])].get(
            "gateway_cpu_ms_per_request"
        )
        top_cpu = results[str(max(workers_list))].get(
            "gateway_cpu_ms_per_request"
        )
        if base_cpu and top_cpu:
            # core-count-independent scaling evidence: per-request gateway
            # CPU must not grow with worker count (gossip/WAL overhead)
            out["cpu_per_request_ratio_Nv1"] = round(top_cpu / base_cpu, 2)
        if "4" in results and "1" in results:
            out["speedup_4_vs_1"] = round(
                results["4"]["req_per_sec"] / results["1"]["req_per_sec"], 2
            )
            if host_cpus >= cores_needed:
                out["passed_3x_bar"] = out["speedup_4_vs_1"] >= 3.0
            else:
                out["passed_3x_bar"] = None
                out["note"] = (
                    f"host has {host_cpus} cores; the 4-worker wall-clock "
                    f"bar needs >= {cores_needed} (workers + load "
                    "generators + stubs). Wall-clock curve recorded as "
                    "measured; cpu_per_request_ratio_Nv1 is the "
                    "core-independent scaling evidence on this host."
                )
        return out
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        shutil.rmtree(tmp, ignore_errors=True)


async def run_chaos_multiworker(seconds: float, concurrency: int,
                                n_workers: int) -> dict:
    """Chaos drill across N shared-nothing worker states wired by the real
    gossip bus: one of two stub endpoints flaps (connect-refused at every
    worker's HTTP boundary, ~50% duty). Clients round-robin across the
    workers; the resilience layer + cross-worker breaker replication must
    hold >=99% client success, and the run measures the breaker
    propagation latency directly (trip on worker 0, time until every
    sibling denies)."""
    import tempfile

    from llmlb_tpu.gateway.app_state import build_app_state
    from llmlb_tpu.gateway.config import ServerConfig
    from llmlb_tpu.gateway.db import Database
    from llmlb_tpu.gateway.faults import FaultInjector, FaultRule
    from llmlb_tpu.gateway.resilience import BreakerState
    from llmlb_tpu.gateway.worker import WorkerInfo
    from tests.support import GatewayHarness, MockOpenAIEndpoint

    from aiohttp.test_utils import TestClient, TestServer
    from llmlb_tpu.gateway.app import create_app

    tmp = tempfile.mkdtemp(prefix="llmlb-chaos-mw-")
    os.environ["LLMLB_GOSSIP_DIR"] = os.path.join(tmp, "bus")
    # bench-tuned breaker/backoff knobs (same spirit as the single-worker
    # chaos run): several trip/half-open/close cycles within the window
    os.environ.update({
        "LLMLB_BREAKER_FAILURE_THRESHOLD": "3",
        "LLMLB_BREAKER_OPEN_SECS": "0.5",
        "LLMLB_BREAKER_OPEN_MAX_SECS": "2.0",
        "LLMLB_RETRY_BACKOFF_BASE": "0.005",
        "LLMLB_RETRY_BACKOFF_CAP": "0.05",
        "LLMLB_FAILOVER_QUEUE_TIMEOUT": "1.0",
        "LLMLB_ADMIN_PASSWORD": "adminpass1",
        "LLMLB_JWT_SECRET": "chaos-mw-secret",
        "LLMLB_AUTH_CACHE_TTL": "60",  # the multi-worker hot-path default
    })
    db_path = os.path.join(tmp, "gw.db")
    config = ServerConfig.from_env()
    config = config.__class__(**{**config.__dict__,
                                 "database_url": db_path})

    states = []
    harnesses: list[GatewayHarness] = []
    stable = await MockOpenAIEndpoint(model="chaos-model").start()
    flappy = await MockOpenAIEndpoint(model="chaos-model").start()
    try:
        for i in range(n_workers):
            state = await build_app_state(
                config, db=Database(db_path), start_background=False,
                worker=WorkerInfo(index=i, count=n_workers),
            )
            state.faults = FaultInjector()
            client = TestClient(TestServer(create_app(state)))
            await client.start_server()
            states.append(state)
            harnesses.append(GatewayHarness(state, client))
        gw0 = harnesses[0]
        gw0.register_mock(stable.url, ["chaos-model"], name="stable")
        ep_flappy = gw0.register_mock(flappy.url, ["chaos-model"],
                                      name="flappy")
        await asyncio.sleep(0.1)  # registry gossip -> sibling reloads
        for s in states[1:]:
            assert s.registry.get(ep_flappy.id) is not None, \
                "registry replication failed"
        headers = dict(await gw0.inference_headers())

        # --- direct propagation measurement (pre-traffic, clean clocks)
        threshold = states[0].resilience.config.breaker_failure_threshold
        t0 = time.perf_counter()
        for _ in range(threshold):
            states[0].resilience.record_failure(ep_flappy.id, "bench_trip")
        while any(s.resilience.allow(ep_flappy.id) for s in states[1:]):
            if time.perf_counter() - t0 > 2.0:
                break
            await asyncio.sleep(0.001)
        propagation_s = time.perf_counter() - t0
        propagated = not any(
            s.resilience.allow(ep_flappy.id) for s in states[1:]
        )
        for s in states:
            s.resilience.reset(ep_flappy.id)

        # --- chaos traffic across all workers
        ok = 0
        failed = 0
        statuses: dict[int, int] = {}
        deadline = time.perf_counter() + seconds
        running = True

        async def flapper() -> None:
            while running:
                rules = [s.faults.add_rule(FaultRule(
                    kind="connect_refused", endpoint="flappy", every_n=1,
                )) for s in states]
                await asyncio.sleep(0.7)
                for s, rule in zip(states, rules):
                    s.faults.remove_rule(rule)
                await asyncio.sleep(0.7)

        async def worker_task(i: int) -> None:
            nonlocal ok, failed
            n = 0
            client = harnesses[i % n_workers].client
            while time.perf_counter() < deadline:
                n += 1
                stream = (i + n) % 4 == 0
                payload = {
                    "model": "chaos-model",
                    "messages": [{"role": "user", "content": f"ping {n}"}],
                    "stream": stream,
                }
                try:
                    resp = await client.post(
                        "/v1/chat/completions", json=payload,
                        headers=headers,
                    )
                    body = await resp.read()
                    statuses[resp.status] = statuses.get(resp.status, 0) + 1
                    if resp.status == 200 and (
                        not stream or b"event: error" not in body
                    ):
                        ok += 1
                    else:
                        failed += 1
                except Exception:
                    failed += 1

        flap_task = asyncio.create_task(flapper())
        t0 = time.perf_counter()
        await asyncio.gather(*(worker_task(i) for i in range(concurrency)))
        elapsed = time.perf_counter() - t0
        running = False
        flap_task.cancel()
        try:
            await flap_task
        except asyncio.CancelledError:
            pass

        total = ok + failed
        success_rate = ok / max(1, total)
        trips = sum(
            1 for s in states
            if s.resilience.state_of(ep_flappy.id) != BreakerState.CLOSED
        )
        gossip_stats = [s.gossip.stats() for s in states
                        if s.gossip is not None]
        return {
            "metric": "chaos_multiworker_client_success_rate",
            "value": round(success_rate, 5),
            "unit": "fraction",
            "passed": success_rate >= 0.99 and propagated,
            "workers": n_workers,
            "requests": total,
            "ok": ok,
            "failed": failed,
            "statuses": statuses,
            "seconds": round(elapsed, 2),
            "req_per_sec": round(total / elapsed, 1),
            "breaker_propagation_ms": round(propagation_s * 1000, 2),
            "breaker_propagated_to_all_workers": propagated,
            "stub_requests": {"stable": len(stable.requests_seen),
                              "flappy": len(flappy.requests_seen)},
            "workers_with_tripped_breaker_at_end": trips,
            "gossip": {
                "sent_total": sum(g["sent_total"] for g in gossip_stats),
                "received_total": sum(
                    g["received_total"] for g in gossip_stats
                ),
                "mean_lag_ms": round(
                    sum(g["lag_s"] or 0.0 for g in gossip_stats)
                    / max(1, len(gossip_stats)) * 1000, 3
                ),
            },
        }
    finally:
        await stable.stop()
        await flappy.stop()
        for h in harnesses:
            await h.client.close()


async def run_chaos_bench(seconds: float, concurrency: int) -> dict:
    """Chaos drill: the real gateway + two stub endpoints serving one model,
    with one endpoint flapping hard (connect-refused injected at the proxy's
    HTTP boundary, ~50% duty cycle) for the whole run. Mixed non-streamed +
    streamed clients hammer /v1/chat/completions; the resilience layer
    (failover + breaker, docs/resilience.md) must keep the client-visible
    success rate >= 99%. Exit code 1 if it doesn't."""
    from llmlb_tpu.gateway.config import ResilienceConfig
    from llmlb_tpu.gateway.faults import FaultInjector, FaultRule
    from llmlb_tpu.gateway.resilience import ResilienceManager
    from tests.support import GatewayHarness, MockOpenAIEndpoint

    gw = await GatewayHarness.create()
    stable = await MockOpenAIEndpoint(model="chaos-model").start()
    flappy = await MockOpenAIEndpoint(model="chaos-model").start()
    try:
        gw.register_mock(stable.url, ["chaos-model"], name="stable")
        ep_flappy = gw.register_mock(flappy.url, ["chaos-model"],
                                     name="flappy")
        # Bench-tuned knobs: fast breaker cycles so several trip/half-open/
        # close rounds happen within a short run; tiny backoff so retries
        # don't dominate the latency figures.
        manager = ResilienceManager(
            ResilienceConfig(
                breaker_failure_threshold=3, breaker_open_s=0.5,
                breaker_open_max_s=2.0, backoff_base_s=0.005,
                backoff_cap_s=0.05, failover_queue_timeout_s=1.0,
            ),
            metrics=gw.state.metrics, events=gw.state.events,
            registry=gw.state.registry,
        )
        gw.state.resilience = manager
        gw.state.load_manager.resilience = manager
        faults = FaultInjector()
        gw.state.faults = faults

        headers = dict(await gw.inference_headers())

        ok = 0
        failed = 0
        stream_errors = 0
        statuses: dict[int, int] = {}
        deadline = time.perf_counter() + seconds
        running = True

        async def flapper() -> None:
            # ~50% duty cycle: dead 0.7 s, alive 0.7 s, forever
            while running:
                rule = faults.add_rule(FaultRule(
                    kind="connect_refused", endpoint="flappy", every_n=1,
                ))
                await asyncio.sleep(0.7)
                faults.remove_rule(rule)
                await asyncio.sleep(0.7)

        async def worker(i: int) -> None:
            nonlocal ok, failed, stream_errors
            n = 0
            while time.perf_counter() < deadline:
                n += 1
                stream = (i + n) % 4 == 0  # 1 in 4 requests streamed
                payload = {
                    "model": "chaos-model",
                    "messages": [{"role": "user", "content": f"ping {n}"}],
                    "stream": stream,
                }
                try:
                    resp = await gw.client.post(
                        "/v1/chat/completions", json=payload, headers=headers
                    )
                    body = await resp.read()
                    statuses[resp.status] = statuses.get(resp.status, 0) + 1
                    if resp.status == 200 and (
                        not stream or b"event: error" not in body
                    ):
                        ok += 1
                    else:
                        failed += 1
                        if resp.status == 200:
                            stream_errors += 1
                except Exception:
                    failed += 1

        flap_task = asyncio.create_task(flapper())
        t0 = time.perf_counter()
        await asyncio.gather(*(worker(i) for i in range(concurrency)))
        elapsed = time.perf_counter() - t0
        running = False
        flap_task.cancel()
        try:
            await flap_task
        except asyncio.CancelledError:
            pass

        # one source of truth: the same figures must appear in /metrics
        resp = await gw.client.get("/metrics")
        exposition = await resp.text()

        def series_sum(name: str) -> float:
            total = 0.0
            for line in exposition.splitlines():
                if line.startswith(name) and not line.startswith("# "):
                    total += float(line.rsplit(" ", 1)[1])
            return total

        total = ok + failed
        success_rate = ok / max(1, total)
        result = {
            "metric": "chaos_client_success_rate",
            "value": round(success_rate, 5),
            "unit": "fraction",
            "passed": success_rate >= 0.99,
            "requests": total,
            "ok": ok,
            "failed": failed,
            "stream_error_frames": stream_errors,
            "statuses": statuses,
            "seconds": round(elapsed, 2),
            "concurrency": concurrency,
            "req_per_sec": round(total / elapsed, 1),
            "stub_requests": {"stable": len(stable.requests_seen),
                              "flappy": len(flappy.requests_seen)},
            "flappy_breaker": manager.breaker_info(ep_flappy.id),
            "prometheus": {
                "failover_retries_total":
                    series_sum("llmlb_gateway_failover_retries_total"),
                "failover_recoveries_total":
                    series_sum("llmlb_gateway_failover_recoveries_total"),
                "breaker_transitions_total":
                    series_sum("llmlb_gateway_breaker_transitions_total"),
                "faults_injected_total":
                    series_sum("llmlb_gateway_faults_injected_total"),
                "retry_budget_exhausted_total":
                    series_sum("llmlb_gateway_retry_budget_exhausted_total"),
            },
        }
        return result
    finally:
        await stable.stop()
        await flappy.stop()
        await gw.close()


# ------------------------------------------------- engine-kill chaos drill


def _spawn_engine_process(port: int, *, extra_env: dict | None = None):
    """A REAL tpu:// engine server process (CPU backend, debug-tiny preset,
    seed-0 weights — every instance generates identical tokens), ready to
    be SIGKILLed/SIGTERMed like production."""
    import subprocess

    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "LLMLB_NATIVE_ROUTER": "0"})
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "llmlb_tpu.engine.server",
         "--preset", "debug-tiny", "--host", "127.0.0.1",
         "--port", str(port), "--num-slots", "16",
         "--slot-capacity", "2048", "--prefill-buckets", "16,32",
         "--kv-page-size", "16"],
        env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


async def _wait_engine_up(session, port: int, timeout_s: float = 120.0):
    import aiohttp

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            async with session.get(
                f"http://127.0.0.1:{port}/v1/models",
                timeout=aiohttp.ClientTimeout(total=2.0),
            ) as resp:
                if resp.status == 200:
                    return
        except Exception:
            pass
        await asyncio.sleep(0.25)
    raise RuntimeError(f"engine on port {port} never came up")


async def _merged_timeline_check(gw, rids, victim_pid) -> dict:
    """Durable-streams observability acceptance (docs/tracing.md): every
    resumed stream's `/api/traces/{id}?view=timeline` merge must carry
    flight-recorder events from BOTH engine processes — the killed
    victim's via the shared spool — in causal order, with the stream
    reaching a terminal event past the cut."""
    victim_src = f"engine-pid{victim_pid}"
    admin = await gw.admin_headers()
    out = {"victim_src": victim_src, "checked": 0, "resumed_verified": 0,
           "failures": []}
    for rid in rids:
        r = await gw.client.get(f"/api/traces/{rid}?view=timeline",
                                headers=admin)
        if r.status != 200:
            await r.release()
            continue
        body = await r.json()
        evs = (body.get("timeline") or {}).get("events") or []
        if not any(e.get("event") == "stream_resume" for e in evs):
            continue  # this stream was never cut
        out["checked"] += 1
        srcs = {e.get("src") for e in evs if e.get("src") != "gateway"}
        tss = [float(e.get("ts") or 0.0) for e in evs]
        victim_evs = [e for e in evs if e.get("src") == victim_src]
        after = [e for e in evs
                 if e.get("src") not in ("gateway", victim_src)]
        problems = []
        if not victim_evs:
            problems.append("no events from the killed engine")
        if len(srcs) < 2:
            problems.append("timeline is single-engine")
        if tss != sorted(tss):
            problems.append("timeline not monotone")
        if not any(e.get("event") in ("finished", "errored")
                   for e in after):
            problems.append("no terminal event past the cut")
        if victim_evs and after and (
                max(float(e.get("ts") or 0.0) for e in victim_evs)
                > min(float(e.get("ts") or 0.0) for e in after)):
            problems.append("survivor events precede the cut")
        if problems:
            out["failures"].append({"rid": rid, "problems": problems})
        else:
            out["resumed_verified"] += 1
    return out


async def run_chaos_engine_kill(streams: int = 8,
                                drills: tuple = ("kill", "drain")) -> dict:
    """The durable-streams chaos drill (docs/resilience.md): REAL engine
    processes behind the real gateway, N streams mid-generation, then

    - ``kill``: SIGKILL one engine — every cut stream must resume
      token-identically on the survivor and complete (>=99% client
      success, completed streams byte-equal to an undisturbed baseline);
    - ``drain``: SIGTERM one engine with a short LLMLB_DRAIN_GRACE_S —
      every in-flight stream either finishes inside the grace or is
      parked + cut for gateway-side resume; ZERO client-visible errors.

    Greedy and seeded-stochastic streams both run (token identity holds
    for both: seed-0 weights, per-request seeds folded by absolute
    position). Exit code 1 when any bar is missed.
    """
    import shutil
    import signal
    import tempfile

    from llmlb_tpu.gateway.config import ResilienceConfig
    from llmlb_tpu.gateway.faults import FaultInjector
    from llmlb_tpu.gateway.resilience import ResilienceManager
    from llmlb_tpu.gateway.types import EndpointStatus, EndpointType
    from tests.support import GatewayHarness, assert_sse_protocol

    t_start = time.monotonic()
    gw = await GatewayHarness.create()
    procs: list = []
    result: dict = {
        "metric": "chaos_engine_kill_drill",
        "unit": "fraction",
        "streams": streams,
        "drills": {},
    }

    # Shared flight-recorder spool: the SIGKILLed engine's lifecycle
    # events survive its death, so the survivor answers the victim's
    # timeline and /api/traces/{id}?view=timeline stays gap-free.
    flightrec_spool = tempfile.mkdtemp(prefix="llmlb-chaos-flightrec-")

    def spawn(extra_env=None):
        port = _free_port()
        env = {"LLMLB_FLIGHTREC_SPOOL": flightrec_spool}
        env.update(extra_env or {})
        proc = _spawn_engine_process(port, extra_env=env)
        procs.append(proc)
        return port, proc

    try:
        manager = ResilienceManager(
            ResilienceConfig(
                breaker_failure_threshold=3, breaker_open_s=0.5,
                breaker_open_max_s=2.0, backoff_base_s=0.005,
                backoff_cap_s=0.05, failover_queue_timeout_s=5.0,
                # the drill cuts ~all streams at once against near-zero
                # request volume, so the ratio term is 0 and the FLOOR is
                # the whole budget — size it for the drill (production
                # budgets scale with real traffic)
                retry_budget_min=4 * streams,
            ),
            metrics=gw.state.metrics, events=gw.state.events,
            registry=gw.state.registry,
        )
        gw.state.resilience = manager
        gw.state.load_manager.resilience = manager
        gw.state.faults = FaultInjector()
        headers = dict(await gw.inference_headers())
        headers["Content-Type"] = "application/json"

        port_a, proc_a = spawn()
        port_b, proc_b = spawn({"LLMLB_DRAIN_GRACE_S": "0.8"})
        await _wait_engine_up(gw.state.http, port_a)
        await _wait_engine_up(gw.state.http, port_b)
        ep_a = gw.register_mock(f"http://127.0.0.1:{port_a}", ["debug-tiny"],
                                endpoint_type=EndpointType.TPU, name="eng-a")
        ep_b = gw.register_mock(f"http://127.0.0.1:{port_b}", ["debug-tiny"],
                                endpoint_type=EndpointType.TPU, name="eng-b")

        contents: list[str] = [""] * streams

        def body_for(i: int, stream: bool, content: str | None = None,
                     max_tokens: int = 160) -> dict:
            body = {
                "model": "debug-tiny",
                "messages": [{"role": "user",
                              "content": content or contents[i]}],
                "max_tokens": max_tokens,
                "stream": stream,
            }
            if i % 2 == 0:
                body["temperature"] = 0.0  # greedy half
            else:
                body["temperature"] = 0.9
                body["seed"] = 1000 + i
            return body

        # ---- undisturbed baseline: non-streaming completions (the engine
        # collects the same stream internally, so text == stream text).
        # Prompt variants are probed for no-early-EOS (>=120 tokens) so the
        # kill reliably lands while streams are still decoding — the same
        # trick the PR 10 slo-mix bench uses.
        async def baseline(i: int) -> str:
            text = ""
            for j in range(12):
                content = f"chaos stream {i}.{j} lorem ipsum dolor"
                r = await gw.client.post(
                    "/v1/chat/completions",
                    json=body_for(i, stream=False, content=content),
                    headers=headers,
                )
                assert r.status == 200, await r.text()
                out = await r.json()
                text = out["choices"][0]["message"]["content"]
                contents[i] = content
                if out["usage"]["completion_tokens"] >= 120:
                    break
            return text

        baselines = list(await asyncio.gather(
            *(baseline(i) for i in range(streams))
        ))

        def stream_text(raw: bytes) -> str:
            parts = []
            for line in raw.split(b"\n"):
                line = line.strip()
                if not line.startswith(b"data:"):
                    continue
                data = line[len(b"data:"):].strip()
                if not data or data == b"[DONE]":
                    continue
                try:
                    obj = json.loads(data)
                except ValueError:
                    continue
                for choice in obj.get("choices") or []:
                    c = (choice.get("delta") or {}).get("content")
                    if isinstance(c, str):
                        parts.append(c)
            return "".join(parts)

        async def one_stream(i: int, first_byte_evt: asyncio.Event,
                             counter: list, rid: str) -> dict:
            out = {"ok": False, "identical": False, "error": None,
                   "rid": rid}
            try:
                r = await gw.client.post("/v1/chat/completions",
                                         json=body_for(i, stream=True),
                                         headers={**headers,
                                                  "X-Request-Id": rid})
                if r.status != 200:
                    out["error"] = f"http_{r.status}"
                    return out
                raw = b""
                async for chunk in r.content.iter_any():
                    raw += chunk
                    if b'"content"' in raw and not out.get("started"):
                        out["started"] = True
                        counter[0] += 1
                        if counter[0] >= streams:
                            first_byte_evt.set()
                if b"event: error" in raw:
                    out["error"] = "error_frame"
                    return out
                assert_sse_protocol(raw, "openai")
                text = stream_text(raw)
                out["ok"] = True
                out["identical"] = text == baselines[i]
                if not out["identical"]:
                    out["error"] = "diverged"
                return out
            except Exception as e:
                out["error"] = f"{type(e).__name__}"
                return out

        engines = [{"proc": proc_a, "ep": ep_a, "alive": True},
                   {"proc": proc_b, "ep": ep_b, "alive": True}]

        async def drill(name: str, victim_sig) -> dict:
            # Reset the TPS EMAs so every live engine scores "unmeasured"
            # and the round-robin tie-break spreads the drill's streams
            # EVENLY — otherwise TPS scoring can concentrate every stream
            # on one endpoint and killing the other proves nothing.
            for e in engines:
                gw.state.load_manager.clear_tps_for_endpoint(e["ep"].id)
            evt = asyncio.Event()
            counter = [0]
            tasks = [
                asyncio.create_task(
                    one_stream(i, evt, counter, f"chaos-{name}-{i}"))
                for i in range(streams)
            ]
            await asyncio.wait_for(evt.wait(), timeout=60)
            victim = next(e for e in engines if e["alive"])
            victim["proc"].send_signal(victim_sig)
            victim["alive"] = False
            outs = await asyncio.gather(*tasks)
            victim["proc"].wait(timeout=30)
            ok = sum(1 for o in outs if o["ok"])
            identical = sum(1 for o in outs if o["identical"])
            return {
                "streams": streams,
                "victim": victim["ep"].name,
                "client_success": ok,
                "token_identical": identical,
                "success_rate": round(ok / streams, 4),
                "errors": [o["error"] for o in outs if o["error"]],
                "timeline": await _merged_timeline_check(
                    gw, [o["rid"] for o in outs if o["ok"]],
                    victim["proc"].pid),
            }

        summary0 = gw.state.metrics.summary()

        if "kill" in drills:
            # SIGKILL the busiest engine while every stream is
            # mid-generation: cut streams must resume on the survivor
            # token-identically
            result["drills"]["sigkill"] = await drill("sigkill",
                                                      signal.SIGKILL)
            # the victim is gone: take it out of the registry the way the
            # health checker eventually would, so the next drill is clean
            for e in engines:
                if not e["alive"]:
                    gw.state.registry.update_status(e["ep"].id,
                                                    EndpointStatus.OFFLINE)

        if "drain" in drills:
            # spawn a fresh peer so the drained engine has a resume target
            port_c, proc_c = spawn({"LLMLB_DRAIN_GRACE_S": "0.8"})
            await _wait_engine_up(gw.state.http, port_c)
            ep_c = gw.register_mock(f"http://127.0.0.1:{port_c}",
                                    ["debug-tiny"],
                                    endpoint_type=EndpointType.TPU,
                                    name="eng-c")
            engines.append({"proc": proc_c, "ep": ep_c, "alive": True})
            # warm the fresh engine (compiles) so the drill's streams are
            # placeable on it the moment the drain cuts them loose
            r = await gw.client.post(
                "/v1/chat/completions",
                json=body_for(0, stream=False,
                              content="warmup prompt", max_tokens=8),
                headers=headers,
            )
            await r.read()
            # SIGTERM the busiest engine (grace 0.8s): in-flight streams
            # finish inside the grace or are parked + cut for gateway-side
            # resume — zero client-visible errors either way
            result["drills"]["sigterm_drain"] = await drill(
                "sigterm_drain", signal.SIGTERM
            )

        summary1 = gw.state.metrics.summary()
        resumes1 = dict(summary1.get("stream_resumes") or {})
        resumes0 = dict(summary0.get("stream_resumes") or {})
        result["stream_resumes"] = {
            k: resumes1.get(k, 0) - resumes0.get(k, 0)
            for k in set(resumes1) | set(resumes0)
        }
        result["stream_resumed_tokens"] = (
            summary1.get("stream_resumed_tokens_total", 0)
            - summary0.get("stream_resumed_tokens_total", 0)
        )
        result["stream_interruptions"] = (
            summary1.get("stream_interruptions_total", 0)
            - summary0.get("stream_interruptions_total", 0)
        )

        bars = []
        for name, d in result["drills"].items():
            bars.append(d["success_rate"] >= 0.99)
            bars.append(d["token_identical"] == d["client_success"])
            # no resumed stream may show a broken merged timeline
            bars.append(not d["timeline"]["failures"])
        if "sigkill" in result["drills"]:
            # the SIGKILL acceptance: at least one resumed stream yields a
            # single merged timeline spanning both engine processes
            bars.append(
                result["drills"]["sigkill"]["timeline"]["resumed_verified"]
                >= 1)
        if "sigterm_drain" in result["drills"]:
            bars.append(not result["drills"]["sigterm_drain"]["errors"])
        # the drill is vacuous unless at least one stream actually resumed
        bars.append(result["stream_resumes"].get("success", 0) >= 1)
        result["value"] = min(
            d["success_rate"] for d in result["drills"].values()
        )
        result["passed"] = all(bars)
        result["seconds"] = round(time.monotonic() - t_start, 1)
        return result
    finally:
        for p in procs:
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:
                pass
        shutil.rmtree(flightrec_spool, ignore_errors=True)
        await gw.close()


def _openai_sse_text(body: bytes) -> str:
    """Concatenated delta content of an OpenAI chat SSE body."""
    parts = []
    for line in body.split(b"\n"):
        line = line.strip()
        if not line.startswith(b"data:"):
            continue
        data = line[len(b"data:"):].strip()
        if not data or data == b"[DONE]":
            continue
        try:
            obj = json.loads(data)
        except ValueError:
            continue
        for choice in obj.get("choices") or []:
            content = (choice.get("delta") or {}).get("content")
            if isinstance(content, str):
                parts.append(content)
    return "".join(parts)


async def run_rebalance_bench(streams: int = 12) -> dict:
    """Zero-downtime rebalancing drill (docs/resilience.md): two scenarios
    against the real gateway pump + mock resumable engines.

    - ``rolling_restart``: >= `streams` concurrent LIVE streams across
      three engines; each engine in turn advertises draining and the
      rebalancer evacuates it through park-export → resume while the
      clients keep reading. Bars: 100% client success, 100% token-identical
      output, zero terminal SSE error frames, every engine fully evacuated
      while draining.
    - ``hotspot``: background streams decode on a slow overloaded engine;
      a fast idle engine appears. Run twice — LLMLB_REBALANCE off
      (baseline: streams stay put) vs on (hot-spot directives migrate
      them) — and compare client-observed inter-chunk ITL p99. Bars:
      >= 1 hotspot/success migration, token identity in BOTH modes, and
      the rebalanced ITL p99 beating the pinned baseline.

    Exit code 1 when any bar is missed.
    """
    from llmlb_tpu.gateway.config import ResilienceConfig
    from llmlb_tpu.gateway.faults import FaultInjector
    from llmlb_tpu.gateway.rebalance import RebalanceConfig, Rebalancer
    from llmlb_tpu.gateway.resilience import ResilienceManager
    from llmlb_tpu.gateway.types import AcceleratorInfo, EndpointType
    from tests.support import GatewayHarness, MockResumableEndpoint

    t_start = time.monotonic()
    chat = "/v1/chat/completions"

    def wire_resilience(gw) -> None:
        manager = ResilienceManager(
            ResilienceConfig(backoff_base_s=0.005, backoff_cap_s=0.05,
                             failover_queue_timeout_s=2.0,
                             breaker_failure_threshold=3),
            metrics=gw.state.metrics, events=gw.state.events,
            registry=gw.state.registry,
        )
        gw.state.resilience = manager
        gw.state.load_manager.resilience = manager
        gw.state.faults = FaultInjector()

    async def one_stream(gw, headers, full_text) -> dict:
        body = {"model": "m", "stream": True,
                "messages": [{"role": "user", "content": "ping"}]}
        buf = bytearray()
        stamps: list[float] = []
        resp = await gw.client.post(chat, json=body, headers=headers)
        ok = resp.status == 200
        async for chunk in resp.content.iter_any():
            buf += chunk
            stamps.append(time.perf_counter())
        raw = bytes(buf)
        gaps = [b - a for a, b in zip(stamps, stamps[1:])]
        return {
            "ok": ok,
            "identical": _openai_sse_text(raw) == full_text,
            "error_frames": raw.count(b"event: error"),
            "gaps": gaps,
        }

    # ------------------------------------------------- (a) rolling restart
    script = list(range(100, 220))  # 120 tokens x 20 ms ≈ 2.4 s per stream
    full_text = "".join(MockResumableEndpoint.text_of(t) for t in script)
    gw = await GatewayHarness.create()
    mocks = []
    try:
        for i in range(3):
            mocks.append(await MockResumableEndpoint(
                model="m", script=script, inter_chunk_delay_s=0.02).start())
        eps = [gw.register_mock(m.url, ["m"], endpoint_type=EndpointType.TPU,
                                name=f"eng-{i}")
               for i, m in enumerate(mocks)]
        wire_resilience(gw)
        directory = gw.state.streams
        cfg = RebalanceConfig(max_concurrent=streams, per_minute=100000,
                              stream_window_s=0.05)
        # the directory enforces the per-stream window itself — give it the
        # drill's short window or a stream that already hopped once sits out
        # the default 60 s and the next drain can never finish
        directory.config = cfg
        reb = Rebalancer(
            gw.state.registry, gw.state.load_manager, directory,
            metrics=gw.state.metrics, config=cfg,
        )
        headers = dict(await gw.inference_headers())

        async def roll() -> dict:
            # wait until every stream is live, then restart engines in turn
            deadline = time.monotonic() + 5.0
            while (len(directory._streams) < streams
                   and time.monotonic() < deadline):
                await asyncio.sleep(0.01)
            peak_live = len(directory._streams)
            evacuated = []
            for ep in eps:
                ep.accelerator = AcceleratorInfo(accelerator="tpu",
                                                 draining=True)
                empty_by = time.monotonic() + 2.0
                while time.monotonic() < empty_by:
                    reb.tick()
                    await asyncio.sleep(0.05)
                    if directory.counts().get(ep.id, 0) == 0:
                        break
                evacuated.append(directory.counts().get(ep.id, 0) == 0)
                # "restart": the engine comes back clean and takes load again
                ep.accelerator = AcceleratorInfo(accelerator="tpu")
            return {"peak_live": peak_live, "evacuated": evacuated}

        roll_task = asyncio.create_task(roll())
        outs = await asyncio.gather(
            *(one_stream(gw, headers, full_text) for _ in range(streams)))
        rolled = await roll_task
        summary = gw.state.metrics.summary()
        rolling = {
            "streams": streams,
            "peak_concurrent_live": rolled["peak_live"],
            "client_success_rate": sum(o["ok"] for o in outs) / streams,
            "token_identical_rate": (
                sum(o["identical"] for o in outs) / streams),
            "error_frames": sum(o["error_frames"] for o in outs),
            "engines_fully_evacuated": sum(rolled["evacuated"]),
            "migrations": summary["rebalance_migrations"],
            "stream_resumes": summary["stream_resumes"],
        }
    finally:
        for m in mocks:
            await m.stop()
        await gw.close()

    # ------------------------------------------------------- (b) hot-spot
    script = list(range(100, 180))  # 80 tokens
    full_text = "".join(MockResumableEndpoint.text_of(t) for t in script)

    async def hotspot_mode(rebalance_on: bool) -> dict:
        gw = await GatewayHarness.create()
        hot = cold = None
        try:
            hot = await MockResumableEndpoint(
                model="m", script=script, inter_chunk_delay_s=0.05).start()
            ep_hot = gw.register_mock(hot.url, ["m"],
                                      endpoint_type=EndpointType.TPU,
                                      name="hot")
            wire_resilience(gw)
            headers = dict(await gw.inference_headers())
            n = max(4, streams // 2)
            tasks = [asyncio.create_task(one_stream(gw, headers, full_text))
                     for _ in range(n)]
            await asyncio.sleep(0.4)  # everyone decoding on the hot engine
            cold = await MockResumableEndpoint(
                model="m", script=script, inter_chunk_delay_s=0.01).start()
            ep_cold = gw.register_mock(cold.url, ["m"],
                                       endpoint_type=EndpointType.TPU,
                                       name="cold")
            ep_hot.accelerator = AcceleratorInfo(
                accelerator="tpu", num_slots=8, active_slots=8,
                queue_depth=4)
            ep_cold.accelerator = AcceleratorInfo(
                accelerator="tpu", num_slots=8)
            ticker = None
            if rebalance_on:
                reb = Rebalancer(
                    gw.state.registry, gw.state.load_manager,
                    gw.state.streams, metrics=gw.state.metrics,
                    config=RebalanceConfig(max_concurrent=n,
                                           per_minute=100000,
                                           stream_window_s=0.05),
                )

                async def tick_loop():
                    while True:
                        reb.tick()
                        await asyncio.sleep(0.05)

                ticker = asyncio.create_task(tick_loop())
            outs = await asyncio.gather(*tasks)
            if ticker is not None:
                ticker.cancel()
                try:
                    await ticker
                except asyncio.CancelledError:
                    pass
            # steady-state ITL: the last half of each stream's gaps — the
            # window where the planner has (or pointedly has not) acted;
            # whole-stream p99 would be dominated by the shared slow start
            gaps = [g for o in outs
                    for g in o["gaps"][len(o["gaps"]) // 2:]]
            summary = gw.state.metrics.summary()
            return {
                "streams": n,
                "client_success_rate": sum(o["ok"] for o in outs) / n,
                "token_identical_rate": sum(o["identical"] for o in outs) / n,
                "error_frames": sum(o["error_frames"] for o in outs),
                "itl": _gap_stats(gaps),
                "migrations": summary["rebalance_migrations"],
            }
        finally:
            for m in (hot, cold):
                if m is not None:
                    await m.stop()
            await gw.close()

    pinned = await hotspot_mode(False)
    rebalanced = await hotspot_mode(True)
    hotspot_migrations = sum(
        n for key, n in rebalanced["migrations"].items()
        if key == "hotspot/success")

    passed = (
        rolling["peak_concurrent_live"] >= streams
        and rolling["client_success_rate"] == 1.0
        and rolling["token_identical_rate"] == 1.0
        and rolling["error_frames"] == 0
        and rolling["engines_fully_evacuated"] == 3
        and rolling["migrations"].get("drain/success", 0) >= streams
        # migration is planning, not failure: nothing in stream_resumes
        and not rolling["stream_resumes"]
        and pinned["token_identical_rate"] == 1.0
        and rebalanced["token_identical_rate"] == 1.0
        and hotspot_migrations >= 1
        and rebalanced["itl"]["p99_ms"] < pinned["itl"]["p99_ms"]
    )
    return {
        "metric": "rebalance_zero_downtime_drill",
        "unit": "fraction",
        "value": rolling["client_success_rate"],
        "passed": passed,
        "rolling_restart": rolling,
        "hotspot": {"pinned": pinned, "rebalanced": rebalanced,
                    "itl_p99_improvement_ms": round(
                        pinned["itl"]["p99_ms"]
                        - rebalanced["itl"]["p99_ms"], 1)},
        "seconds": round(time.monotonic() - t_start, 1),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--seconds", type=float, default=10.0)
    parser.add_argument("--concurrency", type=int, default=50)
    parser.add_argument(
        "--workload",
        choices=("proxy", "shared-prefix", "mixed-length", "chaos",
                 "structured", "spec-decode", "quantized", "throughput",
                 "slo-mix", "disagg", "lora", "kv-ship", "fused",
                 "rebalance"),
        default="proxy",
    )
    parser.add_argument("--requests", type=int, default=24,
                        help="request count for --workload shared-prefix / "
                             "mixed-length / structured / spec-decode / "
                             "quantized")
    parser.add_argument("--engine-kill", action="store_true",
                        help="--workload chaos variant: spawn REAL engine "
                             "processes and SIGKILL one mid-stream (resume "
                             "drill) then SIGTERM-drain another "
                             "(docs/resilience.md durable streams)")
    parser.add_argument("--workers", type=int, default=None,
                        help="gateway worker processes: the top of the "
                             "scaling curve for --workload throughput "
                             "(default 4), or the in-process worker count "
                             "for --workload chaos (default 1)")
    parser.add_argument("--clients", type=int, default=4,
                        help="load-generator processes for --workload "
                             "throughput")
    # hidden child-process entry modes for --workload throughput
    parser.add_argument("--stub-server", type=int, default=None,
                        help=argparse.SUPPRESS)
    parser.add_argument("--client-runner", type=str, default=None,
                        help=argparse.SUPPRESS)
    args = parser.parse_args()
    if args.stub_server:
        _run_stub_server(args.stub_server)
        return
    if args.client_runner:
        _run_client_runner(args.client_runner)
        return
    if args.workload == "throughput":
        top = max(2, args.workers or 4)
        workers_list = sorted({1, 2, top} if top > 2 else {1, top})
        result = run_throughput_bench(
            args.seconds, args.concurrency, workers_list, args.clients
        )
        print(json.dumps(result))
        return
    if args.workload == "rebalance":
        result = asyncio.run(run_rebalance_bench(
            streams=max(12, args.requests // 2)))
        print(json.dumps(result))
        if not result["passed"]:
            sys.exit(1)
        return
    if args.workload not in ("proxy", "chaos"):
        _pin_platform()  # engine workloads touch jax: decide platform first
    if args.workload == "shared-prefix":
        result = asyncio.run(run_prefix_bench(args.requests))
    elif args.workload == "structured":
        result = asyncio.run(run_structured_bench(args.requests))
    elif args.workload == "spec-decode":
        result = asyncio.run(run_spec_bench(args.requests))
    elif args.workload == "mixed-length":
        result = asyncio.run(run_mixed_length_bench(args.requests))
    elif args.workload == "slo-mix":
        result = asyncio.run(run_slo_mix_bench(args.requests))
        print(json.dumps(result))
        if not result["passed"]:
            sys.exit(1)
        return
    elif args.workload == "disagg":
        result = asyncio.run(run_disagg_bench(args.requests))
        print(json.dumps(result))
        if not result["passed"]:
            sys.exit(1)
        return
    elif args.workload == "lora":
        result = asyncio.run(run_lora_bench(args.requests))
        print(json.dumps(result))
        if not result["passed"]:
            sys.exit(1)
        return
    elif args.workload == "kv-ship":
        result = asyncio.run(run_kv_ship_bench(args.requests))
        print(json.dumps(result))
        if not result["passed"]:
            sys.exit(1)
        return
    elif args.workload == "fused":
        result = asyncio.run(run_fused_bench(args.requests))
        print(json.dumps(result))
        if not result["passed"]:
            sys.exit(1)
        return
    elif args.workload == "quantized":
        if args.requests < 40:
            # the peak-concurrency measurement needs enough requests to
            # saturate the int8 pool (~30 concurrent at the bench sizing)
            print(f"[bench] --requests {args.requests} raised to 40: the "
                  "quantized workload must saturate the page pool",
                  file=sys.stderr)
        result = asyncio.run(run_quantized_bench(max(args.requests, 40)))
    elif args.workload == "chaos":
        if args.engine_kill:
            result = asyncio.run(run_chaos_engine_kill(
                streams=max(8, min(16, args.requests // 2))
            ))
            print(json.dumps(result))
            if not result["passed"]:
                sys.exit(1)
            return
        if args.workers and args.workers > 1:
            result = asyncio.run(run_chaos_multiworker(
                args.seconds, min(args.concurrency, 16), args.workers
            ))
        else:
            result = asyncio.run(
                run_chaos_bench(args.seconds, min(args.concurrency, 16))
            )
        print(json.dumps(result))
        if not result["passed"]:
            sys.exit(1)
        return
    else:
        result = asyncio.run(run_bench(args.seconds, args.concurrency))
    print(json.dumps(result))


if __name__ == "__main__":
    sys.path.insert(0, ".")
    main()
