#!/usr/bin/env python
"""Cross-check `LLMLB_*` environment knobs against the docs.

Every `LLMLB_[A-Z0-9_]+` name referenced anywhere in `llmlb_tpu/` source
must be named VERBATIM somewhere under `docs/` (docs/configuration.md is
the canonical table) — a new knob, like `LLMLB_QUANTIZE`, cannot ship
undocumented. Wired as a tier-1 test (tests/test_env_docs.py), same
pattern as scripts/check_metrics_docs.py; also runnable standalone:

    python scripts/check_env_docs.py
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SRC = REPO / "llmlb_tpu"
DOCS = REPO / "docs"

_KNOB_RE = re.compile(r"LLMLB_[A-Z0-9_]+")


def source_knobs() -> set[str]:
    """Every LLMLB_* name in llmlb_tpu/ .py sources. Matches that end with
    an underscore are glob-style prose ("LLMLB_SPEC_{DECODE,...}",
    "LLMLB_RETRY_*") — skipped, their expansions are matched directly."""
    names: set[str] = set()
    for path in sorted(SRC.rglob("*.py")):
        for m in _KNOB_RE.findall(path.read_text()):
            if not m.endswith("_"):
                names.add(m)
    return names


def documented_knobs() -> set[str]:
    names: set[str] = set()
    for path in sorted(DOCS.rglob("*.md")):
        for m in _KNOB_RE.findall(path.read_text()):
            if not m.endswith("_"):
                names.add(m)
    return names


def undocumented() -> list[str]:
    return sorted(source_knobs() - documented_knobs())


def main() -> int:
    knobs = source_knobs()
    missing = sorted(knobs - documented_knobs())
    if missing:
        print("env knobs referenced in llmlb_tpu/ but undocumented in "
              "docs/ (add them to docs/configuration.md):", file=sys.stderr)
        for name in missing:
            print(f"  - {name}", file=sys.stderr)
        return 1
    print(f"all {len(knobs)} LLMLB_* knobs are documented")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
