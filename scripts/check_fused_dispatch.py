#!/usr/bin/env python
"""Fail when a fused decode step issues more than one device dispatch.

The fused-decode contract (docs/fused-decode.md): with
``LLMLB_FUSED_DECODE=1`` every decode-loop step — including steps where
quantized KV, LoRA, speculative verification and grammar-constrained
sampling are ALL active at once — launches exactly ONE device program.
The scheduler's per-step ledger (StepRecorder ``dispatches`` field +
``decode_dispatch_by_loop``) records what actually launched; this checker
drives a real CPU debug engine with all four features on and fails if any
decode/verify record counts more than one dispatch, if a constrained slot
forced a single-step fallback, or if the feature mix silently didn't
engage (a vacuous pass is a finding too).

Wired as a tier-1 test (tests/test_fused_dispatch.py); standalone:

    python scripts/check_fused_dispatch.py
"""

from __future__ import annotations

import os
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

SCHEMA = {
    "type": "object",
    "properties": {
        "ok": {"type": "boolean"},
        "tag": {"enum": ["alpha", "beta"]},
    },
    "required": ["ok", "tag"],
}

# repetitive prompt so prompt-lookup speculation actually drafts
PROMPT = [5, 6, 7, 8, 9] * 5


def _drain(request):
    toks = []
    while True:
        kind, val = request.events.get(timeout=120)
        if kind == "token":
            toks.append(val)
        elif kind == "done":
            return toks
        else:
            raise RuntimeError(f"engine error: {val}")


def run_check() -> list[str]:
    """Drive the 4-feature-on batch; return human-readable findings."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    prior = os.environ.get("LLMLB_FUSED_DECODE")
    os.environ["LLMLB_FUSED_DECODE"] = "1"
    sys.path.insert(0, str(REPO))
    try:
        return _run_check_inner()
    finally:
        # in-process callers (tests/test_fused_dispatch.py) must not leak
        # the forced mode into the rest of the pytest session
        if prior is None:
            del os.environ["LLMLB_FUSED_DECODE"]
        else:
            os.environ["LLMLB_FUSED_DECODE"] = prior


def _run_check_inner() -> list[str]:

    from llmlb_tpu.engine.presets import get_preset
    from llmlb_tpu.engine.scheduler import EngineCore, Request, \
        SamplingParams
    from llmlb_tpu.engine.tokenizer import ByteTokenizer
    from llmlb_tpu.lora import save_adapter
    from llmlb_tpu.structured import ConstraintCompiler

    cfg = get_preset("debug-tiny")
    tok = ByteTokenizer(cfg.vocab_size)
    with tempfile.TemporaryDirectory() as lora_dir:
        save_adapter(lora_dir, "acme", cfg, rank=4)
        core = EngineCore(
            cfg, num_slots=4, slot_capacity=128, prefill_buckets=(16, 32),
            kv_layout="paged", kv_page_size=16, seed=0,
            quantize="kv", lora_dir=lora_dir, spec_decode=True,
            eos_id=tok.eos_id,
        )
        core.constraint_compiler = ConstraintCompiler(tok, cfg.vocab_size)
        core.start()
        try:
            findings: list[str] = []
            if not core.fused_decode:
                return ["LLMLB_FUSED_DECODE=1 did not enable fused decode"]
            reqs = [
                # plain greedy
                Request(prompt_ids=list(PROMPT), sampling=SamplingParams(
                    temperature=0.0, max_tokens=16)),
                # LoRA seeded
                Request(prompt_ids=list(PROMPT), sampling=SamplingParams(
                    temperature=0.8, seed=7, max_tokens=16, lora="acme")),
                # JSON-constrained greedy, riding the same batch
                Request(prompt_ids=list(PROMPT), sampling=SamplingParams(
                    temperature=0.0, max_tokens=24,
                    constraint={"type": "json_schema", "schema": SCHEMA})),
                # JSON-constrained + LoRA, seeded
                Request(prompt_ids=list(PROMPT), sampling=SamplingParams(
                    temperature=0.9, seed=42, max_tokens=24, lora="acme",
                    constraint={"type": "json_schema", "schema": SCHEMA})),
            ]
            for r in reqs:
                core.submit(r)
            for r in reqs:
                _drain(r)

            records = core.step_stats.snapshot(limit=512)["records"]
            decs = [r for r in records
                    if r["kind"] in ("decode", "verify")]
            if not decs:
                findings.append("no decode/verify steps recorded")
            multi = [r for r in decs if r["dispatches"] != 1]
            for r in multi:
                findings.append(
                    f"step seq={r['seq']} kind={r['kind']} launched "
                    f"{r['dispatches']} device dispatches (want 1)")
            m = core.metrics
            if m.constrained_burst_fallback_total:
                findings.append(
                    f"{m.constrained_burst_fallback_total} constrained "
                    "single-step fallback(s) — grammar not device-resident")
            # the feature mix must have engaged, else the pass is vacuous
            if m.masked_decode_steps_total == 0:
                findings.append("no grammar-masked decode steps ran")
            if m.spec_verify_steps_total == 0:
                findings.append("no speculative verify steps ran")
            if m.fused_decode_steps_total == 0:
                findings.append("no fused decode steps counted")
            gt = core._grammar_tables
            if gt is None or gt.schemas_registered == 0:
                findings.append("no schema registered in grammar tables")
            elif gt.schemas_rejected:
                findings.append(
                    f"{gt.schemas_rejected} schema(s) rejected by the "
                    "grammar-table budget")
            total = sum(core.decode_dispatch_by_loop.values())
            if total != len(decs):
                findings.append(
                    f"dispatch ledger {total} != decode/verify step "
                    f"count {len(decs)}")
            return findings
        finally:
            core.stop()


def main() -> int:
    findings = run_check()
    for what in findings:
        print(what, file=sys.stderr)
    if findings:
        print(f"\n{len(findings)} fused-dispatch violation(s) found",
              file=sys.stderr)
        return 1
    print("every decode step under LLMLB_FUSED_DECODE=1 launched exactly "
          "one device program")
    return 0


if __name__ == "__main__":
    sys.exit(main())
