#!/usr/bin/env python
"""Gossip-wire round trip for EVERY message type in MESSAGE_TYPES.

The test_plan_wire / test_handoff_wire discipline applied to the gossip
bus (gateway/gossip.py): a distinctive non-default probe value is
synthesized for every declared dataclass field from its annotation and
round-tripped through `encode_message` → bytes → `decode_message` — the
ONLY paths on/off the wire — so a field added to any message kind without
surviving serialization is a tier-1 failure (tests/test_gossip_wire.py),
not a silently desynced fleet. Version mismatches and unknown inbound
fields must refuse loudly (a newer peer bumps VERSION, never relies on
silent drops). Also runnable standalone:

    python scripts/check_gossip_wire.py
"""

from __future__ import annotations

import dataclasses
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from llmlb_tpu.gateway.gossip import (  # noqa: E402
    MESSAGE_TYPES,
    GossipWireError,
    decode_message,
    encode_message,
)

ORIGIN = "10.0.0.7:7946#w1"
SEQ = 41


def probe_value(cls: type, field: dataclasses.Field):
    """A JSON-safe value distinguishable from the field's default, derived
    from the annotation so newly added fields get covered automatically."""
    ann = str(field.type)
    if "dict" in ann:
        return {"probe": field.name, "n": 3}
    if "bool" in ann:
        default = field.default
        return not default if isinstance(default, bool) else True
    if "float" in ann:
        return 0.125
    if "int" in ann:
        return 7
    if "str" in ann:
        return f"probe-{field.name}"
    raise AssertionError(
        f"{cls.__name__}.{field.name}: add a wire-probe rule for {ann!r} "
        "(and make sure the field is JSON-safe for the gossip wire)"
    )


def probe_data(cls: type) -> dict:
    return {f.name: probe_value(cls, f) for f in dataclasses.fields(cls)}


def check_roundtrip(kind: str, cls: type) -> list[str]:
    """Round-trip every declared field; returns human-readable failures."""
    problems: list[str] = []
    data = probe_data(cls)
    # probes must differ from defaults, or a dropped field that
    # deserializes to its default would round-trip undetected
    defaults = cls()
    for f in dataclasses.fields(cls):
        if data[f.name] == getattr(defaults, f.name):
            problems.append(
                f"{cls.__name__}.{f.name}: probe equals its default; "
                "probe_value needs a better rule"
            )
    try:
        raw = encode_message(kind, data, origin=ORIGIN, seq=SEQ, ts=1000.0)
    except GossipWireError as e:
        return problems + [f"{kind}: encode refused its own fields: {e}"]
    try:
        out_kind, out, meta = decode_message(raw)
    except GossipWireError as e:
        return problems + [f"{kind}: decode refused encode's output: {e}"]
    if out_kind != kind:
        problems.append(f"{kind}: kind changed to {out_kind!r} on the wire")
    for f in dataclasses.fields(cls):
        if out.get(f.name) != data[f.name]:
            problems.append(
                f"{cls.__name__}.{f.name} was lost or mangled on the "
                f"gossip wire ({data[f.name]!r} -> {out.get(f.name)!r})"
            )
    if meta.get("origin") != ORIGIN or meta.get("seq") != SEQ:
        problems.append(f"{kind}: envelope origin/seq mangled: {meta}")
    if tuple(meta.get("ver") or ()) != (SEQ, ORIGIN):
        problems.append(f"{kind}: meta['ver'] != (seq, origin): {meta}")
    return problems


def check_rejections(kind: str, cls: type) -> list[str]:
    """Wrong version and unknown fields must refuse, sender- and
    receiver-side."""
    problems: list[str] = []
    raw = encode_message(kind, probe_data(cls), origin=ORIGIN, seq=SEQ)
    import json

    env = json.loads(raw)
    env["v"] = cls.VERSION + 1
    try:
        decode_message(json.dumps(env).encode())
        problems.append(f"{kind}: wrong VERSION was not rejected")
    except GossipWireError:
        pass
    env = json.loads(raw)
    env["d"]["from_the_future"] = 1
    try:
        decode_message(json.dumps(env).encode())
        problems.append(f"{kind}: unknown inbound field was not rejected")
    except GossipWireError:
        pass
    try:
        encode_message(kind, {"from_the_future": 1}, origin=ORIGIN, seq=SEQ)
        problems.append(f"{kind}: encode accepted an undeclared field")
    except GossipWireError:
        pass
    return problems


def failures() -> list[str]:
    problems: list[str] = []
    if not MESSAGE_TYPES:
        return ["MESSAGE_TYPES is empty — the enumeration broke"]
    for kind, cls in sorted(MESSAGE_TYPES.items()):
        if getattr(cls, "KIND", None) != kind:
            problems.append(f"{cls.__name__}: KIND != registry key {kind!r}")
        if not isinstance(getattr(cls, "VERSION", None), int):
            problems.append(f"{cls.__name__}: VERSION must be an int")
            continue
        problems += check_roundtrip(kind, cls)
        problems += check_rejections(kind, cls)
    try:
        encode_message("not_a_kind", {}, origin=ORIGIN, seq=1)
        problems.append("encode accepted an unknown message kind")
    except GossipWireError:
        pass
    return problems


def main() -> int:
    problems = failures()
    if problems:
        print("gossip wire-format problems:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    n_fields = sum(
        len(dataclasses.fields(cls)) for cls in MESSAGE_TYPES.values()
    )
    print(f"all {len(MESSAGE_TYPES)} gossip message types "
          f"({n_fields} fields) round-trip versioned")
    return 0


if __name__ == "__main__":
    sys.exit(main())
