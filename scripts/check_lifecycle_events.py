#!/usr/bin/env python
"""Fail when a scheduler terminal path lacks a flight-recorder emit.

The flight recorder (llmlb_tpu/engine/flightrec.py) is only trustworthy
if EVERY terminal edge a request can cross — finish, error, shed, park —
writes an event: a missing emit turns a merged timeline into a silent
gap, which reads as "the request vanished". This checker walks
``llmlb_tpu/engine/scheduler.py`` with ``ast`` and enforces, per function:

- every ``<request>.events.put(("done", ...))`` / ``(("error", ...))``
  call (the terminal client-visible edges) is matched by at least as many
  flight-recorder emits (``self._fr_emit(...)`` or
  ``self.flightrec.emit(...)``) in the same function;
- ``_park_slot`` (the preemption/drain park edge — terminal for the slot,
  resumable for the request) contains a ``parked`` emit.

Functions with no terminal puts are not required to emit anything. The
per-function >= pairing is deliberate: an emit belongs NEXT TO the put it
mirrors, and a function that gains a second terminal path without a
second emit fails here. Wired as a tier-1 test
(tests/test_lifecycle_events.py); standalone:

    python scripts/check_lifecycle_events.py
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SCHEDULER = REPO / "llmlb_tpu" / "engine" / "scheduler.py"

TERMINAL_KINDS = ("done", "error")


def _is_terminal_put(node: ast.Call) -> bool:
    """``<anything>.events.put((<"done"|"error">, ...))``."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr == "put"):
        return False
    if not (isinstance(f.value, ast.Attribute) and f.value.attr == "events"):
        return False
    if not node.args:
        return False
    arg = node.args[0]
    if not (isinstance(arg, ast.Tuple) and arg.elts):
        return False
    head = arg.elts[0]
    return (isinstance(head, ast.Constant)
            and head.value in TERMINAL_KINDS)


def _is_fr_emit(node: ast.Call) -> bool:
    """``self._fr_emit(...)`` or ``<anything>.flightrec.emit(...)``."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "_fr_emit":
        return True
    if (isinstance(f, ast.Attribute) and f.attr == "emit"
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "flightrec"):
        return True
    return False


def _emits_event(func: ast.FunctionDef, event: str) -> bool:
    """True when the function contains an ``_fr_emit``/``flightrec.emit``
    call whose event argument is the given string constant."""
    for node in ast.walk(func):
        if not (isinstance(node, ast.Call) and _is_fr_emit(node)):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Constant) and arg.value == event:
                return True
    return False


def check_scheduler(path: Path = SCHEDULER) -> list[tuple[int, str]]:
    source = path.read_text()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:  # broken file: other tooling reports it better
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    findings: list[tuple[int, str]] = []
    park_seen = False
    for func in ast.walk(tree):
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        puts = 0
        emits = 0
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            if _is_terminal_put(node):
                puts += 1
            elif _is_fr_emit(node):
                emits += 1
        if puts and emits < puts:
            findings.append((
                func.lineno,
                f"{func.name}(): {puts} terminal events.put but only "
                f"{emits} flight-recorder emit(s) — every finish/error/"
                f"shed path must emit next to its put",
            ))
        if func.name == "_park_slot":
            park_seen = True
            if not _emits_event(func, "parked"):
                findings.append((
                    func.lineno,
                    "_park_slot(): park edge lacks a 'parked' "
                    "flight-recorder emit",
                ))
    if not park_seen:
        findings.append((0, "_park_slot() not found in scheduler.py — "
                            "checker needs updating for the rename"))
    return findings


def main() -> int:
    findings = check_scheduler()
    for lineno, what in findings:
        rel = SCHEDULER.relative_to(REPO)
        print(f"{rel}:{lineno}: {what}", file=sys.stderr)
    if findings:
        print(f"\n{len(findings)} uninstrumented lifecycle path(s) found",
              file=sys.stderr)
        return 1
    print("every scheduler terminal path carries a flight-recorder emit")
    return 0


if __name__ == "__main__":
    sys.exit(main())
