#!/usr/bin/env python
"""Cross-check exported metric names against docs/monitoring/README.md —
and the monitoring ASSETS against the exporters.

Two directions, both wired as tier-1 tests (tests/test_metrics_docs.py);
also runnable standalone:

    python scripts/check_metrics_docs.py

1. Every Prometheus series the engine and gateway registries can emit must
   be named VERBATIM somewhere in docs/monitoring/README.md — new gauges
   (like the page-pool family) cannot ship undocumented. Enumeration is by
   rendering the real registries (with every optional block enabled and one
   sample recorded per labeled family, so conditional series render too)
   plus the scrape-time gauge/counter literals the gateway /metrics handler
   injects (regex over llmlb_tpu/gateway/app.py — they live in a dict at
   the call site, not in the registry).

2. Every llmlb_* series referenced by docs/monitoring/grafana-tpu-engine.json
   and prometheus-alerts.yml must exist in the exportable set, so dashboards
   and alert rules cannot drift from the exporters (a renamed gauge breaks
   the build, not the on-call's 3am debugging session).
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
DOCS = REPO / "docs" / "monitoring" / "README.md"
GRAFANA = REPO / "docs" / "monitoring" / "grafana-tpu-engine.json"
ALERTS = REPO / "docs" / "monitoring" / "prometheus-alerts.yml"

_TYPE_RE = re.compile(r"^# TYPE (\S+) ", re.MULTILINE)
_GATEWAY_LITERAL_RE = re.compile(r'"(llmlb_gateway_[a-z0-9_]+)"')
# two segments minimum after the prefix: skips prose like "llmlb_gateway_*"
# and module paths like "llmlb_tpu/gateway" in asset comments
_SERIES_RE = re.compile(r"\b(llmlb_[a-z0-9]+(?:_[a-z0-9]+)+)\b")
_CLOUD_LITERAL_RE = re.compile(r"(llmlb_cloud_[a-z0-9_]+)")
# histogram exposition suffixes resolve to their family name
_HIST_SUFFIX_RE = re.compile(r"_(bucket|sum|count)$")


def engine_metric_names() -> set[str]:
    from llmlb_tpu.engine.metrics import EngineMetrics

    m = EngineMetrics()
    # one sample per labeled lora family so the conditional series render
    m.record_lora_request("sample")
    m.record_lora_load(0.0)
    text = m.render(
        queue_depth=0, active_slots=0, num_slots=1,
        prefix_cache={
            "enabled": True, "entries": 0, "pinned_slots": 0,
            "pinned_pages": 0, "pinned_hbm_bytes": 0,
        },
        structured={
            "enabled": True, "mask_cache_entries": 0, "mask_cache_bytes": 0,
        },
        kv_cache={
            "layout": "paged", "page_size": 128, "pages_total": 0,
            "pages_free": 0, "pages_active": 0, "pages_pinned": 0,
            "utilization": 0.0, "fragmentation": 0.0,
            "waste_tokens_mean": 0.0, "bytes_per_page": 0, "hbm_bytes": 0,
            "kv_dtype": "int8",
        },
        perf={
            "available": True, "mfu": 0.0, "hbm_bw_utilization": 0.0,
            "flops_per_token": 0.0, "bytes_per_token": 0.0,
        },
        quant={"mode": "all", "param_bytes": 0},
        sched={"queued_by_class": {"high": 0, "normal": 0, "low": 0},
               "queued_by_role": {"prefill": 0, "decode": 0}},
        lora={"enabled": True, "resident": ["sample"],
              "available": ["sample"], "max_adapters": 8},
        flightrec={"enabled": True, "events_total": 0,
                   "events_dropped_total": 0, "requests_tracked": 0,
                   "queue_seconds_total": 0.0, "service_seconds_total": 0.0},
        kv_offload={"enabled": True, "budget_bytes": 0, "bytes": 0,
                    "entries": 0, "prefix_entries": 0, "parked_entries": 0,
                    "hits": 0, "misses": 0, "spills": 0, "evictions": 0,
                    "spilled_bytes": 0, "restored_bytes": 0},
    )
    return set(_TYPE_RE.findall(text))


def gateway_metric_names() -> set[str]:
    from llmlb_tpu.gateway.config import SloConfig
    from llmlb_tpu.gateway.metrics import GatewayMetrics

    g = GatewayMetrics(slo=SloConfig())
    # one sample per labeled family so every series renders
    g.record_request("/v1/chat/completions", 500)
    g.record_retry("chat")
    g.record_queue_timeout("m")
    g.record_ttft("m", "e", 0.1)
    g.record_e2e("m", "e", 0.1)
    g.record_queue_wait("m", "e", 0.1)
    # resilience families (gateway/resilience.py)
    g.record_failover_retry("m", "connect_error")
    g.record_failover_recovery("m")
    g.record_retry_budget_exhausted()
    g.record_breaker_transition("e", "open")
    g.set_breaker_state("e", 2)
    g.record_stream_interruption("m", "e")
    g.record_fault_injected("connect_refused")
    g.record_structured_request("json_schema")
    g.record_structured_rejected()
    g.record_slo("m", 0.01, 0.01)  # SLO goodput family
    names = set(_TYPE_RE.findall(g.render()))
    # scrape-time gauges/counters injected by the /metrics handler — the
    # exposition builder lives in app_state.gateway_exposition (shared by
    # the handler and the multi-worker metrics spool), with app.py kept in
    # the scan for anything still injected at the route
    for module in ("app.py", "app_state.py"):
        src = (REPO / "llmlb_tpu" / "gateway" / module).read_text()
        names |= set(_GATEWAY_LITERAL_RE.findall(src))
    return names


def cloud_metric_names() -> set[str]:
    """llmlb_cloud_* series from the cloud-proxy exposition builder (string
    literals in api_cloud.py; suffixed bucket/sum/count lines resolve to
    their histogram family)."""
    src = (REPO / "llmlb_tpu" / "gateway" / "api_cloud.py").read_text()
    return {
        _HIST_SUFFIX_RE.sub("", n) for n in _CLOUD_LITERAL_RE.findall(src)
    }


def exportable_names() -> set[str]:
    return (engine_metric_names() | gateway_metric_names()
            | cloud_metric_names())


def referenced_series(*paths: Path) -> set[str]:
    """Every llmlb_* series named in the monitoring assets (dashboard
    exprs, alert exprs), suffix-normalized to family names."""
    names: set[str] = set()
    for path in paths:
        for n in _SERIES_RE.findall(path.read_text()):
            names.add(_HIST_SUFFIX_RE.sub("", n))
    return names


def undocumented(names: set[str], docs_text: str) -> list[str]:
    return sorted(n for n in names if n not in docs_text)


def unknown_references(referenced: set[str],
                       exportable: set[str]) -> list[str]:
    return sorted(n for n in referenced if n not in exportable)


def main() -> int:
    docs_text = DOCS.read_text()
    rc = 0
    missing = undocumented(exportable_names(), docs_text)
    if missing:
        print("metric names exported but not documented in "
              f"{DOCS.relative_to(REPO)}:", file=sys.stderr)
        for name in missing:
            print(f"  - {name}", file=sys.stderr)
        rc = 1
    dangling = unknown_references(referenced_series(GRAFANA, ALERTS),
                                  exportable_names())
    if dangling:
        print("series referenced by dashboards/alerts but exported by "
              "nothing:", file=sys.stderr)
        for name in dangling:
            print(f"  - {name}", file=sys.stderr)
        rc = 1
    if rc == 0:
        print("all exported metric names are documented and every "
              "dashboard/alert series exists")
    return rc


if __name__ == "__main__":
    sys.path.insert(0, str(REPO))
    raise SystemExit(main())
