#!/usr/bin/env python
"""Cross-check exported metric names against docs/monitoring/README.md.

Every Prometheus series the engine and gateway registries can emit must be
named VERBATIM somewhere in docs/monitoring/README.md — new gauges (like the
page-pool family) cannot ship undocumented. Wired as a tier-1 test
(tests/test_metrics_docs.py); also runnable standalone:

    python scripts/check_metrics_docs.py

Enumeration is by rendering the real registries (with every optional block
enabled and one sample recorded per labeled family, so conditional series
render too) plus the scrape-time gauge/counter literals the gateway /metrics
handler injects (regex over llmlb_tpu/gateway/app.py — they live in a dict
at the call site, not in the registry).
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
DOCS = REPO / "docs" / "monitoring" / "README.md"

_TYPE_RE = re.compile(r"^# TYPE (\S+) ", re.MULTILINE)
_GATEWAY_LITERAL_RE = re.compile(r'"(llmlb_gateway_[a-z0-9_]+)"')


def engine_metric_names() -> set[str]:
    from llmlb_tpu.engine.metrics import EngineMetrics

    m = EngineMetrics()
    text = m.render(
        queue_depth=0, active_slots=0, num_slots=1,
        prefix_cache={
            "enabled": True, "entries": 0, "pinned_slots": 0,
            "pinned_pages": 0, "pinned_hbm_bytes": 0,
        },
        structured={
            "enabled": True, "mask_cache_entries": 0, "mask_cache_bytes": 0,
        },
        kv_cache={
            "layout": "paged", "page_size": 128, "pages_total": 0,
            "pages_free": 0, "pages_active": 0, "pages_pinned": 0,
            "utilization": 0.0, "fragmentation": 0.0,
            "waste_tokens_mean": 0.0,
        },
    )
    return set(_TYPE_RE.findall(text))


def gateway_metric_names() -> set[str]:
    from llmlb_tpu.gateway.metrics import GatewayMetrics

    g = GatewayMetrics()
    # one sample per labeled family so every series renders
    g.record_request("/v1/chat/completions", 500)
    g.record_retry("chat")
    g.record_queue_timeout("m")
    g.record_ttft("m", "e", 0.1)
    g.record_e2e("m", "e", 0.1)
    g.record_queue_wait("m", "e", 0.1)
    # resilience families (gateway/resilience.py)
    g.record_failover_retry("m", "connect_error")
    g.record_failover_recovery("m")
    g.record_retry_budget_exhausted()
    g.record_breaker_transition("e", "open")
    g.set_breaker_state("e", 2)
    g.record_stream_interruption("m", "e")
    g.record_fault_injected("connect_refused")
    g.record_structured_request("json_schema")
    g.record_structured_rejected()
    names = set(_TYPE_RE.findall(g.render()))
    # scrape-time gauges/counters injected by the /metrics handler
    app_src = (REPO / "llmlb_tpu" / "gateway" / "app.py").read_text()
    names |= set(_GATEWAY_LITERAL_RE.findall(app_src))
    return names


def undocumented(names: set[str], docs_text: str) -> list[str]:
    return sorted(n for n in names if n not in docs_text)


def main() -> int:
    docs_text = DOCS.read_text()
    missing = undocumented(engine_metric_names() | gateway_metric_names(),
                           docs_text)
    if missing:
        print("metric names exported but not documented in "
              f"{DOCS.relative_to(REPO)}:", file=sys.stderr)
        for name in missing:
            print(f"  - {name}", file=sys.stderr)
        return 1
    print("all exported metric names are documented")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, str(REPO))
    raise SystemExit(main())
