#!/usr/bin/env python
"""Fail on silent exception swallows in llmlb_tpu/.

Crash-recovery code (durable streams, drain, failover) only works when
failures SURFACE: a bare ``except:`` or an ``except Exception:`` whose body
is just ``pass``/``...`` hides exactly the evidence the resilience layer
needs. This checker walks every llmlb_tpu/ source with `ast` and flags:

- bare ``except:`` handlers (any body — they also swallow KeyboardInterrupt
  and the step loop's CancelledError);
- ``except Exception:`` / ``except BaseException:`` handlers whose body is
  only ``pass`` / ``...`` (a swallow with no logging, counting, or fallback).

A handler that is deliberate must carry an ``# allow-silent: <reason>``
comment on the ``except`` line or inside the handler body — the reason is
the point: it forces the author to write down why hiding this error is
safe. Wired as a tier-1 test (tests/test_silent_except.py); standalone:

    python scripts/check_silent_except.py
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SRC = REPO / "llmlb_tpu"

ALLOW_MARKER = "allow-silent:"
BROAD_NAMES = ("Exception", "BaseException")


def _is_trivial_body(body: list[ast.stmt]) -> bool:
    """True when the handler body does nothing: only `pass` and/or bare
    constant expressions (docstrings, `...`)."""
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)):
            continue
        return False
    return True


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    if isinstance(t, ast.Name) and t.id in BROAD_NAMES:
        return True
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in BROAD_NAMES
                   for e in t.elts)
    return False


def _allowed(lines: list[str], handler: ast.ExceptHandler) -> bool:
    """The allow-marker may sit on the `except` line or any line of the
    handler body (comments are invisible to ast, so scan the source)."""
    end = handler.body[-1].end_lineno or handler.body[-1].lineno
    for lineno in range(handler.lineno, end + 1):
        if ALLOW_MARKER in lines[lineno - 1]:
            return True
    return False


def check_file(path: Path) -> list[tuple[int, str]]:
    source = path.read_text()
    lines = source.splitlines()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:  # broken file: other tooling reports it better
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    findings: list[tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            if not _allowed(lines, node):
                findings.append((node.lineno, "bare `except:`"))
            continue
        if _is_broad(node) and _is_trivial_body(node.body):
            if not _allowed(lines, node):
                findings.append((
                    node.lineno,
                    "`except Exception: pass` silent swallow",
                ))
    return findings


def main() -> int:
    bad = 0
    checked = 0
    for path in sorted(SRC.rglob("*.py")):
        checked += 1
        for lineno, what in check_file(path):
            rel = path.relative_to(REPO)
            print(f"{rel}:{lineno}: {what} — log/count it, or annotate "
                  f"`# {ALLOW_MARKER} <reason>`", file=sys.stderr)
            bad += 1
    if bad:
        print(f"\n{bad} silent exception swallow(s) found", file=sys.stderr)
        return 1
    print(f"no silent exception swallows in {checked} files")
    return 0


if __name__ == "__main__":
    sys.exit(main())
