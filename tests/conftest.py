"""Test configuration: force an 8-device virtual CPU platform for sharding tests.

Mirrors the reference's "multi-node without a cluster" strategy (SURVEY.md §4):
everything runs in-process — JAX on a virtual 8-device CPU mesh, gateway servers on
ephemeral localhost ports, SQLite in-memory/tmpdir.
"""

import os

# Env vars alone are not enough here: the machine image injects an `axon` TPU
# plugin via PYTHONPATH sitecustomize that overrides JAX_PLATFORMS. jax.config
# updates before first backend use win over it.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Older jax (< 0.5) has no jax_num_cpu_devices config; the XLA flag is the
# portable spelling and must be in place before the backend initializes.
_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _FLAG
    ).strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # jax < 0.5: XLA_FLAGS above already did it
    pass
# fp32 tests compare against float64/torch references; JAX's default ("fastest")
# matmul precision is bf16-grade even on CPU.
jax.config.update("jax_default_matmul_precision", "highest")

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio.run (pytest-asyncio is not available)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None


def pytest_configure(config):
    """Build the native library once up front so tests exercise native
    paths (router core, SSE scanner, HRW owner, ct_equal). When no C++
    toolchain is present the parity tests skip with a VISIBLE reason
    (native_skip_reason below feeds their skipif) — never silently."""
    import shutil
    import sys

    compiler = shutil.which("g++") or shutil.which("c++") or shutil.which("cc")
    built = False
    try:
        from llmlb_tpu.native import ensure_native_built

        built = ensure_native_built()
    except Exception as e:
        sys.stderr.write(f"[conftest] native build errored: {e}\n")
    if not built:
        sys.stderr.write(
            "[conftest] native library unavailable "
            f"(compiler={'none found' if not compiler else compiler}); "
            "native-parity tests will SKIP with that reason\n"
        )


def native_skip_reason() -> str | None:
    """None when the native library is loadable; otherwise the reason the
    parity tests print in their skip line (tier-1 must show WHY)."""
    import shutil

    try:
        from llmlb_tpu.native import load_native

        if load_native() is not None:
            return None
    except Exception as e:
        return f"native library failed to load: {e}"
    compiler = shutil.which("g++") or shutil.which("c++") or shutil.which("cc")
    if compiler is None:
        return ("no C++ toolchain on this host (install g++ or run "
                "`make -C native` elsewhere)")
    return "native library not built (run `make -C native`)"


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return devices
