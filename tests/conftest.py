"""Test configuration: force an 8-device virtual CPU platform for sharding tests.

Mirrors the reference's "multi-node without a cluster" strategy (SURVEY.md §4):
everything runs in-process — JAX on a virtual 8-device CPU mesh, gateway servers on
ephemeral localhost ports, SQLite in-memory/tmpdir.
"""

import os

# Env vars alone are not enough here: the machine image injects an `axon` TPU
# plugin via PYTHONPATH sitecustomize that overrides JAX_PLATFORMS. jax.config
# updates before first backend use win over it.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Older jax (< 0.5) has no jax_num_cpu_devices config; the XLA flag is the
# portable spelling and must be in place before the backend initializes.
_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _FLAG
    ).strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # jax < 0.5: XLA_FLAGS above already did it
    pass
# fp32 tests compare against float64/torch references; JAX's default ("fastest")
# matmul precision is bf16-grade even on CPU.
jax.config.update("jax_default_matmul_precision", "highest")

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio.run (pytest-asyncio is not available)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None


def pytest_configure(config):
    """Build the native library once up front so tests exercise native paths."""
    try:
        from llmlb_tpu.native import ensure_native_built

        ensure_native_built()
    except Exception:
        pass


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return devices
