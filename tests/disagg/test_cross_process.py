"""Cross-process handoff, functionally: a `--role prefill` engine commits
the first token(s) over /v1/handoff/prefill, a `--role decode` engine
adopts over /v1/handoff by prompt+committed replay, and the joined stream
is token-identical to one engine serving end-to-end — greedy, seeded, and
grammar-constrained (the FSM cursor is rebuilt by re-walking the committed
tokens on the adopter, docs/disaggregation.md).
"""

import asyncio
import json

import jsonschema
import pytest
from aiohttp.test_utils import TestClient, TestServer

# Two full engine builds (~1 min on a CPU host): excluded from the tier-1
# `-m 'not slow'` sweep. The tier-1 handoff coverage lives in
# test_handoff_wire.py (wire contract) and tests/engine/ (split identity +
# the parameterized preemption suite); this file is the functional
# cross-process proof, run explicitly or in full sweeps.
pytestmark = pytest.mark.slow

from llmlb_tpu.engine.server import create_engine_app
from llmlb_tpu.engine.service import Engine

KW = dict(num_slots=2, slot_capacity=128, prefill_buckets=(16, 32),
          seed=0, kv_layout="paged", kv_page_size=16)

SCHEMA = {
    "type": "object",
    "properties": {"name": {"type": "string", "maxLength": 8},
                   "n": {"enum": [0, 1, 2, 3]}},
    "required": ["name", "n"],
}


@pytest.fixture(scope="module")
def rig():
    async def build():
        pre = Engine.from_preset("debug-tiny", role="prefill", **KW)
        dec = Engine.from_preset("debug-tiny", role="decode", **KW)
        cp = TestClient(TestServer(create_engine_app(pre, owns_engine=False)))
        cd = TestClient(TestServer(create_engine_app(dec, owns_engine=False)))
        await cp.start_server()
        await cd.start_server()
        return pre, dec, cp, cd

    loop = asyncio.new_event_loop()
    pre, dec, cp, cd = loop.run_until_complete(build())
    yield loop, cp, cd, pre, dec
    loop.run_until_complete(cp.close())
    loop.run_until_complete(cd.close())
    pre.shutdown()
    dec.shutdown()
    loop.close()


async def _reference(cp, body) -> dict:
    r = await cp.post("/v1/chat/completions", json=body)
    assert r.status == 200, await r.text()
    return await r.json()


async def _via_handoff(cp, cd, body, *, handoff_tokens=1) -> tuple[dict, dict]:
    """(handoff envelope from the prefill engine, adopted completion)."""
    r = await cp.post("/v1/handoff/prefill",
                      json={**body, "handoff_tokens": handoff_tokens})
    assert r.status == 200, await r.text()
    env = await r.json()
    assert env["object"] == "llmlb.handoff"
    r = await cd.post("/v1/handoff", json={
        "handoff": env["handoff"], "stream": False,
        "tool_name": env.get("tool_name"),
    })
    assert r.status == 200, await r.text()
    return env, await r.json()


def _content(completion: dict) -> str:
    return completion["choices"][0]["message"]["content"]


def test_greedy_adoption_token_identical(rig):
    loop, cp, cd, pre, dec = rig

    async def run():
        body = {"messages": [{"role": "user",
                              "content": "tell me about foxes"}],
                "temperature": 0, "max_tokens": 24}
        ref = await _reference(cp, body)
        env, adopted = await _via_handoff(cp, cd, body)
        assert _content(adopted) == _content(ref)
        assert (adopted["choices"][0]["finish_reason"]
                == ref["choices"][0]["finish_reason"])
        # usage counts committed + continuation as one stream
        assert adopted["usage"] == ref["usage"]
    loop.run_until_complete(run())
    assert pre.core.metrics.handoff_total["emitted"] >= 1
    assert dec.core.metrics.handoff_total["adopted"] >= 1


def test_seeded_adoption_token_identical_with_wider_window(rig):
    loop, cp, cd, _pre, _dec = rig

    async def run():
        body = {"messages": [{"role": "user",
                              "content": "tell me about foxes"}],
                "temperature": 0.9, "seed": 42, "max_tokens": 24}
        ref = await _reference(cp, body)
        _, adopted = await _via_handoff(cp, cd, body, handoff_tokens=5)
        assert _content(adopted) == _content(ref)
    loop.run_until_complete(run())


def test_constrained_adoption_rewalks_the_grammar_cursor(rig):
    """JSON-mode across the wire: the adopter rebuilds the FSM cursor by
    advancing over the committed tokens — a start-state cursor would mask
    the continuation as if at the beginning of the document."""
    loop, cp, cd, _pre, dec = rig

    async def run():
        body = {
            "messages": [{"role": "user", "content": "give me json"}],
            "temperature": 0, "max_tokens": 96,
            "response_format": {"type": "json_schema",
                                "json_schema": {"name": "s",
                                                "schema": SCHEMA}},
        }
        ref = await _reference(cp, body)
        violations = dec.core.metrics.constraint_violations_total
        _, adopted = await _via_handoff(cp, cd, body, handoff_tokens=3)
        assert _content(adopted) == _content(ref)
        jsonschema.validate(json.loads(_content(adopted)), SCHEMA)
        assert dec.core.metrics.constraint_violations_total == violations
    loop.run_until_complete(run())


def test_decode_role_refuses_to_originate(rig):
    loop, _cp, cd, _pre, _dec = rig

    async def run():
        r = await cd.post("/v1/handoff/prefill", json={
            "messages": [{"role": "user", "content": "hi"}],
        })
        assert r.status == 409
    loop.run_until_complete(run())


def test_malformed_payload_is_a_400_not_a_crash(rig):
    loop, _cp, cd, _pre, dec = rig

    async def run():
        r = await cd.post("/v1/handoff", json={
            "handoff": {"version": 1, "prompt_ids": "nope",
                        "committed_ids": [], "sampling": {}},
        })
        assert r.status == 400
        body = await r.json()
        assert "prompt_ids" in body["error"]["message"]
        # the engine still serves after the rejection
        r = await cd.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "still alive?"}],
            "max_tokens": 4, "temperature": 0,
        })
        assert r.status == 200
    loop.run_until_complete(run())


def test_handoff_flight_records_pair_across_processes(rig):
    """Each side of the wire records its half of the handoff — `emitted`
    on the prefill engine, `adopted` on the decode engine — keyed by the
    same gateway request id, with cause stamped before effect, so the
    gateway's `?view=timeline` merge can join them (docs/tracing.md)."""
    loop, cp, cd, _pre, _dec = rig
    rid = "trace-xproc-handoff-1"

    async def run():
        body = {"messages": [{"role": "user",
                              "content": "tell me about wires"}],
                "temperature": 0, "max_tokens": 12}
        r = await cp.post("/v1/handoff/prefill",
                          json={**body, "handoff_tokens": 1},
                          headers={"X-Request-Id": rid})
        assert r.status == 200, await r.text()
        env = await r.json()
        r = await cd.post("/v1/handoff", json={
            "handoff": env["handoff"], "stream": False,
            "tool_name": env.get("tool_name"),
        })
        assert r.status == 200, await r.text()

        r = await cp.get(f"/api/requests/{rid}/timeline")
        assert r.status == 200, await r.text()
        emit_tl = await r.json()
        r = await cd.get(f"/api/requests/{rid}/timeline")
        assert r.status == 200, await r.text()
        adopt_tl = await r.json()
        return emit_tl, adopt_tl

    emit_tl, adopt_tl = loop.run_until_complete(run())
    emitted = [e for e in emit_tl["events"]
               if e["event"] == "handoff_emitted"]
    adopted = [e for e in adopt_tl["events"] if e["event"] == "adopted"]
    assert len(emitted) == 1 and len(adopted) == 1
    # the join key both sides share is the gateway rid (the fixture runs
    # both engines in-process, so the pid-based source tag cannot differ)
    assert emitted[0]["request_id"] == adopted[0]["request_id"] == rid
    assert emitted[0]["ts"] <= adopted[0]["ts"]
    assert adopted[0]["attrs"]["committed"] >= 1


def test_adoption_ships_kv_pages_and_skips_the_replay_prefill(rig):
    """The PR 17 tentpole, cross-process: the prefill engine attaches its
    serialized KV pages to the envelope, the adopter lands them H2D and
    enters decode with ZERO prefill dispatches — the dispatch ledger and
    both engines' kv counters prove the pages moved, and the joined
    timeline shows kv_shipped -> kv_restored with no prefill_chunk on the
    adopter (docs/kv-cache.md)."""
    loop, cp, cd, pre, dec = rig
    rid = "trace-xproc-kvship-1"
    shipped0 = pre.core.metrics.kv_ship_total
    restored0 = dec.core.metrics.kv_restored_total
    fallbacks0 = dict(dec.core.metrics.kv_ship_fallback_total)

    async def run():
        body = {"messages": [{"role": "user",
                              "content": "tell me about page tables"}],
                "temperature": 0, "max_tokens": 24}
        ref = await _reference(cp, body)
        r = await cp.post("/v1/handoff/prefill",
                          json={**body, "handoff_tokens": 3},
                          headers={"X-Request-Id": rid})
        assert r.status == 200, await r.text()
        env = await r.json()
        # the page payload rides INSIDE the handoff block — an old adopter
        # ignores the unknown top-level key and replays as before
        assert "kv_pages" in env["handoff"]
        disp0 = sum(dec.core.prefill_dispatch_by_loop.values())
        r = await cd.post("/v1/handoff", json={
            "handoff": env["handoff"], "stream": False,
            "tool_name": env.get("tool_name"),
        })
        assert r.status == 200, await r.text()
        adopted = await r.json()
        disp = sum(dec.core.prefill_dispatch_by_loop.values()) - disp0
        assert _content(adopted) == _content(ref)
        assert disp == 0, f"adoption ran {disp} replay prefill dispatches"
        r = await cd.get(f"/api/requests/{rid}/timeline")
        assert r.status == 200, await r.text()
        return await r.json()

    adopt_tl = loop.run_until_complete(run())
    assert pre.core.metrics.kv_ship_total == shipped0 + 1
    assert dec.core.metrics.kv_restored_total == restored0 + 1
    assert dict(dec.core.metrics.kv_ship_fallback_total) == fallbacks0
    events = [e["event"] for e in adopt_tl["events"]]
    assert "kv_restored" in events
    assert "prefill_chunk" not in events, (
        "the adopter replay-prefilled despite landing shipped pages"
    )
