"""Handoff-wire round trip (the cross-process counterpart of
tests/engine/test_plan_wire.py): every SamplingParams field must survive
`handoff_payload` → JSON → `parse_handoff`, so forgetting a field when
adding a knob is a TEST FAILURE instead of a silently-desynced adopted
stream — `deadline_ms` and `priority` riding the handoff are exactly what
this guards (docs/disaggregation.md).

Same auto-coverage trick as the plan-wire test: a distinctive non-default
probe value is synthesized for EVERY declared field from its annotation, so
a newly declared field is covered the moment it exists.
"""

import dataclasses
import json

import numpy as np
import pytest

from llmlb_tpu.disagg import (
    HANDOFF_WIRE_VERSION,
    HandoffError,
    handoff_payload,
    parse_handoff,
)
from llmlb_tpu.engine.kv_transfer import (
    KV_WIRE_VERSION,
    KVTransferError,
    KVWireHeader,
    expected_sections,
    parse_kv_payload,
    serialize_kv_pages,
)
from llmlb_tpu.engine.scheduler import SamplingParams


def _distinct_value(field: dataclasses.Field):
    """A JSON-safe value distinguishable from the field's default, derived
    from the annotation so newly added fields get covered automatically."""
    ann = str(field.type)
    if "dict" in ann:
        return {"probe": field.name, "n": 3}
    if "bool" in ann:
        default = field.default
        return not default if isinstance(default, bool) else True
    if "float" in ann:
        return 0.125
    if "int" in ann:
        return 7
    if "str" in ann:
        return f"probe-{field.name}"
    raise AssertionError(
        f"SamplingParams.{field.name}: add a wire-probe rule for {ann!r} "
        "(and make sure the field is JSON-safe for the handoff wire)"
    )


def _probe_params() -> SamplingParams:
    return SamplingParams(**{
        f.name: _distinct_value(f) for f in dataclasses.fields(SamplingParams)
    })


def _roundtrip(payload: dict) -> dict:
    """The exact cross-process path: the payload crosses as JSON text."""
    return json.loads(json.dumps(payload))


def test_every_sampling_field_survives_the_handoff_wire():
    params = _probe_params()
    payload = _roundtrip(handoff_payload([1, 2, 3], [9, 9], params,
                                         stop=["\n\n"], request_id="rid-1"))
    prompt, committed, sampling, stop, rid, t = parse_handoff(payload)
    assert prompt == [1, 2, 3]
    assert committed == [9, 9]
    assert stop == ["\n\n"]
    assert rid == "rid-1"
    assert t > 0
    for f in dataclasses.fields(SamplingParams):
        assert getattr(sampling, f.name) == getattr(params, f.name), (
            f"SamplingParams.{f.name} was lost or mangled on the "
            "handoff wire"
        )


def test_probe_values_differ_from_defaults():
    """The round-trip assertion is only meaningful if the probe differs
    from the default (a dropped field that deserializes to its default
    must FAIL the wire test)."""
    params = _probe_params()
    defaults = SamplingParams()
    for f in dataclasses.fields(SamplingParams):
        assert getattr(params, f.name) != getattr(defaults, f.name), (
            f"probe for SamplingParams.{f.name} equals its default; "
            "_distinct_value needs a better rule"
        )


def test_deadline_and_priority_ride_the_wire_verbatim():
    """The PR 11 bugfix satellite, stated explicitly on top of the generic
    probe: a request handed from the prefill pool to the decode pool keeps
    its scheduling class and its deadline."""
    params = SamplingParams(priority=2, deadline_ms=1500.0, seed=42)
    payload = _roundtrip(handoff_payload([5], [1], params))
    _, _, sampling, _, _, _ = parse_handoff(payload)
    assert sampling.priority == 2
    assert sampling.deadline_ms == 1500.0
    assert sampling.seed == 42


def test_constraint_and_speculative_ride_verbatim():
    params = SamplingParams(
        constraint={"type": "json_object"},
        speculative={"enabled": True, "max_draft_tokens": 6},
    )
    payload = _roundtrip(handoff_payload([5], [], params))
    _, _, sampling, _, _, _ = parse_handoff(payload)
    assert sampling.constraint == {"type": "json_object"}
    assert sampling.speculative == {"enabled": True, "max_draft_tokens": 6}


# ------------------------------------------------------------- validation


def _valid() -> dict:
    return handoff_payload([1, 2], [3], SamplingParams())


def test_rejects_wrong_version():
    payload = _valid()
    payload["version"] = HANDOFF_WIRE_VERSION + 1
    with pytest.raises(HandoffError, match="version"):
        parse_handoff(payload)


def test_rejects_unknown_sampling_fields():
    """A NEWER prefill engine's extra field must refuse loudly — silently
    dropping it would desync the adopted continuation."""
    payload = _valid()
    payload["sampling"]["from_the_future"] = 1
    with pytest.raises(HandoffError, match="from_the_future"):
        parse_handoff(payload)


@pytest.mark.parametrize("mutate, match", [
    (lambda p: p.pop("prompt_ids"), "prompt_ids"),
    (lambda p: p.update(prompt_ids=[]), "prompt_ids"),
    (lambda p: p.update(prompt_ids=["x"]), "integers"),
    (lambda p: p.update(committed_ids="nope"), "committed_ids"),
    (lambda p: p.update(sampling=None), "sampling"),
    (lambda p: p.update(stop="raw-string"), "stop"),
    (lambda p: p.update(request_id=7), "request_id"),
])
def test_rejects_malformed_payloads(mutate, match):
    payload = _valid()
    mutate(payload)
    with pytest.raises(HandoffError, match=match):
        parse_handoff(payload)


def test_rejects_non_object_payload():
    with pytest.raises(HandoffError):
        parse_handoff(None)
    with pytest.raises(HandoffError):
        parse_handoff([1, 2, 3])


def test_rejects_implausible_token_counts():
    payload = _valid()
    payload["committed_ids"] = list(range(4_000_001))
    with pytest.raises(HandoffError, match="implausibly"):
        parse_handoff(payload)


# --------------------------------------------- kv page payload header
# The `kv_pages` sibling the envelope can carry (LLMLB_KV_SHIP) has its
# own versioned header; same discipline as the sampling block: every
# declared field must survive the wire, unknown inbound fields refuse.


# One distinctive value per declared header field — pairwise-distinct
# integers so a field-swap bug cannot cancel out. A newly declared field
# fails _kv_probe_header until a probe value (and a wire rule) exists.
_KV_PROBES = {
    "version": KV_WIRE_VERSION,
    "layers": 3,
    "page_size": 8,
    "num_kv_heads": 5,
    "head_dim": 4,
    "kv_dtype": "float32",
    "num_pages": 2,
    "tokens": 13,  # < num_pages * page_size, not a page multiple
}


def _kv_probe_header() -> KVWireHeader:
    for f in dataclasses.fields(KVWireHeader):
        assert f.name in _KV_PROBES, (
            f"KVWireHeader.{f.name}: add a wire-probe value (and make "
            "sure the field survives serialize_kv_pages -> "
            "parse_kv_payload)"
        )
    return KVWireHeader(**_KV_PROBES)


def _kv_probe_sections(header: KVWireHeader) -> dict:
    out = {}
    for i, (name, (shape, dtype)) in enumerate(
            sorted(expected_sections(header).items())):
        n = int(np.prod(shape))
        out[name] = (np.arange(n, dtype=np.float64) % 97 + i) \
            .astype(dtype).reshape(shape)
    return out


def test_every_kv_header_field_survives_the_wire():
    header = _kv_probe_header()
    sections = _kv_probe_sections(header)
    payload = _roundtrip(serialize_kv_pages(header, sections))
    parsed = parse_kv_payload(payload)
    for f in dataclasses.fields(KVWireHeader):
        assert getattr(parsed.header, f.name) == getattr(header, f.name), (
            f"KVWireHeader.{f.name} was lost or mangled on the kv wire"
        )
    for name, arr in sections.items():
        assert np.array_equal(parsed.sections[name], arr), (
            f"kv section {name!r} bytes changed on the wire"
        )


def test_kv_probe_values_are_pairwise_distinct():
    ints = [v for v in _KV_PROBES.values() if isinstance(v, int)]
    assert len(ints) == len(set(ints)), (
        "kv header probe integers collide; a swapped-field bug could "
        "round-trip undetected"
    )


def test_kv_header_rides_the_handoff_envelope():
    """The payload crosses as a top-level sibling of the handoff block —
    an old adopter ignores it (top-level unknowns are tolerated by
    parse_handoff, unlike sampling fields) and replays as before."""
    header = _kv_probe_header()
    kv = serialize_kv_pages(header, _kv_probe_sections(header))
    payload = _roundtrip(handoff_payload([1, 2], [3], SamplingParams(),
                                         kv_pages=kv))
    parse_handoff(payload)  # old-adopter path: kv_pages is invisible
    parsed = parse_kv_payload(payload["kv_pages"])
    assert parsed.header == header


def test_kv_rejects_unknown_header_field():
    """A newer peer's extension must version-bump, never silently drop."""
    header = _kv_probe_header()
    payload = serialize_kv_pages(header, _kv_probe_sections(header))
    payload["from_the_future"] = 1
    with pytest.raises(KVTransferError, match="from_the_future"):
        parse_kv_payload(payload)


def test_kv_rejects_wrong_version_with_reason():
    header = _kv_probe_header()
    payload = serialize_kv_pages(header, _kv_probe_sections(header))
    payload["version"] = KV_WIRE_VERSION + 1
    with pytest.raises(KVTransferError) as e:
        parse_kv_payload(payload)
    assert e.value.reason == "version"
