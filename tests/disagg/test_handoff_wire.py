"""Handoff-wire round trip (the cross-process counterpart of
tests/engine/test_plan_wire.py): every SamplingParams field must survive
`handoff_payload` → JSON → `parse_handoff`, so forgetting a field when
adding a knob is a TEST FAILURE instead of a silently-desynced adopted
stream — `deadline_ms` and `priority` riding the handoff are exactly what
this guards (docs/disaggregation.md).

Same auto-coverage trick as the plan-wire test: a distinctive non-default
probe value is synthesized for EVERY declared field from its annotation, so
a newly declared field is covered the moment it exists.
"""

import dataclasses
import json

import pytest

from llmlb_tpu.disagg import (
    HANDOFF_WIRE_VERSION,
    HandoffError,
    handoff_payload,
    parse_handoff,
)
from llmlb_tpu.engine.scheduler import SamplingParams


def _distinct_value(field: dataclasses.Field):
    """A JSON-safe value distinguishable from the field's default, derived
    from the annotation so newly added fields get covered automatically."""
    ann = str(field.type)
    if "dict" in ann:
        return {"probe": field.name, "n": 3}
    if "bool" in ann:
        default = field.default
        return not default if isinstance(default, bool) else True
    if "float" in ann:
        return 0.125
    if "int" in ann:
        return 7
    if "str" in ann:
        return f"probe-{field.name}"
    raise AssertionError(
        f"SamplingParams.{field.name}: add a wire-probe rule for {ann!r} "
        "(and make sure the field is JSON-safe for the handoff wire)"
    )


def _probe_params() -> SamplingParams:
    return SamplingParams(**{
        f.name: _distinct_value(f) for f in dataclasses.fields(SamplingParams)
    })


def _roundtrip(payload: dict) -> dict:
    """The exact cross-process path: the payload crosses as JSON text."""
    return json.loads(json.dumps(payload))


def test_every_sampling_field_survives_the_handoff_wire():
    params = _probe_params()
    payload = _roundtrip(handoff_payload([1, 2, 3], [9, 9], params,
                                         stop=["\n\n"], request_id="rid-1"))
    prompt, committed, sampling, stop, rid, t = parse_handoff(payload)
    assert prompt == [1, 2, 3]
    assert committed == [9, 9]
    assert stop == ["\n\n"]
    assert rid == "rid-1"
    assert t > 0
    for f in dataclasses.fields(SamplingParams):
        assert getattr(sampling, f.name) == getattr(params, f.name), (
            f"SamplingParams.{f.name} was lost or mangled on the "
            "handoff wire"
        )


def test_probe_values_differ_from_defaults():
    """The round-trip assertion is only meaningful if the probe differs
    from the default (a dropped field that deserializes to its default
    must FAIL the wire test)."""
    params = _probe_params()
    defaults = SamplingParams()
    for f in dataclasses.fields(SamplingParams):
        assert getattr(params, f.name) != getattr(defaults, f.name), (
            f"probe for SamplingParams.{f.name} equals its default; "
            "_distinct_value needs a better rule"
        )


def test_deadline_and_priority_ride_the_wire_verbatim():
    """The PR 11 bugfix satellite, stated explicitly on top of the generic
    probe: a request handed from the prefill pool to the decode pool keeps
    its scheduling class and its deadline."""
    params = SamplingParams(priority=2, deadline_ms=1500.0, seed=42)
    payload = _roundtrip(handoff_payload([5], [1], params))
    _, _, sampling, _, _, _ = parse_handoff(payload)
    assert sampling.priority == 2
    assert sampling.deadline_ms == 1500.0
    assert sampling.seed == 42


def test_constraint_and_speculative_ride_verbatim():
    params = SamplingParams(
        constraint={"type": "json_object"},
        speculative={"enabled": True, "max_draft_tokens": 6},
    )
    payload = _roundtrip(handoff_payload([5], [], params))
    _, _, sampling, _, _, _ = parse_handoff(payload)
    assert sampling.constraint == {"type": "json_object"}
    assert sampling.speculative == {"enabled": True, "max_draft_tokens": 6}


# ------------------------------------------------------------- validation


def _valid() -> dict:
    return handoff_payload([1, 2], [3], SamplingParams())


def test_rejects_wrong_version():
    payload = _valid()
    payload["version"] = HANDOFF_WIRE_VERSION + 1
    with pytest.raises(HandoffError, match="version"):
        parse_handoff(payload)


def test_rejects_unknown_sampling_fields():
    """A NEWER prefill engine's extra field must refuse loudly — silently
    dropping it would desync the adopted continuation."""
    payload = _valid()
    payload["sampling"]["from_the_future"] = 1
    with pytest.raises(HandoffError, match="from_the_future"):
        parse_handoff(payload)


@pytest.mark.parametrize("mutate, match", [
    (lambda p: p.pop("prompt_ids"), "prompt_ids"),
    (lambda p: p.update(prompt_ids=[]), "prompt_ids"),
    (lambda p: p.update(prompt_ids=["x"]), "integers"),
    (lambda p: p.update(committed_ids="nope"), "committed_ids"),
    (lambda p: p.update(sampling=None), "sampling"),
    (lambda p: p.update(stop="raw-string"), "stop"),
    (lambda p: p.update(request_id=7), "request_id"),
])
def test_rejects_malformed_payloads(mutate, match):
    payload = _valid()
    mutate(payload)
    with pytest.raises(HandoffError, match=match):
        parse_handoff(payload)


def test_rejects_non_object_payload():
    with pytest.raises(HandoffError):
        parse_handoff(None)
    with pytest.raises(HandoffError):
        parse_handoff([1, 2, 3])


def test_rejects_implausible_token_counts():
    payload = _valid()
    payload["committed_ids"] = list(range(4_000_001))
    with pytest.raises(HandoffError, match="implausibly"):
        parse_handoff(payload)
