"""Burst decode (k steps per dispatch) must match single-step decode.

The scheduler's decode_burst fuses k decode+sample steps into one jitted
lax.scan with on-device token feedback, syncing the host once per k tokens
instead of per token (the host↔device round trip dominates each step through
the axon tunnel: measured 93 ms RTT vs 3 ms compute). These tests pin the
semantics the fusion must preserve: greedy outputs identical to the k=1 path,
EOS/max_tokens finishing mid-burst trimmed, chunked prefill still interleaves.
"""

import pytest

from llmlb_tpu.engine.presets import get_preset
from llmlb_tpu.engine.scheduler import EngineCore, Request, SamplingParams


def _collect(req: Request, timeout: float = 60.0) -> tuple[list[int], str]:
    tokens: list[int] = []
    while True:
        kind, val = req.events.get(timeout=timeout)
        if kind == "token":
            tokens.append(val)
        elif kind == "done":
            return tokens, val
        elif kind == "error":
            raise RuntimeError(val)


def _run_greedy(core: EngineCore, prompts: list[list[int]],
                max_tokens: int = 12) -> list[tuple[list[int], str]]:
    reqs = [
        Request(prompt_ids=p,
                sampling=SamplingParams(temperature=0.0, max_tokens=max_tokens))
        for p in prompts
    ]
    for r in reqs:
        core.submit(r)
    return [_collect(r) for r in reqs]


@pytest.fixture(scope="module")
def cfg():
    return get_preset("debug-tiny")


def test_burst_matches_single_step_greedy(cfg):
    """Token-for-token equivalence: burst=4 vs burst=1 on the same prompts."""
    prompts = [[5, 9, 2], [7, 7, 7, 7], [3]]
    core1 = EngineCore(cfg, num_slots=4, slot_capacity=64,
                       prefill_buckets=(16, 32), seed=0, decode_burst=1)
    core1.start()
    try:
        base = _run_greedy(core1, prompts)
    finally:
        core1.stop()

    core4 = EngineCore(cfg, num_slots=4, slot_capacity=64,
                       prefill_buckets=(16, 32), seed=0, decode_burst=4)
    core4.start()
    try:
        burst = _run_greedy(core4, prompts)
    finally:
        core4.stop()

    assert burst == base


def test_burst_trims_max_tokens_mid_burst(cfg):
    """max_tokens that is not a multiple of the burst still stops exactly."""
    core = EngineCore(cfg, num_slots=2, slot_capacity=64,
                      prefill_buckets=(16,), seed=0, decode_burst=8)
    core.start()
    try:
        req = Request(prompt_ids=[1, 2, 3],
                      sampling=SamplingParams(temperature=0.0, max_tokens=5))
        core.submit(req)
        tokens, finish = _collect(req)
        # first token comes from prefill; 5 generated total, EOS never hit
        # with random weights on a 64-vocab byte model is unlikely but legal
        assert finish in ("stop", "length")
        assert len(tokens) <= 5
        if finish == "length":
            assert len(tokens) == 5
    finally:
        core.stop()


def test_burst_respects_slot_capacity(cfg):
    """A request whose room runs out mid-burst finishes with 'length' and
    never reports more tokens than the slot can hold."""
    core = EngineCore(cfg, num_slots=2, slot_capacity=24,
                      prefill_buckets=(16,), seed=0, decode_burst=8)
    core.start()
    try:
        prompt = [4] * 10
        req = Request(prompt_ids=prompt,
                      sampling=SamplingParams(temperature=0.0, max_tokens=500))
        core.submit(req)
        tokens, finish = _collect(req)
        assert finish in ("stop", "length")
        # every generated token's KV lands after the prompt's; the sequence
        # must stay within the 24-cell slot row
        assert 10 + len(tokens) <= 24
    finally:
        core.stop()


def test_burst_with_chunked_prefill_interleaves(cfg):
    """A long prompt (chunked prefill) and a short decode share the loop with
    burst decode on: both finish, the short one keeps emitting during the
    long one's prefill."""
    core = EngineCore(cfg, num_slots=2, slot_capacity=128,
                      prefill_buckets=(16, 32), seed=0, decode_burst=4)
    core.start()
    try:
        short = Request(prompt_ids=[8, 8],
                        sampling=SamplingParams(temperature=0.0, max_tokens=20))
        long = Request(prompt_ids=list(range(1, 100)),
                       sampling=SamplingParams(temperature=0.0, max_tokens=4))
        core.submit(short)
        core.submit(long)
        s_tokens, s_finish = _collect(short)
        l_tokens, l_finish = _collect(long)
        assert s_finish in ("stop", "length")
        assert l_finish in ("stop", "length")
    finally:
        core.stop()


def test_burst_cancellation_mid_stream(cfg):
    """Cancel during generation: the slot frees and the request ends with
    'cancelled' even when cancellation lands mid-burst."""
    core = EngineCore(cfg, num_slots=2, slot_capacity=128,
                      prefill_buckets=(16,), seed=0, decode_burst=4)
    core.start()
    try:
        req = Request(prompt_ids=[9, 9, 9],
                      sampling=SamplingParams(temperature=0.0, max_tokens=100))
        core.submit(req)
        # wait for the first token, then cancel
        kind, _ = req.events.get(timeout=60)
        assert kind == "token"
        req.cancel()
        while True:
            kind, val = req.events.get(timeout=60)
            if kind == "done":
                assert val == "cancelled"
                break
        # slot must be reusable afterwards
        nxt = Request(prompt_ids=[2, 2],
                      sampling=SamplingParams(temperature=0.0, max_tokens=3))
        core.submit(nxt)
        _, finish = _collect(nxt)
        assert finish in ("stop", "length")
    finally:
        core.stop()


def test_batched_prefill_matches_sequential(cfg):
    """Same-bucket prompts prefilled together (one padded dispatch) must
    produce the same greedy outputs as one-at-a-time inserts. The padded
    rows repeat the last request, so duplicate scatters are exercised too
    (6 requests -> pow2 pad to 8)."""
    prompts = [[i + 1, i + 2, i + 3] for i in range(6)]

    core_seq = EngineCore(cfg, num_slots=8, slot_capacity=64,
                          prefill_buckets=(16,), seed=0, decode_burst=1)
    core_seq.MAX_PREFILL_GROUP = 1  # force one-at-a-time inserts
    core_seq.start()
    try:
        base = _run_greedy(core_seq, prompts, max_tokens=8)
    finally:
        core_seq.stop()

    core_batch = EngineCore(cfg, num_slots=8, slot_capacity=64,
                            prefill_buckets=(16,), seed=0, decode_burst=1)
    core_batch.start()
    try:
        batched = _run_greedy(core_batch, prompts, max_tokens=8)
    finally:
        core_batch.stop()

    assert batched == base


def test_batched_prefill_mixed_buckets_and_long(cfg):
    """A drain that mixes buckets and a chunked long prompt: every request
    finishes and the long one still interleaves."""
    core = EngineCore(cfg, num_slots=4, slot_capacity=128,
                      prefill_buckets=(16, 32), seed=0, decode_burst=4)
    core.start()
    try:
        reqs = [
            Request(prompt_ids=[1] * 4,
                    sampling=SamplingParams(temperature=0.0, max_tokens=6)),
            Request(prompt_ids=[2] * 20,  # second bucket
                    sampling=SamplingParams(temperature=0.0, max_tokens=6)),
            Request(prompt_ids=list(range(1, 60)),  # > 32: chunked
                    sampling=SamplingParams(temperature=0.0, max_tokens=4)),
            Request(prompt_ids=[3] * 5,
                    sampling=SamplingParams(temperature=0.0, max_tokens=6)),
        ]
        for r in reqs:
            core.submit(r)
        for r in reqs:
            tokens, finish = _collect(r)
            assert finish in ("stop", "length")
    finally:
        core.stop()


def test_prefill_dispatch_failure_reaches_batched_requests(cfg):
    """Requests claimed into a prefill batch get terminal events when the
    dispatch raises — slots are assigned before the dispatch so _fail_all
    can see them (a silent event queue hangs the HTTP stream forever)."""
    core = EngineCore(cfg, num_slots=4, slot_capacity=64,
                      prefill_buckets=(16,), seed=0, decode_burst=1)

    def boom(*args, **kwargs):
        raise RuntimeError("injected prefill failure")

    core.family = type("F", (), {
        **{k: staticmethod(getattr(core.family, k))
           for k in dir(core.family) if not k.startswith("__")},
        # both layouts' insert paths fail (paged is the default layout)
        "prefill_into_slots": staticmethod(boom),
        "prefill_into_pages": staticmethod(boom),
    })()
    core.start()
    try:
        reqs = [
            Request(prompt_ids=[1, 2, 3],
                    sampling=SamplingParams(temperature=0.0, max_tokens=4))
            for _ in range(3)
        ]
        for r in reqs:
            core.submit(r)
        for r in reqs:
            kind, val = r.events.get(timeout=30)
            assert kind == "error", (kind, val)
    finally:
        core.stop()


def test_window_buckets_cross_boundary(cfg):
    """Generation that crosses a context-window bucket boundary (256) must
    be identical to a run with only the full-capacity window available."""
    import dataclasses as _dc

    cfg600 = _dc.replace(cfg, max_position_embeddings=1024)
    prompt = [7] * 250  # window 256 covers prefill; generation crosses it

    core_full = EngineCore(cfg600, num_slots=2, slot_capacity=600,
                           prefill_buckets=(256,), seed=0, decode_burst=4)
    core_full._window_buckets = (600,)  # capacity only: no windowing
    core_full.start()
    try:
        base = _run_greedy(core_full, [prompt], max_tokens=20)
    finally:
        core_full.stop()

    core_win = EngineCore(cfg600, num_slots=2, slot_capacity=600,
                          prefill_buckets=(256,), seed=0, decode_burst=4)
    assert core_win._window_buckets == (256, 512, 600)
    core_win.start()
    try:
        windowed = _run_greedy(core_win, [prompt], max_tokens=20)
    finally:
        core_win.stop()

    assert windowed == base


def test_prewarm_compiles_both_modes(cfg):
    """Prewarm must cover burst AND single-step modes (the k==1 path gained
    per-window static recompiles of decode_step); a signature drift between
    decode_step and the prewarm lowering would otherwise be swallowed by the
    best-effort except and only surface as production compile stalls."""
    import dataclasses as _dc

    from unittest import mock

    from llmlb_tpu.engine import scheduler as sched_mod

    cfg512 = _dc.replace(cfg, max_position_embeddings=1024)
    for burst in (4, 1):
        core = EngineCore(cfg512, num_slots=2, slot_capacity=512,
                          prefill_buckets=(16,), seed=0, decode_burst=burst)
        assert core._window_buckets == (256, 512)
        core._running = True
        try:
            # prewarm swallows failures by design (best-effort in prod);
            # here any swallowed lowering error must fail the test
            with mock.patch.object(sched_mod.log, "exception",
                                   side_effect=AssertionError) as logged:
                core._prewarm_windows()
            assert not logged.called
            if burst > 1:
                assert sorted(core._decode_many) == [256, 512]
        finally:
            core._running = False
