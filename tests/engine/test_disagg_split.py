"""In-process disaggregated serving (`--role split`, docs/disaggregation.md).

The two acceptance invariants of the split architecture:

1. ISOLATION — during a mixed long-prompt/decode workload, ZERO prefill
   dispatches execute on the decode pool's step loop (asserted over the
   per-loop dispatch ledger `EngineCore.prefill_dispatch_by_loop`); decode
   ITL is structurally independent of arriving prompt size, not
   budget-bounded.
2. IDENTITY — streams served through the prefill→handoff→decode path are
   token-identical to `--role both` for greedy and seeded-stochastic
   sampling (the page-id exchange moves KV ownership without moving bytes,
   and adoption is the PR 10 resume-shaped activation).
"""

import asyncio
import time

import pytest

from llmlb_tpu.disagg import normalize_role
from llmlb_tpu.engine.scheduler import EngineCore, SamplingParams
from llmlb_tpu.engine.presets import get_preset
from llmlb_tpu.engine.service import Engine

KW = dict(num_slots=4, slot_capacity=256, prefill_buckets=(16, 32, 64),
          seed=0, kv_layout="paged", kv_page_size=16, prefix_cache=False)


@pytest.fixture(scope="module")
def pair():
    both = Engine.from_preset("debug-tiny", **KW)
    split = Engine.from_preset("debug-tiny", role="split",
                               disagg_prefill_slots=1, **KW)
    yield both, split
    both.shutdown()
    split.shutdown()


async def _consume(agen, out):
    async for delta in agen:
        out.append(delta)


def _text(out):
    return "".join(d.text for d in out)


# ------------------------------------------------------------------ identity


def test_split_greedy_token_identity(pair):
    both, split = pair

    async def run():
        ids = both.tokenizer.encode("the quick brown fox jumps over")
        params = SamplingParams(temperature=0.0, max_tokens=32)
        ref = await both.complete(ids, params)
        got = await split.complete(ids, params)
        assert got.text == ref.text
        assert got.finish_reason == ref.finish_reason
    asyncio.run(run())


def test_split_seeded_stochastic_token_identity(pair):
    both, split = pair

    async def run():
        ids = both.tokenizer.encode("the quick brown fox jumps over")
        params = SamplingParams(temperature=0.9, seed=1234, max_tokens=32)
        ref = await both.complete(ids, params)
        got = await split.complete(ids, params)
        assert got.text == ref.text
    asyncio.run(run())


def test_split_long_prompt_chunked_prefill_identity(pair):
    """A prompt past the largest one-shot bucket runs the chunked prefill
    path in the prefill pool, then hands off — still token-identical."""
    both, split = pair

    async def run():
        ids = both.tokenizer.encode("z" * 150)  # > 64-token bucket
        params = SamplingParams(temperature=0.0, max_tokens=16)
        ref = await both.complete(ids, params)
        got = await split.complete(ids, params)
        assert got.text == ref.text
    asyncio.run(run())


# ----------------------------------------------------------------- isolation


def test_zero_prefill_dispatches_on_the_decode_loop(pair):
    """The acceptance criterion, verbatim: a mixed workload of background
    decoders and long-prompt arrivals runs prefill ONLY on the prefill
    loop. Handoffs flow (so the decode pool demonstrably served adopted
    work) and the decode-loop prefill ledger stays at zero."""
    _, split = pair

    async def run():
        handoffs_before = split.core.metrics.handoff_total["in_process"]
        bg_out: list = []
        bg = asyncio.create_task(_consume(
            split.stream(split.tokenizer.encode("background decoder"),
                         SamplingParams(temperature=0.0, max_tokens=160)),
            bg_out,
        ))
        deadline = time.monotonic() + 15.0
        while not bg_out and time.monotonic() < deadline:
            await asyncio.sleep(0.005)
        assert bg_out, "background decoder never started"
        # long prompts arrive WHILE the decoder streams
        results = await asyncio.gather(*[
            split.complete(split.tokenizer.encode("y" * 150),
                           SamplingParams(temperature=0.0, max_tokens=8))
            for _ in range(3)
        ])
        assert all(r.finish_reason in ("stop", "length") for r in results)
        bg.cancel()
        try:
            await bg
        except asyncio.CancelledError:
            pass
        ledger = split.core.prefill_dispatch_by_loop
        assert ledger["decode"] == 0, (
            f"decode pool ran prefill dispatches: {ledger}"
        )
        assert ledger["main"] == 0, "split mode must not use the main loop"
        assert ledger["prefill"] > 0
        assert (split.core.metrics.handoff_total["in_process"]
                - handoffs_before) >= 4  # 3 long + the background decoder
    asyncio.run(run())


def test_split_surfaces_role_and_queue_depths(pair):
    _, split = pair
    info = split.core.disagg_info()
    assert info["role"] == "split" and info["split"] is True
    assert info["prefill_slots"] == 1 and info["decode_slots"] == 3
    sched = split.core.sched_info()
    assert set(sched["queued_by_role"]) == {"prefill", "decode"}
    text = split.core.metrics.render(
        queue_depth=0, active_slots=0, num_slots=4, sched=sched,
    )
    assert 'llmlb_engine_queue_depth_role{role="decode"}' in text
    assert "llmlb_engine_handoff_total" in text
    assert "llmlb_engine_handoff_backlog" in text


# -------------------------------------------------------------- construction


def test_role_normalization():
    assert normalize_role(None) == "both"
    assert normalize_role("") == "both"
    assert normalize_role(" Split ") == "split"
    with pytest.raises(ValueError):
        normalize_role("shard")


def test_split_requires_paged_layout_and_two_slots():
    with pytest.raises(ValueError, match="paged"):
        EngineCore(get_preset("debug-tiny"), role="split",
                   num_slots=2, slot_capacity=64, prefill_buckets=(16,),
                   kv_layout="dense")
    with pytest.raises(ValueError, match="2 slots"):
        EngineCore(get_preset("debug-tiny"), role="split",
                   num_slots=1, slot_capacity=64, prefill_buckets=(16,))


def test_split_flight_record_pairs_stage_with_adopt(pair):
    """Observability twin (docs/tracing.md): a split run's flight record
    shows the handoff as an emit/adopt pair — `staged` on the prefill
    loop, `adopted` on the decode loop — in causal order, inside one
    request timeline keyed by the gateway request id."""
    _, split = pair
    rid = "trace-split-fr-1"

    async def run():
        ids = split.tokenizer.encode("tell me about staged adoption")
        params = SamplingParams(temperature=0.0, max_tokens=8)
        got = await split.complete(ids, params, request_id=rid)
        assert got.text
    asyncio.run(run())

    tl = split.core.flightrec.timeline(rid)
    assert tl is not None, "split request left no flight record"
    names = [e["event"] for e in tl["events"]]
    assert "staged" in names and "adopted" in names
    assert names.index("staged") < names.index("adopted")
    assert names.count("staged") == names.count("adopted") == 1
    # the pair brackets the lifecycle: prefill before, finish after
    assert names.index("prefill_chunk") < names.index("staged")
    assert names[-1] == "finished"
    adopted = next(e for e in tl["events"] if e["event"] == "adopted")
    assert adopted["attrs"]["in_process"] is True
    assert adopted["attrs"]["staged_s"] >= 0
    tss = [e["ts"] for e in tl["events"]]
    assert tss == sorted(tss)
